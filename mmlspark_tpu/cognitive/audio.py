"""Audio pull-stream helpers for SDK-style continuous recognition.

Reference: cognitive/AudioStreams.scala — ``WavStream`` parses the RIFF
header and exposes fixed-size PCM frame pulls; ``CompressedStream`` passes
opaque compressed bytes through untouched. These feed
:class:`mmlspark_tpu.cognitive.speech.SpeechToTextSDK`'s windowed
continuous-recognition loop (SpeechToTextSDK.scala:204-249).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator, Optional


@dataclass
class WavFormat:
    channels: int
    sample_rate: int
    bits_per_sample: int

    @property
    def bytes_per_second(self) -> int:
        return self.sample_rate * self.channels * (self.bits_per_sample // 8)


class WavStream:
    """Parse a PCM WAV blob; iterate raw PCM in fixed-duration windows."""

    def __init__(self, data: bytes):
        self.format, self.pcm = self._parse(bytes(data))

    @staticmethod
    def _parse(data: bytes) -> tuple:
        if len(data) < 12 or data[:4] != b"RIFF" or data[8:12] != b"WAVE":
            raise ValueError("not a RIFF/WAVE stream")
        pos = 12
        fmt: Optional[WavFormat] = None
        pcm = b""
        while pos + 8 <= len(data):
            chunk_id = data[pos : pos + 4]
            (size,) = struct.unpack_from("<I", data, pos + 4)
            body = data[pos + 8 : pos + 8 + size]
            if chunk_id == b"fmt ":
                try:
                    audio_fmt, channels, rate = struct.unpack_from("<HHI", body, 0)
                    bits = struct.unpack_from("<H", body, 14)[0]
                except struct.error as e:  # truncated fmt chunk
                    raise ValueError(f"malformed WAV fmt chunk: {e}") from e
                if audio_fmt not in (1, 0xFFFE):  # PCM / extensible
                    raise ValueError(f"unsupported WAV audio format {audio_fmt}")
                if channels < 1 or rate < 1 or bits < 8:
                    raise ValueError(
                        f"invalid WAV format: channels={channels} rate={rate} bits={bits}"
                    )
                fmt = WavFormat(channels, rate, bits)
            elif chunk_id == b"data":
                pcm = body
            pos += 8 + size + (size & 1)  # chunks are word-aligned
        if fmt is None:
            raise ValueError("WAV stream has no fmt chunk")
        return fmt, pcm

    @property
    def duration_seconds(self) -> float:
        return len(self.pcm) / max(self.format.bytes_per_second, 1)

    def windows(self, window_seconds: float = 15.0) -> Iterator[bytes]:
        """Yield PCM windows re-wrapped as standalone WAV blobs (the REST
        endpoint consumes whole files; sample-aligned, no torn frames)."""
        step = int(self.format.bytes_per_second * window_seconds)
        frame = self.format.channels * (self.format.bits_per_sample // 8)
        step -= step % max(frame, 1)
        step = max(step, frame)
        for lo in range(0, len(self.pcm), step):
            yield wrap_wav(self.pcm[lo : lo + step], self.format)

    def pull(self, chunk_bytes: int = 3200) -> Iterator[bytes]:
        """The pull-stream read contract (AudioStreams.scala ``read(buf)``):
        fixed-size frame-aligned PCM chunks until exhaustion. 3200 B =
        100 ms of 16 kHz/16-bit mono, the SDK's default pull size."""
        frame = self.format.channels * (self.format.bits_per_sample // 8)
        chunk_bytes -= chunk_bytes % max(frame, 1)
        chunk_bytes = max(chunk_bytes, frame)
        for lo in range(0, len(self.pcm), chunk_bytes):
            yield self.pcm[lo : lo + chunk_bytes]

    def fixed_segments(self, window_seconds: float = 15.0) -> list:
        """Fixed-length windows with exact stream offsets: the same
        (wav_blob, offset_ticks, duration_ticks) contract as
        :meth:`segments`, durations as tick DIFFERENCES so they tile."""
        fmt = self.format
        bps = fmt.bytes_per_second
        step = _win_step(fmt, window_seconds)
        out = []
        for i, w in enumerate(self.windows(window_seconds)):
            b0 = i * step
            b1 = min(b0 + step, len(self.pcm))
            out.append((w, _ticks(b0, bps), _ticks(b1, bps) - _ticks(b0, bps)))
        return out

    def segments(
        self,
        max_seconds: float = 15.0,
        min_silence_s: float = 0.3,
        silence_rel: float = 0.08,
    ) -> list:
        """Phrase-boundary segmentation: split at energy dips (silence runs
        of >= ``min_silence_s`` whose RMS is below ``silence_rel`` x the
        stream's 95th-percentile frame RMS), capped at ``max_seconds`` —
        what continuous recognition's VAD does between utterances
        (SpeechToTextSDK.scala's session emits one result per recognized
        phrase, not per arbitrary window). Returns a list of
        ``(wav_blob, offset_ticks, duration_ticks)`` with offsets in the
        service's 100-ns ticks, rebased to the START of the stream.

        Falls back to fixed windows (with exact offsets) for non-16-bit
        PCM, where frame energies aren't directly readable."""
        import numpy as np

        fmt = self.format
        bps = max(fmt.bytes_per_second, 1)

        def ticks(byte_off: int) -> int:
            return _ticks(byte_off, bps)

        frame = fmt.channels * (fmt.bits_per_sample // 8)
        if fmt.bits_per_sample != 16 or len(self.pcm) < frame:
            return self.fixed_segments(max_seconds)
        samples = np.frombuffer(
            self.pcm[: len(self.pcm) - len(self.pcm) % frame], np.int16
        ).astype(np.float32)
        if fmt.channels > 1:
            samples = samples.reshape(-1, fmt.channels).mean(axis=1)
        # 20 ms analysis frames
        hop = max(int(fmt.sample_rate * 0.02), 1)
        n_frames = len(samples) // hop
        if n_frames == 0:
            return [(wrap_wav(self.pcm, fmt), 0, ticks(len(self.pcm)))]
        rms = np.sqrt(
            (samples[: n_frames * hop].reshape(n_frames, hop) ** 2).mean(axis=1)
        )
        loud = np.percentile(rms, 95)
        silent = rms < max(loud * silence_rel, 1e-3)
        min_run = max(int(min_silence_s / 0.02), 1)
        # boundaries at the middle of each long-enough silence run
        bounds = []
        run = 0
        for i, s in enumerate(silent):
            run = run + 1 if s else 0
            if run == min_run:
                bounds.append((i - min_run // 2) * hop)
        max_samples = max(int(fmt.sample_rate * max_seconds), hop)
        segs: list = []
        start = 0
        cuts = bounds + [len(samples)]
        for cut in cuts:
            while cut - start > max_samples:  # cap long phrases
                segs.append((start, start + max_samples))
                start += max_samples
            if cut > start:
                segs.append((start, cut))
                start = cut
        out = []
        for s0, s1 in segs:
            b0, b1 = s0 * frame, s1 * frame
            chunk = self.pcm[b0:b1]
            if not chunk:
                continue
            # duration as a tick DIFFERENCE so consecutive segments tile
            # the stream exactly (floor-divided ticks(b1-b0) would drift)
            out.append((wrap_wav(chunk, fmt), ticks(b0), ticks(b1) - ticks(b0)))
        return out


def _ticks(byte_off: int, bps: int) -> int:
    """Byte offset -> 100-ns ticks, integer-exact (consecutive segments'
    offsets/durations must tile the stream with no 1-tick drift)."""
    return byte_off * 10_000_000 // max(bps, 1)


class CompressedStream:
    """Opaque compressed audio: single pull of the whole payload
    (CompressedStream in the reference defers decode to the service)."""

    def __init__(self, data: bytes):
        self.data = bytes(data)

    def windows(self, window_seconds: float = 15.0) -> Iterator[bytes]:
        yield self.data


def _win_step(fmt: WavFormat, window_seconds: float) -> int:
    """Byte step of :meth:`WavStream.windows` (frame-aligned)."""
    step = int(fmt.bytes_per_second * window_seconds)
    frame = fmt.channels * (fmt.bits_per_sample // 8)
    step -= step % max(frame, 1)
    return max(step, frame)


def wrap_wav(pcm: bytes, fmt: WavFormat) -> bytes:
    """Minimal RIFF/WAVE envelope around raw PCM."""
    byte_rate = fmt.bytes_per_second
    block_align = fmt.channels * (fmt.bits_per_sample // 8)
    hdr = b"RIFF" + struct.pack("<I", 36 + len(pcm)) + b"WAVE"
    hdr += b"fmt " + struct.pack(
        "<IHHIIHH", 16, 1, fmt.channels, fmt.sample_rate, byte_rate, block_align,
        fmt.bits_per_sample,
    )
    hdr += b"data" + struct.pack("<I", len(pcm))
    return hdr + pcm
