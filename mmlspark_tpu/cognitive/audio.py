"""Audio pull-stream helpers for SDK-style continuous recognition.

Reference: cognitive/AudioStreams.scala — ``WavStream`` parses the RIFF
header and exposes fixed-size PCM frame pulls; ``CompressedStream`` passes
opaque compressed bytes through untouched. These feed
:class:`mmlspark_tpu.cognitive.speech.SpeechToTextSDK`'s windowed
continuous-recognition loop (SpeechToTextSDK.scala:204-249).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator, Optional


@dataclass
class WavFormat:
    channels: int
    sample_rate: int
    bits_per_sample: int

    @property
    def bytes_per_second(self) -> int:
        return self.sample_rate * self.channels * (self.bits_per_sample // 8)


class WavStream:
    """Parse a PCM WAV blob; iterate raw PCM in fixed-duration windows."""

    def __init__(self, data: bytes):
        self.format, self.pcm = self._parse(bytes(data))

    @staticmethod
    def _parse(data: bytes) -> tuple:
        if len(data) < 12 or data[:4] != b"RIFF" or data[8:12] != b"WAVE":
            raise ValueError("not a RIFF/WAVE stream")
        pos = 12
        fmt: Optional[WavFormat] = None
        pcm = b""
        while pos + 8 <= len(data):
            chunk_id = data[pos : pos + 4]
            (size,) = struct.unpack_from("<I", data, pos + 4)
            body = data[pos + 8 : pos + 8 + size]
            if chunk_id == b"fmt ":
                try:
                    audio_fmt, channels, rate = struct.unpack_from("<HHI", body, 0)
                    bits = struct.unpack_from("<H", body, 14)[0]
                except struct.error as e:  # truncated fmt chunk
                    raise ValueError(f"malformed WAV fmt chunk: {e}") from e
                if audio_fmt not in (1, 0xFFFE):  # PCM / extensible
                    raise ValueError(f"unsupported WAV audio format {audio_fmt}")
                if channels < 1 or rate < 1 or bits < 8:
                    raise ValueError(
                        f"invalid WAV format: channels={channels} rate={rate} bits={bits}"
                    )
                fmt = WavFormat(channels, rate, bits)
            elif chunk_id == b"data":
                pcm = body
            pos += 8 + size + (size & 1)  # chunks are word-aligned
        if fmt is None:
            raise ValueError("WAV stream has no fmt chunk")
        return fmt, pcm

    @property
    def duration_seconds(self) -> float:
        return len(self.pcm) / max(self.format.bytes_per_second, 1)

    def windows(self, window_seconds: float = 15.0) -> Iterator[bytes]:
        """Yield PCM windows re-wrapped as standalone WAV blobs (the REST
        endpoint consumes whole files; sample-aligned, no torn frames)."""
        step = int(self.format.bytes_per_second * window_seconds)
        frame = self.format.channels * (self.format.bits_per_sample // 8)
        step -= step % max(frame, 1)
        step = max(step, frame)
        for lo in range(0, len(self.pcm), step):
            yield wrap_wav(self.pcm[lo : lo + step], self.format)


class CompressedStream:
    """Opaque compressed audio: single pull of the whole payload
    (CompressedStream in the reference defers decode to the service)."""

    def __init__(self, data: bytes):
        self.data = bytes(data)

    def windows(self, window_seconds: float = 15.0) -> Iterator[bytes]:
        yield self.data


def wrap_wav(pcm: bytes, fmt: WavFormat) -> bytes:
    """Minimal RIFF/WAVE envelope around raw PCM."""
    byte_rate = fmt.bytes_per_second
    block_align = fmt.channels * (fmt.bits_per_sample // 8)
    hdr = b"RIFF" + struct.pack("<I", 36 + len(pcm)) + b"WAVE"
    hdr += b"fmt " + struct.pack(
        "<IHHIIHH", 16, 1, fmt.channels, fmt.sample_rate, byte_rate, block_align,
        fmt.bits_per_sample,
    )
    hdr += b"data" + struct.pack("<I", len(pcm))
    return hdr + pcm
