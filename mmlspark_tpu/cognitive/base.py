"""Cognitive-service transformer base + ServiceParam.

CognitiveServicesBase analogue (cognitive/CognitiveServiceBase.scala:
258-330). A subclass declares ServiceParams and implements
``_build_request(vals) -> request dict | None`` (None rows are skipped —
the reference's ``shouldSkip``); the base transform resolves every
ServiceParam per row (literal or column), fans requests out with the io
layer's retrying handler, and parses JSON into the output column with
non-2xx responses in the error column.
"""

from __future__ import annotations

import concurrent.futures as _futures
import json
from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.io.clients import AdvancedHandler, BasicHandler
from mmlspark_tpu.io.http_schema import response_to_json


class ServiceParam(Param):
    """Value-or-column param (HasServiceParams, CognitiveServiceBase.scala:
    29-150): holds either ``{"value": v}`` or ``{"col": name}``; resolved
    per row at transform time."""

    def validate(self, value: Any) -> Any:
        if isinstance(value, dict) and set(value) in ({"value"}, {"col"}):
            return value
        return {"value": super().validate(value)}


class _HasServiceParams:
    def set_col(self, name: str, col: str) -> "CognitiveServiceBase":
        """Bind ServiceParam ``name`` to a column instead of a literal."""
        p = self.param(name)
        if not isinstance(p, ServiceParam):
            raise TypeError(f"{name} is not a ServiceParam")
        self._paramMap[name] = {"col": col}
        return self

    def _resolve(self, name: str, row_vals: dict) -> Any:
        v = self.get(name)
        if isinstance(v, dict) and "col" in v:
            return row_vals.get(v["col"])
        if isinstance(v, dict) and "value" in v:
            return v["value"]
        return v

    def _service_cols(self) -> list:
        cols = []
        for pname in self.params():
            v = self.get(pname)
            if isinstance(v, dict) and "col" in v:
                cols.append(v["col"])
        return cols


class CognitiveServiceBase(Transformer, _HasServiceParams, HasOutputCol):
    url = Param("service endpoint URL", type_=str)
    subscription_key = ServiceParam("api key sent as Ocp-Apim-Subscription-Key")
    error_col = Param("column for failed responses", default="", type_=str)
    concurrency = Param("max in-flight requests per partition", default=8, type_=int)
    timeout = Param("per-request timeout seconds", default=60.0, type_=float)
    backoffs_ms = Param("retry backoff schedule (ms)", default=[100, 500, 1000], type_=list)
    use_advanced_handler = Param("retry 429/5xx with backoff", default=True, type_=bool)
    batch_size = Param(
        "documents per HTTP request for batchable services", default=1, type_=int
    )

    # -- subclass surface ----------------------------------------------------

    # subclasses returning non-JSON payloads (e.g. thumbnail bytes) set this
    _binary_response = False
    # typed response record (cognitive/schemas.py) — parsed outputs + column
    # metadata; None keeps raw-dict outputs
    _response_schema = None
    # services whose wire format carries many documents per request set this
    # and implement the _batch_* hooks (SimpleHTTPTransformer.scala:111-154
    # minibatch -> JSON -> flatten pipeline)
    _batchable = False

    def _build_request(self, vals: dict) -> Optional[dict]:
        """Row-resolved ServiceParam values -> request dict (None = skip)."""
        raise NotImplementedError

    def _build_requests(self, vals: dict) -> list:
        """Multi-request rows override this (e.g. windowed audio); default =
        the single ``_build_request`` wrapped in a list."""
        r = self._build_request(vals)
        return [] if r is None else [r]

    def _wrap_handler(self, handler_fn: Any) -> Any:
        """Hook: wrap the per-request handler (runs INSIDE the thread
        pool). Multi-step wire contracts (async operations that poll a
        follow-up URL) compose here so their waiting overlaps across
        rows; default identity."""
        return handler_fn

    def _project_response(self, obj: Any) -> Any:
        """Parsed JSON -> output value; default: the typed record when a
        response schema is declared, else the raw dict."""
        if self._response_schema is not None:
            from mmlspark_tpu.cognitive import schemas as _S

            return _S.from_json(self._response_schema, obj)
        return obj

    # -- batching hooks (only consulted when _batchable) ---------------------

    def _batch_key(self, vals: dict) -> Optional[Any]:
        """Grouping key for one row (rows sharing a key may share a
        request); None = skip the row entirely."""
        raise NotImplementedError

    def _build_batch_request(self, vals_list: list) -> dict:
        """K rows' resolved values -> ONE request carrying K documents."""
        raise NotImplementedError

    def _split_batch_response(self, resp: Optional[dict], k: int) -> list:
        """One response -> K ordered (out, err) pairs."""
        raise NotImplementedError

    def _row_output(self, resps: list) -> tuple:
        """Ordered per-request responses for one row -> (out, err).

        Default: single-request semantics on the first response. Multi-
        request subclasses override to merge.
        """
        resp = resps[0] if resps else None
        if resp is None:
            return None, None
        if resp["status_code"] // 100 == 2:
            try:
                out = (
                    resp["entity"]
                    if self._binary_response
                    else self._project_response(response_to_json(resp))
                )
                return out, None
            except (ValueError, KeyError, TypeError) as e:
                return None, {
                    "status_code": resp["status_code"],
                    "reason": f"parse error: {e}",
                }
        return None, {
            "status_code": resp["status_code"],
            "reason": resp["reason"],
            "entity": resp["entity"],
        }

    # -- shared helpers ------------------------------------------------------

    def _headers(self, vals: dict, content_type: str = "application/json") -> dict:
        headers = {"Content-Type": content_type}
        key = self._resolve("subscription_key", vals)
        if key:
            headers["Ocp-Apim-Subscription-Key"] = key
        return headers

    def _post_json(self, vals: dict, body: Any, path: str = "", query: str = "") -> dict:
        from mmlspark_tpu.io.http_schema import HTTPRequestData

        url = self.get_or_fail("url").rstrip("/") + path + (f"?{query}" if query else "")
        return HTTPRequestData(url, "POST", self._headers(vals), json.dumps(body))

    # -- pipeline-compiler declaration ---------------------------------------

    def pipeline_io(self) -> tuple:
        """Declared I/O for the pipeline compiler: reads the columns bound
        via ``set_col``, writes the output then error column (staged
        insertion order). Host-bound, row-local, row-preserving — the
        scheduler overlaps independent cognitive calls on separate
        branches."""
        out_col = self.get_or_fail("output_col")
        return (
            tuple(self._service_cols()),
            (out_col, self.get("error_col") or f"{out_col}_error"),
        )

    # -- transform -----------------------------------------------------------

    def transform(self, df: DataFrame) -> DataFrame:
        out_col = self.get_or_fail("output_col")
        err_col = self.get("error_col") or f"{out_col}_error"
        handler_fn = (
            AdvancedHandler(backoffs_ms=self.get("backoffs_ms"), timeout=self.get("timeout"))
            if self.get("use_advanced_handler")
            else BasicHandler(timeout=self.get("timeout"))
        )
        handler_fn = self._wrap_handler(handler_fn)
        concurrency = self.get("concurrency")
        param_names = list(self.params())

        bsz = max(1, int(self.get("batch_size") or 1))
        batched = self._batchable and bsz > 1

        def fn_batched(p: dict) -> dict:
            """Minibatch path: K documents per POST, flattened back to rows
            (SimpleHTTPTransformer.scala:111-154 assembles the same
            minibatch -> JSON -> HTTP -> flatten pipeline; TextAnalytics
            posts many documents per call). The practical win: K-fold fewer
            round-trips against rate-limited services."""
            n = len(next(iter(p.values()))) if p else 0
            outs = np.empty(n, dtype=object)
            errs = np.empty(n, dtype=object)
            vals_all: list = [None] * n
            chunks: list = []          # (row indices,)
            cur: list = []
            cur_key: Any = None
            for i in range(n):
                row_vals = {k: v[i] for k, v in p.items()}
                vals_all[i] = {
                    name: self._resolve(name, row_vals) for name in param_names
                }
                try:
                    key = self._batch_key(vals_all[i])
                except (ValueError, TypeError) as e:
                    errs[i] = {"status_code": 0, "reason": str(e)}
                    continue
                if key is None:
                    continue  # skipped row: None out, None err
                if cur and (key != cur_key or len(cur) >= bsz):
                    chunks.append(cur)
                    cur = []
                cur_key = key
                cur.append(i)
            if cur:
                chunks.append(cur)
            if chunks:
                with _futures.ThreadPoolExecutor(max_workers=concurrency) as pool:
                    resps = list(
                        pool.map(
                            lambda idxs: handler_fn(
                                self._build_batch_request(
                                    [vals_all[i] for i in idxs]
                                )
                            ),
                            chunks,
                        )
                    )
                for idxs, resp in zip(chunks, resps):
                    for i, (o, e) in zip(
                        idxs, self._split_batch_response(resp, len(idxs))
                    ):
                        outs[i], errs[i] = o, e
            q = dict(p)
            q[out_col] = outs
            q[err_col] = errs
            return q

        def fn(p: dict) -> dict:
            n = len(next(iter(p.values()))) if p else 0
            # each row may expand to several requests (windowed audio etc.):
            # flatten, fan out once, regroup in request order per row
            row_reqs: list = []
            jobs: list = []  # (row, idx_within_row, request)
            for i in range(n):
                row_vals = {k: v[i] for k, v in p.items()}
                vals = {
                    name: self._resolve(name, row_vals) for name in param_names
                }
                try:
                    reqs = self._build_requests(vals)
                except (ValueError, TypeError) as e:  # bad row input: error, not a crash
                    reqs = [{"__input_error__": str(e)}]
                row_reqs.append(reqs)
                for w, r in enumerate(reqs):
                    if "__input_error__" not in r:
                        jobs.append((i, w, r))
            results: dict = {}
            if jobs:
                with _futures.ThreadPoolExecutor(max_workers=concurrency) as pool:
                    for (i, w), resp in pool.map(
                        lambda j: ((j[0], j[1]), handler_fn(j[2])), jobs
                    ):
                        results[(i, w)] = resp
            outs = np.empty(n, dtype=object)
            errs = np.empty(n, dtype=object)
            row_out_ctx = getattr(self, "_row_output_ctx", None)
            for i, reqs in enumerate(row_reqs):
                if reqs and "__input_error__" in reqs[0]:
                    errs[i] = {"status_code": 0, "reason": reqs[0]["__input_error__"]}
                    continue
                resps = [results.get((i, w)) for w in range(len(reqs))]
                # _row_output_ctx also sees the REQUESTS (per-window
                # metadata like stream offsets rides on the request dicts)
                outs[i], errs[i] = (
                    row_out_ctx(resps, reqs) if row_out_ctx
                    else self._row_output(resps)
                )
            q = dict(p)
            q[out_col] = outs
            q[err_col] = errs
            return q

        out = df.map_partitions(fn_batched if batched else fn)
        if self._response_schema is not None:
            from mmlspark_tpu.cognitive import schemas as _S

            out = out.with_column_metadata(
                out_col, _S.column_metadata(self._response_schema)
            )
        return out
