"""Speech-to-text transformer (cognitive/SpeechToText.scala analogue).

Wire format: Speech REST v1 — POST raw audio bytes (wav) with language in
the query; response JSON carries ``DisplayText``/``RecognitionStatus``.
(The reference's continuous Speech-SDK variant, SpeechToTextSDK.scala, is a
streaming session against the same service; the REST form covers the
capability offline.)
"""

from __future__ import annotations

from typing import Any, Optional

from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.io.http_schema import HTTPRequestData


class SpeechToText(CognitiveServiceBase):
    audio_data = ServiceParam("raw audio bytes (value or column)")
    language = ServiceParam("recognition language", default={"value": "en-US"})
    format = ServiceParam("'simple' or 'detailed'", default={"value": "simple"})
    profanity = ServiceParam("masked|removed|raw", default={"value": "masked"})

    def _build_request(self, vals: dict) -> Optional[dict]:
        audio = vals.get("audio_data")
        if audio is None:
            return None
        query = (
            f"language={vals.get('language') or 'en-US'}"
            f"&format={vals.get('format') or 'simple'}"
            f"&profanity={vals.get('profanity') or 'masked'}"
        )
        url = (
            self.get_or_fail("url").rstrip("/")
            + "/speech/recognition/conversation/cognitiveservices/v1?" + query
        )
        headers = self._headers(vals, content_type="audio/wav; codecs=audio/pcm")
        return HTTPRequestData(url, "POST", headers, bytes(audio))
