"""Speech-to-text transformers (cognitive/SpeechToText.scala +
SpeechToTextSDK.scala analogues).

``SpeechToText``: one-shot REST v1 — POST raw audio bytes (wav) with
language in the query; response JSON carries
``DisplayText``/``RecognitionStatus``.

``SpeechToTextSDK``: continuous recognition over audio streams. The
reference runs a Speech-SDK session fed by ``WavStream``/
``CompressedStream`` pull streams (SpeechToTextSDK.scala:204-249,367);
here the stream is windowed host-side (cognitive/audio.py), each
sample-aligned window is recognized via the same REST wire format, and
the per-row output is the ordered list of segment results.
"""

from __future__ import annotations

from typing import Any, Optional

from mmlspark_tpu.cognitive import schemas as S
from mmlspark_tpu.cognitive.audio import CompressedStream, WavStream
from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.io.http_schema import HTTPRequestData, response_to_json


class SpeechToText(CognitiveServiceBase):
    _response_schema = S.SpeechResponse

    audio_data = ServiceParam("raw audio bytes (value or column)")
    language = ServiceParam("recognition language", default={"value": "en-US"})
    format = ServiceParam("'simple' or 'detailed'", default={"value": "simple"})
    profanity = ServiceParam("masked|removed|raw", default={"value": "masked"})

    def _build_request(self, vals: dict) -> Optional[dict]:
        audio = vals.get("audio_data")
        if audio is None:
            return None
        query = (
            f"language={vals.get('language') or 'en-US'}"
            f"&format={vals.get('format') or 'simple'}"
            f"&profanity={vals.get('profanity') or 'masked'}"
        )
        url = (
            self.get_or_fail("url").rstrip("/")
            + "/speech/recognition/conversation/cognitiveservices/v1?" + query
        )
        headers = self._headers(vals, content_type="audio/wav; codecs=audio/pcm")
        return HTTPRequestData(url, "POST", headers, bytes(audio))


class SpeechToTextSDK(SpeechToText):
    """Continuous recognition: window the audio stream, recognize each
    window, emit the ordered segment list (see module docstring). Failed
    windows keep their position as ``None`` placeholders so transcripts
    never look complete when audio was lost; every window's error is kept.
    """

    window_seconds = Param(
        "recognition window length", default=15.0, type_=float,
        validator=lambda v: v > 0,
    )
    stream_format = Param(
        "'wav' (parsed + sample-aligned windows) or 'compressed' (opaque)",
        default="wav",
        validator=lambda v: v in ("wav", "compressed"),
    )

    def _segments(self, audio: Any) -> list:
        if audio is None:
            return []
        data = bytes(audio)
        if self.get("stream_format") == "wav":
            try:
                stream: Any = WavStream(data)
            except ValueError:
                stream = CompressedStream(data)  # not RIFF: pass through
        else:
            stream = CompressedStream(data)
        return list(stream.windows(self.get("window_seconds")))

    def _build_requests(self, vals: dict) -> list:
        reqs = []
        for window in self._segments(vals.get("audio_data")):
            r = self._build_request({**vals, "audio_data": window})
            if r is not None:
                reqs.append(r)
        return reqs

    # the output column holds the ordered per-window segment list, not a
    # single record — metadata must say so
    from typing import List as _List

    _response_schema = _List[S.SpeechResponse]

    def _row_output(self, resps: list) -> tuple:
        segs: list = []
        errors: list = []
        for w, resp in enumerate(resps):
            if resp is None:
                segs.append(None)
                continue
            if resp["status_code"] // 100 == 2:
                try:
                    segs.append(
                        S.from_json(S.SpeechResponse, response_to_json(resp))
                    )
                    continue
                except (ValueError, KeyError, TypeError) as e:
                    errors.append({"window": w, "status_code": resp["status_code"],
                                   "reason": f"parse error: {e}"})
            else:
                errors.append({"window": w, "status_code": resp["status_code"],
                               "reason": resp["reason"], "entity": resp["entity"]})
            segs.append(None)  # placeholder keeps window positions aligned
        return segs, (errors or None)
