"""Speech-to-text transformers (cognitive/SpeechToText.scala +
SpeechToTextSDK.scala analogues).

``SpeechToText``: one-shot REST v1 — POST raw audio bytes (wav) with
language in the query; response JSON carries
``DisplayText``/``RecognitionStatus``.

``SpeechToTextSDK``: continuous recognition over audio streams. The
reference runs a Speech-SDK session fed by ``WavStream``/
``CompressedStream`` pull streams (SpeechToTextSDK.scala:204-249,367);
here the stream is windowed host-side (cognitive/audio.py), each
sample-aligned window is recognized via the same REST wire format, and
the per-row output is the ordered list of segment results.
"""

from __future__ import annotations

from typing import Any, Optional

from mmlspark_tpu.cognitive import schemas as S
from mmlspark_tpu.cognitive.audio import CompressedStream, WavStream
from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.io.http_schema import HTTPRequestData, response_to_json


class SpeechToText(CognitiveServiceBase):
    _response_schema = S.SpeechResponse

    audio_data = ServiceParam("raw audio bytes (value or column)")
    language = ServiceParam("recognition language", default={"value": "en-US"})
    format = ServiceParam("'simple' or 'detailed'", default={"value": "simple"})
    profanity = ServiceParam("masked|removed|raw", default={"value": "masked"})

    def _build_request(self, vals: dict) -> Optional[dict]:
        audio = vals.get("audio_data")
        if audio is None:
            return None
        query = (
            f"language={vals.get('language') or 'en-US'}"
            f"&format={vals.get('format') or 'simple'}"
            f"&profanity={vals.get('profanity') or 'masked'}"
        )
        url = (
            self.get_or_fail("url").rstrip("/")
            + "/speech/recognition/conversation/cognitiveservices/v1?" + query
        )
        headers = self._headers(vals, content_type="audio/wav; codecs=audio/pcm")
        return HTTPRequestData(url, "POST", headers, bytes(audio))


class SpeechToTextSDK(SpeechToText):
    """Continuous recognition over pull streams: segment the audio at
    phrase boundaries (energy VAD — what the reference SDK's session does
    between utterances, SpeechToTextSDK.scala:204-249), recognize each
    segment, and emit the ordered result list with every record's
    ``Offset``/``Duration`` REBASED to stream time (100-ns ticks from the
    start of the audio). Failed segments keep their position as ``None``
    placeholders so transcripts never look complete when audio was lost;
    every segment's error is kept with its offset.
    """

    window_seconds = Param(
        "max recognition segment length", default=15.0, type_=float,
        validator=lambda v: v > 0,
    )
    stream_format = Param(
        "'wav' (parsed + sample-aligned windows) or 'compressed' (opaque)",
        default="wav",
        validator=lambda v: v in ("wav", "compressed"),
    )
    segmentation = Param(
        "'vad' (phrase boundaries at energy dips, the SDK behavior) or "
        "'fixed' (plain fixed-length windows)",
        default="vad",
        validator=lambda v: v in ("vad", "fixed"),
    )
    min_silence_s = Param(
        "silence run length that ends a phrase (vad mode)",
        default=0.3, type_=float,
    )

    def _segments(self, audio: Any) -> list:
        """-> list of (wav_blob, offset_ticks, duration_ticks)."""
        if audio is None:
            return []
        data = bytes(audio)
        if self.get("stream_format") == "wav":
            try:
                stream: Any = WavStream(data)
            except ValueError:
                stream = CompressedStream(data)  # not RIFF: pass through
        else:
            stream = CompressedStream(data)
        win = self.get("window_seconds")
        if isinstance(stream, WavStream):
            if self.get("segmentation") == "vad":
                return stream.segments(
                    max_seconds=win, min_silence_s=self.get("min_silence_s")
                )
            return stream.fixed_segments(win)
        return [(w, 0, 0) for w in stream.windows(win)]

    def _build_requests(self, vals: dict) -> list:
        reqs = []
        for blob, off, dur in self._segments(vals.get("audio_data")):
            r = self._build_request({**vals, "audio_data": blob})
            if r is not None:
                # per-segment stream position rides on the request dict
                # (the HTTP sender only reads url/method/headers/entity);
                # _row_output_ctx rebases the service's window-relative
                # Offset with it
                r["_segment"] = {"offset_ticks": off, "duration_ticks": dur}
                reqs.append(r)
        return reqs

    # the output column holds the ordered per-segment record list, not a
    # single record — metadata must say so
    from typing import List as _List

    _response_schema = _List[S.SpeechResponse]

    def _row_output_ctx(self, resps: list, reqs: list) -> tuple:
        segs: list = []
        errors: list = []
        for w, resp in enumerate(resps):
            meta = (reqs[w] if w < len(reqs) else {}).get("_segment") or {}
            off = int(meta.get("offset_ticks") or 0)
            if resp is None:
                segs.append(None)
                continue
            if resp["status_code"] // 100 == 2:
                try:
                    rec = S.from_json(S.SpeechResponse, response_to_json(resp))
                    # the service reports Offset relative to the POSTED
                    # window; stream time = segment start + window offset
                    rec.Offset = off + int(rec.Offset or 0)
                    if rec.Duration is None and meta.get("duration_ticks"):
                        rec.Duration = int(meta["duration_ticks"])
                    segs.append(rec)
                    continue
                except (ValueError, KeyError, TypeError) as e:
                    errors.append({"window": w, "offset_ticks": off,
                                   "status_code": resp["status_code"],
                                   "reason": f"parse error: {e}"})
            else:
                errors.append({"window": w, "offset_ticks": off,
                               "status_code": resp["status_code"],
                               "reason": resp["reason"], "entity": resp["entity"]})
            segs.append(None)  # placeholder keeps segment positions aligned
        return segs, (errors or None)
