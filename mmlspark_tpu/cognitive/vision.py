"""Computer-vision transformers (cognitive/ComputerVision.scala analogue).

Wire format: Computer Vision v2 — POST an image by URL (JSON ``{"url"}``)
or raw bytes (``application/octet-stream``), feature selection via query
string.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from mmlspark_tpu.cognitive import schemas as S
from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.io.http_schema import HTTPRequestData


class _VisionBase(CognitiveServiceBase):
    image_url = ServiceParam("image URL (value or column)")
    image_bytes = ServiceParam("raw image bytes (value or column)")

    _path = ""

    def _query(self, vals: dict) -> str:
        return ""

    def _image_request(self, vals: dict, path: Optional[str] = None) -> Optional[dict]:
        query = self._query(vals)
        url = self.get_or_fail("url").rstrip("/") + (
            self._path if path is None else path
        ) + (f"?{query}" if query else "")
        data = vals.get("image_bytes")
        if data is not None:
            return HTTPRequestData(
                url, "POST",
                self._headers(vals, content_type="application/octet-stream"),
                bytes(data),
            )
        img_url = vals.get("image_url")
        if img_url is None:
            return None
        return HTTPRequestData(
            url, "POST", self._headers(vals), json.dumps({"url": str(img_url)})
        )

    def _build_request(self, vals: dict) -> Optional[dict]:
        return self._image_request(vals)


class AnalyzeImage(_VisionBase):
    """Tags/categories/description/faces for an image (AnalyzeImage;
    /vision/v2.0/analyze)."""

    _path = "/vision/v2.0/analyze"
    _response_schema = S.AnalyzeImageResponse
    visual_features = ServiceParam(
        "features to compute", default={"value": ["Categories", "Tags", "Description"]}
    )
    details = ServiceParam("detail domains (Celebrities/Landmarks)")
    language = ServiceParam("response language", default={"value": "en"})

    def _query(self, vals: dict) -> str:
        parts = []
        if vals.get("visual_features"):
            parts.append("visualFeatures=" + ",".join(vals["visual_features"]))
        if vals.get("details"):
            parts.append("details=" + ",".join(vals["details"]))
        parts.append("language=" + (vals.get("language") or "en"))
        return "&".join(parts)


class OCR(_VisionBase):
    """Printed-text OCR (OCR.scala; /vision/v2.0/ocr)."""

    _path = "/vision/v2.0/ocr"
    _response_schema = S.OCRResponse
    detect_orientation = ServiceParam("detect text orientation", default={"value": True})
    language = ServiceParam("BCP-47 language", default={"value": "unk"})

    def _query(self, vals: dict) -> str:
        return (
            f"language={vals.get('language') or 'unk'}"
            f"&detectOrientation={str(bool(vals.get('detect_orientation'))).lower()}"
        )


class RecognizeText(_VisionBase):
    """Async printed/handwritten text recognition
    (RecognizeText, ComputerVision.scala:215-262; /vision/v2.0/recognizeText).

    The service's wire contract is ASYNC: the POST answers 202 with an
    ``Operation-Location`` header, and the result is GET-polled from that
    URL until ``status`` leaves running/notStarted (the reference's
    ``maxPollingRetries``/``pollingDelay`` handler loop). The whole
    POST-then-poll sequence runs inside the per-request handler (the
    ``_wrap_handler`` hook), so rows poll CONCURRENTLY on the base's
    thread pool and reuse the stage's configured retry handler and the
    request's own resolved auth headers."""

    _path = "/vision/v2.0/recognizeText"
    _response_schema = S.RecognizeTextResponse
    mode = ServiceParam(
        "'Printed' or 'Handwritten'", default={"value": "Printed"}
    )
    # plain ints, as in the reference (IntParam maxPollingRetries /
    # pollingDelay) — they configure the stage, not a per-row value
    max_polling_retries = Param("poll attempts", default=1000, type_=int)
    polling_delay_ms = Param("delay between polls (ms)", default=300, type_=int)

    def _query(self, vals: dict) -> str:
        return f"mode={vals.get('mode') or 'Printed'}"

    def _wrap_handler(self, handler_fn: Any) -> Any:
        import time as _time

        from mmlspark_tpu.io.http_schema import (
            HTTPRequestData,
            HTTPResponseData,
            response_to_json,
        )

        retries = max(int(self.get("max_polling_retries")), 1)
        delay_s = int(self.get("polling_delay_ms")) / 1000.0

        def wrapped(req: dict) -> dict:
            resp = handler_fn(req)
            if resp is None or resp["status_code"] not in (200, 202):
                return resp
            op_url = next(
                (v for k, v in (resp.get("headers") or {}).items()
                 if k.lower() == "operation-location"),
                None,
            )
            if not op_url:
                return HTTPResponseData(
                    0,
                    reason=(
                        f"{resp['status_code']} without "
                        "Operation-Location header"
                    ),
                )
            # the ORIGINAL request's resolved headers carry this row's
            # auth (column-bound subscription keys included)
            headers = {
                k: v for k, v in (req.get("headers") or {}).items()
                if k.lower() != "content-type"
            }
            last_status = ""
            for _ in range(retries):
                pr = handler_fn(HTTPRequestData(op_url, "GET", headers))
                if pr["status_code"] // 100 != 2:
                    return pr
                try:
                    body = response_to_json(pr) or {}
                except (ValueError, KeyError, TypeError) as e:
                    return HTTPResponseData(0, reason=f"poll parse error: {e}")
                last_status = str(body.get("status", "")).lower()
                if last_status not in ("running", "notstarted", "not started", ""):
                    if last_status != "succeeded":
                        return HTTPResponseData(
                            0,
                            reason=f"recognition did not succeed: {body.get('status')}",
                        )
                    return pr  # the final response body IS the result
                _time.sleep(delay_s)
            return HTTPResponseData(
                0, reason=f"polling exhausted (last status: {last_status!r})"
            )

        return wrapped


class RecognizeDomainSpecificContent(_VisionBase):
    """Domain model analysis (celebrities/landmarks)
    (RecognizeDomainSpecificContent; /vision/v2.0/models/{model}/analyze)."""

    model = ServiceParam("domain model name", default={"value": "celebrities"})
    _response_schema = S.DomainModelResponse

    def _build_request(self, vals: dict) -> Optional[dict]:
        return self._image_request(
            vals, path=f"/vision/v2.0/models/{vals.get('model')}/analyze"
        )


class GenerateThumbnails(_VisionBase):
    """Smart-cropped thumbnail bytes (GenerateThumbnails;
    /vision/v2.0/generateThumbnail)."""

    _path = "/vision/v2.0/generateThumbnail"
    _binary_response = True
    width = ServiceParam("thumbnail width", default={"value": 64})
    height = ServiceParam("thumbnail height", default={"value": 64})
    smart_cropping = ServiceParam("smart cropping", default={"value": True})

    def _query(self, vals: dict) -> str:
        return (
            f"width={int(vals.get('width') or 64)}&height={int(vals.get('height') or 64)}"
            f"&smartCropping={str(bool(vals.get('smart_cropping'))).lower()}"
        )


class TagImage(_VisionBase):
    """Image tags (TagImage; /vision/v2.0/tag)."""

    _path = "/vision/v2.0/tag"
    _response_schema = S.TagImagesResponse


class DescribeImage(_VisionBase):
    """Natural-language captions (DescribeImage; /vision/v2.0/describe)."""

    _path = "/vision/v2.0/describe"
    _response_schema = S.DescribeImageResponse
    max_candidates = ServiceParam("number of caption candidates", default={"value": 1})

    def _query(self, vals: dict) -> str:
        return f"maxCandidates={int(vals.get('max_candidates') or 1)}"
