"""Typed response schemas for the cognitive services — the SparkBindings
layer (core/schema/SparkBindings.scala:13-46 turns case classes into Spark
struct codecs; cognitive/TextAnalyticsSchemas.scala, Face.scala and
AnomalyDetectorSchemas.scala declare one response case class per service).

Here each service's response is a ``@schema`` dataclass; :func:`from_json`
is the recursive JSON -> record codec (Optional/List/nested records from
type hints, tolerant of missing and extra keys the way spray-json's
``Option`` fields are). Records are dataclasses that ALSO support mapping
access (``rec["sentiment"]`` == ``rec.sentiment``) so downstream code that
handled raw dicts keeps working, and :func:`schema_fields` reflects a
record type into column metadata (the StructType the reference attaches).
"""

from __future__ import annotations

import dataclasses
import typing
from dataclasses import dataclass, field
from typing import Any, List, Optional


class Record:
    """Mixin: dataclass with dict-style read access + dict round-trip."""

    def __getitem__(self, key: str) -> Any:
        try:
            return getattr(self, key)
        except AttributeError:
            raise KeyError(key) from None

    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def keys(self):
        return [f.name for f in dataclasses.fields(self)]

    def to_dict(self) -> dict:
        """Record -> plain JSON-style dict (drops None optionals)."""

        def conv(v: Any) -> Any:
            if isinstance(v, Record):
                return v.to_dict()
            if isinstance(v, list):
                return [conv(x) for x in v]
            return v

        return {
            f.name: conv(getattr(self, f.name))
            for f in dataclasses.fields(self)
            if getattr(self, f.name) is not None
        }


def schema(cls):
    """Class decorator: a cognitive response record (dataclass + Record)."""
    return dataclass(cls)


def _strip_optional(tp: Any) -> Any:
    if typing.get_origin(tp) is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def from_json(cls: Any, obj: Any) -> Any:
    """Parse a JSON value into ``cls`` (a Record dataclass, List[...] of
    them, or a primitive). Missing fields become their defaults (None for
    optionals); unknown response keys are ignored — service API additions
    must not break parsing (the reference's spray-json Option tolerance)."""
    cls = _strip_optional(cls)
    if obj is None:
        return None
    origin = typing.get_origin(cls)
    if origin in (list, List):
        (item_t,) = typing.get_args(cls) or (Any,)
        if not isinstance(obj, list):
            return []
        return [from_json(item_t, x) for x in obj]
    if dataclasses.is_dataclass(cls):
        if not isinstance(obj, dict):
            return None
        hints = typing.get_type_hints(cls)
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name in obj:
                kwargs[f.name] = from_json(hints.get(f.name, Any), obj[f.name])
        return cls(**kwargs)
    return obj  # primitive / Any: pass through


def schema_fields(cls: Any) -> list:
    """Record type -> column-metadata field list: [{"name", "type"}]."""
    if not dataclasses.is_dataclass(cls):
        return []
    hints = typing.get_type_hints(cls)
    out = []
    for f in dataclasses.fields(cls):
        tp = _strip_optional(hints.get(f.name, Any))
        origin = typing.get_origin(tp)
        if origin in (list, List):
            (item_t,) = typing.get_args(tp) or (Any,)
            item_t = _strip_optional(item_t)
            name = getattr(item_t, "__name__", str(item_t))
            out.append({"name": f.name, "type": f"array<{name}>"})
        else:
            out.append({"name": f.name, "type": getattr(tp, "__name__", str(tp))})
    return out


def column_metadata(cls: Any) -> dict:
    """Output-column metadata for a schema'd service column."""
    origin = typing.get_origin(cls)
    if origin in (list, List):
        (item_t,) = typing.get_args(cls) or (Any,)
        item_t = _strip_optional(item_t)
        return {
            "response_schema": f"array<{getattr(item_t, '__name__', str(item_t))}>",
            "response_fields": schema_fields(item_t),
        }
    return {
        "response_schema": getattr(cls, "__name__", str(cls)),
        "response_fields": schema_fields(cls),
    }


# -- Text Analytics v3 (TextAnalyticsSchemas.scala) --------------------------


@schema
class TAWarning(Record):
    code: Optional[str] = None
    message: Optional[str] = None


@schema
class TAError(Record):
    id: Optional[str] = None
    error: Optional[Any] = None
    message: Optional[str] = None


@schema
class DocumentStatistics(Record):
    charactersCount: Optional[int] = None
    transactionsCount: Optional[int] = None


@schema
class SentimentConfidence(Record):
    positive: Optional[float] = None
    neutral: Optional[float] = None
    negative: Optional[float] = None


@schema
class SentenceSentiment(Record):
    text: Optional[str] = None
    sentiment: Optional[str] = None
    confidenceScores: Optional[SentimentConfidence] = None
    offset: Optional[int] = None
    length: Optional[int] = None


@schema
class SentimentDocument(Record):
    """SentimentScoredDocumentV3 (TextAnalyticsSchemas.scala:45-55)."""

    id: Optional[str] = None
    sentiment: Optional[str] = None
    confidenceScores: Optional[SentimentConfidence] = None
    sentences: List[SentenceSentiment] = field(default_factory=list)
    warnings: List[TAWarning] = field(default_factory=list)
    statistics: Optional[DocumentStatistics] = None


@schema
class DetectedLanguage(Record):
    name: Optional[str] = None
    iso6391Name: Optional[str] = None
    confidenceScore: Optional[float] = None


@schema
class LanguageDocument(Record):
    """DocumentLanguageV3 (TextAnalyticsSchemas.scala:67-72)."""

    id: Optional[str] = None
    detectedLanguage: Optional[DetectedLanguage] = None
    warnings: List[TAWarning] = field(default_factory=list)
    statistics: Optional[DocumentStatistics] = None


@schema
class Entity(Record):
    text: Optional[str] = None
    category: Optional[str] = None
    subcategory: Optional[str] = None
    offset: Optional[int] = None
    length: Optional[int] = None
    confidenceScore: Optional[float] = None


@schema
class EntitiesDocument(Record):
    """DetectEntitiesScoreV3 (TextAnalyticsSchemas.scala:77-83)."""

    id: Optional[str] = None
    entities: List[Entity] = field(default_factory=list)
    warnings: List[TAWarning] = field(default_factory=list)
    statistics: Optional[DocumentStatistics] = None


@schema
class KeyPhraseDocument(Record):
    """KeyPhraseScoreV3 analogue."""

    id: Optional[str] = None
    keyPhrases: List[str] = field(default_factory=list)
    warnings: List[TAWarning] = field(default_factory=list)
    statistics: Optional[DocumentStatistics] = None


# -- Computer Vision v2 (ComputerVisionSchemas in ComputerVision.scala) ------


@schema
class ImageTag(Record):
    name: Optional[str] = None
    confidence: Optional[float] = None
    hint: Optional[str] = None


@schema
class ImageCaption(Record):
    text: Optional[str] = None
    confidence: Optional[float] = None


@schema
class ImageDescription(Record):
    tags: List[str] = field(default_factory=list)
    captions: List[ImageCaption] = field(default_factory=list)


@schema
class ImageCategory(Record):
    name: Optional[str] = None
    score: Optional[float] = None
    detail: Optional[Any] = None


@schema
class ImageMetadata(Record):
    width: Optional[int] = None
    height: Optional[int] = None
    format: Optional[str] = None


@schema
class AnalyzeImageResponse(Record):
    """AIResponse (ComputerVision.scala AnalyzeImage)."""

    categories: List[ImageCategory] = field(default_factory=list)
    tags: List[ImageTag] = field(default_factory=list)
    description: Optional[ImageDescription] = None
    faces: List[Any] = field(default_factory=list)
    color: Optional[Any] = None
    adult: Optional[Any] = None
    requestId: Optional[str] = None
    metadata: Optional[ImageMetadata] = None


@schema
class OCRWord(Record):
    boundingBox: Optional[str] = None
    text: Optional[str] = None


@schema
class OCRLine(Record):
    boundingBox: Optional[str] = None
    words: List[OCRWord] = field(default_factory=list)


@schema
class OCRRegion(Record):
    boundingBox: Optional[str] = None
    lines: List[OCRLine] = field(default_factory=list)


@schema
class OCRResponse(Record):
    """OCRResponse (ComputerVision.scala OCR)."""

    language: Optional[str] = None
    textAngle: Optional[float] = None
    orientation: Optional[str] = None
    regions: List[OCRRegion] = field(default_factory=list)


@schema
class RTWord(Record):
    boundingBox: List[int] = field(default_factory=list)
    text: Optional[str] = None


@schema
class RTLine(Record):
    boundingBox: List[int] = field(default_factory=list)
    text: Optional[str] = None
    words: List[RTWord] = field(default_factory=list)


@schema
class RecognitionResult(Record):
    lines: List[RTLine] = field(default_factory=list)


@schema
class RecognizeTextResponse(Record):
    """RTResponse (ComputerVisionSchemas.scala RecognizeText)."""

    status: Optional[str] = None
    recognitionResult: Optional[RecognitionResult] = None


@schema
class TagImagesResponse(Record):
    tags: List[ImageTag] = field(default_factory=list)
    requestId: Optional[str] = None
    metadata: Optional[ImageMetadata] = None


@schema
class DescribeImageResponse(Record):
    description: Optional[ImageDescription] = None
    requestId: Optional[str] = None
    metadata: Optional[ImageMetadata] = None


@schema
class DomainModelResponse(Record):
    """DSIRResponse (RecognizeDomainSpecificContent)."""

    requestId: Optional[str] = None
    metadata: Optional[ImageMetadata] = None
    result: Optional[Any] = None


# -- Face v1.0 (Face.scala schemas) ------------------------------------------


@schema
class FaceRectangle(Record):
    top: Optional[int] = None
    left: Optional[int] = None
    width: Optional[int] = None
    height: Optional[int] = None


@schema
class DetectedFace(Record):
    """Face (Face.scala detect response element)."""

    faceId: Optional[str] = None
    faceRectangle: Optional[FaceRectangle] = None
    faceLandmarks: Optional[Any] = None
    faceAttributes: Optional[Any] = None


@schema
class VerifyResponse(Record):
    isIdentical: Optional[bool] = None
    confidence: Optional[float] = None


@schema
class IdentifyCandidate(Record):
    personId: Optional[str] = None
    confidence: Optional[float] = None


@schema
class IdentifiedFace(Record):
    faceId: Optional[str] = None
    candidates: List[IdentifyCandidate] = field(default_factory=list)


@schema
class SimilarFace(Record):
    faceId: Optional[str] = None
    persistedFaceId: Optional[str] = None
    confidence: Optional[float] = None


@schema
class GroupResponse(Record):
    groups: List[Any] = field(default_factory=list)
    messyGroup: List[str] = field(default_factory=list)


# -- Anomaly Detector (AnomalyDetectorSchemas.scala) -------------------------


@schema
class AnomalyDetectResponse(Record):
    """ADEntireResponse (AnomalyDetectorSchemas.scala)."""

    expectedValues: List[float] = field(default_factory=list)
    isAnomaly: List[bool] = field(default_factory=list)
    isNegativeAnomaly: List[bool] = field(default_factory=list)
    isPositiveAnomaly: List[bool] = field(default_factory=list)
    lowerMargins: List[float] = field(default_factory=list)
    upperMargins: List[float] = field(default_factory=list)
    period: Optional[int] = None


@schema
class LastAnomalyResponse(Record):
    """ADLastResponse (AnomalyDetectorSchemas.scala)."""

    isAnomaly: Optional[bool] = None
    isNegativeAnomaly: Optional[bool] = None
    isPositiveAnomaly: Optional[bool] = None
    expectedValue: Optional[float] = None
    lowerMargin: Optional[float] = None
    upperMargin: Optional[float] = None
    period: Optional[int] = None
    suggestedWindow: Optional[int] = None


# -- Speech (SpeechAPISchemas in SpeechToTextSDK.scala / SpeechToText.scala) --


@schema
class SpeechNBest(Record):
    Confidence: Optional[float] = None
    Lexical: Optional[str] = None
    ITN: Optional[str] = None
    MaskedITN: Optional[str] = None
    Display: Optional[str] = None


@schema
class SpeechResponse(Record):
    """SpeechResponse (SpeechToText.scala)."""

    RecognitionStatus: Optional[str] = None
    DisplayText: Optional[str] = None
    Offset: Optional[int] = None
    Duration: Optional[int] = None
    NBest: List[SpeechNBest] = field(default_factory=list)


# -- Bing search / Azure search (BingImageSearch.scala, AzureSearch.scala) ---


@schema
class BingImage(Record):
    name: Optional[str] = None
    contentUrl: Optional[str] = None
    thumbnailUrl: Optional[str] = None
    contentSize: Optional[str] = None
    encodingFormat: Optional[str] = None
    width: Optional[int] = None
    height: Optional[int] = None


@schema
class BingImagesResponse(Record):
    value: List[BingImage] = field(default_factory=list)
    totalEstimatedMatches: Optional[int] = None


@schema
class IndexResult(Record):
    key: Optional[str] = None
    status: Optional[bool] = None
    errorMessage: Optional[str] = None
    statusCode: Optional[int] = None


@schema
class IndexResponse(Record):
    value: List[IndexResult] = field(default_factory=list)
