"""Cognitive-service-style REST enrichment transformers (SURVEY.md §2.8).

The reference ships ~20 transformers that call Azure Cognitive Services
REST APIs from DataFrame columns (cognitive/, CognitiveServiceBase.scala:
258-330). The service *catalog* — text analytics, vision, face, anomaly
detection, speech, search — is the capability; Azure specifics are not.
Each transformer here speaks the same wire format against any base URL
(self-hosted, proxy, or Azure), with:

- :class:`ServiceParam` value-or-column duality (HasServiceParams,
  CognitiveServiceBase.scala:29-150)
- bounded-concurrency async sends with retry/backoff (RESTHelpers analogue
  via the io layer's AdvancedHandler)
- typed response projection into an output column + error column
"""

from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.cognitive.text import (
    NER,
    EntityDetector,
    KeyPhraseExtractor,
    LanguageDetector,
    TextSentiment,
)
from mmlspark_tpu.cognitive.vision import (
    OCR,
    AnalyzeImage,
    DescribeImage,
    GenerateThumbnails,
    RecognizeDomainSpecificContent,
    RecognizeText,
    TagImage,
)
from mmlspark_tpu.cognitive.face import (
    DetectFace,
    FindSimilarFace,
    GroupFaces,
    IdentifyFaces,
    VerifyFaces,
)
from mmlspark_tpu.cognitive.anomaly import DetectAnomalies, DetectLastAnomaly
from mmlspark_tpu.cognitive.speech import SpeechToText, SpeechToTextSDK
from mmlspark_tpu.cognitive.search import SearchIndex, AzureSearchWriter, BingImageSearch

__all__ = [
    "CognitiveServiceBase",
    "ServiceParam",
    "TextSentiment",
    "LanguageDetector",
    "EntityDetector",
    "KeyPhraseExtractor",
    "NER",
    "AnalyzeImage",
    "OCR",
    "RecognizeText",
    "RecognizeDomainSpecificContent",
    "GenerateThumbnails",
    "TagImage",
    "DescribeImage",
    "DetectFace",
    "VerifyFaces",
    "IdentifyFaces",
    "GroupFaces",
    "FindSimilarFace",
    "DetectAnomalies",
    "DetectLastAnomaly",
    "SpeechToText",
    "SpeechToTextSDK",
    "BingImageSearch",
    "AzureSearchWriter",
    "SearchIndex",
]
