"""Anomaly Detector transformers (cognitive/AnomalyDetection.scala analogue).

Wire format: Anomaly Detector v1.0 — POST ``{"series": [{"timestamp",
"value"}...], "granularity": ...}`` to ``/timeseries/last/detect`` (is the
latest point anomalous) or ``/timeseries/entire/detect`` (whole series).
"""

from __future__ import annotations

from typing import Any, Optional

from mmlspark_tpu.cognitive import schemas as S
from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam


class _AnomalyBase(CognitiveServiceBase):
    series = ServiceParam(
        "time series: list of {timestamp, value} dicts (value or column)"
    )
    granularity = ServiceParam("series granularity", default={"value": "daily"})
    max_anomaly_ratio = ServiceParam("max fraction of anomalies")
    sensitivity = ServiceParam("sensitivity 0-99")
    custom_interval = ServiceParam("interval for 'custom' granularity")

    _path = ""

    def _build_request(self, vals: dict) -> Optional[dict]:
        series = vals.get("series")
        if series is None:
            return None
        body: dict = {
            "series": [
                {"timestamp": str(pt["timestamp"]), "value": float(pt["value"])}
                for pt in series
            ],
            "granularity": vals.get("granularity") or "daily",
        }
        for k, wire in (
            ("max_anomaly_ratio", "maxAnomalyRatio"),
            ("sensitivity", "sensitivity"),
            ("custom_interval", "customInterval"),
        ):
            if vals.get(k) is not None:
                body[wire] = vals[k]
        return self._post_json(vals, body, path=self._path)


class DetectLastAnomaly(_AnomalyBase):
    """Is the most recent point anomalous (DetectLastAnomaly)."""

    _path = "/anomalydetector/v1.0/timeseries/last/detect"
    _response_schema = S.LastAnomalyResponse


class DetectAnomalies(_AnomalyBase):
    """Anomaly flags for the whole series (DetectAnomalies)."""

    _path = "/anomalydetector/v1.0/timeseries/entire/detect"
    _response_schema = S.AnomalyDetectResponse
