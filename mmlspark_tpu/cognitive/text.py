"""Text-analytics transformers (cognitive/TextAnalytics.scala analogue).

Wire format: Text Analytics v3 — POST ``{"documents": [{"id", "language",
"text"}]}``; response ``{"documents": [...], "errors": [...]}`` keyed by
document id. Rows are MINIBATCHED: up to ``batch_size`` (default 10)
documents travel per HTTP request and are flattened back to rows by id —
the reference's minibatch -> JSON -> flatten pipeline
(io/http/SimpleHTTPTransformer.scala:111-154; TextAnalytics.scala posts
document seqs the same way). Outputs are typed records from
cognitive/schemas.py (TextAnalyticsSchemas.scala's SparkBindings
analogue), with the schema reflected into output-column metadata.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from mmlspark_tpu.cognitive import schemas as S
from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.io.http_schema import response_to_json


class _TextAnalyticsBase(CognitiveServiceBase):
    text = ServiceParam("input text (value or column)")
    language = ServiceParam("ISO language hint", default={"value": "en"})
    batch_size = Param(
        "documents per HTTP request (TextAnalytics minibatching)",
        default=10, type_=int,
    )

    _path = ""
    _batchable = True

    # -- document assembly ----------------------------------------------------

    def _doc(self, vals: dict, doc_id: int) -> dict:
        return {
            "id": str(doc_id),
            "language": vals.get("language") or "en",
            "text": str(vals.get("text")),
        }

    def _build_request(self, vals: dict) -> Optional[dict]:
        if vals.get("text") is None:
            return None
        return self._post_json(
            vals, {"documents": [self._doc(vals, 0)]}, path=self._path
        )

    def _project_response(self, obj: Any) -> Any:
        docs = (obj or {}).get("documents") or []
        return S.from_json(self._response_schema, docs[0]) if docs else None

    # -- minibatching ---------------------------------------------------------

    def _batch_key(self, vals: dict) -> Optional[Any]:
        if vals.get("text") is None:
            return None  # skip row (the reference's shouldSkip)
        # rows sharing credentials share a request; url is stage-constant.
        # Wrapped in a tuple: a None credential is still a VALID group key,
        # distinct from the skip sentinel above
        return ("key", vals.get("subscription_key"))

    def _build_batch_request(self, vals_list: list) -> dict:
        docs = [self._doc(v, j) for j, v in enumerate(vals_list)]
        return self._post_json(vals_list[0], {"documents": docs}, path=self._path)

    def _split_batch_response(self, resp: Optional[dict], k: int) -> list:
        if resp is None:
            return [(None, None)] * k
        if resp["status_code"] // 100 != 2:
            err = {
                "status_code": resp["status_code"],
                "reason": resp["reason"],
                "entity": resp["entity"],
            }
            return [(None, err)] * k
        try:
            obj = response_to_json(resp) or {}
        except (ValueError, KeyError, TypeError) as e:
            err = {"status_code": resp["status_code"], "reason": f"parse error: {e}"}
            return [(None, err)] * k
        docs = {str(d.get("id")): d for d in obj.get("documents") or []}
        doc_errs = {str(e.get("id")): e for e in obj.get("errors") or []}
        out = []
        for j in range(k):
            d = docs.get(str(j))
            if d is not None:
                out.append((S.from_json(self._response_schema, d), None))
            elif str(j) in doc_errs:
                out.append(
                    (None, {"status_code": 200, "reason": json.dumps(doc_errs[str(j)])})
                )
            else:
                out.append((None, None))
        return out


class TextSentiment(_TextAnalyticsBase):
    """Sentiment per document (TextSentiment.scala; /sentiment)."""

    _path = "/text/analytics/v3.0/sentiment"
    _response_schema = S.SentimentDocument


class LanguageDetector(_TextAnalyticsBase):
    """Detected language (LanguageDetector; /languages). The v3 wire format
    nests text only, no language hint."""

    _path = "/text/analytics/v3.0/languages"
    _response_schema = S.LanguageDocument

    def _doc(self, vals: dict, doc_id: int) -> dict:
        return {"id": str(doc_id), "text": str(vals.get("text"))}


class EntityDetector(_TextAnalyticsBase):
    """Named-entity recognition (EntityDetector; /entities/recognition/general)."""

    _path = "/text/analytics/v3.0/entities/recognition/general"
    _response_schema = S.EntitiesDocument


class NER(EntityDetector):
    """Named-entity recognition (NERV2/NER in TextAnalytics.scala:217-227;
    the v3 wire format unifies it with EntityDetector's endpoint — this is
    the same stage under the reference's other registry name)."""


class KeyPhraseExtractor(_TextAnalyticsBase):
    """Key-phrase extraction (KeyPhraseExtractor; /keyPhrases)."""

    _path = "/text/analytics/v3.0/keyPhrases"
    _response_schema = S.KeyPhraseDocument
