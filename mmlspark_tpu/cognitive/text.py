"""Text-analytics transformers (cognitive/TextAnalytics.scala analogue).

Wire format: Text Analytics v3 — POST ``{"documents": [{"id", "language",
"text"}]}``; response ``{"documents": [...], "errors": [...]}``. One
document per row; the projected output is the row's document object.
"""

from __future__ import annotations

from typing import Any, Optional

from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam


class _TextAnalyticsBase(CognitiveServiceBase):
    text = ServiceParam("input text (value or column)")
    language = ServiceParam("ISO language hint", default={"value": "en"})

    _path = ""

    def _build_request(self, vals: dict) -> Optional[dict]:
        text = vals.get("text")
        if text is None:
            return None
        body = {
            "documents": [
                {"id": "0", "language": vals.get("language") or "en", "text": str(text)}
            ]
        }
        return self._post_json(vals, body, path=self._path)

    def _project_response(self, obj: Any) -> Any:
        docs = (obj or {}).get("documents") or []
        return docs[0] if docs else None


class TextSentiment(_TextAnalyticsBase):
    """Sentiment per document (TextSentiment.scala; /sentiment)."""

    _path = "/text/analytics/v3.0/sentiment"


class LanguageDetector(_TextAnalyticsBase):
    """Detected language (LanguageDetector; /languages). The v3 wire format
    nests text only, no language hint."""

    _path = "/text/analytics/v3.0/languages"

    def _build_request(self, vals: dict) -> Optional[dict]:
        text = vals.get("text")
        if text is None:
            return None
        body = {"documents": [{"id": "0", "text": str(text)}]}
        return self._post_json(vals, body, path=self._path)


class EntityDetector(_TextAnalyticsBase):
    """Named-entity recognition (EntityDetector; /entities/recognition/general)."""

    _path = "/text/analytics/v3.0/entities/recognition/general"


class KeyPhraseExtractor(_TextAnalyticsBase):
    """Key-phrase extraction (KeyPhraseExtractor; /keyPhrases)."""

    _path = "/text/analytics/v3.0/keyPhrases"
