"""Face-API transformers (cognitive/Face.scala analogue).

Wire format: Face v1.0 — detect posts an image URL; verify/identify/group/
findsimilars post face-id JSON bodies.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from typing import List

from mmlspark_tpu.cognitive import schemas as S
from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.io.http_schema import HTTPRequestData


class DetectFace(CognitiveServiceBase):
    """Face detection (/face/v1.0/detect)."""

    _response_schema = List[S.DetectedFace]

    image_url = ServiceParam("image URL (value or column)")
    return_face_id = ServiceParam("return face ids", default={"value": True})
    return_face_landmarks = ServiceParam("return landmarks", default={"value": False})
    return_face_attributes = ServiceParam("attribute list (age,gender,...)")

    def _build_request(self, vals: dict) -> Optional[dict]:
        img = vals.get("image_url")
        if img is None:
            return None
        parts = [
            f"returnFaceId={str(bool(vals.get('return_face_id'))).lower()}",
            f"returnFaceLandmarks={str(bool(vals.get('return_face_landmarks'))).lower()}",
        ]
        if vals.get("return_face_attributes"):
            parts.append(
                "returnFaceAttributes=" + ",".join(vals["return_face_attributes"])
            )
        return self._post_json(
            vals, {"url": str(img)}, path="/face/v1.0/detect", query="&".join(parts)
        )


class VerifyFaces(CognitiveServiceBase):
    """Same-person verification of two face ids (/face/v1.0/verify)."""

    _response_schema = S.VerifyResponse

    face_id1 = ServiceParam("first face id")
    face_id2 = ServiceParam("second face id")

    def _build_request(self, vals: dict) -> Optional[dict]:
        a, b = vals.get("face_id1"), vals.get("face_id2")
        if a is None or b is None:
            return None
        return self._post_json(
            vals, {"faceId1": str(a), "faceId2": str(b)}, path="/face/v1.0/verify"
        )


class IdentifyFaces(CognitiveServiceBase):
    """Identify face ids against a person group (/face/v1.0/identify)."""

    _response_schema = List[S.IdentifiedFace]

    face_ids = ServiceParam("face ids to identify")
    person_group_id = ServiceParam("person group id")
    max_num_of_candidates = ServiceParam("max candidates", default={"value": 1})
    confidence_threshold = ServiceParam("confidence threshold")

    def _build_request(self, vals: dict) -> Optional[dict]:
        ids = vals.get("face_ids")
        if ids is None:
            return None
        body = {
            "faceIds": [str(i) for i in ids],
            "personGroupId": str(vals.get("person_group_id")),
            "maxNumOfCandidatesReturned": int(vals.get("max_num_of_candidates") or 1),
        }
        if vals.get("confidence_threshold") is not None:
            body["confidenceThreshold"] = float(vals["confidence_threshold"])
        return self._post_json(vals, body, path="/face/v1.0/identify")


class GroupFaces(CognitiveServiceBase):
    """Group face ids by similarity (/face/v1.0/group)."""

    _response_schema = S.GroupResponse

    face_ids = ServiceParam("face ids to group")

    def _build_request(self, vals: dict) -> Optional[dict]:
        ids = vals.get("face_ids")
        if ids is None:
            return None
        return self._post_json(
            vals, {"faceIds": [str(i) for i in ids]}, path="/face/v1.0/group"
        )


class FindSimilarFace(CognitiveServiceBase):
    """Find similar faces to a query face id (/face/v1.0/findsimilars)."""

    _response_schema = List[S.SimilarFace]

    face_id = ServiceParam("query face id")
    face_ids = ServiceParam("candidate face ids")
    face_list_id = ServiceParam("or: a stored face list id")
    max_num_of_candidates = ServiceParam("max results", default={"value": 20})

    def _build_request(self, vals: dict) -> Optional[dict]:
        fid = vals.get("face_id")
        if fid is None:
            return None
        body: dict = {
            "faceId": str(fid),
            "maxNumOfCandidatesReturned": int(vals.get("max_num_of_candidates") or 20),
        }
        if vals.get("face_list_id") is not None:
            body["faceListId"] = str(vals["face_list_id"])
        elif vals.get("face_ids") is not None:
            body["faceIds"] = [str(i) for i in vals["face_ids"]]
        return self._post_json(vals, body, path="/face/v1.0/findsimilars")
