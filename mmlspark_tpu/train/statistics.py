"""ComputeModelStatistics / ComputePerInstanceStatistics
(train/ComputeModelStatistics.scala:153-229, ComputePerInstanceStatistics.scala).

Outputs a metrics DataFrame (confusion matrix included as a dense array
cell, like the reference's matrix-in-DataFrame) or per-row statistics.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.metrics import (
    MetricConstants,
    classification_metrics,
    confusion_matrix,
    regression_metrics,
)
from mmlspark_tpu.core.params import HasLabelCol, Param
from mmlspark_tpu.core.pipeline import Transformer


class ComputeModelStatistics(Transformer, HasLabelCol):
    evaluation_metric = Param(
        "classification|regression|all|<metric name>", default="all", type_=str
    )
    scores_col = Param("prediction column", default="prediction", type_=str)
    scored_probabilities_col = Param("probability column (binary AUC)", type_=str)

    def transform(self, df: DataFrame) -> DataFrame:
        y = df[self.get("label_col")]
        pred = df[self.get("scores_col")]
        want = self.get("evaluation_metric")
        is_classification = want in ("classification", "all") or want in MetricConstants.ALL_CLASSIFICATION
        if y.dtype == object or pred.dtype == object:
            # string labels: index jointly so labels and predictions share codes
            if want == "regression" or want in MetricConstants.ALL_REGRESSION:
                raise ValueError("regression metrics need numeric labels/predictions")
            levels = {v: i for i, v in enumerate(np.unique(
                np.concatenate([np.asarray(y, dtype=object), np.asarray(pred, dtype=object)]).astype(str)
            ))}
            y = np.array([levels[str(v)] for v in y], dtype=np.int64)
            pred = np.array([levels[str(v)] for v in pred], dtype=np.int64)
            looks_classy = True
        else:
            looks_classy = np.issubdtype(np.asarray(y).dtype, np.integer) or (
                np.asarray(y, dtype=np.float64) % 1 == 0
            ).all()
        row: dict = {}
        if is_classification and looks_classy and want != "regression":
            scores = None
            pc = self.get("scored_probabilities_col")
            if pc and pc in df.columns:
                probs = df[pc]
                scores = probs[:, 1] if probs.ndim == 2 and probs.shape[1] == 2 else probs
            row.update(classification_metrics(y, pred, scores))
            row["confusion_matrix"] = confusion_matrix(
                np.asarray(y, np.int64), np.asarray(pred, np.int64)
            ).astype(np.float64)
        if want in ("regression", "all") and not (want == "all" and looks_classy):
            row.update(regression_metrics(y, pred))
        if want not in ("classification", "regression", "all"):
            row = {want: row.get(want, float("nan"))} if want in row else _single(want, y, pred, df, self)
        return DataFrame.from_rows([row])


def _single(metric: str, y: Any, pred: Any, df: DataFrame, stage: ComputeModelStatistics) -> dict:
    if metric in MetricConstants.ALL_REGRESSION:
        return {metric: regression_metrics(y, pred)[metric]}
    scores = None
    pc = stage.get("scored_probabilities_col")
    if pc and pc in df.columns:
        probs = df[pc]
        scores = probs[:, 1] if probs.ndim == 2 and probs.shape[1] == 2 else probs
    m = classification_metrics(y, pred, scores)
    return {metric: m.get(metric, float("nan"))}


class ComputePerInstanceStatistics(Transformer, HasLabelCol):
    """Per-row L1/L2 (regression) or log-loss (classification with probs)."""

    scores_col = Param("prediction column", default="prediction", type_=str)
    scored_probabilities_col = Param("probability column", type_=str)

    def transform(self, df: DataFrame) -> DataFrame:
        label = self.get("label_col")
        pc = self.get("scored_probabilities_col")

        def fn(p: dict) -> dict:
            q = dict(p)
            y = np.asarray(p[label], np.float64)
            pred = np.asarray(p[self.get("scores_col")], np.float64)
            if pc and pc in p:
                probs = np.asarray(p[pc], np.float64)
                idx = np.clip(np.asarray(y, np.int64), 0, probs.shape[1] - 1)
                ll = -np.log(np.clip(probs[np.arange(len(y)), idx], 1e-15, 1.0))
                q["log_loss"] = ll
            q["L1_loss"] = np.abs(y - pred)
            q["L2_loss"] = (y - pred) ** 2
            return q

        return df.map_partitions(fn)
