"""TrainClassifier / TrainRegressor — auto-featurize + fit any predictor.

Reference: train/TrainClassifier.scala:94-130 (label reindex via
ValueIndexer, auto Featurize, classifier fit), train/TrainRegressor.scala.
The model wraps (featurizer, value-indexer, inner model) and exposes
original label values on output.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, HasFeaturesCol, HasLabelCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.schema import CATEGORICAL_KEY
from mmlspark_tpu.featurize import Featurize, ValueIndexer
from mmlspark_tpu.featurize.featurize import NUM_FEATURES_DEFAULT, NUM_FEATURES_TREE_OR_NN


class TrainClassifier(Estimator, HasLabelCol):
    model = ComplexParam("inner classifier estimator (defaults to LogisticRegression)")
    number_of_features = Param("hash space for featurization", default=NUM_FEATURES_TREE_OR_NN, type_=int)
    reindex_label = Param("reindex labels via ValueIndexer", default=True, type_=bool)

    def fit(self, df: DataFrame) -> "TrainedClassifierModel":
        label = self.get("label_col")
        inner = self.get("model")
        if inner is None:
            from mmlspark_tpu.models.linear import LogisticRegression

            inner = LogisticRegression()
        levels: Optional[list] = None
        work = df
        if self.get("reindex_label"):
            vi = ValueIndexer(input_col=label, output_col="__label_idx__").fit(df)
            work = vi.transform(df)
            levels = vi.get("levels")
            work = work.drop(label).rename({"__label_idx__": label})
        feat_cols = [c for c in work.columns if c != label]
        featurizer = Featurize(
            input_cols=feat_cols,
            output_col="features",
            number_of_features=self.get("number_of_features"),
        ).fit(work)
        feats = featurizer.transform(work)
        if hasattr(inner, "param") and "label_col" in inner.params():
            inner = inner.copy({"label_col": label})
        inner_model = inner.fit(feats)
        m = TrainedClassifierModel(label_col=label)
        m.set(featurizer=featurizer, inner_model=inner_model)
        if levels is not None:
            m.set(levels=levels)
        return m


class TrainedClassifierModel(Model, HasLabelCol):
    featurizer = ComplexParam("fitted featurizer")
    inner_model = ComplexParam("fitted classifier model")
    levels = Param("original label values", type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        work = df
        label = self.get("label_col")
        if label in work.columns and self.get("levels") is not None:
            # map labels to indices for scoring consistency
            table = {str(v): i for i, v in enumerate(self.get("levels"))}
            work = work.with_column(
                label,
                lambda p: np.array([table.get(str(v), -1) for v in p[label]], np.int32),
            )
        feats = self.get_or_fail("featurizer").transform(work)
        out = self.get_or_fail("inner_model").transform(feats)
        levels = self.get("levels")
        if levels is not None:
            out = out.with_column_metadata("prediction", {CATEGORICAL_KEY: levels})
        return out

    def get_scored_labels(self, out: DataFrame, col: str = "scored_labels") -> DataFrame:
        """Map integer predictions back to original label values."""
        levels = self.get("levels")
        if levels is None:
            return out
        lv = np.array(levels, dtype=object)
        return out.with_column(
            col, lambda p: lv[np.asarray(p["prediction"], np.int64)]
        )


class TrainRegressor(Estimator, HasLabelCol):
    model = ComplexParam("inner regressor estimator (defaults to LinearRegression)")
    number_of_features = Param("hash space for featurization", default=NUM_FEATURES_DEFAULT, type_=int)

    def fit(self, df: DataFrame) -> "TrainedRegressorModel":
        label = self.get("label_col")
        inner = self.get("model")
        if inner is None:
            from mmlspark_tpu.models.linear import LinearRegression

            inner = LinearRegression()
        feat_cols = [c for c in df.columns if c != label]
        featurizer = Featurize(
            input_cols=feat_cols,
            output_col="features",
            number_of_features=self.get("number_of_features"),
        ).fit(df)
        feats = featurizer.transform(df)
        if hasattr(inner, "param") and "label_col" in inner.params():
            inner = inner.copy({"label_col": label})
        inner_model = inner.fit(feats)
        m = TrainedRegressorModel(label_col=label)
        m.set(featurizer=featurizer, inner_model=inner_model)
        return m


class TrainedRegressorModel(Model, HasLabelCol):
    featurizer = ComplexParam("fitted featurizer")
    inner_model = ComplexParam("fitted regressor model")

    def transform(self, df: DataFrame) -> DataFrame:
        feats = self.get_or_fail("featurizer").transform(df)
        return self.get_or_fail("inner_model").transform(feats)
