"""TrainClassifier / TrainRegressor — auto-featurize + fit any predictor.

Reference: train/TrainClassifier.scala:94-130 (label reindex via
ValueIndexer, auto Featurize, classifier fit), train/TrainRegressor.scala.
The model wraps (featurizer, value-indexer, inner model) and exposes
original label values on output.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.schema import CATEGORICAL_KEY
from mmlspark_tpu.featurize import Featurize, ValueIndexer
from mmlspark_tpu.featurize.featurize import NUM_FEATURES_DEFAULT, NUM_FEATURES_TREE_OR_NN


class TrainClassifier(Estimator, HasLabelCol):
    model = ComplexParam("inner classifier estimator (defaults to LogisticRegression)")
    number_of_features = Param("hash space for featurization", default=NUM_FEATURES_TREE_OR_NN, type_=int)
    reindex_label = Param("reindex labels via ValueIndexer", default=True, type_=bool)

    def fit(self, df: DataFrame) -> "TrainedClassifierModel":
        label = self.get("label_col")
        inner = self.get("model")
        if inner is None:
            from mmlspark_tpu.models.linear import LogisticRegression

            inner = LogisticRegression()
        levels: Optional[list] = None
        work = df
        if self.get("reindex_label"):
            vi = ValueIndexer(input_col=label, output_col="__label_idx__").fit(df)
            work = vi.transform(df)
            levels = vi.get("levels")
            work = work.drop(label).rename({"__label_idx__": label})
        feat_cols = [c for c in work.columns if c != label]
        featurizer = Featurize(
            input_cols=feat_cols,
            output_col="features",
            number_of_features=self.get("number_of_features"),
        ).fit(work)
        feats = featurizer.transform(work)
        if hasattr(inner, "param") and "label_col" in inner.params():
            inner = inner.copy({"label_col": label})
        inner_model = inner.fit(feats)
        m = TrainedClassifierModel(label_col=label)
        m.set(featurizer=featurizer, inner_model=inner_model)
        if levels is not None:
            m.set(levels=levels)
        return m


class TrainedClassifierModel(Model, HasLabelCol):
    featurizer = ComplexParam("fitted featurizer")
    inner_model = ComplexParam("fitted classifier model")
    levels = Param("original label values", type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        work = df
        label = self.get("label_col")
        if label in work.columns and self.get("levels") is not None:
            # map labels to indices for scoring consistency
            table = {str(v): i for i, v in enumerate(self.get("levels"))}
            work = work.with_column(
                label,
                lambda p: np.array([table.get(str(v), -1) for v in p[label]], np.int32),
            )
        feats = self.get_or_fail("featurizer").transform(work)
        out = self.get_or_fail("inner_model").transform(feats)
        levels = self.get("levels")
        if levels is not None:
            out = out.with_column_metadata("prediction", {CATEGORICAL_KEY: levels})
        return out

    def get_scored_labels(self, out: DataFrame, col: str = "scored_labels") -> DataFrame:
        """Map integer predictions back to original label values."""
        levels = self.get("levels")
        if levels is None:
            return out
        lv = np.array(levels, dtype=object)
        return out.with_column(
            col, lambda p: lv[np.asarray(p["prediction"], np.int64)]
        )


class TrainRegressor(Estimator, HasLabelCol):
    model = ComplexParam("inner regressor estimator (defaults to LinearRegression)")
    number_of_features = Param("hash space for featurization", default=NUM_FEATURES_DEFAULT, type_=int)

    def fit(self, df: DataFrame) -> "TrainedRegressorModel":
        label = self.get("label_col")
        inner = self.get("model")
        if inner is None:
            from mmlspark_tpu.models.linear import LinearRegression

            inner = LinearRegression()
        feat_cols = [c for c in df.columns if c != label]
        featurizer = Featurize(
            input_cols=feat_cols,
            output_col="features",
            number_of_features=self.get("number_of_features"),
        ).fit(df)
        feats = featurizer.transform(df)
        if hasattr(inner, "param") and "label_col" in inner.params():
            inner = inner.copy({"label_col": label})
        inner_model = inner.fit(feats)
        m = TrainedRegressorModel(label_col=label)
        m.set(featurizer=featurizer, inner_model=inner_model)
        return m


class TrainedRegressorModel(Model, HasLabelCol):
    featurizer = ComplexParam("fitted featurizer")
    inner_model = ComplexParam("fitted regressor model")

    def transform(self, df: DataFrame) -> DataFrame:
        feats = self.get_or_fail("featurizer").transform(df)
        return self.get_or_fail("inner_model").transform(feats)


class OneVsRest(Estimator, HasLabelCol, HasFeaturesCol, HasPredictionCol):
    """Fit one binary copy of any classifier per class; predict argmax of
    per-class positive scores.

    The reference promotes multiclass LogisticRegression through Spark's
    OneVsRest (train/TrainClassifier.scala:106-128) because its LR is
    binary-only; here LogisticRegression is natively softmax-multiclass,
    so this stage exists as the user-facing meta-estimator, not a
    promotion workaround."""

    classifier = ComplexParam("binary base classifier (cloned per class)")

    def fit(self, df: DataFrame) -> "OneVsRestModel":
        import copy

        base = self.get_or_fail("classifier")
        label = self.get("label_col")
        y = np.asarray(df[label], np.float64)
        classes = sorted(float(c) for c in np.unique(y))
        models = []
        for c in classes:
            est = copy.deepcopy(base)
            # base estimators vary in declared params ("any classifier"):
            # set only what each one understands (train.py pattern above)
            if "label_col" in est.params():
                est.set(label_col="__ovr_label__")
            if "features_col" in est.params():
                est.set(features_col=self.get("features_col"))
            # the multiclass label must not leak into featurize-all bases
            binary = df.with_column(
                "__ovr_label__", (y == c).astype(np.float64)
            ).drop(label)
            models.append(est.fit(binary))
        m = OneVsRestModel(
            features_col=self.get("features_col"),
            prediction_col=self.get("prediction_col"),
        )
        m.set(models=models, classes=classes)
        return m


class OneVsRestModel(Model, HasFeaturesCol, HasPredictionCol):
    models = ComplexParam("per-class fitted binary models")
    classes = ComplexParam("class label per model")

    def transform(self, df: DataFrame) -> DataFrame:
        models = self.get_or_fail("models")
        classes = np.asarray(self.get_or_fail("classes"))
        scores = []
        for sub in models:
            out = sub.transform(df)
            # positive-class confidence from the sub-model's CONFIGURED
            # columns (probability_col when it has one, else prediction_col);
            # wrapper models (TrainedClassifierModel) don't declare the
            # param but their inner model still emits "probability"
            pc = (
                sub.get("probability_col")
                if "probability_col" in sub.params()
                else None
            )
            if pc is None and "probability" in out.columns:
                pc = "probability"
            if pc and pc in out.columns:
                p = np.asarray(out[pc], np.float64)
                scores.append(p[:, 1] if p.ndim == 2 else p)
            else:
                spc = (
                    sub.get("prediction_col")
                    if "prediction_col" in sub.params()
                    else "prediction"
                )
                scores.append(np.asarray(out[spc], np.float64))
        stacked = np.stack(scores, axis=1)  # (n, k)
        return df.with_column(
            self.get("prediction_col"), classes[stacked.argmax(axis=1)]
        )
