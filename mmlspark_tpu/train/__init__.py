from mmlspark_tpu.train.train import (
    TrainClassifier,
    TrainRegressor,
    TrainedClassifierModel,
    TrainedRegressorModel,
)
from mmlspark_tpu.train.statistics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
)

__all__ = [
    "TrainClassifier",
    "TrainRegressor",
    "TrainedClassifierModel",
    "TrainedRegressorModel",
    "ComputeModelStatistics",
    "ComputePerInstanceStatistics",
]
