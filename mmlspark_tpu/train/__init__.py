from mmlspark_tpu.train.train import (
    OneVsRest,
    OneVsRestModel,
    TrainClassifier,
    TrainRegressor,
    TrainedClassifierModel,
    TrainedRegressorModel,
)
from mmlspark_tpu.train.statistics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
)

__all__ = [
    "OneVsRest",
    "OneVsRestModel",
    "TrainClassifier",
    "TrainRegressor",
    "TrainedClassifierModel",
    "TrainedRegressorModel",
    "ComputeModelStatistics",
    "ComputePerInstanceStatistics",
]
