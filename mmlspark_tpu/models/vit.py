"""Vision Transformer family — the attention-based zoo backbone.

The reference's model zoo serves CNTK conv-net graphs only
(downloader/ModelDownloader.scala, Schema.scala:54-66); this adds the
transformer generation of image backbones to the same
ImageFeaturizer/zoo machinery (named layer outputs, ``cutOutputLayers``
truncation, torchvision checkpoint import), built TPU-first:

- patch embedding is a strided conv (one big MXU matmul per image);
- encoder blocks are pre-LN MHSA + MLP in bf16, fused by XLA;
- the attention can run **sequence-parallel over a mesh axis** via
  :func:`mmlspark_tpu.ops.ring_attention.ring_attention`: the token dim
  is padded to a multiple of the axis size and the pad tail masked with
  the ring's ``kv_mask``, so ViT's N = (H/P)*(W/P) + 1 tokens (197 for
  224/16 — never divisible) shard cleanly. Single-device meshes use
  dense attention automatically.

Naming/structure mirrors torchvision's ``vit_b_16`` closely enough that
``torch_import.import_torch_vit`` maps its checkpoints 1:1 (erf GELU,
pre-LN, class-token pooling).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class ViTEncoderBlock(nn.Module):
    """One pre-LN transformer block: x + MHSA(LN(x)); x + MLP(LN(x))."""

    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    attn: Optional[Callable] = None  # (B,N,H,D)x3 -> (B,N,H,D)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from mmlspark_tpu.ops.ring_attention import dense_attention

        b, n, c = x.shape
        h = self.num_heads
        d = c // h
        y = nn.LayerNorm(dtype=self.dtype, name="ln_1")(x)
        qkv = nn.DenseGeneral((3, h, d), dtype=self.dtype, name="qkv")(y)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (B, N, H, D)
        attend = self.attn if self.attn is not None else dense_attention
        o = attend(q, k, v).reshape(b, n, c)
        o = nn.Dense(c, dtype=self.dtype, name="out")(o)
        x = x + o
        y = nn.LayerNorm(dtype=self.dtype, name="ln_2")(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_1")(y)
        y = nn.gelu(y, approximate=False)  # erf GELU: torchvision parity
        y = nn.Dense(c, dtype=self.dtype, name="mlp_2")(y)
        return x + y


class ViT(nn.Module):
    """ViT with named outputs for ``cutOutputLayers`` truncation.

    Layer-name order (outermost first) matches the zoo convention:
    ["logits", "pool", "encoder", "patches"] — ``pool`` is the
    class-token embedding after the final LayerNorm (the standard
    featurization vector), ``encoder`` the full token sequence.
    """

    patch_size: int = 16
    hidden_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    # sequence parallelism: shard the token dim over mesh[seq_axis] with
    # ring attention (pad + kv_mask when N doesn't divide the axis size)
    seq_mesh: Any = None
    seq_axis: str = "data"

    LAYER_NAMES = ("logits", "pool", "encoder", "patches")

    def _attend(self) -> Optional[Callable]:
        mesh = self.seq_mesh
        if mesh is None or dict(mesh.shape).get(self.seq_axis, 1) <= 1:
            return None  # dense attention

        from mmlspark_tpu.ops.ring_attention import ring_attention

        n_sh = dict(mesh.shape)[self.seq_axis]

        def attend(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray):
            b, n, h, d = q.shape
            n_pad = ((n + n_sh - 1) // n_sh) * n_sh
            if n_pad == n:
                return ring_attention(
                    q, k, v, mesh=mesh, axis=self.seq_axis
                )
            pad = ((0, 0), (0, n_pad - n), (0, 0), (0, 0))
            mask = jnp.broadcast_to(
                jnp.arange(n_pad)[None, :] < n, (b, n_pad)
            )
            o = ring_attention(
                jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad),
                mesh=mesh, axis=self.seq_axis, kv_mask=mask,
            )
            return o[:, :n]

        return attend

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> dict:
        outputs: dict = {}
        ps = self.patch_size
        x = x.astype(self.dtype)
        p = nn.Conv(
            self.hidden_dim, (ps, ps), strides=(ps, ps),
            dtype=self.dtype, name="conv_proj", padding="VALID",
        )(x)                                       # (B, H/ps, W/ps, C)
        b, gh, gw, c = p.shape
        seq = p.reshape(b, gh * gw, c)
        outputs["patches"] = seq.astype(jnp.float32)
        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, c), jnp.float32
        )
        seq = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype), (b, 1, c)), seq], axis=1
        )
        n = seq.shape[1]
        pos = self.param(
            "pos_embedding", nn.initializers.normal(0.02), (1, n, c),
            jnp.float32,
        )
        seq = seq + pos.astype(self.dtype)
        attend = self._attend()
        for i in range(self.depth):
            seq = ViTEncoderBlock(
                num_heads=self.num_heads, mlp_dim=self.mlp_dim,
                dtype=self.dtype, attn=attend, name=f"block_{i}",
            )(seq)
        seq = nn.LayerNorm(dtype=self.dtype, name="ln")(seq)
        outputs["encoder"] = seq.astype(jnp.float32)
        pooled = seq[:, 0].astype(jnp.float32)     # class token
        outputs["pool"] = pooled
        logits = nn.Dense(
            self.num_classes, dtype=self.dtype, name="head"
        )(pooled)
        outputs["logits"] = logits.astype(jnp.float32)
        return outputs


def vit_b16(**kw: Any) -> ViT:
    return ViT(**kw)


def vit_tiny(**kw: Any) -> ViT:
    """Test-scale ViT (the ResNet8-of-ViTs): fast to init and trace."""
    kw.setdefault("patch_size", 4)
    kw.setdefault("hidden_dim", 32)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("mlp_dim", 64)
    return ViT(**kw)


VITS: dict = {"ViTB16": vit_b16, "ViTTiny": vit_tiny}


def init_vit(name: str, image_size: int = 224, num_classes: int = 1000,
             seed: int = 0, **kw: Any):
    """(module, variables) at the given input size (pos-emb length is
    size-dependent, like the reference schema's input shape).

    Init always runs on the host CPU backend, same rationale as
    ``init_resnet``: weight materialization must not be hostage to a
    dead/remote accelerator backend."""
    import jax

    model = VITS[name](num_classes=num_classes, **kw)
    dummy = np.zeros((1, image_size, image_size, 3), np.float32)
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu = None
    if cpu is not None:
        with jax.default_device(cpu):
            variables = jax.jit(
                lambda: model.init(
                    jax.random.PRNGKey(seed), dummy, train=False
                )
            )()
        variables = jax.tree_util.tree_map(np.asarray, variables)
    else:
        variables = model.init(jax.random.PRNGKey(seed), dummy, train=False)
    return model, variables
