"""ResNet family in Flax — the deep-image backbone of the model zoo.

The reference ships CNTK model-zoo graphs (ResNet50 etc.) evaluated by the
CNTK JNI engine (downloader/ModelDownloader.scala, image/ImageFeaturizer
.scala:121-129). Here the backbone is a Flax module compiled by XLA for the
MXU: bf16 activations, fused conv+bn+relu, static shapes.

``apply_with_layers`` returns *named intermediate outputs* so
ImageFeaturizer can truncate output layers by name/count — the
``cutOutputLayers``/``layerNames`` capability (ImageFeaturizer.scala:96-129)
without graph surgery: XLA dead-code-eliminates branches that aren't used.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

ModuleDef = Any


def _conv_padding(kernel: int, strides: int, torch_padding: bool):
    """'SAME' unless torch parity is requested on a STRIDED conv.

    At stride 1 XLA's SAME padding equals torch's symmetric (k-1)//2; at
    stride 2 SAME becomes asymmetric ((0,1) for 3x3, (2,3) for 7x7) while
    torch stays symmetric — importing torchvision weights without matching
    this shifts every strided feature map by a pixel."""
    if torch_padding and strides > 1:
        p = (kernel - 1) // 2
        return ((p, p), (p, p))
    return "SAME"


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    torch_padding: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9, dtype=self.dtype
        )
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=_conv_padding(3, self.strides, self.torch_padding),
        )(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1), strides=(self.strides, self.strides), name="proj")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    torch_padding: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9, dtype=self.dtype
        )
        residual = x
        y = conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=_conv_padding(3, self.strides, self.torch_padding),
        )(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=(self.strides, self.strides), name="proj")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet with named stage outputs.

    Layer-name order (outermost last) mirrors the reference's model schema
    ``layerNames`` ordering used by ``cutOutputLayers``:
    ["logits", "pool", "layer4", "layer3", "layer2", "layer1", "stem"].
    """

    stage_sizes: Sequence[int]
    block: type = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    small_inputs: bool = False  # CIFAR-style stem (3x3, no maxpool)
    # torch-exact padding on strided convs/pool so torchvision-imported
    # weights reproduce torchvision features (see _conv_padding)
    torch_padding: bool = False

    LAYER_NAMES = ("logits", "pool", "layer4", "layer3", "layer2", "layer1", "stem")

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> dict:
        outputs: dict = {}
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9, dtype=self.dtype
        )
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(
                self.num_filters, (7, 7), strides=(2, 2), name="conv_init",
                padding=_conv_padding(7, 2, self.torch_padding),
            )(x)
        x = nn.relu(norm(name="bn_init")(x))
        if not self.small_inputs:
            pool_pad = ((1, 1), (1, 1)) if self.torch_padding else "SAME"
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=pool_pad)
        outputs["stem"] = x
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block(
                    filters=self.num_filters * 2 ** i,
                    strides=strides,
                    dtype=self.dtype,
                    torch_padding=self.torch_padding,
                )(x, train=train)
            outputs[f"layer{i + 1}"] = x
        x = jnp.mean(x, axis=(1, 2))
        outputs["pool"] = x.astype(jnp.float32)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        outputs["logits"] = x.astype(jnp.float32)
        return outputs


def resnet8(**kw: Any) -> ResNet:
    """Three-stage compact ResNet (~80k params at width 16): small enough
    to train in-repo and commit trained weights to the zoo, the committed
    counterpart of the reference's downloaded model files
    (downloader/Schema.scala:54-66)."""
    kw.setdefault("num_filters", 16)
    return ResNet(stage_sizes=[1, 1, 1], block=BasicBlock, **kw)


def resnet18(**kw: Any) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block=BasicBlock, **kw)


def resnet34(**kw: Any) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block=BasicBlock, **kw)


def resnet50(**kw: Any) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block=BottleneckBlock, **kw)


def resnet101(**kw: Any) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 23, 3], block=BottleneckBlock, **kw)


RESNETS: dict = {
    "ResNet8": resnet8,
    "ResNet18": resnet18,
    "ResNet34": resnet34,
    "ResNet50": resnet50,
    "ResNet101": resnet101,
}


def init_resnet(
    name: str = "ResNet50",
    num_classes: int = 1000,
    image_size: int = 224,
    seed: int = 0,
    small_inputs: bool = False,
    dtype: Any = jnp.bfloat16,
    num_filters: int = 64,
) -> tuple:
    """Build a ResNet and init variables. Returns (module, variables).

    Init always runs on the host CPU backend: weight materialization is a
    one-off that needs no accelerator, and routing it through a remote TPU
    compile path makes model *loading* hostage to accelerator availability
    (the exact failure that killed round-2's benchmark mid-``model.init``).
    """
    model = RESNETS[name](
        num_classes=num_classes, small_inputs=small_inputs, dtype=dtype,
        num_filters=num_filters,
    )
    # host-side allocation: a jnp.zeros here would already dispatch to the
    # default (possibly dead-remote) backend before the CPU scope below
    dummy = np.zeros((1, image_size, image_size, 3), np.float32)
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu = None
    if cpu is not None:
        with jax.default_device(cpu):
            variables = jax.jit(
                lambda: model.init(jax.random.PRNGKey(seed), dummy, train=False)
            )()
        variables = jax.tree_util.tree_map(np.asarray, variables)
    else:
        variables = model.init(jax.random.PRNGKey(seed), dummy, train=False)
    return model, variables
