"""XLAModel — batched model evaluation on TPU (the CNTKModel analogue).

The reference broadcasts a serialized CNTK graph to executors and feeds
minibatches through the native eval API per partition
(cntk/CNTKModel.scala:86-138,490-530). The TPU design:

- the "graph" is a jittable ``apply_fn(variables, x)``; XLA HLO is the
  compiled artifact (compile-once-per-shape replaces broadcast-once).
- weights are replicated onto the device mesh a single time per transform
  (the broadcast analogue, cntk/CNTKModel.scala:411-413).
- partitions are padded to a fixed batch size (FixedMiniBatchTransformer
  analogue — static shapes are load-bearing on TPU: any new shape is a new
  XLA compilation) and batch-sharded over the mesh ``data`` axis.
- multi-output graphs return name->array dicts; ``output_node`` selects one
  (the ARGUMENT_i/OUTPUT_i resolution analogue,
  com/microsoft/CNTK/SerializableFunction.scala:115-129). XLA dead-code
  eliminates the unused heads.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasBatchSize,
    HasInputCol,
    HasOutputCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Model
from mmlspark_tpu.parallel.mesh import get_mesh
from mmlspark_tpu.parallel.sharding import pad_batch, replicate, shard_batch


class XLAModel(Model, HasInputCol, HasOutputCol, HasBatchSize):
    apply_fn = ComplexParam(
        "jittable function (variables, batch) -> array | dict[name, array]"
    )
    variables = ComplexParam("model variables pytree (replicated to the mesh)")
    output_node = Param(
        "name of the output to keep when apply_fn returns a dict", type_=str
    )
    batch_size = Param(
        "fixed minibatch size; padded to a multiple of the mesh size",
        default=64,
        type_=int,
    )
    input_dtype = Param(
        "cast input batches to this dtype; None = keep the host dtype "
        "(e.g. ship uint8 pixels and cast on device: 4x less host->device "
        "traffic when the program starts with a cast anyway)",
        default="float32",
        type_=str,
    )

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        self._jit_cache: dict = {}
        self._dev_vars: Any = None
        self._dev_vars_src: Any = None

    @classmethod
    def from_flax(
        cls,
        module: Any,
        variables: Any,
        output_node: Optional[str] = None,
        **kw: Any,
    ) -> "XLAModel":
        def apply_fn(vs: Any, x: Any) -> Any:
            return module.apply(vs, x, train=False)

        m = cls(**kw)
        m.set(apply_fn=apply_fn, variables=variables)
        if output_node is not None:
            m.set(output_node=output_node)
        return m

    # -- device-side plumbing ----------------------------------------------

    def _effective_batch(self, mesh: Any) -> int:
        bs = self.get("batch_size")
        n_dev = mesh.devices.size
        return ((bs + n_dev - 1) // n_dev) * n_dev

    def _device_variables(self, mesh: Any) -> Any:
        vs = self.get_or_fail("variables")
        if self._dev_vars is None or self._dev_vars_src is not vs:
            self._dev_vars = replicate(vs, mesh)
            self._dev_vars_src = vs
        return self._dev_vars

    def _compiled(self, shape: tuple, mesh: Any) -> Callable:
        key = (shape, id(mesh))
        fn = self._jit_cache.get(key)
        if fn is None:
            apply_fn = self.get_or_fail("apply_fn")
            node = self.get("output_node")

            def run(vs: Any, x: Any) -> Any:
                out = apply_fn(vs, x)
                if isinstance(out, dict):
                    if node is None:
                        raise ValueError(
                            f"apply_fn returned outputs {sorted(out)}; set output_node"
                        )
                    out = out[node]
                return out

            fn = jax.jit(run)
            self._jit_cache[key] = fn
        return fn

    # how many minibatches may be in flight on device at once: JAX's async
    # dispatch then overlaps host staging of batch i+1..i+k with compute of
    # batch i, while bounding live HBM for inputs+outputs
    _MAX_IN_FLIGHT = 4

    def apply_batch(self, x: np.ndarray) -> np.ndarray:
        """Evaluate one host batch (used by transform and by serving).

        Double-buffered: the main thread ONLY stages + dispatches (upload of
        batch k+1 streams while batch k computes), and result fetches run on
        a dedicated thread — over a remote-device link a blocking fetch
        costs ~70-100 ms that would otherwise serialize with the next
        dispatch (CNTKModel.scala:515-520 batches for the same
        keep-the-accelerator-busy reason). The in-flight window bounds live
        HBM and applies backpressure."""
        import concurrent.futures as _futures

        mesh = get_mesh()
        vs = self._device_variables(mesh)
        bs = self._effective_batch(mesh)
        dt = self.get("input_dtype")
        x = np.asarray(x, dtype=dt) if dt else np.asarray(x)
        padded, n = pad_batch(x, bs)
        fn = self._compiled(padded[:bs].shape, mesh)
        outs: list = []
        pending: list = []
        # one fetcher thread keeps results ordered; np.asarray releases the
        # GIL while it waits on the transfer, so dispatch continues
        with _futures.ThreadPoolExecutor(max_workers=1) as fetcher:
            for i in range(0, padded.shape[0], bs):
                chunk = shard_batch(padded[i: i + bs], mesh)
                y = fn(vs, chunk)  # async dispatch, no host sync
                pending.append(fetcher.submit(np.asarray, y))
                if len(pending) >= self._MAX_IN_FLIGHT:
                    outs.append(pending.pop(0).result())
            outs.extend(f.result() for f in pending)
        return np.concatenate(outs, axis=0)[:n]

    # -- stage interface ----------------------------------------------------

    def transform(self, df: DataFrame) -> DataFrame:
        ic = self.get_or_fail("input_col")
        oc = self.get_or_fail("output_col")

        def fn(p: Partition) -> Partition:
            q = dict(p)
            x = p[ic]
            if x.dtype == object:  # ragged rows: stack (must be uniform shape)
                x = np.stack(list(x))
            q[oc] = self.apply_batch(x)
            return q

        # partitions run sequentially: there is one device mesh; overlap
        # comes from async dispatch inside JAX, not host threads
        return df.map_partitions(fn, parallel=False)
