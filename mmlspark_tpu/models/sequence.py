"""Recurrent sequence models — the BiLSTM family of the zoo.

The reference's deep-learning catalog includes recurrent graphs served
through the batched eval stage (the BiLSTM entity-extraction sample runs
a pretrained CNTK BiLSTM via CNTKModel; notebooks/samples/"DeepLearning -
BiLSTM Medical Entity Extraction.ipynb", cntk/CNTKModel.scala:490-530).
Here the recurrence is a ``flax.linen.RNN`` over an LSTM cell — a
``lax.scan`` under jit, so the whole tagger is one fixed-shape XLA
program: embedding and output projection hit the MXU, the scan carries
the (B, hidden) state without Python-level loops, and ``XLAModel``
serves it batched like any other backbone.

Sequence batches are padded + masked (``seq_lengths``): the forward scan
simply runs over the pad tail (its outputs are masked out), and the
backward direction uses ``flax``'s ``reverse + keep_order`` which
respects ``seq_lengths`` so padding never leaks into real positions.

``XLAModel``'s apply contract is (variables, one batch array) — to keep
the mask on the SERVING path too, pack each row's length as a trailing
column (:func:`pack_lengths`) and serve
:meth:`BiLSTMTagger.packed_apply_fn`, which unpacks it inside the
jitted program. Serving unpacked tokens without lengths runs the
backward scan over whatever sits in the pad tail.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class BiLSTMTagger(nn.Module):
    """Per-token tagger: embed -> BiLSTM -> per-position logits.

    Named outputs follow the zoo convention for ``cut_output_layers``:
    ["logits", "hidden", "embedded"].
    """

    vocab_size: int
    num_tags: int
    embed_dim: int = 64
    hidden_dim: int = 64
    dtype: Any = jnp.float32

    LAYER_NAMES = ("logits", "hidden", "embedded")

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,                    # (B, T) int32 token ids
        seq_lengths: Optional[jnp.ndarray] = None,  # (B,) int32
    ) -> dict:
        outputs: dict = {}
        x = nn.Embed(
            self.vocab_size, self.embed_dim, dtype=self.dtype,
            name="embed",
        )(tokens)
        outputs["embedded"] = x
        fwd = nn.RNN(
            nn.OptimizedLSTMCell(self.hidden_dim), name="lstm_fwd"
        )(x, seq_lengths=seq_lengths)
        bwd = nn.RNN(
            nn.OptimizedLSTMCell(self.hidden_dim), reverse=True,
            keep_order=True, name="lstm_bwd",
        )(x, seq_lengths=seq_lengths)
        h = jnp.concatenate([fwd, bwd], axis=-1)   # (B, T, 2H)
        outputs["hidden"] = h
        logits = nn.Dense(self.num_tags, dtype=self.dtype, name="head")(h)
        outputs["logits"] = logits
        if seq_lengths is not None:
            # padded positions predict tag 0 deterministically so batch
            # content can't leak through the pad tail
            t = tokens.shape[1]
            valid = jnp.arange(t)[None, :] < seq_lengths[:, None]
            neg = jnp.full_like(logits, -1e9).at[..., 0].set(0.0)
            outputs["logits"] = jnp.where(valid[..., None], logits, neg)
        return outputs

    def packed_apply_fn(self, node: str = "logits"):
        """Jittable ``(variables, packed) -> output`` for ``XLAModel``:
        ``packed`` is (B, T+1) int with each row's true length in the
        LAST column (:func:`pack_lengths`), so the seq_lengths mask
        rides the single-input serving contract."""

        def fn(variables: Any, packed: jnp.ndarray) -> jnp.ndarray:
            return self.apply(variables, packed[:, :-1], packed[:, -1])[node]

        return fn


def pack_lengths(tokens: np.ndarray, seq_lengths: np.ndarray) -> np.ndarray:
    """(B, T) tokens + (B,) lengths -> (B, T+1) with the length as the
    trailing column — the serving-side carrier for the pad mask."""
    tokens = np.asarray(tokens)
    return np.concatenate(
        [tokens, np.asarray(seq_lengths, tokens.dtype)[:, None]], axis=1
    )


def train_tagger(
    tokens: np.ndarray,
    tags: np.ndarray,
    vocab_size: int,
    num_tags: int,
    seq_lengths: Optional[np.ndarray] = None,
    num_steps: int = 200,
    learning_rate: float = 3e-3,
    seed: int = 0,
    **kw: Any,
):
    """Fit a :class:`BiLSTMTagger` with Adam on token-level cross-entropy
    (masked by ``seq_lengths``). Returns (module, variables). One jitted
    update step; the loop stays in Python for simplicity — tagger
    training is a convenience for samples/tests, not a perf path."""
    import jax
    import optax

    model = BiLSTMTagger(vocab_size=vocab_size, num_tags=num_tags, **kw)
    tok = jnp.asarray(tokens, jnp.int32)
    tg = jnp.asarray(tags, jnp.int32)
    sl = None if seq_lengths is None else jnp.asarray(seq_lengths, jnp.int32)
    variables = model.init(jax.random.PRNGKey(seed), tok[:1],
                           None if sl is None else sl[:1])
    opt = optax.adam(learning_rate)
    opt_state = opt.init(variables)

    def loss_fn(vs):
        logits = model.apply(vs, tok, sl)["logits"]
        ll = optax.softmax_cross_entropy_with_integer_labels(logits, tg)
        if sl is not None:
            mask = jnp.arange(tok.shape[1])[None, :] < sl[:, None]
            return (ll * mask).sum() / jnp.maximum(mask.sum(), 1)
        return ll.mean()

    @jax.jit
    def step(vs, os_):
        loss, grads = jax.value_and_grad(loss_fn)(vs)
        updates, os_ = opt.update(grads, os_)
        return optax.apply_updates(vs, updates), os_, loss

    for _ in range(num_steps):
        variables, opt_state, loss = step(variables, opt_state)
    return model, variables
