"""Baseline linear learners on TPU: logistic + linear regression.

The reference leans on SparkML's LogisticRegression/linear models as the
default learners inside TrainClassifier/TuneHyperparameters
(train/TrainClassifier.scala:106-128, automl/DefaultHyperparams). These are
the TPU equivalents: full-batch L-BFGS-free GD under ``jax.jit`` — the
whole training loop is one compiled program via ``lax.scan`` (no Python
per-iteration overhead), batch rows sharded over the mesh ``data`` axis.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasWeightCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Estimator, Model


def _device_fit_logistic(
    x: np.ndarray,
    y: np.ndarray,
    w: Optional[np.ndarray],
    n_classes: int,
    reg: float,
    lr: float,
    iters: int,
) -> tuple:
    """Jitted full-batch GD with Nesterov momentum; returns (W, b)."""
    xd = jnp.asarray(x, jnp.float32)
    yd = jax.nn.one_hot(jnp.asarray(y, jnp.int32), n_classes)
    wd = jnp.asarray(w, jnp.float32) if w is not None else jnp.ones((x.shape[0],), jnp.float32)
    wd = wd / wd.sum()

    def loss_fn(params: Any) -> jnp.ndarray:
        logits = xd @ params["W"] + params["b"]
        ll = (jax.nn.log_softmax(logits) * yd).sum(-1)
        return -(wd * ll).sum() + reg * (params["W"] ** 2).sum()

    grad_fn = jax.grad(loss_fn)

    def step(carry: Any, _: Any) -> tuple:
        params, vel = carry
        g = grad_fn(params)
        vel = jax.tree_util.tree_map(lambda v, gi: 0.9 * v - lr * gi, vel, g)
        params = jax.tree_util.tree_map(lambda p, v: p + v, params, vel)
        return (params, vel), None

    @jax.jit
    def train() -> Any:
        params = {
            "W": jnp.zeros((x.shape[1], n_classes), jnp.float32),
            "b": jnp.zeros((n_classes,), jnp.float32),
        }
        vel = jax.tree_util.tree_map(jnp.zeros_like, params)
        (params, _), _ = jax.lax.scan(step, (params, vel), None, length=iters)
        return params

    params = train()
    return np.asarray(params["W"]), np.asarray(params["b"])


class LogisticRegression(Estimator, HasFeaturesCol, HasLabelCol, HasWeightCol):
    reg_param = Param("L2 regularization", default=1e-4, type_=float)
    learning_rate = Param("GD learning rate", default=0.5, type_=float)
    max_iter = Param("GD iterations", default=200, type_=int)

    def fit(self, df: DataFrame) -> "LogisticRegressionModel":
        if df.count() == 0:
            raise ValueError("LogisticRegression: cannot fit on an empty dataframe")
        x = df[self.get("features_col")].astype(np.float32)
        y = df[self.get("label_col")].astype(np.int64)
        w = df[self.get("weight_col")] if self.get("weight_col") else None
        n_classes = int(y.max()) + 1 if len(y) else 2
        n_classes = max(n_classes, 2)
        W, b = _device_fit_logistic(
            x, y, w, n_classes,
            self.get("reg_param"), self.get("learning_rate"), self.get("max_iter"),
        )
        m = LogisticRegressionModel(
            features_col=self.get("features_col"), num_classes=n_classes
        )
        m.set(weights=W, bias=b)
        return m


class LogisticRegressionModel(
    Model, HasFeaturesCol, HasPredictionCol, HasProbabilityCol, HasRawPredictionCol
):
    weights = ComplexParam("(d, k) weight matrix")
    bias = ComplexParam("(k,) bias")
    num_classes = Param("number of classes", default=2, type_=int)

    def transform(self, df: DataFrame) -> DataFrame:
        W = jnp.asarray(self.get_or_fail("weights"))
        b = jnp.asarray(self.get_or_fail("bias"))

        @jax.jit
        def fwd(x: jnp.ndarray) -> tuple:
            logits = x @ W + b
            return logits, jax.nn.softmax(logits)

        fc = self.get("features_col")

        def fn(p: dict) -> dict:
            x = jnp.asarray(np.asarray(p[fc], np.float32))
            logits, probs = fwd(x)
            q = dict(p)
            q[self.get("raw_prediction_col")] = np.asarray(logits)
            q[self.get("probability_col")] = np.asarray(probs)
            q[self.get("prediction_col")] = np.asarray(jnp.argmax(logits, -1)).astype(np.float64)
            return q

        return df.map_partitions(fn, parallel=False)

    def fusable_kernel(self) -> Any:
        """The transform above is already one jitted program (matmul +
        softmax + argmax on f32): the kernel re-traces the identical ops
        into the fused segment, so exact-mode output is bit-equal."""
        from mmlspark_tpu.compiler.kernels import StageKernel, guard_f32_safe

        W = np.asarray(self.get_or_fail("weights"))
        b = np.asarray(self.get_or_fail("bias"))
        fc = self.get("features_col")
        raw_c = self.get("raw_prediction_col")
        prob_c = self.get("probability_col")
        pred_c = self.get("prediction_col")

        def fn(cols: dict) -> dict:
            import jax

            x = cols[fc].astype(jnp.float32)
            logits = x @ jnp.asarray(W) + jnp.asarray(b)
            return {
                raw_c: logits,
                prob_c: jax.nn.softmax(logits),
                pred_c: jnp.argmax(logits, -1),
            }

        return StageKernel(
            reads=(fc,),
            writes=(raw_c, prob_c, pred_c),
            fn=fn,
            # staged prediction is argmax cast to float64 on host
            out_dtypes={pred_c: np.dtype(np.float64)},
            guard=guard_f32_safe,
            cost_hint=1.0,
        )


class LinearRegression(Estimator, HasFeaturesCol, HasLabelCol, HasWeightCol):
    """Ridge regression by normal equations on device (one MXU solve)."""

    reg_param = Param("L2 regularization", default=1e-6, type_=float)

    def fit(self, df: DataFrame) -> "LinearRegressionModel":
        x = df[self.get("features_col")].astype(np.float32)
        y = df[self.get("label_col")].astype(np.float32)

        @jax.jit
        def solve(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
            xb = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
            gram = xb.T @ xb + self.get("reg_param") * jnp.eye(xb.shape[1])
            return jnp.linalg.solve(gram, xb.T @ y)

        wb = np.asarray(solve(jnp.asarray(x), jnp.asarray(y)))
        m = LinearRegressionModel(features_col=self.get("features_col"))
        m.set(weights=wb[:-1], bias=float(wb[-1]))
        return m


class LinearRegressionModel(Model, HasFeaturesCol, HasPredictionCol):
    weights = ComplexParam("(d,) weights")
    bias = Param("intercept", default=0.0, type_=float)

    def pipeline_io(self) -> tuple:
        """Declared I/O for the pipeline compiler: the staged transform is
        a float64 host matmul, which an x64-disabled device program cannot
        bit-match — so this model plans host-bound, with exact DAG edges."""
        return (self.get("features_col"),), (self.get("prediction_col"),)

    def transform(self, df: DataFrame) -> DataFrame:
        W = np.asarray(self.get_or_fail("weights"))
        b = self.get("bias")
        fc = self.get("features_col")
        return df.with_column(
            self.get("prediction_col"),
            lambda p: np.asarray(p[fc], np.float64) @ W + b,
        )
