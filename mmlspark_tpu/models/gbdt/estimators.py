"""LightGBM-compatible estimator facades on the TPU GBDT.

API parity with the reference's SparkML facades
(lightgbm/LightGBMClassifier.scala, LightGBMRegressor.scala,
LightGBMRanker.scala + LightGBMParams.scala): same estimator/model split,
same core params (num_leaves, num_iterations, learning_rate, objective,
parallelism=data_parallel|voting_parallel, early stopping via a validation
indicator column, init-score column, continued training via model string).

The distributed knobs of the reference (driver ports, barrier mode,
timeouts — LightGBMParams.scala) do not exist here: gang scheduling and the
histogram allreduce come from SPMD launch over the device mesh (SURVEY §5.8).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasGroupCol,
    HasInitScoreCol,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasValidationIndicatorCol,
    HasWeightCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.models.gbdt import objectives
from mmlspark_tpu.models.gbdt.booster import Booster
from mmlspark_tpu.models.gbdt.train import TrainConfig, train


class _LightGBMParams(
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    HasValidationIndicatorCol,
    HasInitScoreCol,
):
    num_iterations = Param("boosting rounds", default=100, type_=int)
    learning_rate = Param("shrinkage", default=0.1, type_=float)
    num_leaves = Param("max leaves per tree", default=31, type_=int)
    max_depth = Param("max tree depth (-1 = unlimited)", default=-1, type_=int)
    lambda_l2 = Param("L2 leaf regularization", default=0.0, type_=float)
    lambda_l1 = Param("L1 leaf regularization (ThresholdL1)", default=0.0, type_=float)
    min_sum_hessian_in_leaf = Param(
        "min child hessian mass for a valid split", default=1e-3, type_=float
    )
    min_gain_to_split = Param("min split gain", default=0.0, type_=float)
    min_data_in_leaf = Param("min rows per leaf", default=20, type_=int)
    max_bin = Param(
        "histogram bins (max 255: uint8 bin matrix)",
        default=255,
        type_=int,
        validator=lambda v: 2 <= v <= 255,
    )
    feature_fraction = Param("feature subsample per tree", default=1.0, type_=float)
    bagging_fraction = Param("row subsample", default=1.0, type_=float)
    bagging_freq = Param("bagging frequency (0=off)", default=0, type_=int)
    early_stopping_round = Param("early stopping patience (0=off)", default=0, type_=int)
    metric = Param("eval metric name ('' = objective default)", default="", type_=str)
    parallelism = Param(
        "data_parallel | voting_parallel (parity; both lower to the sharded program)",
        default="data_parallel",
        type_=str,
    )
    growth_policy = Param(
        "lossguide (LightGBM leaf-wise, default) | depthwise (level-wise; "
        "one multi-leaf histogram pass per level — O(depth) row passes)",
        default="lossguide",
        type_=str,
        validator=lambda v: v in ("lossguide", "depthwise"),
    )
    default_listen_port = Param("parity no-op (no sockets on TPU)", default=12400, type_=int)
    use_barrier_execution_mode = Param("parity no-op (SPMD is the gang)", default=False, type_=bool)
    top_k = Param("voting_parallel K (parity)", default=20, type_=int)
    boost_from_average = Param("init score from label average", default=True, type_=bool)
    boosting_type = Param(
        "gbdt | goss | dart | rf (LightGBMParams boostingType)",
        default="gbdt",
        type_=str,
        validator=lambda v: v in ("gbdt", "goss", "dart", "rf"),
    )
    drop_rate = Param("dart: per-iteration tree dropout rate", default=0.1, type_=float)
    max_drop = Param("dart: max trees dropped per iteration", default=50, type_=int)
    skip_drop = Param("dart: probability of skipping dropout", default=0.5, type_=float)
    top_rate = Param("goss: large-gradient retain fraction", default=0.2, type_=float)
    other_rate = Param("goss: small-gradient sample fraction", default=0.1, type_=float)
    eval_at = Param("ranking eval truncation (ndcg@k)", default=5, type_=int)
    categorical_slot_indexes = Param(
        "feature indices treated as categorical (subset splits; "
        "LightGBMParams categoricalSlotIndexes analogue). Values must be "
        "non-negative integers < max_bin-1.",
        default=None,
    )
    model_string = Param("initial model for continued training", default="", type_=str)
    alpha = Param(
        "quantile level (objective=quantile) / huber delta (objective=huber)",
        default=0.9, type_=float,
    )
    tweedie_variance_power = Param(
        "tweedie variance power in (1, 2)", default=1.5, type_=float
    )
    poisson_max_delta_step = Param(
        "poisson hessian stabilizer exp(score + step)", default=0.7, type_=float
    )
    fair_c = Param("fair-loss scale c", default=1.0, type_=float)
    num_batches = Param("fold training into k sequential batches", default=0, type_=int)
    checkpoint_dir = Param(
        "directory for round-level preemption-safe checkpoints ('' = off); "
        "see docs/robustness.md", default="", type_=str,
    )
    checkpoint_every = Param(
        "boosting rounds between checkpoints (each save re-serializes the "
        "full booster — small values trade training throughput for a "
        "tighter recovery window)", default=10, type_=int
    )
    resume_from = Param(
        "checkpoint directory to resume training from ('' = fresh run); "
        "point it at checkpoint_dir for crash-loop-safe auto-resume",
        default="", type_=str,
    )
    delegate = ComplexParam(
        "LightGBMDelegate: lifecycle callbacks + dynamic learning rate"
    )
    seed = Param("rng seed", default=0, type_=int)
    verbosity = Param("log level", default=-1, type_=int)
    fused_rounds = Param(
        "scan-fused chunk size: 0 = auto (one dispatch per run, bounded "
        "chunks under early stopping), 1 = legacy per-round dispatch "
        "loop (fallback; identical model), N > 1 = cap chunks at N rounds",
        default=0, type_=int,
    )

    def _config(self, objective: str, num_class: int = 1) -> TrainConfig:
        return TrainConfig(
            objective=objective,
            num_class=num_class,
            num_iterations=self.get("num_iterations"),
            learning_rate=self.get("learning_rate"),
            num_leaves=self.get("num_leaves"),
            max_depth=self.get("max_depth"),
            lambda_l2=self.get("lambda_l2"),
            lambda_l1=self.get("lambda_l1"),
            min_sum_hessian_in_leaf=self.get("min_sum_hessian_in_leaf"),
            min_gain_to_split=self.get("min_gain_to_split"),
            min_data_in_leaf=self.get("min_data_in_leaf"),
            max_bin=self.get("max_bin"),
            feature_fraction=self.get("feature_fraction"),
            bagging_fraction=self.get("bagging_fraction"),
            bagging_freq=self.get("bagging_freq"),
            early_stopping_round=self.get("early_stopping_round"),
            metric=self.get("metric"),
            seed=self.get("seed"),
            parallelism=self.get("parallelism"),
            growth_policy=self.get("growth_policy"),
            top_k=self.get("top_k"),
            verbosity=self.get("verbosity"),
            categorical_features=tuple(self.get("categorical_slot_indexes") or ()),
            boosting_type=self.get("boosting_type"),
            delegate=self.get("delegate"),
            drop_rate=self.get("drop_rate"),
            max_drop=self.get("max_drop"),
            skip_drop=self.get("skip_drop"),
            top_rate=self.get("top_rate"),
            other_rate=self.get("other_rate"),
            eval_at=self.get("eval_at"),
            alpha=self.get("alpha"),
            tweedie_variance_power=self.get("tweedie_variance_power"),
            poisson_max_delta_step=self.get("poisson_max_delta_step"),
            fair_c=self.get("fair_c"),
        )

    def _gather(self, df: DataFrame) -> dict:
        out = {
            "x": df[self.get("features_col")].astype(np.float32),
            "y": df[self.get("label_col")].astype(np.float64),
        }
        wc = self.get("weight_col")
        out["w"] = df[wc].astype(np.float32) if wc else None
        vc = self.get("validation_indicator_col")
        out["valid"] = df[vc].astype(bool) if vc else None
        ic = self.get("init_score_col")
        out["init"] = df[ic].astype(np.float32) if ic else None
        return out

    def _init_booster(self) -> Optional[Booster]:
        s = self.get("model_string")
        return Booster.from_model_string(s) if s else None

    def _fit_batches(
        self, data: dict, make_cfg: Any, base_score: Any = 0.0, **kw: Any
    ) -> Booster:
        """numBatches semantics (LightGBMBase.scala:29-50): split rows into
        k sequential batches, fold the previous booster into each.

        ``base_score`` applies only to the first training segment (later
        segments continue from a booster whose predictions include it)."""
        nb = self.get("num_batches")
        booster = self._init_booster()
        delegate = self.get("delegate")
        if not (nb and nb > 1):
            kw.setdefault("checkpoint_dir", self.get("checkpoint_dir") or None)
            kw.setdefault("checkpoint_every", self.get("checkpoint_every"))
            kw.setdefault("resume_from", self.get("resume_from") or None)
        elif self.get("checkpoint_dir") or self.get("resume_from"):
            # refuse rather than silently train unprotected: numBatches
            # folds k train() calls whose round indices would collide in
            # one checkpoint directory
            raise ValueError(
                "checkpoint_dir/resume_from are incompatible with "
                "num_batches > 1 (per-segment round indices would collide "
                "in one checkpoint directory)"
            )
        kw.setdefault("fused_rounds", self.get("fused_rounds"))
        if nb and nb > 1:
            n = len(data["y"])
            bounds = np.linspace(0, n, nb + 1).astype(int)
            for i in range(nb):
                sl = slice(bounds[i], bounds[i + 1])
                kw_sl = {
                    k: (v[sl] if isinstance(v, np.ndarray) else v) for k, v in kw.items()
                }
                if delegate is not None:
                    delegate.before_train_batch(i, bounds[i + 1] - bounds[i], booster)
                booster = train(
                    data["x"][sl],
                    data["y"][sl],
                    make_cfg(),
                    sample_weight=None if data["w"] is None else data["w"][sl],
                    init_score=None if data["init"] is None else data["init"][sl],
                    valid_mask=None if data["valid"] is None else data["valid"][sl],
                    init_booster=booster,
                    base_score=0.0 if booster is not None else base_score,
                    **kw_sl,
                )
                if delegate is not None:
                    delegate.after_train_batch(i, booster)
            return booster
        return train(
            data["x"],
            data["y"],
            make_cfg(),
            sample_weight=data["w"],
            init_score=data["init"],
            valid_mask=data["valid"],
            init_booster=booster,
            base_score=0.0 if booster is not None else base_score,
            **kw,
        )


class _NativeModelIO:
    """Native LightGBM model interop on every model facade — the
    reference's saveNativeModel / loadNativeModelFromFile / ...FromString
    (lightgbm/LightGBMClassifier.scala). ``model_string`` transparently
    accepts BOTH our JSON format and LightGBM's text format, so a model
    trained with the reference (or python lightgbm) drops straight in."""

    def save_native_model(self, path: str) -> None:
        """Write the booster in LightGBM's own text format."""
        with open(path, "w") as f:
            f.write(self.booster.to_lightgbm_string())

    @classmethod
    def load_native_model_from_string(cls, text: str, **kw: Any):
        m = cls(**kw)
        m.set(model_string=text)
        m.booster  # parse eagerly: malformed input fails here, not at transform
        return m

    @classmethod
    def load_native_model_from_file(cls, path: str, **kw: Any):
        with open(path) as f:
            return cls.load_native_model_from_string(f.read(), **kw)


class LightGBMClassifier(Estimator, _LightGBMParams, HasProbabilityCol, HasRawPredictionCol, HasPredictionCol):
    objective = Param("binary | multiclass", default="binary", type_=str)

    def fit(self, df: DataFrame) -> "LightGBMClassificationModel":
        data = self._gather(df)
        y = data["y"].astype(np.int64)
        n_classes = int(y.max()) + 1 if len(y) else 2
        objective = self.get("objective")
        if objective == "binary" and n_classes > 2:
            objective = "multiclass"
        num_class = n_classes if objective == "multiclass" else 1
        data["y"] = y.astype(np.float64)
        base: Any = 0.0
        if self.get("boost_from_average") and data["init"] is None and len(y):
            if objective == "binary":
                p = float(np.clip(y.mean(), 1e-6, 1 - 1e-6))
                base = float(np.log(p / (1 - p)))
            else:  # multiclass: per-class log prior
                priors = np.bincount(y, minlength=num_class) / len(y)
                base = np.log(np.clip(priors, 1e-6, None)).astype(np.float32)
        booster = self._fit_batches(
            data, lambda: self._config(objective, num_class), base_score=base
        )
        m = LightGBMClassificationModel(
            features_col=self.get("features_col"),
            prediction_col=self.get("prediction_col"),
            probability_col=self.get("probability_col"),
            raw_prediction_col=self.get("raw_prediction_col"),
        )
        m.set(model_string=booster.to_model_string())
        return m


def _booster_raw_device_fn(booster: Any, features_col: str, raw_key: str) -> Any:
    """Jit-traceable ``cols -> {raw_key: predict_raw(x)}`` bit-matching the
    host :meth:`Booster.predict_raw` for the pipeline compiler.

    The staged path already runs the tree traversal on device
    (``treegrow.predict_leaves``) — here the same program is traced into
    the fused segment (integer leaf outputs are exact under any lowering),
    the leaf-value gather is pure selection, and the cross-tree float32
    reduction uses :func:`~mmlspark_tpu.compiler.kernels.pairwise_sum`,
    which reproduces ``np.sum``'s association order so the device total is
    bit-equal to the host's. Returns None for an empty booster (host path
    covers the broadcast-base degenerate case).
    """
    from mmlspark_tpu.compiler.kernels import pairwise_sum
    from mmlspark_tpu.models.gbdt import treegrow
    from mmlspark_tpu.models.gbdt.booster import _stack_trees

    trees = booster.trees
    if booster.best_iteration > 0:
        trees = trees[: booster.best_iteration * booster.num_class]
    if not trees:
        return None
    stacked = _stack_trees(trees)
    (rec_leaf, rec_feature, rec_threshold, rec_active, values, is_cat,
     catmask, default_left) = stacked
    k = booster.num_class
    T = len(trees)
    denom = float((T // k) if booster.boosting_type == "rf" else 1)
    base = np.asarray(booster.base_score, np.float32)

    def fn(cols: dict) -> dict:
        import jax.numpy as jnp

        x = cols[features_col].astype(jnp.float32)
        leaves = treegrow.predict_leaves(
            x,
            jnp.asarray(rec_leaf),
            jnp.asarray(rec_feature),
            jnp.asarray(rec_threshold),
            jnp.asarray(rec_active),
            jnp.asarray(is_cat) if is_cat is not None else None,
            jnp.asarray(catmask) if catmask is not None else None,
            jnp.asarray(default_left) if default_left is not None else None,
        )  # (n, T) int32 — exact
        vals = jnp.asarray(values)  # (T, L)
        per_tree = vals[jnp.arange(T)[None, :], leaves]  # (n, T) gather
        d = jnp.float32(denom)
        b = jnp.asarray(base)
        if k == 1:
            raw = pairwise_sum(per_tree) / d + b
        else:
            raw = jnp.stack(
                [pairwise_sum(per_tree[:, c::k]) / d for c in range(k)],
                axis=1,
            ) + b
        return {raw_key: raw}

    return fn


class LightGBMClassificationModel(
    Model, _NativeModelIO, HasFeaturesCol, HasPredictionCol, HasProbabilityCol, HasRawPredictionCol
):
    model_string = Param("serialized booster", default="", type_=str)

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        self._booster: Optional[Booster] = None
        self._booster_src: Optional[str] = None

    @property
    def booster(self) -> Booster:
        s = self.get_or_fail("model_string")
        if self._booster is None or self._booster_src != s:
            self._booster = Booster.from_model_string(s)
            self._booster_src = s
        return self._booster

    def transform(self, df: DataFrame) -> DataFrame:
        booster = self.booster

        def fn(p: Partition) -> Partition:
            x = np.asarray(p[self.get("features_col")], np.float32)
            raw = booster.predict_raw(x)
            q = dict(p)
            if booster.num_class == 1:
                # imported models may carry a non-default sigmoid slope
                # ("binary sigmoid:s"): p = sigmoid(s * score)
                probs1 = objectives.sigmoid(booster.sigmoid * raw)
                probs = np.stack([1 - probs1, probs1], axis=1)
                raw2 = np.stack([-raw, raw], axis=1)
            else:
                probs = objectives.softmax(raw)
                raw2 = raw
            q[self.get("raw_prediction_col")] = raw2.astype(np.float64)
            q[self.get("probability_col")] = probs.astype(np.float64)
            q[self.get("prediction_col")] = probs.argmax(axis=1).astype(np.float64)
            return q

        return df.map_partitions(fn, parallel=False)

    def fusable_kernel(self) -> Any:
        """Device traversal + gather + numpy-order summed scores in the
        fused program; the sigmoid/softmax/argmax/float64 epilogue replays
        the exact staged numpy code as a host ``finalize`` (libm ``exp``
        has no bit-equal device twin with x64 off)."""
        from mmlspark_tpu.compiler.kernels import StageKernel, guard_f32_safe

        booster = self.booster
        fc = self.get("features_col")
        raw_c = self.get("raw_prediction_col")
        prob_c = self.get("probability_col")
        pred_c = self.get("prediction_col")
        raw_key = f"__device_raw__{raw_c}"
        fn = _booster_raw_device_fn(booster, fc, raw_key)
        if fn is None:
            return None

        def finalize(host: dict) -> dict:
            raw = host[raw_key]
            if booster.num_class == 1:
                probs1 = objectives.sigmoid(booster.sigmoid * raw)
                probs = np.stack([1 - probs1, probs1], axis=1)
                raw2 = np.stack([-raw, raw], axis=1)
            else:
                probs = objectives.softmax(raw)
                raw2 = raw
            return {
                raw_c: raw2.astype(np.float64),
                prob_c: probs.astype(np.float64),
                pred_c: probs.argmax(axis=1).astype(np.float64),
            }

        return StageKernel(
            reads=(fc,),
            writes=(raw_c, prob_c, pred_c),
            fn=fn,
            guard=guard_f32_safe,
            finalize=finalize,
            device_writes=(raw_key,),
            cost_hint=1.0 + len(booster.trees) / 100.0,
        )

    def predict_leaf(self, x: np.ndarray) -> np.ndarray:
        return self.booster.predict_leaf(np.asarray(x, np.float32))

    def features_shap(self, x: np.ndarray, approximate: bool = False) -> np.ndarray:
        """Exact TreeSHAP by default; ``approximate=True`` = the vectorized
        Saabas walk (orders of magnitude faster on large batches)."""
        return self.booster.feature_contribs(
            np.asarray(x, np.float32), approximate=approximate
        )

    def get_feature_importances(self, importance_type: str = "split") -> np.ndarray:
        return self.booster.feature_importances(importance_type)


class LightGBMRegressor(Estimator, _LightGBMParams, HasPredictionCol):
    objective = Param(
        "regression | regression_l1 | quantile | huber | fair | poisson | "
        "tweedie | gamma | mape (LightGBM objective passthrough, "
        "TrainParams.scala:8-40)",
        default="regression", type_=str,
    )

    def fit(self, df: DataFrame) -> "LightGBMRegressionModel":
        data = self._gather(df)
        obj = objectives.canonical_objective(self.get("objective"))
        base = 0.0
        y = data["y"]
        if self.get("boost_from_average") and data["init"] is None and len(y):
            # LightGBM's BoostFromScore per objective family: log-link
            # objectives start at log(mean) (scores live in log space),
            # quantile at the alpha-percentile, l1/mape at the median
            if obj in objectives.LOG_LINK_KINDS:
                base = float(np.log(np.clip(y.mean(), 1e-9, None)))
            elif obj == "quantile":
                base = float(np.percentile(y, self.get("alpha") * 100.0))
            elif obj in ("regression_l1", "mape"):
                base = float(np.median(y))
            else:
                base = float(y.mean())
        booster = self._fit_batches(
            data, lambda: self._config(obj), base_score=base
        )
        m = LightGBMRegressionModel(
            features_col=self.get("features_col"),
            prediction_col=self.get("prediction_col"),
        )
        m.set(model_string=booster.to_model_string())
        return m


class LightGBMRegressionModel(Model, _NativeModelIO, HasFeaturesCol, HasPredictionCol):
    model_string = Param("serialized booster", default="", type_=str)

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        self._booster: Optional[Booster] = None
        self._booster_src: Optional[str] = None

    @property
    def booster(self) -> Booster:
        s = self.get_or_fail("model_string")
        if self._booster is None or self._booster_src != s:
            self._booster = Booster.from_model_string(s)
            self._booster_src = s
        return self._booster

    def transform(self, df: DataFrame) -> DataFrame:
        booster = self.booster
        fc = self.get("features_col")
        return df.with_column(
            self.get("prediction_col"),
            lambda p: booster.predict(np.asarray(p[fc], np.float32)).astype(np.float64),
        )

    def fusable_kernel(self) -> Any:
        """Like the classifier's kernel: scores on device, the objective's
        output transform (log-link ``np.exp``) + float64 cast on host."""
        from mmlspark_tpu.compiler.kernels import StageKernel, guard_f32_safe

        booster = self.booster
        fc = self.get("features_col")
        pred_c = self.get("prediction_col")
        raw_key = f"__device_raw__{pred_c}"
        fn = _booster_raw_device_fn(booster, fc, raw_key)
        if fn is None:
            return None

        def finalize(host: dict) -> dict:
            raw = host[raw_key]
            if booster.objective in objectives.LOG_LINK_KINDS:
                raw = np.exp(raw)
            return {pred_c: raw.astype(np.float64)}

        return StageKernel(
            reads=(fc,),
            writes=(pred_c,),
            fn=fn,
            guard=guard_f32_safe,
            finalize=finalize,
            device_writes=(raw_key,),
            cost_hint=1.0 + len(booster.trees) / 100.0,
        )

    def features_shap(self, x: np.ndarray, approximate: bool = False) -> np.ndarray:
        """Exact TreeSHAP by default; ``approximate=True`` = the vectorized
        Saabas walk (orders of magnitude faster on large batches)."""
        return self.booster.feature_contribs(
            np.asarray(x, np.float32), approximate=approximate
        )


class LightGBMRanker(Estimator, _LightGBMParams, HasGroupCol, HasPredictionCol):
    objective = Param("lambdarank", default="lambdarank", type_=str)
    evaluate_at = Param("NDCG truncation positions", default=[1, 3, 5, 10], type_=list)

    def fit(self, df: DataFrame) -> "LightGBMRankerModel":
        gc = self.get("group_col")
        if not gc:
            raise ValueError("LightGBMRanker requires group_col (query column)")
        data = self._gather(df)
        groups_raw = df[gc]
        _, group_ids = np.unique(
            groups_raw.astype(str) if groups_raw.dtype == object else groups_raw,
            return_inverse=True,
        )
        booster = self._fit_batches(
            data, lambda: self._config("lambdarank"), group_ids=group_ids
        )
        m = LightGBMRankerModel(
            features_col=self.get("features_col"),
            prediction_col=self.get("prediction_col"),
        )
        m.set(model_string=booster.to_model_string())
        return m


class LightGBMRankerModel(Model, _NativeModelIO, HasFeaturesCol, HasPredictionCol):
    model_string = Param("serialized booster", default="", type_=str)

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        self._booster: Optional[Booster] = None
        self._booster_src: Optional[str] = None

    @property
    def booster(self) -> Booster:
        s = self.get_or_fail("model_string")
        if self._booster is None or self._booster_src != s:
            self._booster = Booster.from_model_string(s)
            self._booster_src = s
        return self._booster

    def transform(self, df: DataFrame) -> DataFrame:
        booster = self.booster
        fc = self.get("features_col")
        return df.with_column(
            self.get("prediction_col"),
            lambda p: booster.predict_raw(np.asarray(p[fc], np.float32)).astype(np.float64),
        )
