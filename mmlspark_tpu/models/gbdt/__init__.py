from mmlspark_tpu.models.gbdt.binning import BinMapper, BinnedDataset
from mmlspark_tpu.models.gbdt.sketch import QuantileSketch
from mmlspark_tpu.models.gbdt.booster import Booster, Tree
from mmlspark_tpu.models.gbdt.checkpoint import (
    TrainCheckpoint,
    load_checkpoint,
    save_checkpoint,
)
from mmlspark_tpu.models.gbdt.delegate import LightGBMDelegate
from mmlspark_tpu.models.gbdt.train import TrainConfig, train
from mmlspark_tpu.models.gbdt.estimators import (
    LightGBMClassificationModel,
    LightGBMClassifier,
    LightGBMRanker,
    LightGBMRankerModel,
    LightGBMRegressionModel,
    LightGBMRegressor,
)

__all__ = [
    "BinMapper",
    "BinnedDataset",
    "QuantileSketch",
    "Booster",
    "Tree",
    "LightGBMDelegate",
    "TrainConfig",
    "train",
    "TrainCheckpoint",
    "save_checkpoint",
    "load_checkpoint",
    "LightGBMClassifier",
    "LightGBMClassificationModel",
    "LightGBMRegressor",
    "LightGBMRegressionModel",
    "LightGBMRanker",
    "LightGBMRankerModel",
]
