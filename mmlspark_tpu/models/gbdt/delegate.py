"""Training-lifecycle callback interface — the LightGBMDelegate analogue.

The reference exposes a delegate trait whose hooks fire around batches and
iterations and can rewrite the learning rate mid-training
(lightgbm/LightGBMDelegate.scala, called from TrainUtils.scala:192-218).
Here the same surface, minus the Spark/JNI plumbing: hooks receive plain
Python state. Set it on the estimator (``delegate=...``) or on
``TrainConfig.delegate``.
"""

from __future__ import annotations

from typing import Any, Optional


class LightGBMDelegate:
    """Override any subset; defaults are no-ops (trait parity)."""

    def before_train_batch(
        self, batch_index: int, n_rows: int, previous_booster: Optional[Any]
    ) -> None:
        """numBatches mode: fires before each sequential batch segment."""

    def after_train_batch(self, batch_index: int, booster: Any) -> None:
        """numBatches mode: fires after each segment with its booster."""

    def before_train_iteration(self, iteration: int) -> None:
        """Fires before each boosting iteration."""

    def after_train_iteration(
        self,
        iteration: int,
        eval_result: Optional[tuple],
        is_finished: bool,
    ) -> None:
        """Fires after each iteration. ``eval_result`` is the
        (metric_name, value, higher_is_better) triple when validation ran
        this round, else None; ``is_finished`` is True on the final
        iteration (early stop or last round)."""

    def get_learning_rate(self, iteration: int, previous_rate: float) -> float:
        """Dynamic learning rate: the returned value drives this
        iteration's tree (dynamic-rate delegate semantics). The default
        keeps the configured rate."""
        return previous_rate
