"""Booster: host-side model container for the TPU GBDT.

Plays the role of the reference's ``LightGBMBooster`` serializable model
string + scoring entry points (lightgbm/LightGBMBooster.scala:37-128):
- ``to_model_string``/``from_model_string`` — text round-trip (JSON here,
  LightGBM's own text format there)
- ``merge`` — continued-training semantics (LGBM_BoosterMerge,
  TrainUtils.scala:157-174)
- ``predict_raw`` / ``predict_leaf`` / ``feature_contribs`` (the
  featuresShap analogue: EXACT TreeSHAP by default via treeshap.py, with
  ``approximate=True`` selecting the fast vectorized Saabas walk)
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from mmlspark_tpu.models.gbdt import treegrow


@dataclass
class Tree:
    leaf: np.ndarray        # (S,) int32 parent leaf per split (-1 inactive)
    feature: np.ndarray     # (S,) int32
    threshold: np.ndarray   # (S,) float64 real-valued, <= goes left
    active: np.ndarray      # (S,) bool
    gain: np.ndarray        # (S,) float32
    values: np.ndarray      # (L,) float32
    counts: np.ndarray      # (L,) int32
    # categorical subset splits (LightGBM cat_threshold analogue): for split
    # k with is_cat[k], routing is by membership — category value v (bin
    # v+1) goes LEFT iff catmask[k, v+1]. None = all-numerical tree.
    is_cat: Optional[np.ndarray] = None     # (S,) bool
    catmask: Optional[np.ndarray] = None    # (S, B) bool
    # per-split missing-value direction (LightGBM decision_type default-left
    # bit): NaN routes LEFT iff default_left[k]. None = all left (the
    # native trainer's convention; imports may carry default-right splits)
    default_left: Optional[np.ndarray] = None  # (S,) bool

    @property
    def num_splits(self) -> int:
        return int(self.active.sum())

    @property
    def has_categorical(self) -> bool:
        return self.is_cat is not None and bool(np.any(self.is_cat))

    def to_dict(self) -> dict:
        # non-finite thresholds are meaningful (+inf: inactive/"all left",
        # -inf: split on the missing bin) — keep their signs through JSON
        def enc(t: float):
            if np.isfinite(t):
                return float(t)
            return "inf" if t > 0 else "-inf"

        out = {
            "leaf": self.leaf.tolist(),
            "feature": self.feature.tolist(),
            "threshold": [enc(t) for t in self.threshold],
            "active": self.active.astype(int).tolist(),
            "gain": np.asarray(self.gain, dtype=np.float64).tolist(),
            "values": np.asarray(self.values, dtype=np.float64).tolist(),
            "counts": self.counts.tolist(),
        }
        if self.has_categorical:
            # compact: only active categorical splits, as left-bin id lists
            out["cat_splits"] = {
                str(k): np.flatnonzero(self.catmask[k]).tolist()
                for k in np.flatnonzero(self.is_cat)
            }
        if self.default_left is not None and not self.default_left.all():
            # compact: only the default-RIGHT split ids (rare; import-only)
            out["default_right"] = np.flatnonzero(~self.default_left).tolist()
        return out

    @staticmethod
    def from_dict(d: dict) -> "Tree":
        def dec(t) -> float:
            if t is None or t == "inf":
                return np.inf
            if t == "-inf":
                return -np.inf
            return float(t)

        thr = np.array([dec(t) for t in d["threshold"]], dtype=np.float64)
        default_left = None
        if d.get("default_right"):
            default_left = np.ones(len(d["leaf"]), bool)
            default_left[np.asarray(d["default_right"], np.int64)] = False
        is_cat = catmask = None
        if d.get("cat_splits"):
            from mmlspark_tpu.ops.histogram import NUM_BINS

            S = len(d["leaf"])
            is_cat = np.zeros(S, bool)
            catmask = np.zeros((S, NUM_BINS), bool)
            for k_str, left_bins in d["cat_splits"].items():
                k = int(k_str)
                is_cat[k] = True
                catmask[k, np.asarray(left_bins, np.int64)] = True
        return Tree(
            leaf=np.asarray(d["leaf"], np.int32),
            feature=np.asarray(d["feature"], np.int32),
            threshold=thr,
            active=np.asarray(d["active"], bool),
            gain=np.asarray(d["gain"], np.float32),
            values=np.asarray(d["values"], np.float32),
            counts=np.asarray(d["counts"], np.int32),
            is_cat=is_cat,
            catmask=catmask,
            default_left=default_left,
        )


@dataclass
class Booster:
    trees: list = field(default_factory=list)  # flat; class of tree t = t % num_class
    objective: str = "binary"
    num_class: int = 1
    num_features: int = 0
    best_iteration: int = -1
    feature_names: Optional[list] = None
    # boost_from_average baseline added to every raw score: float, or a
    # per-class list for multiclass (LightGBM's init score from label avg)
    base_score: Any = 0.0
    # gbdt|goss|dart|rf — rf predictions AVERAGE trees instead of summing
    # (LightGBM boostingType, lightgbm/LightGBMParams.scala)
    boosting_type: str = "gbdt"
    # binary sigmoid slope: p = sigmoid(sigmoid * score). Trained models use
    # 1.0; imported LightGBM models may carry e.g. "binary sigmoid:2"
    sigmoid: float = 1.0
    # regression-objective knob round-tripped through model text (quantile/
    # huber alpha, tweedie variance power, fair c); None = objective default
    objective_param: Optional[float] = None

    # -- serialization ------------------------------------------------------

    def to_model_string(self) -> str:
        return json.dumps(
            {
                "format": "mmlspark_tpu_gbdt_v1",
                "objective": self.objective,
                "num_class": self.num_class,
                "num_features": self.num_features,
                "best_iteration": self.best_iteration,
                "feature_names": self.feature_names,
                "base_score": (
                    self.base_score.tolist()
                    if isinstance(self.base_score, np.ndarray)
                    else self.base_score
                ),
                "boosting_type": self.boosting_type,
                "sigmoid": self.sigmoid,
                "objective_param": self.objective_param,
                "trees": [t.to_dict() for t in self.trees],
            }
        )

    @staticmethod
    def from_model_string(s: str) -> "Booster":
        if not s.lstrip().startswith("{"):
            # LightGBM's own text format (starts with the "tree" section):
            # accept it transparently so reference-trained models load
            return Booster.from_lightgbm_string(s)
        d = json.loads(s)
        b = Booster(
            trees=[Tree.from_dict(t) for t in d["trees"]],
            objective=d["objective"],
            num_class=d["num_class"],
            num_features=d["num_features"],
            best_iteration=d.get("best_iteration", -1),
            feature_names=d.get("feature_names"),
            base_score=d.get("base_score", 0.0),
            boosting_type=d.get("boosting_type", "gbdt"),
            sigmoid=d.get("sigmoid", 1.0),
            objective_param=d.get("objective_param"),
        )
        return b

    def to_lightgbm_string(self) -> str:
        """Serialize in LightGBM's native text format (saveNativeModel
        analogue, LightGBMBooster.scala) — loadable by python ``lightgbm``,
        the CLI, and the reference."""
        from mmlspark_tpu.models.gbdt.lgbm_format import to_lightgbm_string

        return to_lightgbm_string(self)

    @staticmethod
    def from_lightgbm_string(s: str) -> "Booster":
        """Parse a native LightGBM text model (loadNativeModelFromString
        analogue) — models trained with the reference carry over."""
        from mmlspark_tpu.models.gbdt.lgbm_format import from_lightgbm_string

        return from_lightgbm_string(s)

    def merge(self, other: "Booster") -> "Booster":
        """Continued training: append other's trees (BoosterMerge analogue)."""
        assert self.num_class == other.num_class, "class-count mismatch in merge"
        return Booster(
            trees=self.trees + other.trees,
            objective=other.objective,
            num_class=self.num_class,
            num_features=max(self.num_features, other.num_features),
            feature_names=self.feature_names or other.feature_names,
            # continued training fit residuals on top of self's predictions,
            # which already include self's baseline — keep it
            base_score=self.base_score,
            boosting_type=self.boosting_type,
            # imported prediction semantics ride the ORIGINAL model
            sigmoid=self.sigmoid,
            objective_param=(
                self.objective_param
                if self.objective_param is not None
                else other.objective_param
            ),
        )

    # -- device scoring ------------------------------------------------------

    def predict_raw(self, x: np.ndarray, num_iteration: Optional[int] = None) -> np.ndarray:
        """(n, d) -> (n,) raw scores (binary/regression) or (n, k) multiclass."""
        n = x.shape[0]
        if num_iteration is None and self.best_iteration > 0:
            num_iteration = self.best_iteration
        trees = self.trees[: num_iteration * self.num_class] if num_iteration else self.trees
        k = self.num_class
        base = np.asarray(self.base_score, np.float32)
        if not trees:
            return np.broadcast_to(
                base, (n,) if k == 1 else (n, k)
            ).astype(np.float32).copy()
        per_tree = per_tree_raw(trees, x)  # (n, T)
        T = per_tree.shape[1]
        # rf averages the forest; boosting sums it
        denom = (T // k) if self.boosting_type == "rf" else 1
        if k == 1:
            return (per_tree.sum(axis=1) / denom + base).astype(np.float32)
        out = np.zeros((n, k), np.float32)
        for c in range(k):
            out[:, c] = per_tree[:, c::k].sum(axis=1) / denom
        return out + base

    def predict(self, x: np.ndarray, num_iteration: Optional[int] = None) -> np.ndarray:
        """Raw scores through the objective's output transform: log-link
        objectives (poisson/tweedie/gamma) train in log space and predict
        exp(score) (LightGBM's convert_output); everything else is raw."""
        from mmlspark_tpu.models.gbdt.objectives import LOG_LINK_KINDS

        raw = self.predict_raw(x, num_iteration=num_iteration)
        if self.objective in LOG_LINK_KINDS:
            return np.exp(raw)
        return raw

    def predict_leaf(self, x: np.ndarray) -> np.ndarray:
        """(n, d) -> (n, T) leaf index per tree (predictLeaf analogue)."""
        if not self.trees:
            return np.zeros((x.shape[0], 0), np.int32)
        return tree_leaves(self.trees, x)

    def feature_contribs(
        self,
        x: np.ndarray,
        approximate: bool = False,
        num_iteration: Optional[int] = None,
    ) -> np.ndarray:
        """Per-feature contributions (n, d+1), last column = expected value.

        Default is EXACT TreeSHAP (treeshap.py — the reference surfaces
        LightGBM's exact ``featuresShap``); ``approximate=True`` switches
        to the fast Saabas walk (the change in subtree expectation at each
        split credited to its feature — TreeSHAP's first-order
        approximation). Both satisfy sum(contribs) == raw score, including
        under rf averaging and best-iteration truncation (Shapley values
        are linear in the ensemble, so the same denominator/prefix
        predict_raw applies transfers to each tree's contributions)."""
        n, d = x.shape
        if num_iteration is None and self.best_iteration > 0:
            num_iteration = self.best_iteration
        trees = self.trees[: num_iteration * self.num_class] if num_iteration else self.trees
        out = np.zeros((n, d + 1), np.float64)
        out[:, d] += float(np.sum(np.asarray(self.base_score)))
        scale = 1.0
        if self.boosting_type == "rf" and trees:
            scale = 1.0 / (len(trees) // self.num_class)
        if approximate:
            for tree in trees:
                out += scale * _tree_contribs(tree, x)
            return out
        from mmlspark_tpu.models.gbdt.treeshap import shap_values

        for tree in trees:
            out += scale * shap_values(tree, x)
        return out

    def feature_importances(self, importance_type: str = "split") -> np.ndarray:
        imp = np.zeros(max(self.num_features, 1), np.float64)
        for t in self.trees:
            for s in range(len(t.leaf)):
                if t.active[s]:
                    f = int(t.feature[s])
                    imp[f] += 1.0 if importance_type == "split" else float(t.gain[s])
        return imp

    def dump_model(self) -> dict:
        return json.loads(self.to_model_string())


def _stack_trees(trees: list) -> Optional[tuple]:
    """Pad a tree list to common split/leaf counts for the batched device
    traversal (treegrow.predict_leaves evaluates all trees in one program)."""
    if not trees:
        return None
    S = max(len(t.leaf) for t in trees)
    L = max(len(t.values) for t in trees)
    T = len(trees)

    def pad(a: np.ndarray, n: int, fill: Any) -> np.ndarray:
        out = np.full((n,), fill, dtype=a.dtype)
        out[: len(a)] = a
        return out

    rec_leaf = np.stack([pad(t.leaf, S, -1) for t in trees])
    rec_feature = np.stack([pad(np.clip(t.feature, 0, None), S, 0) for t in trees])
    rec_threshold = np.stack(
        [pad(t.threshold.astype(np.float32), S, np.float32(np.inf)) for t in trees]
    )
    rec_active = np.stack([pad(t.active, S, False) for t in trees])
    values = np.stack([pad(t.values, L, np.float32(0)) for t in trees])
    rec_default_left = None
    if any(
        t.default_left is not None and not np.asarray(t.default_left).all()
        for t in trees
    ):
        rec_default_left = np.ones((T, S), bool)
        for i, t in enumerate(trees):
            if t.default_left is not None:
                rec_default_left[i, : len(t.default_left)] = t.default_left
    rec_is_cat = rec_catmask = None
    if any(t.has_categorical for t in trees):
        from mmlspark_tpu.ops.histogram import NUM_BINS

        rec_is_cat = np.zeros((T, S), bool)
        rec_catmask = np.zeros((T, S, NUM_BINS), bool)
        for i, t in enumerate(trees):
            if t.is_cat is not None:
                rec_is_cat[i, : len(t.is_cat)] = t.is_cat
                rec_catmask[i, : t.catmask.shape[0]] = t.catmask
    return (
        rec_leaf, rec_feature, rec_threshold, rec_active, values,
        rec_is_cat, rec_catmask, rec_default_left,
    )


def _leaves_from_stacked(stacked: tuple, x: np.ndarray) -> np.ndarray:
    import jax.numpy as jnp

    (rec_leaf, rec_feature, rec_threshold, rec_active, _, is_cat, catmask,
     default_left) = stacked
    return np.asarray(
        treegrow.predict_leaves(
            jnp.asarray(x, jnp.float32),
            jnp.asarray(rec_leaf),
            jnp.asarray(rec_feature),
            jnp.asarray(rec_threshold),
            jnp.asarray(rec_active),
            jnp.asarray(is_cat) if is_cat is not None else None,
            jnp.asarray(catmask) if catmask is not None else None,
            jnp.asarray(default_left) if default_left is not None else None,
        )
    )


def tree_leaves(trees: list, x: np.ndarray) -> np.ndarray:
    """(n, T) leaf index per tree: the single batched device traversal every
    scoring entry point shares."""
    stacked = _stack_trees(trees)
    if stacked is None:
        return np.zeros((x.shape[0], 0), np.int32)
    return _leaves_from_stacked(stacked, x)


def per_tree_raw(trees: list, x: np.ndarray) -> np.ndarray:
    """(n, T) raw contribution of each tree (device traversal + gather)."""
    stacked = _stack_trees(trees)
    if stacked is None:
        return np.zeros((x.shape[0], 0), np.float32)
    values = stacked[4]  # (T, L) padded leaf values from the same stacking
    leaves = _leaves_from_stacked(stacked, x)  # (n, T)
    return np.take_along_axis(values[None], leaves[..., None], axis=2)[..., 0]


def _tree_contribs(tree: Tree, x: np.ndarray) -> np.ndarray:
    """Saabas contributions for one tree via split replay."""
    n, d = x.shape
    S = len(tree.leaf)
    L = len(tree.values)

    # expected value of every intermediate "leaf state" during replay:
    # replay k: leaf set grows; E[node] = weighted mean of final leaf values
    # reachable from it. Reconstruct reachability by running the replay on
    # leaf ids symbolically.
    # final leaves reachable from state (step k, leaf id l): determined by
    # future splits; compute bottom-up over steps.
    counts = tree.counts.astype(np.float64)
    values = tree.values.astype(np.float64)
    # weighted sums per leaf id, evolved backwards through splits
    wsum = values * counts
    csum = counts.copy()
    # expectation table per step: exp_before[k][l] = E[value | at leaf l
    # just before split k executes]. Build backwards.
    exp_steps = np.zeros((S + 1, L), np.float64)
    with np.errstate(invalid="ignore", divide="ignore"):
        exp_steps[S] = np.where(csum > 0, wsum / csum, 0.0)
    ws, cs = wsum.copy(), csum.copy()
    for k in range(S - 1, -1, -1):
        if tree.active[k]:
            parent = int(tree.leaf[k])
            right = k + 1
            ws[parent] = ws[parent] + ws[right]
            cs[parent] = cs[parent] + cs[right]
        with np.errstate(invalid="ignore", divide="ignore"):
            exp_steps[k] = np.where(cs > 0, ws / cs, 0.0)

    row_leaf = np.zeros(n, np.int64)
    out = np.zeros((n, d + 1), np.float64)
    out[:, d] = exp_steps[0][0]  # base expected value
    for k in range(S):
        if not tree.active[k]:
            continue
        parent = int(tree.leaf[k])
        f = int(tree.feature[k])
        thr = tree.threshold[k]
        in_leaf = row_leaf == parent
        vals = x[:, f]
        if tree.is_cat is not None and tree.is_cat[k]:
            # categorical subset routing: the shared value->bin encoding
            # (treegrow.category_bin_slot), membership in the left set
            vbin = treegrow.category_bin_slot(vals, tree.catmask.shape[1], np)
            goes_right = in_leaf & ~tree.catmask[k][vbin]
        else:
            nan_right = not (
                tree.default_left is None or bool(tree.default_left[k])
            )
            goes_right = in_leaf & np.where(
                np.isnan(vals), nan_right, vals > thr
            )
        stays_left = in_leaf & ~goes_right
        before = exp_steps[k][parent]
        # after this split the row is at (parent|right); its new expectation
        # is exp of that node at step k+1
        out[goes_right, f] += exp_steps[k + 1][k + 1] - before
        out[stays_left, f] += exp_steps[k + 1][parent] - before
        row_leaf[goes_right] = k + 1
    return out
