"""GBDT training loop.

The analogue of lightgbm/TrainUtils.scala's ``trainCore`` iteration loop
(:220-315): per boosting iteration compute grad/hess from current scores,
grow one tree per class (the compiled ``grow_tree`` program — histogram +
split search + partition assignment all on device), update scores from the
grower's own row->leaf output (free, no re-predict), evaluate + early-stop.

Boosting modes (``boostingType`` in lightgbm/LightGBMParams.scala, golden
matrix src/test/resources/benchmarks/benchmarks_VerifyLightGBMClassifier.csv):
- ``gbdt``  — plain gradient boosting.
- ``goss``  — gradient-based one-side sampling: keep the top ``top_rate``
  fraction of rows by |gradient|, sample ``other_rate`` of the rest and
  amplify their weight by (1-a)/b so histogram sums stay unbiased.
- ``dart``  — per iteration (unless ``skip_drop`` fires) drop a random
  subset of past iterations, fit the new tree against the scores without
  them, then normalize: new tree x 1/(k+1), dropped trees x k/(k+1).
- ``rf``    — random forest: constant gradients at the initial score,
  bagging per iteration, no shrinkage; prediction averages trees.

Device residency: scores, gradients, labels and bagging/GOSS masks live on
device (sharded over the mesh ``data`` axis) across all iterations — the
host sees only the per-tree split records and the eval-metric scalar
(lightgbm/TrainUtils.scala:220-315 keeps the equivalent state inside the
native booster for the same reason). LambdaRank is the exception: its
group-sorted pairwise gradients run on host, so scores round-trip per
iteration on that objective only.

Distribution: rows are batch-sharded over the mesh ``data`` axis before the
loop. ``data_parallel`` lets GSPMD partition the histogram scatter and
insert the full-plane ICI allreduce; ``voting_parallel`` switches to the
PV-Tree grower (models/gbdt/voting.py) — local top-K feature votes, one
tiny vote psum, and an allreduce of only the winning candidates' histogram
columns (LightGBMParams.scala:13-18 semantics, real reduced communication).
Voting needs >1 shard and all-numerical features; otherwise training falls
back to data_parallel with a log note.
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.models.gbdt import objectives
from mmlspark_tpu.models.gbdt.binning import BinMapper
from mmlspark_tpu.models.gbdt.booster import Booster, Tree, per_tree_raw
from mmlspark_tpu.models.gbdt.treegrow import grow_tree

log = logging.getLogger("mmlspark_tpu.gbdt")

BOOSTING_TYPES = ("gbdt", "goss", "dart", "rf")


@dataclass
class TrainConfig:
    objective: str = "binary"          # binary|multiclass|regression|lambdarank
    num_class: int = 1
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = -1
    lambda_l2: float = 0.0
    lambda_l1: float = 0.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    min_data_in_leaf: int = 20
    max_bin: int = 255
    feature_fraction: float = 1.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    early_stopping_round: int = 0
    metric: str = ""                   # default chosen by objective
    seed: int = 0
    parallelism: str = "data_parallel"  # accepted for parity
    # lossguide = LightGBM's leaf-wise best-first growth (default);
    # depthwise = level-wise growth whose histograms batch into one
    # multi-leaf pass per level (XGBoost-hist policy; O(depth) row passes)
    growth_policy: str = "lossguide"
    top_k: int = 20                     # voting_parallel K (parity)
    verbosity: int = -1
    # feature indices treated as categorical (LightGBM categoricalSlotIndexes
    # analogue): identity-binned, split by subset membership
    categorical_features: tuple = ()
    boosting_type: str = "gbdt"        # gbdt|goss|dart|rf
    # dart knobs (LightGBM drop_rate/max_drop/skip_drop defaults)
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    # goss knobs (LightGBM top_rate/other_rate defaults)
    top_rate: float = 0.2
    other_rate: float = 0.1
    # lambdarank eval truncation: NDCG@eval_at on the validation rows
    eval_at: int = 5
    # training-lifecycle callbacks + dynamic learning rate
    # (LightGBMDelegate analogue, models/gbdt/delegate.py)
    delegate: Optional[Any] = None


_TREE_FIELDS = (
    "rec_leaf", "rec_feature", "rec_bin", "rec_is_cat", "rec_active",
    "rec_gain", "leaf_values", "leaf_counts", "rec_catmask",
)


def _trees_from_device_batched(pending: list, mapper: BinMapper) -> list:
    """Materialize many device-grown trees with ONE host fetch per field.

    The per-iteration loop keeps every split record on device; fetching the
    ~8 small record arrays tree by tree costs a full host round-trip each
    (70 ms over a remote-device link — it dominated training wall-clock).
    Stacking per field first turns 8 x n_trees fetches into 8."""
    if not pending:
        return []
    stacked = {
        f: np.asarray(jnp.stack([getattr(g, f) for g in pending]))
        for f in _TREE_FIELDS
    }
    return [
        _tree_from_host_records({f: stacked[f][i] for f in _TREE_FIELDS}, mapper)
        for i in range(len(pending))
    ]


def _tree_from_host_records(rec: dict, mapper: BinMapper) -> Tree:
    rec_leaf = rec["rec_leaf"]
    rec_feature = rec["rec_feature"]
    rec_bin = rec["rec_bin"]
    is_cat = rec["rec_is_cat"]
    thr = np.array(
        [
            mapper.threshold_value(int(f), int(b)) if (f >= 0 and not c) else np.inf
            for f, b, c in zip(rec_feature, rec_bin, is_cat)
        ],
        dtype=np.float64,
    )
    has_cat = bool(is_cat.any())
    return Tree(
        leaf=rec_leaf,
        feature=rec_feature,
        threshold=thr,
        active=rec["rec_active"],
        gain=rec["rec_gain"],
        values=rec["leaf_values"],
        counts=rec["leaf_counts"],
        is_cat=is_cat if has_cat else None,
        catmask=rec["rec_catmask"] if has_cat else None,
    )


def _tree_from_device(grown: Any, mapper: BinMapper, value_scale: float = 1.0) -> Tree:
    rec_leaf = np.asarray(grown.rec_leaf)
    rec_feature = np.asarray(grown.rec_feature)
    rec_bin = np.asarray(grown.rec_bin)
    is_cat = np.asarray(grown.rec_is_cat)
    thr = np.array(
        [
            # categorical splits route by catmask, never by threshold:
            # +inf keeps any accidental numeric comparison all-left
            mapper.threshold_value(int(f), int(b)) if (f >= 0 and not c) else np.inf
            for f, b, c in zip(rec_feature, rec_bin, is_cat)
        ],
        dtype=np.float64,
    )
    has_cat = bool(is_cat.any())
    values = np.asarray(grown.leaf_values)
    if value_scale != 1.0:
        values = (values * value_scale).astype(values.dtype)
    return Tree(
        leaf=rec_leaf,
        feature=rec_feature,
        threshold=thr,
        active=np.asarray(grown.rec_active),
        gain=np.asarray(grown.rec_gain),
        values=values,
        counts=np.asarray(grown.leaf_counts),
        is_cat=is_cat if has_cat else None,
        catmask=np.asarray(grown.rec_catmask) if has_cat else None,
    )


def grouped_ndcg(
    scores: np.ndarray, labels: np.ndarray, group_ids: np.ndarray, k: int = 5
) -> float:
    """Mean NDCG@k over query groups with LightGBM's 2^rel-1 gain.

    The real ranking eval the reference's early stopping uses
    (lightgbm/LightGBMRanker.scala; TrainUtils.scala:276-308 evaluates the
    native booster's ndcg@k). Mirrors recommendation/evaluator.py's
    per-user NDCG, specialized to flat score/label arrays."""
    total, n_groups = 0.0, 0
    for gid in np.unique(group_ids):
        m = group_ids == gid
        s, rel = scores[m], labels[m]
        if len(s) == 0:
            continue
        kk = min(k, len(s))
        order = np.argsort(-s, kind="stable")[:kk]
        gains = 2.0 ** rel - 1.0
        disc = 1.0 / np.log2(np.arange(2, kk + 2))
        dcg = float((gains[order] * disc).sum())
        ideal = np.sort(gains)[::-1][:kk]
        idcg = float((ideal * disc).sum())
        # all-zero-relevance groups score 1.0 (LightGBM's NDCG convention:
        # nothing to rank correctly means nothing ranked incorrectly)
        total += dcg / idcg if idcg > 0 else 1.0
        n_groups += 1
    return total / max(n_groups, 1)


def _local_block_rows(garr: Any, n: int) -> np.ndarray:
    """First ``n`` rows of THIS process's block of a process-stacked global
    array (the layout shard_batch_multihost builds: one contiguous block
    per process, local padding at the block tail)."""
    shards = sorted(
        garr.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    block = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    return block[:n]


def _gather_rows(local: np.ndarray, n: int, share: int) -> np.ndarray:
    """Pad this process's first-n rows to the common block size and
    allgather -> (nproc * share, ...) global rows (padding rows are 0).
    Every process computes validation metrics on the identical gathered
    arrays, so early-stopping decisions stay convergent across SPMD
    processes (divergent control flow would deadlock the next collective).
    """
    import jax.experimental.multihost_utils as mhu

    local = local.reshape(n, -1).astype(np.float64)
    buf = np.zeros((share, local.shape[1]), np.float64)
    buf[:n] = local
    ga = np.asarray(mhu.process_allgather(buf))
    return ga.reshape(-1, local.shape[1])


def _eval_metric(
    cfg: TrainConfig,
    scores: np.ndarray,
    y: np.ndarray,
    mask: np.ndarray,
    group_ids: Optional[np.ndarray] = None,
) -> tuple:
    """Returns (name, value, higher_is_better) on masked rows."""
    if mask.sum() == 0:
        return ("none", float("nan"), False)
    s, yy = scores[mask], y[mask]
    obj = cfg.objective
    metric = cfg.metric
    if obj == "binary":
        p = objectives.sigmoid(s)
        if metric in ("", "binary_logloss"):
            p = np.clip(p, 1e-15, 1 - 1e-15)
            return ("binary_logloss", float(-(yy * np.log(p) + (1 - yy) * np.log(1 - p)).mean()), False)
        if metric == "auc":
            from mmlspark_tpu.core.metrics import binary_auc

            return ("auc", binary_auc(yy, p), True)
        return ("binary_error", float(((p > 0.5) != (yy > 0.5)).mean()), False)
    if obj == "multiclass":
        p = objectives.softmax(s)
        idx = yy.astype(np.int64)
        return (
            "multi_logloss",
            float(-np.log(np.clip(p[np.arange(len(idx)), idx], 1e-15, 1)).mean()),
            False,
        )
    if obj == "lambdarank":
        k = cfg.eval_at
        if metric.startswith("ndcg@"):
            k = int(metric.split("@", 1)[1])
        g = group_ids[mask] if group_ids is not None else np.zeros(len(yy), np.int64)
        return (f"ndcg@{k}", grouped_ndcg(s, yy, g, k=k), True)
    return ("l2", float(((s - yy) ** 2).mean()), False)


@functools.partial(
    jax.jit,
    static_argnames=(
        "objective", "k", "grad_pre", "is_goss", "use_voting", "has_cat",
        "num_leaves", "max_depth", "min_data_in_leaf", "top_k", "mesh",
        "depthwise",
    ),
)
def _fused_iteration(
    bins: jnp.ndarray,
    scores: jnp.ndarray,
    y_enc: Optional[jnp.ndarray],
    w_it: jnp.ndarray,
    it_key: jnp.ndarray,
    fm: jnp.ndarray,
    cat_mask: Optional[jnp.ndarray],
    g_pre: Optional[jnp.ndarray],
    h_pre: Optional[jnp.ndarray],
    top_rate: float,
    other_rate: float,
    lambda_l2: float,
    lambda_l1: float,
    min_sum_hessian: float,
    min_gain: float,
    learning_rate: float,
    *,
    objective: str,
    k: int,
    grad_pre: bool,
    is_goss: bool,
    use_voting: bool,
    has_cat: bool,
    num_leaves: int,
    max_depth: int,
    min_data_in_leaf: int,
    top_k: int,
    mesh: Any,
    depthwise: bool = False,
) -> tuple:
    """One whole boosting iteration as ONE XLA program: gradients, GOSS
    weights, k tree grows and the score update. Collapsing the per-iteration
    dispatch chain matters on remote/tunneled devices (each dispatch is a
    ~35 ms round trip) and saves scheduling overhead everywhere else.
    Returns (new_scores, tuple of GrownTree per class)."""
    if grad_pre:
        g_dev, h_dev = g_pre, h_pre
    elif objective == "binary":
        g_dev, h_dev = objectives.binary_grad_hess(scores, y_enc)
    elif objective == "multiclass":
        g_dev, h_dev = objectives.multiclass_grad_hess(scores, y_enc)
    else:
        g_dev, h_dev = objectives.l2_grad_hess(scores, y_enc)
    if is_goss:
        g_abs = jnp.abs(g_dev).sum(axis=1) if k > 1 else jnp.abs(g_dev)
        u = jax.random.uniform(jax.random.fold_in(it_key, 2), w_it.shape)
        w_it = w_it * _goss_weights(g_abs, w_it, u, top_rate, other_rate)
    grow_kw = dict(
        num_leaves=num_leaves,
        lambda_l2=lambda_l2,
        lambda_l1=lambda_l1,
        min_sum_hessian=min_sum_hessian,
        min_gain=min_gain,
        learning_rate=learning_rate,
        feature_mask=fm,
        max_depth=max_depth,
        min_data_in_leaf=min_data_in_leaf,
    )
    grown_list, deltas = [], []
    for c in range(k) if k > 1 else [0]:
        gc = g_dev[:, c] if k > 1 else g_dev
        hc = h_dev[:, c] if k > 1 else h_dev
        if use_voting:
            from mmlspark_tpu.models.gbdt.voting import grow_tree_voting

            grown = grow_tree_voting(
                bins, gc, hc, w_it, top_k=top_k, mesh=mesh, **grow_kw
            )
        elif depthwise:
            from mmlspark_tpu.models.gbdt.treegrow import grow_tree_depthwise

            grown = grow_tree_depthwise(
                bins, gc, hc, w_it, categorical_mask=cat_mask, **grow_kw
            )
        else:
            grown = grow_tree(bins, gc, hc, w_it, categorical_mask=cat_mask, **grow_kw)
        grown_list.append(grown)
        deltas.append(grown.leaf_values[grown.row_leaf])
    new_scores = scores + (jnp.stack(deltas, axis=1) if k > 1 else deltas[0])
    return new_scores, tuple(grown_list)


@jax.jit
def _goss_weights(g_abs: jnp.ndarray, w: jnp.ndarray, u: jnp.ndarray,
                  top_rate: float, other_rate: float) -> jnp.ndarray:
    """One-side sampling weights on device: rows ranked by |g| among rows
    with nonzero base weight; top a kept at 1x, random b of the rest kept
    at (1-a)/b, remainder dropped."""
    eligible = w > 0
    n_eligible = jnp.maximum(eligible.sum(), 1)
    n_top = jnp.maximum((top_rate * n_eligible).astype(jnp.int32), 1)
    masked = jnp.where(eligible, g_abs, -jnp.inf)
    # value threshold for the top-a set (ties may admit a few extra rows;
    # LightGBM's exact-count selection differs by at most the tie set)
    srt = jnp.sort(masked)[::-1]
    thresh = srt[jnp.clip(n_top - 1, 0, masked.shape[0] - 1)]
    is_top = eligible & (masked >= thresh)
    # LightGBM draws b*n rows out of the (1-a)*n remainder — per-row
    # probability b/(1-a) — and amplifies by (1-a)/b, so each non-top row's
    # EXPECTED histogram weight is exactly 1 (unbiased)
    p_other = jnp.minimum(other_rate / jnp.maximum(1.0 - top_rate, 1e-12), 1.0)
    amp = (1.0 - top_rate) / jnp.maximum(other_rate, 1e-12)
    is_other = eligible & ~is_top & (u < p_other)
    return jnp.where(is_top, 1.0, jnp.where(is_other, amp, 0.0)).astype(jnp.float32)


def train(
    x: np.ndarray,
    y: np.ndarray,
    cfg: TrainConfig,
    sample_weight: Optional[np.ndarray] = None,
    init_score: Optional[np.ndarray] = None,
    valid_mask: Optional[np.ndarray] = None,
    group_ids: Optional[np.ndarray] = None,
    init_booster: Optional[Booster] = None,
    base_score: Any = 0.0,
    shard: bool = True,
) -> Booster:
    """Fit a booster on dense (n, d) features or a CSR triple.

    ``x`` may be a scipy-style CSR matrix (anything with ``data``/
    ``indices``/``indptr``/``shape``); binning then runs per-column over the
    stored values only (LightGBMUtils.scala:211-265 builds native datasets
    from dense or sparse rows the same way).

    ``base_score``: boost_from_average baseline (scalar, or (k,) for
    multiclass) — added to the initial scores AND stored on the booster so
    prediction replays it."""
    if cfg.boosting_type not in BOOSTING_TYPES:
        raise ValueError(f"boosting_type must be one of {BOOSTING_TYPES}")
    if cfg.growth_policy not in ("lossguide", "depthwise"):
        raise ValueError(
            f"growth_policy must be 'lossguide' or 'depthwise', got {cfg.growth_policy!r}"
        )
    if cfg.growth_policy == "depthwise" and cfg.parallelism == "voting_parallel":
        # the voting grower is leaf-wise; silently dropping an explicit
        # depthwise request would benchmark/deploy the wrong policy
        raise ValueError("growth_policy='depthwise' is incompatible with voting_parallel")
    if cfg.boosting_type == "goss" and cfg.top_rate + cfg.other_rate > 1.0:
        # LightGBM hard-errors here too: the sampler's unbiasedness
        # guarantee needs b/(1-a) <= 1
        raise ValueError("goss requires top_rate + other_rate <= 1")
    from mmlspark_tpu.models.gbdt.binning import is_sparse

    sparse_input = is_sparse(x)
    n, d = x.shape
    # np.matrix-shaped labels (scipy .sum(axis=) results) flatten silently
    y = np.asarray(y).reshape(n)
    k = cfg.num_class if cfg.objective == "multiclass" else 1
    cat_features = tuple(int(f) for f in (cfg.categorical_features or ()))

    # multi-host: every process calls train() with ITS OWN rows; the jitted
    # grower then runs SPMD over the process-spanning mesh and XLA carries
    # the histogram allreduce over DCN (the reference's per-machine dataset
    # build + socket allreduce, TrainUtils.scala:26-66,496-512)
    multihost = shard and jax.process_count() > 1
    if multihost:
        unsupported = [
            name
            for flag, name in (
                # lambdarank gradients need group-contiguous global sorts;
                # voting's shard_map grower is untested across processes
                (cfg.objective == "lambdarank", "lambdarank"),
                (cfg.parallelism == "voting_parallel", "voting_parallel"),
            )
            if flag
        ]
        if unsupported:
            raise NotImplementedError(
                f"multi-host training does not yet support: {unsupported}"
            )

    if multihost:
        # bin bounds must be IDENTICAL on every process: fit the mapper on
        # a NaN-padded sample allgathered from all processes (NaN rows are
        # ignored by quantile fitting; for sparse inputs absent entries
        # densify to NaN, matching the missing-bin transform semantics)
        import jax.experimental.multihost_utils as mhu

        # FIXED buffer size (process-count-based only): processes may hold
        # unequal row counts, and allgather needs identical shapes — short
        # processes leave NaN rows, which quantile fitting ignores
        k_s = max(1, 50_000 // jax.process_count())
        samp = np.full((k_s, d), np.nan, np.float32)
        take = np.random.default_rng(cfg.seed).choice(
            n, min(n, k_s), replace=False
        )
        samp[: len(take)] = (
            _densify(x[take]) if sparse_input else np.asarray(x[take], np.float32)
        )
        if cat_features:
            if sparse_input:
                # match the single-host BinMapper error exactly — the
                # sample-densified path must not silently accept what one
                # process would reject
                raise ValueError(
                    "categorical features require dense input (sparse "
                    "columns have no stable category<->bin identity for "
                    "absent entries)"
                )
            # categorical hi must cover every category present ANYWHERE,
            # not just in the capped sample: allgather full-column extrema
            # (also makes the range validation a globally identical
            # decision — a raise on one process only would desync SPMD)
            ext = np.zeros((len(cat_features), 2), np.float64)
            for j, f in enumerate(cat_features):
                col = np.asarray(x[:, f], np.float64)
                col = col[~np.isnan(col)]
                ext[j] = (col.min(), col.max()) if len(col) else (0.0, 0.0)
            gext = np.asarray(mhu.process_allgather(ext))
            gmin = gext[..., 0].min(axis=0)
            gmax = gext[..., 1].max(axis=0)
            bad = np.flatnonzero((gmin < 0) | (gmax > cfg.max_bin - 2))
            if len(bad):
                raise ValueError(
                    f"categorical features {[cat_features[b] for b in bad]} "
                    f"have values outside [0, {cfg.max_bin - 2}] — "
                    "re-index categories first"
                )
            # plant the global max into this process's sample so the
            # fitted identity range covers the unsampled tail everywhere
            for j, f in enumerate(cat_features):
                samp[0, f] = gmax[j]
        global_sample = np.asarray(mhu.process_allgather(samp)).reshape(-1, d)
        mapper = BinMapper.fit(
            global_sample, max_bin=cfg.max_bin, seed=cfg.seed,
            categorical_features=cat_features,
        )
    else:
        mapper = BinMapper.fit(
            x, max_bin=cfg.max_bin, seed=cfg.seed, categorical_features=cat_features
        )
    bins_host = mapper.transform(x)
    cat_mask_dev = None
    if cat_features:
        cat_mask_host = np.zeros(d, bool)
        cat_mask_host[list(cat_features)] = True
        cat_mask_dev = jnp.asarray(cat_mask_host)

    train_mask = (
        ~valid_mask if valid_mask is not None else np.ones(n, bool)
    )
    w = sample_weight if sample_weight is not None else np.ones(n, np.float32)
    w = np.where(train_mask, w, 0.0).astype(np.float32)

    bagging_fraction = cfg.bagging_fraction
    bagging_freq = cfg.bagging_freq
    if cfg.boosting_type == "rf" and not (bagging_freq > 0 and bagging_fraction < 1.0):
        # rf without bagging would grow the same tree every round; LightGBM
        # hard-errors here, we default to the classic 0.632 bootstrap rate
        log.info("rf boosting without bagging params: defaulting to bagging_fraction=0.632, bagging_freq=1")
        bagging_fraction, bagging_freq = 0.632, 1
    if cfg.boosting_type == "goss" and bagging_freq > 0:
        log.info("goss boosting: bagging disabled (GOSS is the row sampler)")
        bagging_freq = 0

    # device placement: rows sharded over the data axis when a mesh exists
    mesh = None
    use_voting = False
    if multihost:
        from mmlspark_tpu.parallel.mesh import get_mesh
        from mmlspark_tpu.parallel.sharding import (
            multihost_pad_target,
            shard_batch_multihost,
        )

        mesh = get_mesh()
        share = multihost_pad_target(n)  # equal local block per process
        pad = share - n
        bins_dev = shard_batch_multihost(
            np.pad(bins_host, ((0, pad), (0, 0))), mesh
        )
        w_dev = shard_batch_multihost(np.pad(w, (0, pad)), mesh)
        n_pad = share * jax.process_count()  # GLOBAL padded row count
    elif shard:
        from mmlspark_tpu.parallel.mesh import DATA_AXIS, get_mesh
        from mmlspark_tpu.parallel.sharding import pad_batch, shard_batch

        mesh = get_mesh()
        n_dev = mesh.devices.size
        bins_p, n_real = pad_batch(bins_host, n_dev)
        pad = bins_p.shape[0] - n
        bins_dev = shard_batch(bins_p, mesh)
        w_dev = shard_batch(np.pad(w, (0, pad)), mesh)
        n_pad = n + pad
        if cfg.parallelism == "voting_parallel":
            if dict(mesh.shape).get(DATA_AXIS, 1) > 1 and not cat_features:
                use_voting = True
            else:
                log.info(
                    "voting_parallel needs >1 data shard and numerical "
                    "features; falling back to data_parallel"
                )
    else:
        pad = 0
        bins_dev = jnp.asarray(bins_host)
        w_dev = jnp.asarray(w)
        n_pad = n

    def padded(a: np.ndarray) -> jnp.ndarray:
        if pad:
            a = np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        if multihost:
            from mmlspark_tpu.parallel.sharding import shard_batch_multihost

            return shard_batch_multihost(a, mesh)
        if shard:
            from mmlspark_tpu.parallel.sharding import shard_batch

            return shard_batch(a)
        return jnp.asarray(a)

    # -- device-resident loop state -----------------------------------------
    # scores, labels and per-iteration gradients stay sharded on device for
    # the whole loop; the host receives only split records + eval scalars.
    if k > 1:
        scores0 = np.zeros((n, k), np.float32)
        y_onehot_dev = padded(np.eye(k, dtype=np.float32)[y.astype(np.int64)])
    else:
        scores0 = np.zeros(n, np.float32)
        y_dev = padded(y.astype(np.float32))
    scores0 = scores0 + np.asarray(base_score, np.float32)
    if init_score is not None:
        scores0 = scores0 + init_score.astype(scores0.dtype)
    if init_booster is not None and init_booster.trees:
        # score with ALL trees (not the best_iteration prefix predict_raw
        # would default to): merge() replays every init tree, so residuals
        # must be fit against exactly that
        all_iters = len(init_booster.trees) // init_booster.num_class
        prev = init_booster.predict_raw(
            _densify(x) if sparse_input else x, num_iteration=all_iters
        )
        scores0 = scores0 + prev.astype(scores0.dtype)
    scores = padded(scores0)

    is_rf = cfg.boosting_type == "rf"
    is_dart = cfg.boosting_type == "dart"
    is_goss = cfg.boosting_type == "goss"
    early_stopping_round = cfg.early_stopping_round
    if is_dart and early_stopping_round > 0:
        # dropout keeps rescaling trees INSIDE any recorded best-iteration
        # prefix, so the prefix can't reproduce the scores that won —
        # LightGBM hard-errors on this combination, we disable with a note
        log.info("early stopping is not available in dart mode; disabled")
        early_stopping_round = 0
    if is_rf:
        # constant gradients at the initial score; `scores` becomes the
        # running SUM of tree contributions (averaged for eval/predict)
        rf_base = scores
        scores = padded(np.zeros_like(scores0))
        if cfg.objective == "binary":
            g_rf, h_rf = objectives.binary_grad_hess(rf_base, y_dev)
        elif cfg.objective == "multiclass":
            g_rf, h_rf = objectives.multiclass_grad_hess(rf_base, y_onehot_dev)
        elif cfg.objective == "lambdarank":
            g_np, h_np = objectives.lambdarank_grad_hess(
                scores0.astype(np.float64), y.astype(np.float64), group_ids
            )
            g_rf, h_rf = padded(g_np.astype(np.float32)), padded(h_np.astype(np.float32))
        else:
            g_rf, h_rf = objectives.l2_grad_hess(rf_base, y_dev)

    rng = np.random.default_rng(cfg.seed)
    base_key = jax.random.PRNGKey(cfg.seed)
    # per-iteration random masks and the small split-record reads must be
    # REPLICATED arrays under multihost (a bare jax.random.uniform commits
    # to process-local devices, incompatible with cross-process-sharded
    # operands); both jits are hoisted here so the cache hits every round
    if multihost:
        _rep_sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()
        )
        _uniform_global = jax.jit(
            lambda key: jax.random.uniform(key, (n_pad,)),
            out_shardings=_rep_sharding,
        )
        _replicate_small = jax.jit(lambda t: t, out_shardings=_rep_sharding)
    else:
        def _uniform_global(key: Any) -> jnp.ndarray:
            return jax.random.uniform(key, (n_pad,))
    booster = Booster(
        trees=[], objective=cfg.objective, num_class=k, num_features=d,
        base_score=base_score, boosting_type=cfg.boosting_type,
    )
    pending_trees: list = []  # device-grown records, materialized after the loop
    x_host_dense: Optional[np.ndarray] = None  # dart re-predicts dropped trees

    best_val = None
    best_iter = -1
    rounds_no_improve = 0
    bag = None
    mh_eval_ctx = None  # lazily gathered (y, valid) global eval arrays

    delegate = cfg.delegate
    lr_cur = float(cfg.learning_rate)

    for it in range(cfg.num_iterations):
        if delegate is not None:
            delegate.before_train_iteration(it)
            # dynamic learning rate (getLearningRate delegate semantics);
            # lr is a dynamic jit arg, so no recompile on change
            lr_cur = float(delegate.get_learning_rate(it, lr_cur))
        it_key = jax.random.fold_in(base_key, it)
        # bagging for this iteration (device mask, no host transfer)
        if bagging_freq > 0 and bagging_fraction < 1.0:
            if it % bagging_freq == 0 or bag is None:
                bag = (
                    _uniform_global(jax.random.fold_in(it_key, 1))
                    < bagging_fraction
                ).astype(jnp.float32)
        else:
            bag = None
        w_it = w_dev * bag if bag is not None else w_dev
        if cfg.feature_fraction < 1.0:
            fm = (rng.random(d) < cfg.feature_fraction).astype(np.float32)
            if fm.sum() == 0:
                fm[rng.integers(d)] = 1.0
        else:
            fm = np.ones(d, np.float32)
        fm_dev = jnp.asarray(fm)

        # dart: choose dropped iterations, fit against scores without them
        drop_set: list = []
        drop_contrib = None
        eff_scores = scores
        if is_dart and it > 0 and rng.random() >= cfg.skip_drop:
            sel = np.flatnonzero(rng.random(it) < cfg.drop_rate)
            if len(sel) > cfg.max_drop:
                sel = rng.choice(sel, cfg.max_drop, replace=False)
            drop_set = [int(s) for s in sel]
        if drop_set:
            if x_host_dense is None:
                x_host_dense = _densify(x) if sparse_input else np.asarray(x, np.float32)
            drop_contrib = _iterations_contrib(booster, x_host_dense, drop_set, k)
            eff_scores = scores - padded(drop_contrib)

        # dart normalization factors (paper semantics: new tree 1/(k+1),
        # dropped trees k/(k+1))
        n_drop = len(drop_set)
        nf_new = 1.0 / (n_drop + 1) if is_dart else 1.0
        nf_drop = n_drop / (n_drop + 1) if n_drop else 1.0

        # precomputed gradients: rf (constant at the initial score) and
        # lambdarank's group-sorted host path; everything else is computed
        # inside the fused program from the running scores
        g_pre = h_pre = None
        if is_rf:
            g_pre, h_pre = g_rf, h_rf
        elif cfg.objective == "lambdarank":
            s_host = np.asarray(eff_scores)[:n]
            g_np, h_np = objectives.lambdarank_grad_hess(
                s_host.astype(np.float64), y.astype(np.float64), group_ids
            )
            g_pre, h_pre = padded(g_np.astype(np.float32)), padded(h_np.astype(np.float32))
        grad_pre = g_pre is not None
        y_enc = None if grad_pre else (y_onehot_dev if k > 1 else y_dev)
        new_scores, grown_all = _fused_iteration(
            bins_dev, eff_scores, y_enc, w_it, it_key, fm_dev, cat_mask_dev,
            g_pre, h_pre,
            float(cfg.top_rate), float(cfg.other_rate),
            float(cfg.lambda_l2), float(cfg.lambda_l1),
            float(cfg.min_sum_hessian_in_leaf), float(cfg.min_gain_to_split),
            1.0 if is_rf else lr_cur,
            objective=cfg.objective, k=k, grad_pre=grad_pre, is_goss=is_goss,
            use_voting=use_voting, has_cat=cat_mask_dev is not None,
            num_leaves=int(cfg.num_leaves), max_depth=int(cfg.max_depth),
            min_data_in_leaf=int(cfg.min_data_in_leaf),
            top_k=int(cfg.top_k), mesh=mesh if use_voting else None,
            depthwise=cfg.growth_policy == "depthwise",
        )
        # the fused step fit against eff_scores (dart: scores minus dropped
        # trees); the running total keeps the dropped contribution
        scores = (scores - eff_scores) + new_scores if drop_set else new_scores
        if is_dart and nf_new != 1.0:
            # the fused delta was unscaled; the stored tree shrinks by
            # nf_new, so fold the same factor into the running scores
            corr = [g.leaf_values[g.row_leaf] * (nf_new - 1.0) for g in grown_all]
            scores = scores + (jnp.stack(corr, axis=1) if k > 1 else corr[0])
        for grown in grown_all:
            if multihost:
                # the small split-record outputs must be fully replicated so
                # every process can read them to host (row_leaf stays
                # sharded — it is only ever consumed on device)
                grown = grown._replace(
                    **{
                        f: _replicate_small(getattr(grown, f))
                        for f in grown._fields
                        if f != "row_leaf"
                    }
                )
            if is_dart:
                # dart mutates PAST trees' values mid-loop, so it needs
                # host-materialized trees as it goes (eager, per-tree fetch)
                booster.trees.append(
                    _tree_from_device(grown, mapper, value_scale=nf_new)
                )
            else:
                # deferred materialization: split records stay on device;
                # the host fetch happens ONCE, batched, after the loop
                pending_trees.append(grown)
        if drop_set:
            # dropped trees shrink to k/(k+1): mutate their stored values
            # and fold the same correction into the running scores
            for itdrop in drop_set:
                for c in range(k):
                    t = booster.trees[itdrop * k + c]
                    t.values = (t.values * nf_drop).astype(t.values.dtype)
            scores = scores - padded(drop_contrib * (1.0 - nf_drop))

        # eval + early stopping on validation rows (the only host sync).
        # Multihost: every process must take this branch together — the
        # allgather inside is a collective
        eval_result = None
        stop_now = False
        if valid_mask is not None and (multihost or valid_mask.any()):
            name = None
            if multihost:
                s_eval = _local_block_rows(scores, n)
                if is_rf:
                    s_eval = _local_block_rows(rf_base, n) + s_eval / (it + 1)
                if mh_eval_ctx is None:
                    # y and the valid mask are loop-invariant: one gather
                    ym = _gather_rows(
                        np.stack([y, valid_mask.astype(np.float64)], 1),
                        n, share,
                    )
                    mh_eval_ctx = (ym[:, 0], ym[:, 1] > 0.5)
                y_g, m_g = mh_eval_ctx
                sg2 = _gather_rows(s_eval, n, share)
                s_g = sg2 if k > 1 else sg2[:, 0]
                if m_g.any():
                    name, val, higher = _eval_metric(cfg, s_g, y_g, m_g, None)
            else:
                s_eval = np.asarray(scores)[:n]
                if is_rf:
                    s_eval = np.asarray(rf_base)[:n] + s_eval / (it + 1)
                name, val, higher = _eval_metric(cfg, s_eval, y, valid_mask, group_ids)
            if name is not None:
                eval_result = (name, val, higher)
                if cfg.verbosity > 0:
                    log.info("iter %d %s=%.6f", it, name, val)
                improved = (
                    best_val is None
                    or (higher and val > best_val)
                    or (not higher and val < best_val)
                )
                if improved:
                    best_val, best_iter, rounds_no_improve = val, it + 1, 0
                else:
                    rounds_no_improve += 1
                    if early_stopping_round > 0 and rounds_no_improve >= early_stopping_round:
                        log.info("early stop at iter %d (best %d)", it, best_iter)
                        booster.best_iteration = best_iter
                        stop_now = True
        if delegate is not None:
            delegate.after_train_iteration(
                it, eval_result, stop_now or it == cfg.num_iterations - 1
            )
        if stop_now:
            break

    booster.trees.extend(_trees_from_device_batched(pending_trees, mapper))
    # dart never records best_iteration: later dropouts rescale trees inside
    # any prefix, so no prefix reproduces a historical eval score
    if valid_mask is not None and best_iter > 0 and booster.best_iteration < 0 and not is_dart:
        booster.best_iteration = best_iter
    if init_booster is not None and init_booster.trees:
        new_best = booster.best_iteration
        init_iters = len(init_booster.trees) // init_booster.num_class
        booster = init_booster.merge(booster)
        if new_best > 0:
            # best iteration counts from the front of the merged tree list
            booster.best_iteration = init_iters + new_best
    return booster


def _densify(x: Any) -> np.ndarray:
    """CSR -> dense float32 with absent entries as NaN (prediction-time
    only; training stays sparse). NaN, not 0: trees trained on sparse data
    route absent entries through the missing bin."""
    from mmlspark_tpu.models.gbdt.binning import densify_missing, is_sparse

    if is_sparse(x):
        return densify_missing(x)
    return np.asarray(x, np.float32)


def _iterations_contrib(
    booster: Booster, x: np.ndarray, iterations: list, k: int
) -> np.ndarray:
    """Summed raw contribution of the given iterations: (n,) or (n, k)."""
    idx = [it * k + c for it in iterations for c in range(k)]
    per = per_tree_raw([booster.trees[i] for i in idx], x)  # (n, len(idx))
    if k == 1:
        return per.sum(axis=1).astype(np.float32)
    n = per.shape[0]
    out = np.zeros((n, k), np.float32)
    for j, i in enumerate(idx):
        out[:, i % k] += per[:, j]
    return out
