"""GBDT training loop.

The analogue of lightgbm/TrainUtils.scala's ``trainCore`` iteration loop
(:220-315): per boosting iteration compute grad/hess from current scores,
grow one tree per class (the compiled ``grow_tree`` program — histogram +
split search + partition assignment all on device), update scores from the
grower's own row->leaf output (free, no re-predict), evaluate + early-stop.

Distribution: rows are batch-sharded over the mesh ``data`` axis before the
loop. ``data_parallel`` lets GSPMD partition the histogram scatter and
insert the full-plane ICI allreduce; ``voting_parallel`` switches to the
PV-Tree grower (models/gbdt/voting.py) — local top-K feature votes, one
tiny vote psum, and an allreduce of only the winning candidates' histogram
columns (LightGBMParams.scala:13-18 semantics, real reduced communication).
Voting needs >1 shard and all-numerical features; otherwise training falls
back to data_parallel with a log note.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.models.gbdt import objectives
from mmlspark_tpu.models.gbdt.binning import BinMapper
from mmlspark_tpu.models.gbdt.booster import Booster, Tree
from mmlspark_tpu.models.gbdt.treegrow import grow_tree

log = logging.getLogger("mmlspark_tpu.gbdt")


@dataclass
class TrainConfig:
    objective: str = "binary"          # binary|multiclass|regression|lambdarank
    num_class: int = 1
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = -1
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    min_data_in_leaf: int = 20
    max_bin: int = 255
    feature_fraction: float = 1.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    early_stopping_round: int = 0
    metric: str = ""                   # default chosen by objective
    seed: int = 0
    parallelism: str = "data_parallel"  # accepted for parity
    top_k: int = 20                     # voting_parallel K (parity)
    verbosity: int = -1
    # feature indices treated as categorical (LightGBM categoricalSlotIndexes
    # analogue): identity-binned, split by subset membership
    categorical_features: tuple = ()


def _tree_from_device(grown: Any, mapper: BinMapper) -> Tree:
    rec_leaf = np.asarray(grown.rec_leaf)
    rec_feature = np.asarray(grown.rec_feature)
    rec_bin = np.asarray(grown.rec_bin)
    is_cat = np.asarray(grown.rec_is_cat)
    thr = np.array(
        [
            # categorical splits route by catmask, never by threshold:
            # +inf keeps any accidental numeric comparison all-left
            mapper.threshold_value(int(f), int(b)) if (f >= 0 and not c) else np.inf
            for f, b, c in zip(rec_feature, rec_bin, is_cat)
        ],
        dtype=np.float64,
    )
    has_cat = bool(is_cat.any())
    return Tree(
        leaf=rec_leaf,
        feature=rec_feature,
        threshold=thr,
        active=np.asarray(grown.rec_active),
        gain=np.asarray(grown.rec_gain),
        values=np.asarray(grown.leaf_values),
        counts=np.asarray(grown.leaf_counts),
        is_cat=is_cat if has_cat else None,
        catmask=np.asarray(grown.rec_catmask) if has_cat else None,
    )


def _eval_metric(cfg: TrainConfig, scores: np.ndarray, y: np.ndarray, mask: np.ndarray) -> tuple:
    """Returns (name, value, higher_is_better) on masked rows."""
    if mask.sum() == 0:
        return ("none", float("nan"), False)
    s, yy = scores[mask], y[mask]
    obj = cfg.objective
    metric = cfg.metric
    if obj == "binary":
        p = objectives.sigmoid(s)
        if metric in ("", "binary_logloss"):
            p = np.clip(p, 1e-15, 1 - 1e-15)
            return ("binary_logloss", float(-(yy * np.log(p) + (1 - yy) * np.log(1 - p)).mean()), False)
        if metric == "auc":
            from mmlspark_tpu.core.metrics import binary_auc

            return ("auc", binary_auc(yy, p), True)
        return ("binary_error", float(((p > 0.5) != (yy > 0.5)).mean()), False)
    if obj == "multiclass":
        p = objectives.softmax(s)
        idx = yy.astype(np.int64)
        return (
            "multi_logloss",
            float(-np.log(np.clip(p[np.arange(len(idx)), idx], 1e-15, 1)).mean()),
            False,
        )
    if obj == "lambdarank":
        return ("ndcg_proxy", float(-np.corrcoef(s, yy)[0, 1]) if len(yy) > 1 else 0.0, False)
    return ("l2", float(((s - yy) ** 2).mean()), False)


def train(
    x: np.ndarray,
    y: np.ndarray,
    cfg: TrainConfig,
    sample_weight: Optional[np.ndarray] = None,
    init_score: Optional[np.ndarray] = None,
    valid_mask: Optional[np.ndarray] = None,
    group_ids: Optional[np.ndarray] = None,
    init_booster: Optional[Booster] = None,
    base_score: Any = 0.0,
    shard: bool = True,
) -> Booster:
    """Fit a booster on dense (n, d) features.

    ``base_score``: boost_from_average baseline (scalar, or (k,) for
    multiclass) — added to the initial scores AND stored on the booster so
    prediction replays it."""
    n, d = x.shape
    k = cfg.num_class if cfg.objective == "multiclass" else 1
    cat_features = tuple(int(f) for f in (cfg.categorical_features or ()))
    mapper = BinMapper.fit(
        x, max_bin=cfg.max_bin, seed=cfg.seed, categorical_features=cat_features
    )
    bins_host = mapper.transform(x)
    cat_mask_dev = None
    if cat_features:
        cat_mask_host = np.zeros(d, bool)
        cat_mask_host[list(cat_features)] = True
        cat_mask_dev = jnp.asarray(cat_mask_host)

    train_mask = (
        ~valid_mask if valid_mask is not None else np.ones(n, bool)
    )
    w = sample_weight if sample_weight is not None else np.ones(n, np.float32)
    w = np.where(train_mask, w, 0.0).astype(np.float32)

    # device placement: rows sharded over the data axis when a mesh exists
    mesh = None
    use_voting = False
    if shard:
        from mmlspark_tpu.parallel.mesh import DATA_AXIS, get_mesh
        from mmlspark_tpu.parallel.sharding import pad_batch, shard_batch

        mesh = get_mesh()
        n_dev = mesh.devices.size
        bins_p, n_real = pad_batch(bins_host, n_dev)
        pad = bins_p.shape[0] - n
        bins_dev = shard_batch(bins_p, mesh)
        w_dev = shard_batch(np.pad(w, (0, pad)), mesh)
        if cfg.parallelism == "voting_parallel":
            if dict(mesh.shape).get(DATA_AXIS, 1) > 1 and not cat_features:
                use_voting = True
            else:
                log.info(
                    "voting_parallel needs >1 data shard and numerical "
                    "features; falling back to data_parallel"
                )
    else:
        pad = 0
        bins_dev = jnp.asarray(bins_host)
        w_dev = jnp.asarray(w)

    def padded(a: np.ndarray) -> jnp.ndarray:
        if pad:
            a = np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        if shard:
            from mmlspark_tpu.parallel.sharding import shard_batch

            return shard_batch(a)
        return jnp.asarray(a)

    if k > 1:
        scores = np.zeros((n, k), np.float32)
        y_onehot = np.eye(k, dtype=np.float32)[y.astype(np.int64)]
    else:
        scores = np.zeros(n, np.float32)
    scores = scores + np.asarray(base_score, np.float32)
    if init_score is not None:
        scores = scores + init_score.astype(scores.dtype)
    if init_booster is not None and init_booster.trees:
        # score with ALL trees (not the best_iteration prefix predict_raw
        # would default to): merge() replays every init tree, so residuals
        # must be fit against exactly that
        all_iters = len(init_booster.trees) // init_booster.num_class
        prev = init_booster.predict_raw(x, num_iteration=all_iters)
        scores = scores + prev.astype(scores.dtype)

    rng = np.random.default_rng(cfg.seed)
    booster = Booster(
        trees=[], objective=cfg.objective, num_class=k, num_features=d,
        base_score=base_score,
    )

    best_val = None
    best_iter = -1
    rounds_no_improve = 0

    for it in range(cfg.num_iterations):
        # bagging / feature sampling for this iteration
        if cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0 and it % cfg.bagging_freq == 0:
            bag = (rng.random(n) < cfg.bagging_fraction).astype(np.float32)
        elif cfg.bagging_fraction >= 1.0 or cfg.bagging_freq == 0:
            bag = np.ones(n, np.float32)
        w_it = w * bag
        if cfg.feature_fraction < 1.0:
            fm = (rng.random(d) < cfg.feature_fraction).astype(np.float32)
            if fm.sum() == 0:
                fm[rng.integers(d)] = 1.0
        else:
            fm = np.ones(d, np.float32)
        fm_dev = jnp.asarray(fm)

        # gradients
        if cfg.objective == "binary":
            g, h = binary_np(scores, y)
        elif cfg.objective == "multiclass":
            g_all, h_all = objectives.multiclass_grad_hess(
                jnp.asarray(scores), jnp.asarray(y_onehot)
            )
            g_all, h_all = np.asarray(g_all), np.asarray(h_all)
        elif cfg.objective == "lambdarank":
            g, h = objectives.lambdarank_grad_hess(
                scores.astype(np.float64), y.astype(np.float64), group_ids
            )
        else:
            g, h = np.asarray(scores - y, np.float32), np.ones(n, np.float32)

        classes = range(k) if k > 1 else [0]
        for c in classes:
            if k > 1:
                gc, hc = g_all[:, c], h_all[:, c]
            else:
                gc, hc = g, h
            grow_kw = dict(
                num_leaves=cfg.num_leaves,
                lambda_l2=float(cfg.lambda_l2),
                min_gain=float(cfg.min_gain_to_split),
                learning_rate=float(cfg.learning_rate),
                feature_mask=fm_dev,
                max_depth=int(cfg.max_depth),
                min_data_in_leaf=int(cfg.min_data_in_leaf),
            )
            if use_voting:
                from mmlspark_tpu.models.gbdt.voting import grow_tree_voting

                grown = grow_tree_voting(
                    bins_dev,
                    padded(gc.astype(np.float32)),
                    padded(hc.astype(np.float32)),
                    padded(w_it),
                    top_k=int(cfg.top_k),
                    mesh=mesh,
                    **grow_kw,
                )
            else:
                grown = grow_tree(
                    bins_dev,
                    padded(gc.astype(np.float32)),
                    padded(hc.astype(np.float32)),
                    padded(w_it),
                    categorical_mask=cat_mask_dev,
                    **grow_kw,
                )
            tree = _tree_from_device(grown, mapper)
            booster.trees.append(tree)
            # score update from the grower's own leaf assignment
            row_leaf = np.asarray(grown.row_leaf)[:n]
            delta = tree.values[row_leaf]
            if k > 1:
                scores[:, c] += delta
            else:
                scores += delta

        # eval + early stopping on validation rows
        if valid_mask is not None and valid_mask.any():
            name, val, higher = _eval_metric(cfg, scores, y, valid_mask)
            if cfg.verbosity > 0:
                log.info("iter %d %s=%.6f", it, name, val)
            improved = (
                best_val is None
                or (higher and val > best_val)
                or (not higher and val < best_val)
            )
            if improved:
                best_val, best_iter, rounds_no_improve = val, it + 1, 0
            else:
                rounds_no_improve += 1
                if cfg.early_stopping_round > 0 and rounds_no_improve >= cfg.early_stopping_round:
                    log.info("early stop at iter %d (best %d)", it, best_iter)
                    booster.best_iteration = best_iter
                    break

    if valid_mask is not None and best_iter > 0 and booster.best_iteration < 0:
        booster.best_iteration = best_iter
    if init_booster is not None and init_booster.trees:
        new_best = booster.best_iteration
        init_iters = len(init_booster.trees) // init_booster.num_class
        booster = init_booster.merge(booster)
        if new_best > 0:
            # best iteration counts from the front of the merged tree list
            booster.best_iteration = init_iters + new_best
    return booster


def binary_np(scores: np.ndarray, y: np.ndarray) -> tuple:
    p = objectives.sigmoid(scores)
    return (p - y).astype(np.float32), (p * (1 - p)).astype(np.float32)
