"""GBDT training loop.

The analogue of lightgbm/TrainUtils.scala's ``trainCore`` iteration loop
(:220-315): per boosting iteration compute grad/hess from current scores,
grow one tree per class (the compiled ``grow_tree`` program — histogram +
split search + partition assignment all on device), update scores from the
grower's own row->leaf output (free, no re-predict), evaluate + early-stop.

Boosting modes (``boostingType`` in lightgbm/LightGBMParams.scala, golden
matrix src/test/resources/benchmarks/benchmarks_VerifyLightGBMClassifier.csv):
- ``gbdt``  — plain gradient boosting.
- ``goss``  — gradient-based one-side sampling: keep the top ``top_rate``
  fraction of rows by |gradient|, sample ``other_rate`` of the rest and
  amplify their weight by (1-a)/b so histogram sums stay unbiased.
- ``dart``  — per iteration (unless ``skip_drop`` fires) drop a random
  subset of past iterations, fit the new tree against the scores without
  them, then normalize: new tree x 1/(k+1), dropped trees x k/(k+1).
- ``rf``    — random forest: constant gradients at the initial score,
  bagging per iteration, no shrinkage; prediction averages trees.

Device residency: scores, gradients, labels and bagging/GOSS masks live on
device (sharded over the mesh ``data`` axis) across all iterations — the
host sees only the per-tree split records and the eval-metric scalar
(lightgbm/TrainUtils.scala:220-315 keeps the equivalent state inside the
native booster for the same reason). LambdaRank's pairwise gradients are
device-resident too (objectives.lambdarank_grad_hess_device over padded
contiguous groups), so ranking joins the scan-fused path; only multihost
ranking (and pathological group sizes whose padded pair tensors exceed the
device budget) falls back to host gradients.

Distribution: rows are batch-sharded over the mesh ``data`` axis before the
loop. ``data_parallel`` lets GSPMD partition the histogram scatter and
insert the full-plane ICI allreduce; ``voting_parallel`` switches to the
PV-Tree grower (models/gbdt/voting.py) — local top-K feature votes, one
tiny vote psum, and an allreduce of only the winning candidates' histogram
columns (LightGBMParams.scala:13-18 semantics, real reduced communication).
Voting needs >1 shard; single-shard layouts fall back to data_parallel
with a log note. Categorical features vote and split like anywhere else.
"""

from __future__ import annotations

import functools
import logging
import time as _time
from dataclasses import dataclass, replace as _dc_replace
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.models.gbdt import objectives
from mmlspark_tpu.parallel.mesh import DATA_AXIS as _DATA_AXIS
from mmlspark_tpu.models.gbdt.binning import BinMapper
from mmlspark_tpu.ops.histogram import NUM_BINS, hist_lowering as _hist_lowering
from mmlspark_tpu.models.gbdt.booster import Booster, Tree, per_tree_raw
from mmlspark_tpu.models.gbdt.treegrow import grow_tree

log = logging.getLogger("mmlspark_tpu.gbdt")

BOOSTING_TYPES = ("gbdt", "goss", "dart", "rf")

# training telemetry (docs/observability.md): round wall-clock covers
# gradients + grow + score update + (fast path) on-device eval, i.e. the
# whole per-iteration cost the next perf PR will be judged against
_M_ROUNDS = obs.counter(
    "mmlspark_gbdt_rounds_total", "Completed boosting rounds",
)
_M_ROUND_SECONDS = obs.histogram(
    "mmlspark_gbdt_round_seconds",
    "Per-round wall time (scan-fused chunks report chunk time / rounds)",
)
_M_CHUNK_SECONDS = obs.histogram(
    "mmlspark_gbdt_chunk_seconds",
    "Scan-fused chunk wall time: dispatch + eval read + record unpack",
)
_M_FUSED_CHUNKS = obs.counter(
    "mmlspark_gbdt_fused_chunks_total",
    "Scan-fused chunk dispatches: a training run costs O(rounds / chunk) "
    "of these instead of O(rounds) per-round dispatches",
)
_M_DEVICE_EVAL_ROUNDS = obs.counter(
    "mmlspark_gbdt_device_eval_rounds_total",
    "Boosting rounds whose eval metric was computed on device inside the "
    "fused chunk (no per-round host sync)",
)


@dataclass
class TrainConfig:
    objective: str = "binary"          # binary|multiclass|regression|lambdarank
    num_class: int = 1
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = -1
    lambda_l2: float = 0.0
    lambda_l1: float = 0.0
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    min_data_in_leaf: int = 20
    max_bin: int = 255
    feature_fraction: float = 1.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    early_stopping_round: int = 0
    metric: str = ""                   # default chosen by objective
    seed: int = 0
    parallelism: str = "data_parallel"  # accepted for parity
    # lossguide = LightGBM's leaf-wise best-first growth (default);
    # depthwise = level-wise growth whose histograms batch into one
    # multi-leaf pass per level (XGBoost-hist policy; O(depth) row passes)
    growth_policy: str = "lossguide"
    top_k: int = 20                     # voting_parallel K (parity)
    verbosity: int = -1
    # feature indices treated as categorical (LightGBM categoricalSlotIndexes
    # analogue): identity-binned, split by subset membership
    categorical_features: tuple = ()
    boosting_type: str = "gbdt"        # gbdt|goss|dart|rf
    # dart knobs (LightGBM drop_rate/max_drop/skip_drop defaults)
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    # goss knobs (LightGBM top_rate/other_rate defaults)
    top_rate: float = 0.2
    other_rate: float = 0.1
    # lambdarank eval truncation: NDCG@eval_at on the validation rows
    eval_at: int = 5
    # regression-objective knobs (LightGBM TrainParams.scala:8-40)
    alpha: float = 0.9                 # quantile level / huber delta
    tweedie_variance_power: float = 1.5
    poisson_max_delta_step: float = 0.7
    fair_c: float = 1.0
    # training-lifecycle callbacks + dynamic learning rate
    # (LightGBMDelegate analogue, models/gbdt/delegate.py)
    delegate: Optional[Any] = None


def _objective_p1(cfg: "TrainConfig") -> float:
    """The (single) knob each regression objective consumes."""
    return {
        "quantile": cfg.alpha,
        "huber": cfg.alpha,
        "fair": cfg.fair_c,
        "poisson": cfg.poisson_max_delta_step,
        "tweedie": cfg.tweedie_variance_power,
    }.get(cfg.objective, 0.0)


_TREE_FIELDS = (
    "rec_leaf", "rec_feature", "rec_bin", "rec_is_cat", "rec_active",
    "rec_gain", "leaf_values", "leaf_counts", "rec_catmask",
)


def _trees_from_device_batched(pending: list, mapper: BinMapper) -> list:
    """Materialize many device-grown trees with ONE host fetch per field.

    The per-iteration loop keeps every split record on device; fetching the
    ~8 small record arrays tree by tree costs a full host round-trip each
    (70 ms over a remote-device link — it dominated training wall-clock).
    Stacking per field first turns 8 x n_trees fetches into 8."""
    if not pending:
        return []
    stacked = {
        f: np.asarray(jnp.stack([getattr(g, f) for g in pending]))
        for f in _TREE_FIELDS
    }
    return [
        _tree_from_host_records({f: stacked[f][i] for f in _TREE_FIELDS}, mapper)
        for i in range(len(pending))
    ]


def _pad_catmask(cm: np.ndarray) -> np.ndarray:
    """Histogram-space catmask (S, B_hist) -> record-space (S, NUM_BINS).

    Training histograms use the smallest tile-aligned bin space covering
    ``max_bin``; stored trees keep the full uint8 space so prediction's
    category->bin lookup (category_bin_slot, clipped to NUM_BINS-1) can
    never index out of the mask. Padding bins carry no categories -> False
    (unseen categories route RIGHT, LightGBM's other-category default)."""
    if cm.shape[-1] >= NUM_BINS:
        return cm
    pad = [(0, 0)] * (cm.ndim - 1) + [(0, NUM_BINS - cm.shape[-1])]
    return np.pad(cm, pad)


def _tree_from_host_records(rec: dict, mapper: BinMapper) -> Tree:
    rec_leaf = rec["rec_leaf"]
    rec_feature = rec["rec_feature"]
    rec_bin = rec["rec_bin"]
    is_cat = rec["rec_is_cat"]
    thr = np.array(
        [
            mapper.threshold_value(int(f), int(b)) if (f >= 0 and not c) else np.inf
            for f, b, c in zip(rec_feature, rec_bin, is_cat)
        ],
        dtype=np.float64,
    )
    has_cat = bool(is_cat.any())
    return Tree(
        leaf=rec_leaf,
        feature=rec_feature,
        threshold=thr,
        active=rec["rec_active"],
        gain=rec["rec_gain"],
        values=rec["leaf_values"],
        counts=rec["leaf_counts"],
        is_cat=is_cat if has_cat else None,
        catmask=_pad_catmask(rec["rec_catmask"]) if has_cat else None,
    )


def _tree_from_device(grown: Any, mapper: BinMapper, value_scale: float = 1.0) -> Tree:
    rec_leaf = np.asarray(grown.rec_leaf)
    rec_feature = np.asarray(grown.rec_feature)
    rec_bin = np.asarray(grown.rec_bin)
    is_cat = np.asarray(grown.rec_is_cat)
    thr = np.array(
        [
            # categorical splits route by catmask, never by threshold:
            # +inf keeps any accidental numeric comparison all-left
            mapper.threshold_value(int(f), int(b)) if (f >= 0 and not c) else np.inf
            for f, b, c in zip(rec_feature, rec_bin, is_cat)
        ],
        dtype=np.float64,
    )
    has_cat = bool(is_cat.any())
    values = np.asarray(grown.leaf_values)
    if value_scale != 1.0:
        values = (values * value_scale).astype(values.dtype)
    return Tree(
        leaf=rec_leaf,
        feature=rec_feature,
        threshold=thr,
        active=np.asarray(grown.rec_active),
        gain=np.asarray(grown.rec_gain),
        values=values,
        counts=np.asarray(grown.leaf_counts),
        is_cat=is_cat if has_cat else None,
        catmask=_pad_catmask(np.asarray(grown.rec_catmask)) if has_cat else None,
    )


def grouped_ndcg(
    scores: np.ndarray, labels: np.ndarray, group_ids: np.ndarray, k: int = 5
) -> float:
    """Mean NDCG@k over query groups with LightGBM's 2^rel-1 gain.

    The real ranking eval the reference's early stopping uses
    (lightgbm/LightGBMRanker.scala; TrainUtils.scala:276-308 evaluates the
    native booster's ndcg@k). Mirrors recommendation/evaluator.py's
    per-user NDCG, specialized to flat score/label arrays."""
    total, n_groups = 0.0, 0
    for gid in np.unique(group_ids):
        m = group_ids == gid
        s, rel = scores[m], labels[m]
        if len(s) == 0:
            continue
        kk = min(k, len(s))
        order = np.argsort(-s, kind="stable")[:kk]
        gains = 2.0 ** rel - 1.0
        disc = 1.0 / np.log2(np.arange(2, kk + 2))
        dcg = float((gains[order] * disc).sum())
        ideal = np.sort(gains)[::-1][:kk]
        idcg = float((ideal * disc).sum())
        # all-zero-relevance groups score 1.0 (LightGBM's NDCG convention:
        # nothing to rank correctly means nothing ranked incorrectly)
        total += dcg / idcg if idcg > 0 else 1.0
        n_groups += 1
    return total / max(n_groups, 1)


def _local_block_rows(garr: Any, n: int) -> np.ndarray:
    """First ``n`` rows of THIS process's block of a process-stacked global
    array (the layout shard_batch_multihost builds: one contiguous block
    per process, local padding at the block tail)."""
    shards = sorted(
        garr.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    block = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    return block[:n]


def _gather_rows(local: np.ndarray, n: int, share: int) -> np.ndarray:
    """Pad this process's first-n rows to the common block size and
    allgather -> (nproc * share, ...) global rows (padding rows are 0).
    Every process computes validation metrics on the identical gathered
    arrays, so early-stopping decisions stay convergent across SPMD
    processes (divergent control flow would deadlock the next collective).
    """
    import jax.experimental.multihost_utils as mhu

    local = local.reshape(n, -1).astype(np.float64)
    buf = np.zeros((share, local.shape[1]), np.float64)
    buf[:n] = local
    ga = np.asarray(mhu.process_allgather(buf))
    return ga.reshape(-1, local.shape[1])


def _eval_metric(
    cfg: TrainConfig,
    scores: np.ndarray,
    y: np.ndarray,
    mask: np.ndarray,
    group_ids: Optional[np.ndarray] = None,
) -> tuple:
    """Returns (name, value, higher_is_better) on masked rows."""
    if mask.sum() == 0:
        return ("none", float("nan"), False)
    s, yy = scores[mask], y[mask]
    obj = cfg.objective
    metric = cfg.metric
    if obj == "binary":
        p = objectives.sigmoid(s)
        if metric in ("", "binary_logloss"):
            p = np.clip(p, 1e-15, 1 - 1e-15)
            return ("binary_logloss", float(-(yy * np.log(p) + (1 - yy) * np.log(1 - p)).mean()), False)
        if metric == "auc":
            from mmlspark_tpu.core.metrics import binary_auc

            return ("auc", binary_auc(yy, p), True)
        return ("binary_error", float(((p > 0.5) != (yy > 0.5)).mean()), False)
    if obj == "multiclass":
        p = objectives.softmax(s)
        idx = yy.astype(np.int64)
        return (
            "multi_logloss",
            float(-np.log(np.clip(p[np.arange(len(idx)), idx], 1e-15, 1)).mean()),
            False,
        )
    if obj == "lambdarank":
        k = cfg.eval_at
        if metric.startswith("ndcg@"):
            k = int(metric.split("@", 1)[1])
        g = group_ids[mask] if group_ids is not None else np.zeros(len(yy), np.int64)
        return (f"ndcg@{k}", grouped_ndcg(s, yy, g, k=k), True)
    return (
        objectives.regression_metric_name(obj),
        float(
            objectives.regression_loss(obj, s, yy, _objective_p1(cfg)).mean()
        ),
        False,
    )


def _iteration_core(
    bins: jnp.ndarray,
    scores: jnp.ndarray,
    y_enc: Optional[jnp.ndarray],
    w_it: jnp.ndarray,
    it_key: jnp.ndarray,
    fm: jnp.ndarray,
    cat_mask: Optional[jnp.ndarray],
    g_pre: Optional[jnp.ndarray],
    h_pre: Optional[jnp.ndarray],
    rank_idx: Optional[jnp.ndarray],
    rank_valid: Optional[jnp.ndarray],
    obj_p1: Any,
    top_rate: float,
    other_rate: float,
    lambda_l2: float,
    lambda_l1: float,
    min_sum_hessian: float,
    min_gain: float,
    learning_rate: float,
    *,
    objective: str,
    k: int,
    grad_pre: bool,
    is_goss: bool,
    use_voting: bool,
    has_cat: bool,
    num_leaves: int,
    max_depth: int,
    min_data_in_leaf: int,
    top_k: int,
    mesh: Any,
    depthwise: bool = False,
    partitioned: bool = False,
    num_bins: int = NUM_BINS,
) -> tuple:
    """One boosting iteration (traced): gradients, GOSS weights, k tree
    grows and the score update. Shared by the per-iteration dispatch path
    (:func:`_fused_iteration`) and the scan-fused chunk path
    (:func:`_scan_chunk`). Returns (new_scores, list of GrownTree)."""
    if grad_pre:
        g_dev, h_dev = g_pre, h_pre
    elif objective == "binary":
        g_dev, h_dev = objectives.binary_grad_hess(scores, y_enc)
    elif objective == "multiclass":
        g_dev, h_dev = objectives.multiclass_grad_hess(scores, y_enc)
    elif objective == "lambdarank":
        # device-resident pairwise gradients over padded contiguous groups
        # — ranking trains scan-fused with zero per-iteration host syncs
        g_dev, h_dev = objectives.lambdarank_grad_hess_device(
            scores, y_enc, rank_idx, rank_valid
        )
    else:
        g_dev, h_dev = objectives.regression_grad_hess(
            objective, scores, y_enc, obj_p1
        )
    # pre-GOSS weights (bagging/user weights only): LightGBM's
    # RenewTreeOutput computes the leaf percentile over the sampled rows at
    # their ORIGINAL data weights — the (1-a)/b amplification is a
    # histogram-unbiasedness device, not a data weight
    w_renew = w_it
    if is_goss:
        g_abs = jnp.abs(g_dev).sum(axis=1) if k > 1 else jnp.abs(g_dev)
        u = jax.random.uniform(jax.random.fold_in(it_key, 2), w_it.shape)
        w_it = w_it * _goss_weights(g_abs, w_it, u, top_rate, other_rate)
    grow_kw = dict(
        num_leaves=num_leaves,
        lambda_l2=lambda_l2,
        lambda_l1=lambda_l1,
        min_sum_hessian=min_sum_hessian,
        min_gain=min_gain,
        learning_rate=learning_rate,
        feature_mask=fm,
        max_depth=max_depth,
        min_data_in_leaf=min_data_in_leaf,
        num_bins=num_bins,
    )
    grown_list, deltas = [], []
    for c in range(k) if k > 1 else [0]:
        gc = g_dev[:, c] if k > 1 else g_dev
        hc = h_dev[:, c] if k > 1 else h_dev
        if use_voting:
            from mmlspark_tpu.models.gbdt.voting import grow_tree_voting

            grown = grow_tree_voting(
                bins, gc, hc, w_it, top_k=top_k, mesh=mesh,
                categorical_mask=cat_mask, **grow_kw
            )
        elif depthwise:
            from mmlspark_tpu.models.gbdt.treegrow import grow_tree_depthwise

            grown = grow_tree_depthwise(
                bins, gc, hc, w_it, categorical_mask=cat_mask,
                mesh=mesh, shard_axis=_DATA_AXIS if mesh is not None else None,
                **grow_kw,
            )
        else:
            grown = grow_tree(
                bins, gc, hc, w_it, categorical_mask=cat_mask,
                partitioned=partitioned,
                mesh=mesh, shard_axis=_DATA_AXIS if mesh is not None else None,
                **grow_kw,
            )
        if (
            objective in objectives.RENEWED_KINDS
            and not grad_pre
            and not use_voting
        ):
            # LightGBM's RenewTreeOutput: quantile-family leaf values are
            # the weighted alpha-percentile of the leaf's residuals, not
            # the unit-hessian Newton step (which undershoots the target
            # percentile). Voting keeps Newton values: its row_leaf stays
            # shard-local and a global sort would defeat the reduced-
            # communication design.
            q = obj_p1 if objective == "quantile" else 0.5
            # percentile over the SAMPLED rows (w_it > 0) at their
            # pre-GOSS data weights (see w_renew above)
            w_sel = jnp.where(w_it > 0, w_renew, 0.0)
            w_q = (
                w_sel / jnp.maximum(1.0, jnp.abs(y_enc))
                if objective == "mape" else w_sel
            )
            renewed = objectives.leaf_quantile_renewal(
                grown.row_leaf, y_enc - scores, w_q, num_leaves, q
            ) * learning_rate
            grown = grown._replace(
                leaf_values=jnp.where(grown.leaf_counts > 0, renewed, 0.0)
            )
        grown_list.append(grown)
        deltas.append(grown.leaf_values[grown.row_leaf])
    new_scores = scores + (jnp.stack(deltas, axis=1) if k > 1 else deltas[0])
    return new_scores, grown_list


@functools.partial(
    jax.jit,
    static_argnames=(
        "objective", "k", "grad_pre", "is_goss", "use_voting", "has_cat",
        "num_leaves", "max_depth", "min_data_in_leaf", "top_k", "mesh",
        "depthwise", "partitioned", "num_bins", "hist_mode",
    ),
)
def _fused_iteration(
    bins: jnp.ndarray,
    scores: jnp.ndarray,
    y_enc: Optional[jnp.ndarray],
    w_it: jnp.ndarray,
    it_key: jnp.ndarray,
    fm: jnp.ndarray,
    cat_mask: Optional[jnp.ndarray],
    g_pre: Optional[jnp.ndarray],
    h_pre: Optional[jnp.ndarray],
    rank_idx: Optional[jnp.ndarray],
    rank_valid: Optional[jnp.ndarray],
    obj_p1: Any,
    top_rate: float,
    other_rate: float,
    lambda_l2: float,
    lambda_l1: float,
    min_sum_hessian: float,
    min_gain: float,
    learning_rate: float,
    *,
    objective: str,
    k: int,
    grad_pre: bool,
    is_goss: bool,
    use_voting: bool,
    has_cat: bool,
    num_leaves: int,
    max_depth: int,
    min_data_in_leaf: int,
    top_k: int,
    mesh: Any,
    depthwise: bool = False,
    partitioned: bool = False,
    num_bins: int = NUM_BINS,
    hist_mode: str = "",
) -> tuple:
    """One whole boosting iteration as ONE XLA program — the dispatch-per-
    iteration path kept for the modes whose loop does host work between
    iterations (dart's tree mutation, lambdarank's host gradients,
    delegates, multihost's replicated reads). Everything else trains
    through :func:`_scan_chunk`, which fuses MANY iterations per dispatch.
    Returns (new_scores, tuple of GrownTree per class)."""
    new_scores, grown_list = _iteration_core(
        bins, scores, y_enc, w_it, it_key, fm, cat_mask, g_pre, h_pre,
        rank_idx, rank_valid,
        obj_p1, top_rate, other_rate, lambda_l2, lambda_l1, min_sum_hessian,
        min_gain, learning_rate,
        objective=objective, k=k, grad_pre=grad_pre, is_goss=is_goss,
        use_voting=use_voting, has_cat=has_cat, num_leaves=num_leaves,
        max_depth=max_depth, min_data_in_leaf=min_data_in_leaf,
        top_k=top_k, mesh=mesh, depthwise=depthwise,
        partitioned=partitioned, num_bins=num_bins,
    )
    return new_scores, tuple(grown_list)


# computed on device inside the scan so eval costs no extra host round
# trip (the host only reads the (C,) metric vector); all lower-is-better
# except auc/ndcg (see _HIGHER_METRICS)
_DEVICE_METRICS = (
    "binary_logloss", "binary_error", "multi_logloss", "auc",
) + objectives.REGRESSION_KINDS
_HIGHER_METRICS = ("ndcg", "auc")


def _device_metric(
    s: jnp.ndarray, y: jnp.ndarray, vw: jnp.ndarray, eval_kind: str,
    obj_p1: Any = 0.0,
) -> jnp.ndarray:
    """Masked-mean validation metric, formula-matched to :func:`_eval_metric`
    (same clips/logs so early-stopping decisions agree across paths)."""
    wsum = jnp.maximum(vw.sum(), 1.0)
    if eval_kind == "auc":
        return objectives.binary_auc_device(s, y, vw)
    if eval_kind == "binary_logloss":
        p = jnp.clip(jax.nn.sigmoid(s), 1e-15, 1 - 1e-15)
        loss = -(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))
    elif eval_kind == "binary_error":
        p = jax.nn.sigmoid(s)
        loss = ((p > 0.5) != (y > 0.5)).astype(jnp.float32)
    elif eval_kind == "multi_logloss":
        p = jax.nn.softmax(s, axis=-1)
        picked = jnp.clip((p * y).sum(axis=-1), 1e-15, 1.0)
        loss = -jnp.log(picked)
    else:  # the regression-objective zoo's own pointwise loss
        loss = objectives.regression_loss(eval_kind, s, y, obj_p1, xp=jnp)
    return (loss * vw).sum() / wsum


# fields packed (in this order) into the one per-chunk host fetch;
# rec_catmask is appended only when the model has categorical splits
_PACK_FIELDS = (
    "rec_leaf", "rec_feature", "rec_bin", "rec_active", "rec_gain",
    "leaf_values", "leaf_counts", "rec_is_cat",
)


@functools.partial(
    jax.jit,
    static_argnames=(
        "objective", "k", "grad_pre", "is_goss", "use_voting", "has_cat",
        "num_leaves", "max_depth", "min_data_in_leaf", "top_k", "mesh",
        "depthwise", "partitioned", "bagging_freq", "eval_kind", "is_rf",
        "num_bins", "eval_k", "hist_mode",
    ),
)
def _scan_chunk(
    bins: jnp.ndarray,
    scores0: jnp.ndarray,
    y_enc: Optional[jnp.ndarray],
    w_base: jnp.ndarray,
    bag0: jnp.ndarray,
    base_key: jnp.ndarray,
    it_idx: jnp.ndarray,          # (C,) int32 absolute iteration numbers
    fms: jnp.ndarray,             # (C, d) f32 feature-fraction masks
    cat_mask: Optional[jnp.ndarray],
    g_pre: Optional[jnp.ndarray],
    h_pre: Optional[jnp.ndarray],
    rank_idx: Optional[jnp.ndarray],
    rank_valid: Optional[jnp.ndarray],
    rank_idx_eval: Optional[jnp.ndarray],
    rank_valid_eval: Optional[jnp.ndarray],
    y_eval: Optional[jnp.ndarray],
    valid_w: Optional[jnp.ndarray],
    rf_base: Optional[jnp.ndarray],
    obj_p1: Any,
    bagging_fraction: float,
    top_rate: float,
    other_rate: float,
    lambda_l2: float,
    lambda_l1: float,
    min_sum_hessian: float,
    min_gain: float,
    learning_rate: float,
    *,
    objective: str,
    k: int,
    grad_pre: bool,
    is_goss: bool,
    use_voting: bool,
    has_cat: bool,
    num_leaves: int,
    max_depth: int,
    min_data_in_leaf: int,
    top_k: int,
    mesh: Any,
    depthwise: bool,
    partitioned: bool,
    bagging_freq: int,
    eval_kind: str,
    is_rf: bool,
    num_bins: int = NUM_BINS,
    eval_k: int = 5,
    hist_mode: str = "",
) -> tuple:
    """C whole boosting iterations as ONE XLA program (``lax.scan`` over
    iterations). On a relay-attached TPU every dispatch costs ~35 ms and
    every fetch ~70 ms, so the per-iteration loop pays
    O(iterations) round trips; this pays ONE dispatch per chunk, computes
    the eval metric on device, and packs every tree record of the chunk
    into a single f32 buffer so the host does exactly one fetch.

    Returns (final_scores, final_bag, packed (C, k, W) f32, metrics (C,)).
    """
    L = num_leaves

    def body(carry: tuple, xs: tuple) -> tuple:
        scores, bag = carry
        it, fm = xs
        it_key = jax.random.fold_in(base_key, it)
        if bagging_freq > 0:
            u = jax.random.uniform(jax.random.fold_in(it_key, 1), bag.shape)
            newbag = (u < bagging_fraction).astype(jnp.float32)
            bag = jnp.where(it % bagging_freq == 0, newbag, bag)
            w_it = w_base * bag
        else:
            w_it = w_base
        new_scores, grown_list = _iteration_core(
            bins, scores, y_enc, w_it, it_key, fm, cat_mask, g_pre, h_pre,
            rank_idx, rank_valid,
            obj_p1, top_rate, other_rate, lambda_l2, lambda_l1,
            min_sum_hessian, min_gain, learning_rate,
            objective=objective, k=k, grad_pre=grad_pre, is_goss=is_goss,
            use_voting=use_voting, has_cat=has_cat, num_leaves=num_leaves,
            max_depth=max_depth, min_data_in_leaf=min_data_in_leaf,
            top_k=top_k, mesh=mesh, depthwise=depthwise,
            partitioned=partitioned, num_bins=num_bins,
        )
        recs = tuple(
            tuple(
                # counts split hi/lo so the f32 buffer stays exact past
                # 2^24 rows per leaf (a single f32 would round them)
                (getattr(g, f) // 4096, getattr(g, f) % 4096)
                if f == "leaf_counts"
                else (getattr(g, f),)
                for f in _PACK_FIELDS
            )
            for g in grown_list
        )
        recs = tuple(
            tuple(a for grp in r for a in grp)
            + ((g.rec_catmask,) if has_cat else ())
            for r, g in zip(recs, grown_list)
        )
        if eval_kind == "none":
            m = jnp.float32(0.0)
        else:
            s_eval = new_scores
            if is_rf:
                s_eval = rf_base + new_scores / (it.astype(jnp.float32) + 1.0)
            if eval_kind == "ndcg":
                m = objectives.grouped_ndcg_device(
                    s_eval, y_eval, rank_idx_eval, rank_valid_eval, k=eval_k
                )
            else:
                m = _device_metric(s_eval, y_eval, valid_w, eval_kind, obj_p1)
        return (new_scores, bag), (recs, m)

    (scores, bag), (recs, metrics) = jax.lax.scan(
        body, (scores0, bag0), (it_idx, fms)
    )
    C = it_idx.shape[0]

    def flat(i: int, a: jnp.ndarray) -> jnp.ndarray:
        if has_cat and i == len(recs[0]) - 1:
            # categorical bitmask: 16 bools per f32 word (exact: < 2^16),
            # a 32x smaller fetch than one f32 per bool
            bits = a.reshape(C, -1, 16).astype(jnp.float32)
            return (bits * (2.0 ** jnp.arange(16, dtype=jnp.float32))).sum(-1)
        return a.astype(jnp.float32).reshape(C, -1)

    packed = jnp.stack(
        [
            jnp.concatenate(
                [flat(i, a) for i, a in enumerate(recs[c])], axis=1
            )
            for c in range(len(recs))
        ],
        axis=1,
    )  # (C, k, W)
    return scores, bag, packed, metrics


def _unpack_chunk_trees(
    packed: np.ndarray, keep: int, k: int, L: int, has_cat: bool,
    num_bins: int, mapper: BinMapper,
) -> list:
    """Split the chunk's packed f32 record buffer back into host Trees."""
    widths = (
        [L - 1] * 5 + [L, L, L, L - 1]
        + ([(L - 1) * num_bins // 16] if has_cat else [])
    )
    offs = np.cumsum([0] + widths)
    trees = []
    for i in range(keep):
        for c in range(k):
            row = packed[i, c]
            parts = [
                row[offs[j]: offs[j + 1]] for j in range(len(widths))
            ]
            counts = (
                parts[6].astype(np.int64) * 4096 + parts[7].astype(np.int64)
            )
            rec = {
                "rec_leaf": parts[0].astype(np.int32),
                "rec_feature": parts[1].astype(np.int32),
                "rec_bin": parts[2].astype(np.int32),
                "rec_active": parts[3] > 0.5,
                "rec_gain": parts[4].astype(np.float32),
                "leaf_values": parts[5].astype(np.float32),
                "leaf_counts": counts.astype(np.int32),
                "rec_is_cat": parts[8] > 0.5,
                "rec_catmask": (
                    (
                        (
                            parts[9].astype(np.int64)[:, None]
                            >> np.arange(16)
                        ) & 1
                    ).astype(bool).reshape(L - 1, num_bins)
                    if has_cat
                    else np.zeros((L - 1, num_bins), bool)
                ),
            }
            trees.append(_tree_from_host_records(rec, mapper))
    return trees


@jax.jit
def _goss_weights(g_abs: jnp.ndarray, w: jnp.ndarray, u: jnp.ndarray,
                  top_rate: float, other_rate: float) -> jnp.ndarray:
    """One-side sampling weights on device: rows ranked by |g| among rows
    with nonzero base weight; top a kept at 1x, random b of the rest kept
    at (1-a)/b, remainder dropped."""
    eligible = w > 0
    n_eligible = jnp.maximum(eligible.sum(), 1)
    n_top = jnp.maximum((top_rate * n_eligible).astype(jnp.int32), 1)
    masked = jnp.where(eligible, g_abs, -jnp.inf)
    # value threshold for the top-a set (ties may admit a few extra rows;
    # LightGBM's exact-count selection differs by at most the tie set)
    srt = jnp.sort(masked)[::-1]
    thresh = srt[jnp.clip(n_top - 1, 0, masked.shape[0] - 1)]
    is_top = eligible & (masked >= thresh)
    # LightGBM draws b*n rows out of the (1-a)*n remainder — per-row
    # probability b/(1-a) — and amplifies by (1-a)/b, so each non-top row's
    # EXPECTED histogram weight is exactly 1 (unbiased)
    p_other = jnp.minimum(other_rate / jnp.maximum(1.0 - top_rate, 1e-12), 1.0)
    amp = (1.0 - top_rate) / jnp.maximum(other_rate, 1e-12)
    is_other = eligible & ~is_top & (u < p_other)
    return jnp.where(is_top, 1.0, jnp.where(is_other, amp, 0.0)).astype(jnp.float32)


def train(
    x: np.ndarray,
    y: np.ndarray,
    cfg: TrainConfig,
    sample_weight: Optional[np.ndarray] = None,
    init_score: Optional[np.ndarray] = None,
    valid_mask: Optional[np.ndarray] = None,
    group_ids: Optional[np.ndarray] = None,
    init_booster: Optional[Booster] = None,
    base_score: Any = 0.0,
    shard: bool = True,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 10,
    resume_from: Optional[str] = None,
    fused_rounds: int = 0,
) -> Booster:
    """Fit a booster on dense (n, d) features or a CSR triple.

    ``x`` may be a scipy-style CSR matrix (anything with ``data``/
    ``indices``/``indptr``/``shape``); binning then runs per-column over the
    stored values only (LightGBMUtils.scala:211-265 builds native datasets
    from dense or sparse rows the same way).

    ``base_score``: boost_from_average baseline (scalar, or (k,) for
    multiclass) — added to the initial scores AND stored on the booster so
    prediction replays it.

    ``fused_rounds``: scan-fused chunk control — 0 (default) sizes chunks
    automatically (the whole run without early stopping, bounded chunks
    with it), 1 forces the legacy one-dispatch-per-round loop (kept as
    the debugging/fallback path; bit-identical results), N > 1 caps the
    chunk at N rounds. Chunk size never changes the trained model — only
    how many XLA dispatches the loop costs (O(rounds / N) vs O(rounds)).

    Preemption safety (models/gbdt/checkpoint.py): ``checkpoint_dir``
    serializes trees + device score/bag state + host RNG every
    ``checkpoint_every`` rounds; ``resume_from`` continues from the last
    complete checkpoint and reproduces the uninterrupted run bit-for-bit
    (same config fingerprint enforced). Passing the same directory for
    both gives crash-loop-safe auto-resume. Single-process only."""
    if cfg.boosting_type not in BOOSTING_TYPES:
        raise ValueError(f"boosting_type must be one of {BOOSTING_TYPES}")
    canon = objectives.canonical_objective(cfg.objective)
    if canon not in ("binary", "multiclass", "lambdarank") + objectives.REGRESSION_KINDS:
        raise ValueError(f"unknown objective {cfg.objective!r}")
    if canon != cfg.objective:
        cfg = _dc_replace(cfg, objective=canon)
    if canon in objectives.LOG_LINK_KINDS and np.any(np.asarray(y) < 0):
        # log-link objectives model a nonnegative mean; LightGBM errors too
        raise ValueError(f"objective {canon!r} requires non-negative labels")
    if cfg.growth_policy not in ("lossguide", "depthwise"):
        raise ValueError(
            f"growth_policy must be 'lossguide' or 'depthwise', got {cfg.growth_policy!r}"
        )
    if cfg.growth_policy == "depthwise" and cfg.parallelism == "voting_parallel":
        # the voting grower is leaf-wise; silently dropping an explicit
        # depthwise request would benchmark/deploy the wrong policy
        raise ValueError("growth_policy='depthwise' is incompatible with voting_parallel")
    if cfg.boosting_type == "goss" and cfg.top_rate + cfg.other_rate > 1.0:
        # LightGBM hard-errors here too: the sampler's unbiasedness
        # guarantee needs b/(1-a) <= 1
        raise ValueError("goss requires top_rate + other_rate <= 1")
    from mmlspark_tpu.models.gbdt.binning import BinnedDataset, is_sparse

    pre_binned = isinstance(x, BinnedDataset)
    sparse_input = False if pre_binned else is_sparse(x)
    if pre_binned:
        # the out-of-core path: rows were binned chunk-by-chunk against
        # a mapper fitted from streaming sketches — everything that
        # would need the FLOAT matrix back is out of contract here
        if cfg.boosting_type == "dart":
            raise ValueError(
                "pre-binned input does not support dart (dropped-tree "
                "re-prediction needs the float matrix)"
            )
        if init_booster is not None and init_booster.trees:
            raise ValueError(
                "pre-binned input does not support init_booster "
                "(warm-start scoring needs the float matrix)"
            )
        if cfg.categorical_features:
            raise ValueError(
                "pre-binned input does not support categorical_features "
                "(identity binning is a fit-time decision)"
            )
        if x.mapper.max_bin > cfg.max_bin:
            # hist_bins is sized from cfg.max_bin: a code past it would
            # scatter into the wrong plane and train a silently wrong
            # model — refuse instead
            raise ValueError(
                f"pre-binned input was quantized with max_bin="
                f"{x.mapper.max_bin} but cfg.max_bin={cfg.max_bin}; "
                "bin codes would overflow the histogram space"
            )
    n, d = x.shape
    # np.matrix-shaped labels (scipy .sum(axis=) results) flatten silently
    y = np.asarray(y).reshape(n)
    k = cfg.num_class if cfg.objective == "multiclass" else 1
    cat_features = tuple(int(f) for f in (cfg.categorical_features or ()))

    # multi-host: every process calls train() with ITS OWN rows; the jitted
    # grower then runs SPMD over the process-spanning mesh and XLA carries
    # the histogram allreduce over DCN (the reference's per-machine dataset
    # build + socket allreduce, TrainUtils.scala:26-66,496-512)
    multihost = shard and jax.process_count() > 1
    # elastic gang training (parallel/elastic.py): each member trains its
    # contiguous partition rows UNSHARDED; the host growers' histograms
    # are summed across members by the gang's TCP allreduce, so every
    # member grows the identical tree. Checkpoints gather/scatter global
    # row state so a resume at a different world size is well-defined.
    from mmlspark_tpu.parallel import elastic as _elastic

    gang = _elastic.active_gang()
    if gang is not None:
        if shard or multihost:
            raise ValueError(
                "elastic gang training requires shard=False (members "
                "train their partition rows unsharded; the gang "
                "allreduce crosses hosts)"
            )
        if sparse_input:
            raise ValueError(
                "elastic gang training requires dense input (the global "
                "bin-bound gather is dense)"
            )
        if valid_mask is not None and np.any(valid_mask):
            raise ValueError(
                "elastic gang training does not support validation/"
                "early stopping (the eval metric would be member-local)"
            )
    # lambdarank across processes: each process computes its own groups'
    # pairwise gradients on host — a query group must live ENTIRELY on one
    # process (the reference has the same contract: LightGBMRanker requires
    # a query's rows on a single partition, LightGBMRanker.scala).
    # voting_parallel across processes: the shard_map grower's psums simply
    # ride DCN instead of ICI — same program, bigger mesh.

    if pre_binned:
        if multihost:
            raise ValueError(
                "pre-binned input is single-process / elastic-gang only"
            )
        mapper = x.mapper
    elif multihost:
        # bin bounds must be IDENTICAL on every process: fit the mapper on
        # a NaN-padded sample allgathered from all processes (NaN rows are
        # ignored by quantile fitting; for sparse inputs absent entries
        # densify to NaN, matching the missing-bin transform semantics)
        import jax.experimental.multihost_utils as mhu

        # FIXED buffer size (process-count-based only): processes may hold
        # unequal row counts, and allgather needs identical shapes — short
        # processes leave NaN rows, which quantile fitting ignores
        k_s = max(1, 50_000 // jax.process_count())
        samp = np.full((k_s, d), np.nan, np.float32)
        take = np.random.default_rng(cfg.seed).choice(
            n, min(n, k_s), replace=False
        )
        samp[: len(take)] = (
            _densify(x[take]) if sparse_input else np.asarray(x[take], np.float32)
        )
        if cat_features:
            if sparse_input:
                # match the single-host BinMapper error exactly — the
                # sample-densified path must not silently accept what one
                # process would reject
                raise ValueError(
                    "categorical features require dense input (sparse "
                    "columns have no stable category<->bin identity for "
                    "absent entries)"
                )
            # categorical hi must cover every category present ANYWHERE,
            # not just in the capped sample: allgather full-column extrema
            # (also makes the range validation a globally identical
            # decision — a raise on one process only would desync SPMD)
            ext = np.zeros((len(cat_features), 2), np.float64)
            for j, f in enumerate(cat_features):
                col = np.asarray(x[:, f], np.float64)
                col = col[~np.isnan(col)]
                ext[j] = (col.min(), col.max()) if len(col) else (0.0, 0.0)
            gext = np.asarray(mhu.process_allgather(ext))
            gmin = gext[..., 0].min(axis=0)
            gmax = gext[..., 1].max(axis=0)
            bad = np.flatnonzero((gmin < 0) | (gmax > cfg.max_bin - 2))
            if len(bad):
                raise ValueError(
                    f"categorical features {[cat_features[b] for b in bad]} "
                    f"have values outside [0, {cfg.max_bin - 2}] — "
                    "re-index categories first"
                )
            # plant the global max into this process's sample so the
            # fitted identity range covers the unsampled tail everywhere
            for j, f in enumerate(cat_features):
                samp[0, f] = gmax[j]
        global_sample = np.asarray(mhu.process_allgather(samp)).reshape(-1, d)
        mapper = BinMapper.fit(
            global_sample, max_bin=cfg.max_bin, seed=cfg.seed,
            categorical_features=cat_features,
        )
    elif gang is not None:
        # bin bounds must be identical on every gang member AND invariant
        # across world sizes (a resumed shrunk-world run must interpret
        # bins exactly like a fresh run from the same checkpoint): fit on
        # the gang-gathered GLOBAL rows, not this member's slice
        mapper = BinMapper.fit(
            gang.binning_rows(np.asarray(x, np.float32)),
            max_bin=cfg.max_bin, seed=cfg.seed,
            categorical_features=cat_features,
        )
    else:
        mapper = BinMapper.fit(
            x, max_bin=cfg.max_bin, seed=cfg.seed, categorical_features=cat_features
        )
    bins_host = x.bins if pre_binned else mapper.transform(x)
    # histogram bin space: the smallest MXU-tile-aligned width covering
    # every bin code (codes live in [0, max_bin-1]). At the default
    # max_bin=255 this is the full uint8 space (256); smaller max_bin
    # shrinks the one-hot compare loop — the VPU-bound part of the Pallas
    # kernel — nearly proportionally. 16-aligned: bf16 sublane tile.
    hist_bins = max(16, ((cfg.max_bin + 15) // 16) * 16)
    cat_mask_dev = None
    if cat_features:
        cat_mask_host = np.zeros(d, bool)
        cat_mask_host[list(cat_features)] = True
        cat_mask_dev = jnp.asarray(cat_mask_host)

    train_mask = (
        ~valid_mask if valid_mask is not None else np.ones(n, bool)
    )
    w = sample_weight if sample_weight is not None else np.ones(n, np.float32)
    w = np.where(train_mask, w, 0.0).astype(np.float32)

    bagging_fraction = cfg.bagging_fraction
    bagging_freq = cfg.bagging_freq
    if cfg.boosting_type == "rf" and not (bagging_freq > 0 and bagging_fraction < 1.0):
        # rf without bagging would grow the same tree every round; LightGBM
        # hard-errors here, we default to the classic 0.632 bootstrap rate
        log.info("rf boosting without bagging params: defaulting to bagging_fraction=0.632, bagging_freq=1")
        bagging_fraction, bagging_freq = 0.632, 1
    if cfg.boosting_type == "goss" and bagging_freq > 0:
        log.info("goss boosting: bagging disabled (GOSS is the row sampler)")
        bagging_freq = 0

    # device placement: rows sharded over the data axis when a mesh exists
    mesh = None
    use_voting = False
    if multihost:
        from mmlspark_tpu.parallel.mesh import get_mesh
        from mmlspark_tpu.parallel.sharding import (
            multihost_pad_target,
            shard_batch_multihost,
        )

        mesh = get_mesh()
        share = multihost_pad_target(n)  # equal local block per process
        pad = share - n
        bins_dev = shard_batch_multihost(
            np.pad(bins_host, ((0, pad), (0, 0))), mesh
        )
        w_dev = shard_batch_multihost(np.pad(w, (0, pad)), mesh)
        n_pad = share * jax.process_count()  # GLOBAL padded row count
        if cfg.parallelism == "voting_parallel":
            use_voting = True
    elif shard:
        from mmlspark_tpu.parallel.mesh import DATA_AXIS, get_mesh
        from mmlspark_tpu.parallel.sharding import pad_batch, shard_batch

        mesh = get_mesh()
        n_dev = mesh.devices.size
        bins_p, n_real = pad_batch(bins_host, n_dev)
        pad = bins_p.shape[0] - n
        bins_dev = shard_batch(bins_p, mesh)
        w_dev = shard_batch(np.pad(w, (0, pad)), mesh)
        n_pad = n + pad
        if cfg.parallelism == "voting_parallel":
            if dict(mesh.shape).get(DATA_AXIS, 1) > 1:
                use_voting = True
            else:
                log.info(
                    "voting_parallel needs >1 data shard; "
                    "falling back to data_parallel"
                )
    else:
        pad = 0
        bins_dev = jnp.asarray(bins_host)
        w_dev = jnp.asarray(w)
        n_pad = n

    def padded(a: np.ndarray) -> jnp.ndarray:
        if pad:
            a = np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        if multihost:
            from mmlspark_tpu.parallel.sharding import shard_batch_multihost

            return shard_batch_multihost(a, mesh)
        if shard:
            from mmlspark_tpu.parallel.sharding import shard_batch

            return shard_batch(a)
        return jnp.asarray(a)

    # data-partitioned leaf-wise growth (LightGBM's DataPartition +
    # histogram subtraction, treegrow._grow_tree_partitioned): single-device
    # layouts only — the per-split global row permutation would become
    # cross-device traffic on a sharded mesh, where the masked scatter +
    # GSPMD allreduce path is the right cost model
    import os as _os

    # default OFF on every backend: measured on TPU v5e (tools/
    # tpu_validation.py, 100k x 32, 50 iters, 63 leaves) the partitioned
    # grower runs 9.15 s vs the masked grower's 3.0 s — the MXU one-hot
    # histogram amortizes the full pass so well that the per-split
    # permutation gathers + bucketed re-histogram cost more than they
    # save, inverting the CPU cost model the partition was designed
    # around. Env forces either way (tests force on to cover the path).
    _part_env = _os.environ.get("MMLSPARK_TPU_GBDT_PARTITION")
    _part_default = False
    partitioned = (
        cfg.growth_policy == "lossguide"
        and not multihost
        and not use_voting
        and (mesh is None or mesh.devices.size == 1)
        and (
            _part_env not in ("0", "false") if _part_env is not None
            else _part_default
        )
    )
    # rows sharded over the mesh data axis: hand the mesh to the growers so
    # the histogram op can run its Pallas kernel per shard + psum the planes
    # (ops/histogram.py shard_map lowering) instead of the GSPMD scatter
    hist_sharded = (
        mesh is not None and dict(mesh.shape).get(_DATA_AXIS, 1) > 1
    )

    # -- device-resident loop state -----------------------------------------
    # scores, labels and per-iteration gradients stay sharded on device for
    # the whole loop; the host receives only split records + eval scalars.
    if k > 1:
        scores0 = np.zeros((n, k), np.float32)
        y_onehot_dev = padded(np.eye(k, dtype=np.float32)[y.astype(np.int64)])
    else:
        scores0 = np.zeros(n, np.float32)
        y_dev = padded(y.astype(np.float32))
    scores0 = scores0 + np.asarray(base_score, np.float32)
    if init_score is not None:
        scores0 = scores0 + init_score.astype(scores0.dtype)
    if init_booster is not None and init_booster.trees:
        # score with ALL trees (not the best_iteration prefix predict_raw
        # would default to): merge() replays every init tree, so residuals
        # must be fit against exactly that
        all_iters = len(init_booster.trees) // init_booster.num_class
        prev = init_booster.predict_raw(
            _densify(x) if sparse_input else x, num_iteration=all_iters
        )
        scores0 = scores0 + prev.astype(scores0.dtype)
    scores = padded(scores0)

    is_rf = cfg.boosting_type == "rf"
    is_dart = cfg.boosting_type == "dart"
    is_goss = cfg.boosting_type == "goss"
    early_stopping_round = cfg.early_stopping_round
    if is_dart and early_stopping_round > 0:
        # dropout keeps rescaling trees INSIDE any recorded best-iteration
        # prefix, so the prefix can't reproduce the scores that won —
        # LightGBM hard-errors on this combination, we disable with a note
        log.info("early stopping is not available in dart mode; disabled")
        early_stopping_round = 0
    if is_rf:
        # constant gradients at the initial score; `scores` becomes the
        # running SUM of tree contributions (averaged for eval/predict)
        rf_base = scores
        scores = padded(np.zeros_like(scores0))
        if cfg.objective == "binary":
            g_rf, h_rf = objectives.binary_grad_hess(rf_base, y_dev)
        elif cfg.objective == "multiclass":
            g_rf, h_rf = objectives.multiclass_grad_hess(rf_base, y_onehot_dev)
        elif cfg.objective == "lambdarank":
            g_np, h_np = objectives.lambdarank_grad_hess(
                scores0.astype(np.float64), y.astype(np.float64), group_ids
            )
            g_rf, h_rf = padded(g_np.astype(np.float32)), padded(h_np.astype(np.float32))
        else:
            g_rf, h_rf = objectives.regression_grad_hess(
                cfg.objective, rf_base, y_dev,
                jnp.float32(_objective_p1(cfg)),
            )

    rng = np.random.default_rng(cfg.seed)
    base_key = jax.random.PRNGKey(cfg.seed)
    # per-iteration random masks and the small split-record reads must be
    # REPLICATED arrays under multihost (a bare jax.random.uniform commits
    # to process-local devices, incompatible with cross-process-sharded
    # operands); both jits are hoisted here so the cache hits every round
    if multihost:
        _rep_sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()
        )
        _uniform_global = jax.jit(
            lambda key: jax.random.uniform(key, (n_pad,)),
            out_shardings=_rep_sharding,
        )
        _replicate_small = jax.jit(lambda t: t, out_shardings=_rep_sharding)
    else:
        def _uniform_global(key: Any) -> jnp.ndarray:
            return jax.random.uniform(key, (n_pad,))
    booster = Booster(
        trees=[], objective=cfg.objective, num_class=k, num_features=d,
        base_score=base_score, boosting_type=cfg.boosting_type,
        objective_param=(
            _objective_p1(cfg)
            if cfg.objective in ("quantile", "huber", "fair", "tweedie")
            else None
        ),
    )
    pending_trees: list = []  # device-grown records, materialized after the loop
    x_host_dense: Optional[np.ndarray] = None  # dart re-predicts dropped trees

    best_val = None
    best_iter = -1
    rounds_no_improve = 0
    bag = None
    mh_eval_ctx = None  # lazily gathered (y, valid) global eval arrays

    delegate = cfg.delegate
    lr_cur = float(cfg.learning_rate)

    # -- preemption-safe checkpoint/resume -----------------------------------
    # round-level state capture: trees so far, device scores/bag (exact f32
    # through the host round-trip), the host rng stream, early-stop counters.
    # Resume restores all of it, so the continued run replays the identical
    # iteration-by-iteration computation (chaos suite asserts bit-identity).
    start_round = 0
    resume_bag: Optional[np.ndarray] = None
    _ckpt_fp = None
    checkpoint_every = max(1, int(checkpoint_every))
    if checkpoint_dir or resume_from:
        if multihost:
            raise ValueError(
                "GBDT checkpoint/resume is single-process only (multihost "
                "runs re-rendezvous via jax.distributed instead)"
            )
        from mmlspark_tpu.models.gbdt.checkpoint import (
            TrainCheckpoint,
            config_fingerprint,
            load_checkpoint,
            save_checkpoint,
        )

        # elastic gang: fingerprint the GLOBAL dataset shape — the same
        # run re-sharded over a different world is still the same run
        _ckpt_fp = config_fingerprint(
            cfg, gang.global_n if gang is not None else n, d, k
        )
    if resume_from:
        _rck = load_checkpoint(resume_from)
        if _rck is not None:
            if _rck.fingerprint != _ckpt_fp:
                raise ValueError(
                    f"checkpoint at {resume_from!r} was written by a "
                    "different training configuration or dataset shape — "
                    "refusing to resume (fingerprint mismatch)"
                )
            start_round = _rck.round
            _res_scores = np.asarray(_rck.scores, np.float32)
            if gang is not None:
                # the checkpoint holds GLOBAL row state in global row
                # order: take this member's contiguous slice (which may
                # differ from the slice the checkpoint was written under
                # — that is exactly what a reshard is)
                _res_scores = np.asarray(gang.take_local(_res_scores))
            scores = padded(_res_scores.reshape(scores0.shape))
            resume_bag = _rck.bag
            if gang is not None and resume_bag is not None:
                resume_bag = np.asarray(gang.take_local(resume_bag))
            if resume_bag is not None:
                # the dispatch-per-iteration loop's bagging carry; the
                # fast path re-pads resume_bag into its own scan carry
                bag = padded(np.asarray(resume_bag, np.float32))
            rng.bit_generator.state = _rck.rng_state
            best_val = _rck.best_val
            best_iter = _rck.best_iter
            rounds_no_improve = _rck.rounds_no_improve
            lr_cur = _rck.lr
            booster.trees = list(_rck.booster.trees)
            booster.best_iteration = _rck.booster.best_iteration
            log.info("resuming GBDT training from round %d", start_round)

    def _save_ckpt(next_round: int, bag_state: Any) -> None:
        """Persist state as of entering ``next_round`` (reads the CURRENT
        loop locals — call only at a completed round boundary)."""
        scores_arr = np.asarray(scores)[:n]
        bag_arr = (
            np.asarray(bag_state)[:n] if bag_state is not None else None
        )
        if gang is not None:
            # collective: EVERY member gathers global row state (scatter
            # + allreduce keeps the gang in lockstep), but only the
            # generation coordinator writes the shared checkpoint dir
            scores_arr = gang.all_rows(scores_arr)
            if bag_arr is not None:
                bag_arr = gang.all_rows(bag_arr)
            if not gang.is_writer:
                return
        save_checkpoint(
            checkpoint_dir,
            TrainCheckpoint(
                round=next_round,
                booster=booster,
                scores=scores_arr,
                bag=bag_arr,
                rng_state=rng.bit_generator.state,
                fingerprint=_ckpt_fp,
                best_val=best_val,
                best_iter=best_iter,
                rounds_no_improve=rounds_no_improve,
                lr=lr_cur,
            ),
        )

    # -- scan-fused fast path ------------------------------------------------
    # Everything whose loop needs no host work between iterations trains as
    # chunked lax.scan programs: ONE dispatch (and one packed record fetch)
    # per chunk instead of one per iteration. Excluded: dart (mutates past
    # trees on host), delegates (host callbacks), multihost (replicated
    # small-read choreography), and lambdarank only when its groups are
    # non-contiguous or too large for the padded device kernel (rank_fast
    # above). Eval metrics all run on device now (incl. the searchsorted
    # rank-statistic AUC), so no metric forces the host loop.
    rank_fast = False
    rank_pads = None
    if cfg.objective == "lambdarank" and not multihost and group_ids is not None:
        gids = np.asarray(group_ids)
        runs = 1 + int((gids[1:] != gids[:-1]).sum()) if len(gids) else 0
        # non-contiguous group ids would change grouping semantics — the
        # host path handles those, so don't even build the pad grid
        if runs == len(np.unique(gids)):
            pi, va = objectives.lambdarank_pad_groups(group_ids)
            # padded pairwise tensors are (G, M, M): bound device memory (a
            # few hundred MB) or keep the host-gradient path
            if pi.shape[0] * pi.shape[1] * pi.shape[1] <= (1 << 26):
                rank_fast = True
                rank_pads = (pi, va)
    fast = (
        int(fused_rounds) != 1
        and delegate is None and not multihost and not is_dart
        and (cfg.objective != "lambdarank" or rank_fast)
    )
    eval_needed = valid_mask is not None and bool(np.any(valid_mask))
    eval_kind = "none"
    eval_k = cfg.eval_at
    if eval_needed:
        if cfg.objective == "binary":
            eval_kind = (
                "binary_logloss" if cfg.metric in ("", "binary_logloss")
                else "auc" if cfg.metric == "auc" else "binary_error"
            )
        elif cfg.objective == "multiclass":
            eval_kind = "multi_logloss"
        elif cfg.objective == "lambdarank":
            eval_kind = "ndcg"
            if cfg.metric.startswith("ndcg@"):
                eval_k = int(cfg.metric.split("@", 1)[1])
        else:
            eval_kind = cfg.objective
        if eval_kind not in _DEVICE_METRICS and not (
            eval_kind == "ndcg" and rank_fast
        ):
            fast = False

    if fast:
        eval_on = eval_kind != "none"
        use_bag = bagging_freq > 0 and bagging_fraction < 1.0
        # without early stopping the whole run is ONE chunk; with it, chunk
        # so overshoot past the stopping point is bounded (surplus trees
        # are computed then discarded — stopping decisions replay the (C,)
        # device metric vector and match the sequential path exactly)
        C_full = (
            cfg.num_iterations if early_stopping_round == 0
            else min(cfg.num_iterations, max(16, early_stopping_round))
        )
        if int(fused_rounds) > 1:
            C_full = max(1, min(C_full, int(fused_rounds)))
        if checkpoint_dir:
            # chunk boundaries ARE the checkpoint (and fault-injection)
            # boundaries; align them so every checkpoint lands exactly
            # every checkpoint_every rounds
            C_full = max(1, min(C_full, checkpoint_every))
        bag_dev = jnp.ones_like(w_dev)
        if resume_bag is not None:
            bag_dev = padded(np.asarray(resume_bag, np.float32))
        y_eval = valid_w = rf_base_dev = None
        rank_idx_dev = rank_valid_dev = None
        rank_idx_eval_dev = rank_valid_eval_dev = None
        if rank_fast:
            pi, va = rank_pads
            rank_idx_dev = jnp.asarray(pi)
            rank_valid_dev = jnp.asarray(va)
        if eval_on:
            y_eval = y_onehot_dev if k > 1 else y_dev
            valid_w = padded(valid_mask.astype(np.float32))
            if eval_kind == "ndcg":
                pi, va = objectives.lambdarank_pad_groups(
                    group_ids, keep=valid_mask
                )
                rank_idx_eval_dev = jnp.asarray(pi)
                rank_valid_eval_dev = jnp.asarray(va)
        grad_pre_f = is_rf
        if is_rf:
            g_pre_f, h_pre_f = g_rf, h_rf
            rf_base_dev = rf_base if eval_on else None
        else:
            g_pre_f = h_pre_f = None
        # lambdarank: y_dev is the relevance vector the device kernel reads
        y_enc_f = None if grad_pre_f else (y_onehot_dev if k > 1 else y_dev)
        it0 = start_round
        stopped = False
        while it0 < cfg.num_iterations and not stopped:
            # preemption fires BETWEEN rounds: state through round it0-1 is
            # checkpointed, rounds >= it0 have not run
            faults.inject("gbdt.round", step=it0)
            if gang is not None:
                # elastic gang boundary: straggler EWMA, loss detection,
                # grow-back — raises to abort when the world changed
                gang.on_round(it0)
            t_chunk_ns = _time.perf_counter_ns()
            C = min(C_full, cfg.num_iterations - it0)
            if cfg.feature_fraction < 1.0:
                fms = np.empty((C, d), np.float32)
                for i in range(C):
                    fm = (rng.random(d) < cfg.feature_fraction).astype(np.float32)
                    if fm.sum() == 0:
                        fm[rng.integers(d)] = 1.0
                    fms[i] = fm
            else:
                fms = np.ones((C, d), np.float32)
            scores, bag_dev, packed, metrics = _scan_chunk(
                bins_dev, scores, y_enc_f, w_dev, bag_dev, base_key,
                jnp.arange(it0, it0 + C, dtype=jnp.int32), jnp.asarray(fms),
                cat_mask_dev, g_pre_f, h_pre_f,
                rank_idx_dev, rank_valid_dev,
                rank_idx_eval_dev, rank_valid_eval_dev,
                y_eval, valid_w, rf_base_dev,
                float(_objective_p1(cfg)),
                float(bagging_fraction),
                float(cfg.top_rate), float(cfg.other_rate),
                float(cfg.lambda_l2), float(cfg.lambda_l1),
                float(cfg.min_sum_hessian_in_leaf),
                float(cfg.min_gain_to_split),
                1.0 if is_rf else lr_cur,
                objective=cfg.objective, k=k, grad_pre=grad_pre_f,
                is_goss=is_goss, use_voting=use_voting,
                has_cat=cat_mask_dev is not None,
                num_leaves=int(cfg.num_leaves), max_depth=int(cfg.max_depth),
                min_data_in_leaf=int(cfg.min_data_in_leaf),
                top_k=int(cfg.top_k),
                mesh=mesh if (use_voting or hist_sharded) else None,
                depthwise=cfg.growth_policy == "depthwise",
                partitioned=partitioned,
                bagging_freq=int(bagging_freq) if use_bag else 0,
                eval_kind=eval_kind, is_rf=is_rf, num_bins=hist_bins,
                eval_k=int(eval_k), hist_mode=_hist_lowering(),
            )
            keep = C
            if eval_on:
                higher = eval_kind in _HIGHER_METRICS
                mvals = np.asarray(metrics)
                for i in range(C):
                    val = float(mvals[i])
                    if cfg.verbosity > 0:
                        log.info("iter %d %s=%.6f", it0 + i, eval_kind, val)
                    if best_val is None or (
                        val > best_val if higher else val < best_val
                    ):
                        best_val, best_iter = val, it0 + i + 1
                        rounds_no_improve = 0
                    else:
                        rounds_no_improve += 1
                        if (
                            early_stopping_round > 0
                            and rounds_no_improve >= early_stopping_round
                        ):
                            log.info(
                                "early stop at iter %d (best %d)",
                                it0 + i, best_iter,
                            )
                            booster.best_iteration = best_iter
                            stopped = True
                            keep = i + 1
                            break
            booster.trees.extend(
                _unpack_chunk_trees(
                    np.asarray(packed), keep, k, int(cfg.num_leaves),
                    cat_mask_dev is not None, hist_bins, mapper,
                )
            )
            done_ns = _time.perf_counter_ns()
            obs.record_span("gbdt.chunk", t_chunk_ns, done_ns)
            _M_CHUNK_SECONDS.observe((done_ns - t_chunk_ns) / 1e9)
            _M_FUSED_CHUNKS.inc()
            if eval_on:
                _M_DEVICE_EVAL_ROUNDS.inc(keep)
            _M_ROUNDS.inc(keep)
            # one observation per completed round at the amortized cost —
            # sum and count stay exact for scrape-side mean/rate math
            per_round = (done_ns - t_chunk_ns) / 1e9 / max(keep, 1)
            for _ in range(keep):
                _M_ROUND_SECONDS.observe(per_round)
            it0 += C
            # checkpoint at the configured cadence: snapshot whenever this
            # chunk crossed a checkpoint_every boundary (chunk sizes that
            # do not divide the cadence still checkpoint at the first
            # boundary after each cadence point, never skip one)
            if (
                checkpoint_dir and not stopped
                and ((it0 - C) // checkpoint_every < it0 // checkpoint_every
                     or it0 >= cfg.num_iterations)
            ):
                _save_ckpt(it0, bag_dev if use_bag else None)

    # dispatch-per-iteration path (dart / lambdarank / multihost /
    # delegates / host-only eval metrics)
    for it in (range(0) if fast else range(start_round, cfg.num_iterations)):
        faults.inject("gbdt.round", step=it)
        if gang is not None:
            gang.on_round(it)
        t_round_ns = _time.perf_counter_ns()
        if delegate is not None:
            delegate.before_train_iteration(it)
            # dynamic learning rate (getLearningRate delegate semantics);
            # lr is a dynamic jit arg, so no recompile on change
            lr_cur = float(delegate.get_learning_rate(it, lr_cur))
        it_key = jax.random.fold_in(base_key, it)
        # bagging for this iteration (device mask, no host transfer)
        if bagging_freq > 0 and bagging_fraction < 1.0:
            if it % bagging_freq == 0 or bag is None:
                bag = (
                    _uniform_global(jax.random.fold_in(it_key, 1))
                    < bagging_fraction
                ).astype(jnp.float32)
        else:
            bag = None
        w_it = w_dev * bag if bag is not None else w_dev
        if cfg.feature_fraction < 1.0:
            fm = (rng.random(d) < cfg.feature_fraction).astype(np.float32)
            if fm.sum() == 0:
                fm[rng.integers(d)] = 1.0
        else:
            fm = np.ones(d, np.float32)
        fm_dev = jnp.asarray(fm)

        # dart: choose dropped iterations, fit against scores without them
        drop_set: list = []
        drop_contrib = None
        eff_scores = scores
        if is_dart and it > 0 and rng.random() >= cfg.skip_drop:
            sel = np.flatnonzero(rng.random(it) < cfg.drop_rate)
            if len(sel) > cfg.max_drop:
                sel = rng.choice(sel, cfg.max_drop, replace=False)
            drop_set = [int(s) for s in sel]
        if drop_set:
            if x_host_dense is None:
                x_host_dense = _densify(x) if sparse_input else np.asarray(x, np.float32)
            drop_contrib = _iterations_contrib(booster, x_host_dense, drop_set, k)
            eff_scores = scores - padded(drop_contrib)

        # dart normalization factors (paper semantics: new tree 1/(k+1),
        # dropped trees k/(k+1))
        n_drop = len(drop_set)
        nf_new = 1.0 / (n_drop + 1) if is_dart else 1.0
        nf_drop = n_drop / (n_drop + 1) if n_drop else 1.0

        # precomputed gradients: rf (constant at the initial score) and
        # lambdarank's group-sorted host path; everything else is computed
        # inside the fused program from the running scores
        g_pre = h_pre = None
        if is_rf:
            g_pre, h_pre = g_rf, h_rf
        elif cfg.objective == "lambdarank":
            # multihost: this process's score block only — its groups are
            # process-local by contract, so the pairwise grads are exact
            s_host = (
                _local_block_rows(eff_scores, n)
                if multihost else np.asarray(eff_scores)[:n]
            )
            g_np, h_np = objectives.lambdarank_grad_hess(
                s_host.astype(np.float64), y.astype(np.float64), group_ids
            )
            g_pre, h_pre = padded(g_np.astype(np.float32)), padded(h_np.astype(np.float32))
        grad_pre = g_pre is not None
        y_enc = None if grad_pre else (y_onehot_dev if k > 1 else y_dev)
        new_scores, grown_all = _fused_iteration(
            bins_dev, eff_scores, y_enc, w_it, it_key, fm_dev, cat_mask_dev,
            g_pre, h_pre, None, None,
            float(_objective_p1(cfg)),
            float(cfg.top_rate), float(cfg.other_rate),
            float(cfg.lambda_l2), float(cfg.lambda_l1),
            float(cfg.min_sum_hessian_in_leaf), float(cfg.min_gain_to_split),
            1.0 if is_rf else lr_cur,
            objective=cfg.objective, k=k, grad_pre=grad_pre, is_goss=is_goss,
            use_voting=use_voting, has_cat=cat_mask_dev is not None,
            num_leaves=int(cfg.num_leaves), max_depth=int(cfg.max_depth),
            min_data_in_leaf=int(cfg.min_data_in_leaf),
            top_k=int(cfg.top_k),
            mesh=mesh if (use_voting or hist_sharded) else None,
            depthwise=cfg.growth_policy == "depthwise",
            partitioned=partitioned, num_bins=hist_bins,
            hist_mode=_hist_lowering(),
        )
        # the fused step fit against eff_scores (dart: scores minus dropped
        # trees); the running total keeps the dropped contribution
        scores = (scores - eff_scores) + new_scores if drop_set else new_scores
        if is_dart and nf_new != 1.0:
            # the fused delta was unscaled; the stored tree shrinks by
            # nf_new, so fold the same factor into the running scores
            corr = [g.leaf_values[g.row_leaf] * (nf_new - 1.0) for g in grown_all]
            scores = scores + (jnp.stack(corr, axis=1) if k > 1 else corr[0])
        for grown in grown_all:
            if multihost:
                # the small split-record outputs must be fully replicated so
                # every process can read them to host (row_leaf stays
                # sharded — it is only ever consumed on device)
                grown = grown._replace(
                    **{
                        f: _replicate_small(getattr(grown, f))
                        for f in grown._fields
                        if f != "row_leaf"
                    }
                )
            if is_dart:
                # dart mutates PAST trees' values mid-loop, so it needs
                # host-materialized trees as it goes (eager, per-tree fetch)
                booster.trees.append(
                    _tree_from_device(grown, mapper, value_scale=nf_new)
                )
            else:
                # deferred materialization: split records stay on device;
                # the host fetch happens ONCE, batched, after the loop.
                # row_leaf (an (n,)-sized device buffer) is dropped here —
                # keeping it pinned per pending tree would hold
                # O(n_rows x num_iterations) accelerator memory
                pending_trees.append(grown._replace(row_leaf=None))
        if drop_set:
            # dropped trees shrink to k/(k+1): mutate their stored values
            # and fold the same correction into the running scores
            for itdrop in drop_set:
                for c in range(k):
                    t = booster.trees[itdrop * k + c]
                    t.values = (t.values * nf_drop).astype(t.values.dtype)
            scores = scores - padded(drop_contrib * (1.0 - nf_drop))

        # eval + early stopping on validation rows (the only host sync).
        # Multihost: every process must take this branch together — the
        # allgather inside is a collective
        eval_result = None
        stop_now = False
        if valid_mask is not None and (multihost or valid_mask.any()):
            name = None
            if multihost:
                s_eval = _local_block_rows(scores, n)
                if is_rf:
                    s_eval = _local_block_rows(rf_base, n) + s_eval / (it + 1)
                if mh_eval_ctx is None:
                    # y, the valid mask and (ranking) group ids are
                    # loop-invariant: one gather. Group labels are only
                    # unique per process — offset by process index so two
                    # processes' query 0s stay distinct queries globally
                    gid_l = (
                        group_ids.astype(np.float64) * jax.process_count()
                        + jax.process_index()
                        if group_ids is not None
                        else np.zeros(n, np.float64)
                    )
                    ym = _gather_rows(
                        np.stack(
                            [y, valid_mask.astype(np.float64), gid_l], 1
                        ),
                        n, share,
                    )
                    mh_eval_ctx = (
                        ym[:, 0], ym[:, 1] > 0.5, ym[:, 2].astype(np.int64)
                    )
                y_g, m_g, gid_g = mh_eval_ctx
                sg2 = _gather_rows(s_eval, n, share)
                s_g = sg2 if k > 1 else sg2[:, 0]
                if m_g.any():
                    name, val, higher = _eval_metric(
                        cfg, s_g, y_g, m_g,
                        gid_g if group_ids is not None else None,
                    )
            else:
                s_eval = np.asarray(scores)[:n]
                if is_rf:
                    s_eval = np.asarray(rf_base)[:n] + s_eval / (it + 1)
                name, val, higher = _eval_metric(cfg, s_eval, y, valid_mask, group_ids)
            if name is not None:
                eval_result = (name, val, higher)
                if cfg.verbosity > 0:
                    log.info("iter %d %s=%.6f", it, name, val)
                improved = (
                    best_val is None
                    or (higher and val > best_val)
                    or (not higher and val < best_val)
                )
                if improved:
                    best_val, best_iter, rounds_no_improve = val, it + 1, 0
                else:
                    rounds_no_improve += 1
                    if early_stopping_round > 0 and rounds_no_improve >= early_stopping_round:
                        log.info("early stop at iter %d (best %d)", it, best_iter)
                        booster.best_iteration = best_iter
                        stop_now = True
        if delegate is not None:
            delegate.after_train_iteration(
                it, eval_result, stop_now or it == cfg.num_iterations - 1
            )
        if checkpoint_dir and not stop_now and (it + 1) % checkpoint_every == 0:
            # materialize deferred trees now — the checkpointed booster
            # must contain every completed round (dart's are already eager)
            booster.trees.extend(_trees_from_device_batched(pending_trees, mapper))
            pending_trees = []
            _save_ckpt(it + 1, bag)
        done_ns = _time.perf_counter_ns()
        obs.record_span("gbdt.round", t_round_ns, done_ns)
        _M_ROUND_SECONDS.observe((done_ns - t_round_ns) / 1e9)
        _M_ROUNDS.inc()
        if stop_now:
            break

    booster.trees.extend(_trees_from_device_batched(pending_trees, mapper))
    # dart never records best_iteration: later dropouts rescale trees inside
    # any prefix, so no prefix reproduces a historical eval score
    if valid_mask is not None and best_iter > 0 and booster.best_iteration < 0 and not is_dart:
        booster.best_iteration = best_iter
    if init_booster is not None and init_booster.trees:
        new_best = booster.best_iteration
        init_iters = len(init_booster.trees) // init_booster.num_class
        booster = init_booster.merge(booster)
        if new_best > 0:
            # best iteration counts from the front of the merged tree list
            booster.best_iteration = init_iters + new_best
    return booster


def _densify(x: Any) -> np.ndarray:
    """CSR -> dense float32 with absent entries as NaN (prediction-time
    only; training stays sparse). NaN, not 0: trees trained on sparse data
    route absent entries through the missing bin."""
    from mmlspark_tpu.models.gbdt.binning import densify_missing, is_sparse

    if is_sparse(x):
        return densify_missing(x)
    return np.asarray(x, np.float32)


def _iterations_contrib(
    booster: Booster, x: np.ndarray, iterations: list, k: int
) -> np.ndarray:
    """Summed raw contribution of the given iterations: (n,) or (n, k)."""
    idx = [it * k + c for it in iterations for c in range(k)]
    per = per_tree_raw([booster.trees[i] for i in idx], x)  # (n, len(idx))
    if k == 1:
        return per.sum(axis=1).astype(np.float32)
    n = per.shape[0]
    out = np.zeros((n, k), np.float32)
    for j, i in enumerate(idx):
        out[:, i % k] += per[:, j]
    return out
