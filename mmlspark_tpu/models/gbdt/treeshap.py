"""Exact TreeSHAP for the replay-log trees.

The reference surfaces LightGBM's ``featuresShap`` (exact conditional-
expectation Shapley values, LightGBMBooster.scala:37-128); Saabas-style
attribution (booster.feature_contribs' fast path) is only its first-order
approximation. This module implements the exact polynomial-time algorithm
(Lundberg et al., "Consistent Individualized Feature Attribution for Tree
Ensembles": maintain, along each root->leaf path, the fraction of all
feature-subset permutations that flow to the leaf with each path feature
included ("one fraction") or excluded (cover-proportional "zero
fraction"), then read each feature's Shapley weight off the path by
unwinding it).

Cost is O(leaves * depth^2) per tree per row on the host — attribution is
an explanation workload, scored on demand for a handful of rows, unlike
the device scoring paths.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.models.gbdt import treegrow


class _BinaryTree:
    """Replay log -> explicit binary tree with per-node covers."""

    __slots__ = (
        "left", "right", "feature", "threshold", "is_cat", "catmask",
        "value", "cover", "default_left",
    )

    def __init__(self, tree) -> None:
        S = len(tree.leaf)
        max_nodes = 2 * S + 1
        self.left = np.full(max_nodes, -1, np.int64)
        self.right = np.full(max_nodes, -1, np.int64)
        self.feature = np.full(max_nodes, -1, np.int64)
        self.threshold = np.zeros(max_nodes, np.float64)
        self.is_cat = np.zeros(max_nodes, bool)
        self.catmask = [None] * max_nodes
        self.default_left = np.ones(max_nodes, bool)
        self.value = np.zeros(max_nodes, np.float64)
        self.cover = np.zeros(max_nodes, np.float64)

        node_of_leaf = {0: 0}  # leaf-id -> current tree node
        next_node = 1
        for k in range(S):
            if not tree.active[k]:
                continue
            parent_leaf = int(tree.leaf[k])
            node = node_of_leaf[parent_leaf]
            l_node, r_node = next_node, next_node + 1
            next_node += 2
            self.left[node] = l_node
            self.right[node] = r_node
            self.feature[node] = int(tree.feature[k])
            self.threshold[node] = float(tree.threshold[k])
            if tree.is_cat is not None and tree.is_cat[k]:
                self.is_cat[node] = True
                self.catmask[node] = tree.catmask[k]
            if tree.default_left is not None:
                self.default_left[node] = bool(tree.default_left[k])
            node_of_leaf[parent_leaf] = l_node
            node_of_leaf[k + 1] = r_node
        for leaf_id, node in node_of_leaf.items():
            self.value[node] = float(tree.values[leaf_id])
            self.cover[node] = float(tree.counts[leaf_id])
        # internal covers bottom-up (children were always created after
        # their parent, so a reverse sweep sees children first)
        for node in range(next_node - 1, -1, -1):
            if self.left[node] >= 0:
                self.cover[node] = (
                    self.cover[self.left[node]] + self.cover[self.right[node]]
                )

    def goes_left(self, x_row: np.ndarray, node: int) -> bool:
        f = self.feature[node]
        v = x_row[f]
        if self.is_cat[node]:
            vbin = treegrow.category_bin_slot(np.asarray([v]), len(self.catmask[node]), np)[0]
            return bool(self.catmask[node][vbin])
        # NaN routes by the split's default direction (left unless an
        # imported default-right split), matching predict_leaves/Saabas
        if np.isnan(v):
            return bool(self.default_left[node])
        return bool(v <= self.threshold[node])


def shap_values(tree, x: np.ndarray) -> np.ndarray:
    """(n, d) -> (n, d+1) exact SHAP values for ONE replay-log tree; the
    last column is the expected value (base rate)."""
    bt = _BinaryTree(tree)
    n, d = x.shape
    out = np.zeros((n, d + 1), np.float64)
    if bt.cover[0] <= 0:
        return out
    base = _expected_value(bt, 0)
    for i in range(n):
        phi = out[i]
        _recurse(
            bt, x[i], phi,
            node=0,
            path=_Path(),
            zero_fraction=1.0,
            one_fraction=1.0,
            feature_index=-1,
        )
        phi[d] += base
    return out


def _expected_value(bt: _BinaryTree, node: int) -> float:
    if bt.left[node] < 0:
        return bt.value[node]
    l, r = bt.left[node], bt.right[node]
    c = bt.cover[node]
    return (
        bt.cover[l] / c * _expected_value(bt, l)
        + bt.cover[r] / c * _expected_value(bt, r)
    )


class _Path:
    """Subset-permutation bookkeeping along the active path."""

    __slots__ = ("d", "z", "o", "w")

    def __init__(self) -> None:
        self.d: list = []  # feature index per path element
        self.z: list = []  # zero fraction (cover-proportional flow)
        self.o: list = []  # one fraction (decision-path flow)
        self.w: list = []  # permutation weight

    def copy(self) -> "_Path":
        p = _Path.__new__(_Path)
        p.d, p.z, p.o, p.w = list(self.d), list(self.z), list(self.o), list(self.w)
        return p

    def extend(self, zero_fraction: float, one_fraction: float, feature_index: int) -> None:
        m = len(self.d)
        self.d.append(feature_index)
        self.z.append(zero_fraction)
        self.o.append(one_fraction)
        self.w.append(1.0 if m == 0 else 0.0)
        for i in range(m - 1, -1, -1):
            self.w[i + 1] += one_fraction * self.w[i] * (i + 1) / (m + 1)
            self.w[i] = zero_fraction * self.w[i] * (m - i) / (m + 1)

    def unwind(self, index: int) -> "_Path":
        m = len(self.d) - 1
        p = self.copy()
        one = p.o[index]
        zero = p.z[index]
        n_ = p.w[m]
        for j in range(m - 1, -1, -1):
            if one != 0:
                t = p.w[j]
                p.w[j] = n_ * (m + 1) / ((j + 1) * one)
                n_ = t - p.w[j] * zero * (m - j) / (m + 1)
            else:
                p.w[j] = p.w[j] * (m + 1) / (zero * (m - j))
        # after the loop w[0..m-1] are the rebuilt weights; the stale slot
        # is the LAST one. Only d/z/o shift at ``index``.
        del p.d[index], p.z[index], p.o[index], p.w[-1]
        return p

    def unwound_sum(self, index: int) -> float:
        m = len(self.d) - 1
        one = self.o[index]
        zero = self.z[index]
        total = 0.0
        if one != 0:
            n_ = self.w[m]
            for j in range(m - 1, -1, -1):
                t = n_ / ((j + 1) * one)
                total += t
                n_ = self.w[j] - t * zero * (m - j)
        else:
            for j in range(m - 1, -1, -1):
                total += self.w[j] / (zero * (m - j))
        return total * (m + 1)


def _recurse(
    bt: _BinaryTree,
    x_row: np.ndarray,
    phi: np.ndarray,
    node: int,
    path: _Path,
    zero_fraction: float,
    one_fraction: float,
    feature_index: int,
) -> None:
    path = path.copy()
    path.extend(zero_fraction, one_fraction, feature_index)

    if bt.left[node] < 0:  # leaf
        for i in range(1, len(path.d)):
            w = path.unwound_sum(i)
            phi[path.d[i]] += w * (path.o[i] - path.z[i]) * bt.value[node]
        return

    f = int(bt.feature[node])
    hot, cold = (
        (bt.left[node], bt.right[node])
        if bt.goes_left(x_row, node)
        else (bt.right[node], bt.left[node])
    )
    hot_zero = bt.cover[hot] / bt.cover[node]
    cold_zero = bt.cover[cold] / bt.cover[node]
    incoming_zero, incoming_one = 1.0, 1.0
    # a feature met twice on one path: undo its earlier element first so the
    # path never holds duplicates (its fractions multiply)
    for i in range(1, len(path.d)):
        if path.d[i] == f:
            incoming_zero, incoming_one = path.z[i], path.o[i]
            path = path.unwind(i)
            break
    _recurse(bt, x_row, phi, hot, path, hot_zero * incoming_zero,
             incoming_one, f)
    _recurse(bt, x_row, phi, cold, path, cold_zero * incoming_zero,
             0.0, f)
