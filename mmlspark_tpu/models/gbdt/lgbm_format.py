"""Native LightGBM text-model interop.

The reference persists boosters in LightGBM's own text format and exposes
``saveNativeModel`` / ``loadNativeModelFromFile`` so models flow between
Spark, Python lightgbm and the CLI (lightgbm/LightGBMClassifier.scala
loadNativeModelFromFile/String, LightGBMBooster.scala saveNativeModel).
This module gives the TPU rebuild the same interop surface:

- :func:`to_lightgbm_string` — serialize a :class:`Booster` as a LightGBM
  v3 text model (explicit left/right-child arrays, ``<= threshold`` goes
  left, categorical splits as cat_threshold bitsets).
- :func:`from_lightgbm_string` — parse a LightGBM text model (e.g. written
  by the reference or by python ``lightgbm``) into a :class:`Booster`,
  rebuilding each explicit tree as our sequential split log (split ``k``
  turns slot ``l`` into an internal node; the right child becomes slot
  ``k + 1`` — any parent-before-child emission order is valid).

Semantics notes:
- Missing values: the replay honors each split's ``decision_type``
  default-left bit (NaN routes by the recorded direction; trained trees
  are all default-left). What it cannot reproduce is missing_type None
  (real LightGBM compares NaN as 0.0) and Zero (zero-as-missing); those
  imports warn once per model.
- Categorical values are capped at NUM_BINS - 2 (the identity-binning
  range); imported bitsets beyond that raise.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import numpy as np

from mmlspark_tpu.ops.histogram import NUM_BINS

log = logging.getLogger("mmlspark_tpu.gbdt")

_CAT_BIT = 1       # decision_type bit 0: categorical split
_DEFAULT_LEFT = 2  # decision_type bit 1: missing goes left
_MISSING_NAN = 2 << 2  # bits 2-3: missing_type (0=None, 1=Zero, 2=NaN)


def _objective_string(booster: Any) -> str:
    """LightGBM's objective header line, with the objective's knobs in
    LightGBM's own key:value token format."""
    objective, num_class = booster.objective, booster.num_class
    p = booster.objective_param
    if objective == "binary":
        return f"binary sigmoid:{booster.sigmoid:g}"
    if objective == "multiclass":
        return f"multiclass num_class:{num_class}"
    if objective == "quantile":
        return f"quantile alpha:{0.9 if p is None else p:g}"
    if objective == "huber":
        return f"huber alpha:{0.9 if p is None else p:g}"
    if objective == "fair":
        return f"fair fair_c:{1.0 if p is None else p:g}"
    if objective == "tweedie":
        return (
            f"tweedie tweedie_variance_power:{1.5 if p is None else p:g}"
        )
    return objective


def _parse_objective(s: str) -> tuple:
    """objective= header -> (canonical name, num_class, param, sigmoid).

    ``param`` is the regression knob (alpha / tweedie_variance_power /
    fair_c) when present; ``sigmoid`` is the binary slope (default 1.0 —
    models trained with a non-default slope must predict through it or
    probabilities silently differ from real LightGBM)."""
    from mmlspark_tpu.models.gbdt.objectives import (
        REGRESSION_KINDS,
        canonical_objective,
    )

    parts = s.split()
    name = parts[0]
    num_class = 1
    param = None
    sigmoid = 1.0
    for p in parts[1:]:
        if p.startswith("num_class:"):
            num_class = int(p.split(":", 1)[1])
        elif p.startswith("sigmoid:"):
            sigmoid = float(p.split(":", 1)[1])
        elif p.startswith(("alpha:", "tweedie_variance_power:", "fair_c:")):
            param = float(p.split(":", 1)[1])
    if name.startswith("binary"):
        return "binary", 1, None, sigmoid
    if name.startswith("multiclass") or name.startswith("softmax"):
        return "multiclass", num_class, None, 1.0
    if name.startswith("lambdarank") or name.startswith("rank"):
        return "lambdarank", 1, None, 1.0
    canon = canonical_objective(name)
    if canon in REGRESSION_KINDS:
        return canon, 1, param, 1.0
    return "regression", 1, None, 1.0


# ---------------------------------------------------------------------------
# export: split log -> explicit tree -> LightGBM text
# ---------------------------------------------------------------------------


def _tree_to_explicit(tree: Any) -> dict:
    """Split-log -> LightGBM-style arrays (children as node ids, leaves as
    ``~leaf_idx``)."""
    order = [k for k in range(len(tree.leaf)) if tree.active[k]]
    n_int = len(order)
    if n_int == 0:
        # single-leaf tree: LightGBM writes num_leaves=1 with just the value
        return {
            "num_leaves": 1,
            "leaf_value": [float(tree.values[0])],
            "leaf_count": [int(tree.counts[0])],
            "internal": 0,
        }
    split_feature, threshold, gain, decision_type = [], [], [], []
    left_child, right_child = [], []
    cat_sets: list = []
    # slot -> ("root", None) | (parent_internal, side)
    slot_parent: dict = {int(tree.leaf[order[0]]): ("root", None)}
    leaf_ids: dict = {}  # slot -> final leaf index (assigned on close)

    def set_child(parent: int, side: int, value: int) -> None:
        (left_child if side == 0 else right_child)[parent] = value

    for i, k in enumerate(order):
        slot = int(tree.leaf[k])
        parent = slot_parent.pop(slot)
        split_feature.append(int(tree.feature[k]))
        gain.append(float(tree.gain[k]))
        left_child.append(None)
        right_child.append(None)
        is_cat = tree.is_cat is not None and bool(tree.is_cat[k])
        if is_cat:
            # catmask slot v+1 = category value v goes left; slot 0 is the
            # missing (NaN) bin — LightGBM's bitset cannot carry it, so
            # NaN-goes-left rides the default_left bit (our importer
            # restores it; real LightGBM routes categorical NaN right and
            # ignores the bit — a documented semantic edge)
            dt = _CAT_BIT | (_DEFAULT_LEFT if tree.catmask[k][0] else 0)
            decision_type.append(dt)
            vals = np.flatnonzero(tree.catmask[k][1:]).tolist()
            cat_sets.append(vals)
            threshold.append(len(cat_sets) - 1)  # index into cat bitsets
        else:
            # missing_type NaN + the split's default direction (trained
            # trees are all default-left; imported default-right splits
            # round-trip their bit)
            dl = tree.default_left is None or bool(tree.default_left[k])
            decision_type.append((_DEFAULT_LEFT if dl else 0) | _MISSING_NAN)
            threshold.append(float(tree.threshold[k]))
        if parent[0] != "root":
            set_child(parent[0], parent[1], i)
        slot_parent[slot] = (i, 0)       # left child keeps the slot
        slot_parent[k + 1] = (i, 1)      # right child is the new slot

    # remaining open slots are final leaves
    for slot, (parent, side) in slot_parent.items():
        leaf_idx = len(leaf_ids)
        leaf_ids[slot] = leaf_idx
        set_child(parent, side, ~leaf_idx)
    leaf_value = [0.0] * len(leaf_ids)
    leaf_count = [0] * len(leaf_ids)
    for slot, idx in leaf_ids.items():
        leaf_value[idx] = float(tree.values[slot])
        leaf_count[idx] = int(tree.counts[slot])

    # internal aggregates (bottom-up): value = count-weighted mean of
    # leaves. Iterative post-order — a chain-shaped leaf-wise tree can be
    # thousands of levels deep, past Python's recursion limit
    int_count = [0] * n_int
    int_value = [0.0] * n_int
    stack = [(0, False)]
    while stack:
        node, expanded = stack.pop()
        if not expanded:
            stack.append((node, True))
            for child in (left_child[node], right_child[node]):
                if child >= 0:
                    stack.append((child, False))
            continue
        c_tot, v_tot = 0.0, 0.0
        for child in (left_child[node], right_child[node]):
            if child < 0:
                c, v = leaf_count[~child], leaf_value[~child]
            else:  # post-order: children already aggregated
                c, v = int_count[child], int_value[child]
            c_tot += c
            v_tot += v * c
        int_count[node] = int(c_tot)
        int_value[node] = v_tot / c_tot if c_tot else 0.0
    out = {
        "num_leaves": len(leaf_ids),
        "split_feature": split_feature,
        "split_gain": gain,
        "threshold": threshold,
        "decision_type": decision_type,
        "left_child": left_child,
        "right_child": right_child,
        "leaf_value": leaf_value,
        "leaf_count": leaf_count,
        "internal_value": int_value,
        "internal_count": int_count,
        "internal": n_int,
    }
    if cat_sets:
        boundaries = [0]
        bits: list = []
        for vals in cat_sets:
            # 32-bit word bitset, little-endian words (LightGBM layout)
            n_words = max(v // 32 for v in vals) + 1 if vals else 1
            words = [0] * n_words
            for v in vals:
                words[v // 32] |= 1 << (v % 32)
            bits.extend(words)
            boundaries.append(len(bits))
        out["num_cat"] = len(cat_sets)
        out["cat_boundaries"] = boundaries
        out["cat_threshold"] = bits
    else:
        out["num_cat"] = 0
    return out


def _fmt(xs: list) -> str:
    out = []
    for x in xs:
        if isinstance(x, float):
            out.append(repr(x) if np.isfinite(x) else ("inf" if x > 0 else "-inf"))
        else:
            out.append(str(x))
    return " ".join(out)


def to_lightgbm_string(booster: Any) -> str:
    """Serialize a Booster in LightGBM v3 text-model format."""
    lines = [
        "tree",
        "version=v3",
        f"num_class={booster.num_class}",
        f"num_tree_per_iteration={booster.num_class}",
        "label_index=0",
        f"max_feature_idx={booster.num_features - 1}",
        f"objective={_objective_string(booster)}",
    ]
    if booster.boosting_type == "rf":
        lines.append("average_output")
    names = booster.feature_names or [
        f"Column_{i}" for i in range(booster.num_features)
    ]
    lines.append("feature_names=" + " ".join(names))
    lines.append(
        "feature_infos=" + " ".join(["[-1e308:1e308]"] * booster.num_features)
    )
    # base_score is folded into leaf values on export (LightGBM's
    # boost_from_average bakes the average into the first trees the same way)
    base = np.broadcast_to(
        np.asarray(booster.base_score, np.float64).ravel(), (booster.num_class,)
    )
    # the text format carries no best_iteration: export the early-stopped
    # prefix (what predict_raw scores), like LightGBM's own save_model
    trees = booster.trees
    if booster.best_iteration > 0:
        trees = trees[: booster.best_iteration * booster.num_class]
    lines.append("")
    for t, tree in enumerate(trees):
        ex = _tree_to_explicit(tree)
        if booster.boosting_type == "rf":
            # rf predictions AVERAGE trees: base must ride every tree so
            # mean(v_t + base) == mean(v_t) + base
            fold = float(base[t % booster.num_class])
        else:
            fold = float(base[t % booster.num_class]) if t < booster.num_class else 0.0
        if fold:
            ex["leaf_value"] = [v + fold for v in ex["leaf_value"]]
            if ex["internal"]:
                ex["internal_value"] = [v + fold for v in ex["internal_value"]]
        lines.append(f"Tree={t}")
        lines.append(f"num_leaves={ex['num_leaves']}")
        lines.append(f"num_cat={ex.get('num_cat', 0)}")
        if ex["internal"]:
            lines.append("split_feature=" + _fmt(ex["split_feature"]))
            lines.append("split_gain=" + _fmt(ex["split_gain"]))
            lines.append("threshold=" + _fmt(ex["threshold"]))
            lines.append("decision_type=" + _fmt(ex["decision_type"]))
            lines.append("left_child=" + _fmt(ex["left_child"]))
            lines.append("right_child=" + _fmt(ex["right_child"]))
        lines.append("leaf_value=" + _fmt(ex["leaf_value"]))
        lines.append("leaf_count=" + _fmt(ex["leaf_count"]))
        if ex["internal"]:
            lines.append("internal_value=" + _fmt(ex["internal_value"]))
            lines.append("internal_count=" + _fmt(ex["internal_count"]))
        if ex.get("num_cat", 0):
            lines.append("cat_boundaries=" + _fmt(ex["cat_boundaries"]))
            lines.append("cat_threshold=" + _fmt(ex["cat_threshold"]))
        lines.append("shrinkage=1")
        lines.append("")
    lines.append("end of trees")
    lines.append("")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# import: LightGBM text -> explicit tree -> split log
# ---------------------------------------------------------------------------


def _explicit_to_tree(fields: dict, notes: Optional[set] = None) -> Any:
    from mmlspark_tpu.models.gbdt.booster import Tree

    num_leaves = int(fields["num_leaves"][0])
    if num_leaves <= 1:
        v = float(fields["leaf_value"][0])
        cnt = int(fields.get("leaf_count", [0])[0])
        return Tree(
            leaf=np.full(0, -1, np.int32), feature=np.zeros(0, np.int32),
            threshold=np.zeros(0, np.float64), active=np.zeros(0, bool),
            gain=np.zeros(0, np.float32), values=np.array([v], np.float32),
            counts=np.array([cnt], np.int32),
        )
    n_int = num_leaves - 1
    split_feature = np.asarray(fields["split_feature"], np.int64)
    raw_threshold = np.asarray(fields["threshold"], np.float64)
    decision_type = np.asarray(
        fields.get("decision_type", [_DEFAULT_LEFT] * n_int), np.int64
    )
    left = np.asarray(fields["left_child"], np.int64)
    right = np.asarray(fields["right_child"], np.int64)
    leaf_value = np.asarray(fields["leaf_value"], np.float64)
    leaf_count = np.asarray(
        fields.get("leaf_count", np.zeros(num_leaves)), np.float64
    )
    gain = np.asarray(fields.get("split_gain", np.zeros(n_int)), np.float64)
    cat_boundaries = [int(v) for v in fields.get("cat_boundaries", [])]
    cat_threshold = [int(v) for v in fields.get("cat_threshold", [])]
    has_cat = bool((decision_type & _CAT_BIT).any())
    numerical = (decision_type & _CAT_BIT) == 0
    missing_type = (decision_type >> 2) & 3
    # the replay honors each split's default-left bit (NaN direction); what
    # it cannot reproduce is missing_type None (LightGBM compares NaN as
    # 0.0) and Zero (zeros routed as missing) — collect the note, the
    # caller warns ONCE per model, not once per tree
    if notes is not None and (numerical & (missing_type != 2)).any():
        notes.add(
            "imported LightGBM tree has numerical splits with missing_type "
            "None or Zero (NaN-as-0.0 / zero-as-missing); this replay "
            "compares NaN by the default-left bit and zeros numerically — "
            "rows with missing values may route differently"
        )
    has_dright = bool(
        (numerical & ((decision_type & _DEFAULT_LEFT) == 0)).any()
    )

    S = n_int
    rec_leaf = np.full(S, -1, np.int32)
    rec_feature = np.zeros(S, np.int32)
    rec_threshold = np.full(S, np.inf, np.float64)
    rec_active = np.zeros(S, bool)
    rec_gain = np.zeros(S, np.float32)
    values = np.zeros(S + 1, np.float32)
    counts = np.zeros(S + 1, np.int32)
    is_cat = np.zeros(S, bool) if has_cat else None
    catmask = np.zeros((S, NUM_BINS), bool) if has_cat else None
    default_left = np.ones(S, bool) if has_dright else None

    queue = [(0, 0)]  # (internal node id, slot)
    k = 0
    while queue:
        node, slot = queue.pop(0)
        rec_leaf[k] = slot
        rec_feature[k] = split_feature[node]
        rec_active[k] = True
        rec_gain[k] = gain[node]
        if default_left is not None and not (decision_type[node] & _CAT_BIT):
            default_left[k] = bool(decision_type[node] & _DEFAULT_LEFT)
        if decision_type[node] & _CAT_BIT:
            ti = int(raw_threshold[node])
            words = cat_threshold[cat_boundaries[ti]: cat_boundaries[ti + 1]]
            vals = [
                w * 32 + b
                for w, word in enumerate(words)
                for b in range(32)
                if word >> b & 1
            ]
            if vals and max(vals) > NUM_BINS - 2:
                raise ValueError(
                    f"categorical value {max(vals)} exceeds the supported "
                    f"range [0, {NUM_BINS - 2}]"
                )
            is_cat[k] = True
            catmask[k, np.asarray(vals, np.int64) + 1] = True
            # default_left on a categorical split is our NaN-bin-left marker
            # (see export); real LightGBM never sets it on cat splits
            if decision_type[node] & _DEFAULT_LEFT:
                catmask[k, 0] = True
        else:
            rec_threshold[k] = raw_threshold[node]
        for side, child in ((0, left[node]), (1, right[node])):
            child_slot = slot if side == 0 else k + 1
            if child < 0:
                values[child_slot] = leaf_value[~child]
                counts[child_slot] = leaf_count[~child]
            else:
                queue.append((int(child), child_slot))
        k += 1
    return Tree(
        leaf=rec_leaf, feature=rec_feature, threshold=rec_threshold,
        active=rec_active, gain=rec_gain.astype(np.float32),
        values=values, counts=counts, is_cat=is_cat, catmask=catmask,
        default_left=default_left,
    )


def from_lightgbm_string(text: str) -> Any:
    """Parse a LightGBM text model into a Booster."""
    from mmlspark_tpu.models.gbdt.booster import Booster

    header: dict = {}
    trees = []
    cur: Optional[dict] = None
    average_output = False
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line == "end of trees":
            break
        if line.startswith("Tree="):
            if cur is not None:
                trees.append(cur)
            cur = {}
            continue
        if line == "average_output":
            average_output = True
            continue
        if "=" not in line:
            continue
        key, val = line.split("=", 1)
        if cur is None:
            header[key] = val
        else:
            cur[key] = val.split()
    if cur is not None:
        trees.append(cur)
    if "objective" not in header:
        raise ValueError("not a LightGBM model string (no objective= header)")
    objective, num_class, obj_param, sigmoid = _parse_objective(
        header["objective"]
    )
    num_class = int(header.get("num_class", num_class))
    notes: set = set()
    parsed = [_explicit_to_tree(t, notes) for t in trees]
    for note in sorted(notes):
        log.warning(note)
    booster = Booster(
        trees=parsed,
        objective=objective,
        num_class=num_class,
        num_features=int(header.get("max_feature_idx", -1)) + 1,
        feature_names=header.get("feature_names", "").split() or None,
        base_score=0.0,  # LightGBM bakes the average into leaf values
        boosting_type="rf" if average_output else "gbdt",
        sigmoid=sigmoid,
        objective_param=obj_param,
    )
    return booster
