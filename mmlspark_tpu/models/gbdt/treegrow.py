"""Device-side leaf-wise tree growth + prediction kernels.

The TPU replacement for LightGBM's native histogram trainer
(lightgbm/TrainUtils.scala:220-315 drives `LGBM_BoosterUpdateOneIter`,
whose C++ internally builds per-leaf histograms and allreduces them across
workers over sockets). Here:

- the WHOLE per-tree growth loop is ONE jitted XLA program
  (``lax.fori_loop`` over split steps; static shapes L-1 steps);
- histograms are scatter-adds into a (num_leaves x features x bins) cube;
  under a row-sharded mesh GSPMD turns the scatter into partial histograms
  + an ICI allreduce — exactly LightGBM's data_parallel mode
  (LightGBMConstants "data_parallel", LightGBMParams.scala:13-18) with XLA
  collectives instead of socket rings;
- prediction replays split records with ``lax.scan`` — vectorized over
  rows x trees, no pointer-chasing (TPU-friendly tree inference).

Convention: a split sends ``bin <= threshold_bin`` (and missing/NaN) LEFT;
the left child keeps the parent's leaf id, the right child gets a fresh id.
Trees are therefore fully described by the ordered split records + leaf
values — LightGBM's leaf-wise growth expressed as a replay log.
"""

from __future__ import annotations

import functools
import os
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.ops.histogram import NUM_BINS  # uint8 bin space; bin 0 = missing


class GrownTree(NamedTuple):
    """Device outputs of one grown tree (fixed shapes; L = num_leaves)."""

    rec_leaf: jnp.ndarray      # (L-1,) int32 parent leaf id per split
    rec_feature: jnp.ndarray   # (L-1,) int32
    rec_bin: jnp.ndarray       # (L-1,) int32 threshold bin (<= goes left)
    rec_active: jnp.ndarray    # (L-1,) bool: split actually made
    rec_gain: jnp.ndarray      # (L-1,) float32
    leaf_values: jnp.ndarray   # (L,) float32 (shrinkage applied)
    leaf_counts: jnp.ndarray   # (L,) int32
    row_leaf: jnp.ndarray      # (n,) int32 final leaf of every row
    rec_is_cat: jnp.ndarray    # (L-1,) bool: categorical subset split
    rec_catmask: jnp.ndarray   # (L-1, B) bool: bins going LEFT (cat splits)


def threshold_l1(G: jnp.ndarray, l1: Any) -> jnp.ndarray:
    """LightGBM ThresholdL1: sign(G) * max(|G| - l1, 0). The ONE L1
    soft-threshold both growers (single-chip and voting) share."""
    return jnp.sign(G) * jnp.maximum(jnp.abs(G) - l1, 0.0)


def split_gain_term(G: jnp.ndarray, H: jnp.ndarray, lam: Any, l1: Any) -> jnp.ndarray:
    """One side's contribution to split gain: ThresholdL1(G)^2 / (H + lam)."""
    t = threshold_l1(G, l1)
    return t * t / (H + lam)


def make_leaf_best(
    d: int,
    feature_mask: jnp.ndarray,
    min_data_in_leaf: int,
    msh: Any,
    lam: Any,
    l1: Any,
    cat_f: jnp.ndarray,
    has_categorical: bool,
    num_bins: int = NUM_BINS,
):
    """Best-split search over ONE leaf's (d*B, 3) histogram plane — the
    single source of split semantics shared by the leaf-wise (lossguide)
    and depthwise growers. Returns (gain, feature, bin/prefix, catmask)."""
    B = num_bins

    def gscore(Gv: jnp.ndarray, Hv: jnp.ndarray) -> jnp.ndarray:
        return split_gain_term(Gv, Hv, lam, l1)

    def leaf_best(plane: jnp.ndarray) -> tuple:
        cube = plane.reshape(d, B, 3)
        hg, hh, hc = cube[..., 0], cube[..., 1], cube[..., 2]
        cg = jnp.cumsum(hg, axis=1)
        ch = jnp.cumsum(hh, axis=1)
        cc = jnp.cumsum(hc, axis=1)
        G, H, C = cg[:, -1:], ch[:, -1:], cc[:, -1:]
        GL, HL, CL = cg, ch, cc
        GR, HR, CR = G - GL, H - HL, C - CL
        gain_num = gscore(GL, HL) + gscore(GR, HR) - gscore(G, H)
        feat_ok = (feature_mask > 0)[:, None]
        valid_num = (
            feat_ok
            & (CL >= min_data_in_leaf) & (CR >= min_data_in_leaf)
            & (HL >= msh) & (HR >= msh)
        )
        if has_categorical:
            # categorical subset split (LightGBM's sorted-by-ratio scan:
            # order category bins by G/H, then the best LEFT set is some
            # prefix — Fisher's optimal-partition result for convex
            # losses). ``bb`` for a categorical split is the PREFIX LENGTH
            # in this order, not a bin.
            ratio = jnp.where(hc > 0, hg / (hh + 1e-12), -jnp.inf)
            order = jnp.argsort(-ratio, axis=1)  # (d, B) bin ids, best first
            sgs = jnp.take_along_axis(hg, order, 1)
            shs = jnp.take_along_axis(hh, order, 1)
            scs = jnp.take_along_axis(hc, order, 1)
            cgs = jnp.cumsum(sgs, axis=1)
            chs = jnp.cumsum(shs, axis=1)
            ccs = jnp.cumsum(scs, axis=1)
            gain_cat = (
                gscore(cgs, chs) + gscore(G - cgs, H - chs) - gscore(G, H)
            )
            valid_cat = (
                feat_ok
                & (ccs >= min_data_in_leaf)
                & ((C - ccs) >= min_data_in_leaf)
                & (chs >= msh) & ((H - chs) >= msh)
            )
            gain = jnp.where(
                cat_f[:, None],
                jnp.where(valid_cat, gain_cat, -jnp.inf),
                jnp.where(valid_num, gain_num, -jnp.inf),
            )
        else:
            gain = jnp.where(valid_num, gain_num, -jnp.inf)
        flat = gain.reshape(-1)
        best = jnp.argmax(flat)
        bf = (best // B).astype(jnp.int32)
        bb = (best % B).astype(jnp.int32)
        if has_categorical:
            # left-set membership per bin for the chosen feature:
            # rank[bin] = position of bin in the sorted order; prefix <= bb
            order_sel = order[bf]                 # (B,)
            rank = jnp.argsort(order_sel)         # inverse permutation
            catmask = rank <= bb                  # (B,) bool: LEFT bins
        else:
            catmask = jnp.zeros((B,), bool)
        return flat[best], bf, bb, catmask

    return leaf_best


def grow_tree(
    bins: jnp.ndarray,            # (n, d) uint8/int32
    grad: jnp.ndarray,            # (n,) f32
    hess: jnp.ndarray,            # (n,) f32
    row_weight: jnp.ndarray,      # (n,) f32 (bagging/validation mask; 0 = ignore)
    num_leaves: int,
    lambda_l2: float,
    min_gain: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,    # (d,) f32 1/0 (feature_fraction)
    max_depth: int = -1,
    min_data_in_leaf: int = 20,
    categorical_mask: Optional[jnp.ndarray] = None,  # (d,) bool
    lambda_l1: float = 0.0,
    min_sum_hessian: float = 1e-3,
    num_bins: int = NUM_BINS,
    partitioned: bool = False,
    mesh: Any = None,
    shard_axis: Optional[str] = None,
) -> GrownTree:
    """Grow one tree. The categorical-split machinery (per-leaf argsort of
    category bins) is statically compiled OUT when ``categorical_mask`` is
    None — the common all-numerical case pays nothing for it.

    ``lambda_l1`` soft-thresholds gradient sums in both split gains and
    leaf values; ``min_sum_hessian`` invalidates splits whose child
    hessian mass is below it (LightGBM lambda_l1 /
    min_sum_hessian_in_leaf semantics).

    ``partitioned=True`` selects the data-partitioned grower
    (:func:`_grow_tree_partitioned`): rows kept physically grouped by leaf
    so each split histograms only the smaller child's contiguous range —
    LightGBM's DataPartition + sibling-subtraction design. Single-device
    layouts only (the global row permutation would thrash a sharded mesh)."""
    has_categorical = categorical_mask is not None
    if not has_categorical:
        categorical_mask = jnp.zeros((bins.shape[1],), bool)
    # the lowering choice is env/backend-dependent and invisible to jit's
    # cache key — thread it as a static arg so flipping
    # MMLSPARK_TPU_HIST_HOST / MMLSPARK_TPU_PALLAS between calls with
    # identical shapes can never reuse a stale-lowering program
    from mmlspark_tpu.ops.histogram import (
        _rows_sharded,
        hist_lowering,
        use_host_hist,
    )

    hm = hist_lowering()
    if (
        use_host_hist()
        and not partitioned
        and not _rows_sharded(mesh, shard_axis)
    ):
        # CPU lowering: the whole leaf-wise tree behind ONE host callback
        # (see _grow_tree_depthwise_hostcall for the cost argument)
        return _grow_tree_lossguide_hostcall(
            bins, grad, hess, row_weight,
            num_leaves=num_leaves, max_depth=max_depth, num_bins=num_bins,
            min_data_in_leaf=min_data_in_leaf, min_gain=min_gain,
            lambda_l2=lambda_l2, lambda_l1=lambda_l1,
            min_sum_hessian=min_sum_hessian, learning_rate=learning_rate,
            feature_mask=feature_mask, categorical_mask=categorical_mask,
            has_categorical=has_categorical,
        )
    if partitioned:
        return _grow_tree_partitioned(
            bins, grad, hess, row_weight,
            num_leaves=num_leaves, lambda_l2=lambda_l2, min_gain=min_gain,
            learning_rate=learning_rate, feature_mask=feature_mask,
            max_depth=max_depth, min_data_in_leaf=min_data_in_leaf,
            categorical_mask=categorical_mask, has_categorical=has_categorical,
            lambda_l1=lambda_l1, min_sum_hessian=min_sum_hessian,
            num_bins=num_bins, hist_mode=hm,
        )
    return _grow_tree(
        bins, grad, hess, row_weight,
        num_leaves=num_leaves, lambda_l2=lambda_l2, min_gain=min_gain,
        learning_rate=learning_rate, feature_mask=feature_mask,
        max_depth=max_depth, min_data_in_leaf=min_data_in_leaf,
        categorical_mask=categorical_mask, has_categorical=has_categorical,
        lambda_l1=lambda_l1, min_sum_hessian=min_sum_hessian,
        num_bins=num_bins, mesh=mesh, shard_axis=shard_axis, hist_mode=hm,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_leaves", "max_depth", "min_data_in_leaf", "has_categorical",
        "num_bins", "mesh", "shard_axis", "hist_mode",
    ),
)
def _grow_tree(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_weight: jnp.ndarray,
    num_leaves: int,
    lambda_l2: float,
    min_gain: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,
    max_depth: int,
    min_data_in_leaf: int,
    categorical_mask: jnp.ndarray,
    has_categorical: bool,
    lambda_l1: float = 0.0,
    min_sum_hessian: float = 1e-3,
    num_bins: int = NUM_BINS,
    mesh: Any = None,
    shard_axis: Optional[str] = None,
    hist_mode: str = "",
) -> GrownTree:
    del hist_mode  # jit cache key only (see grow_tree)
    n, d = bins.shape
    L = num_leaves
    B = num_bins
    bins = bins.astype(jnp.int32)
    cat_f = categorical_mask.astype(bool)
    lam = lambda_l2
    l1 = lambda_l1
    msh = min_sum_hessian
    g = grad * row_weight
    h = hess * row_weight
    cnt_w = row_weight

    def soft(Gv: jnp.ndarray) -> jnp.ndarray:
        return threshold_l1(Gv, l1)

    # per-row (g, h, count) stats; the histogram op picks its lowering
    # (Pallas one-hot matmul on single-chip TPU, GSPMD-partitioned scatter
    # under sharded meshes / CPU) — see ops/histogram.py
    from mmlspark_tpu.ops.histogram import plane_histogram

    row_stats = jnp.stack([g, h, cnt_w], axis=-1)  # (n, 3)

    def plane_hist(mask: jnp.ndarray) -> jnp.ndarray:
        """Histogram of the rows selected by ``mask`` -> (d*B, 3)."""
        return plane_histogram(
            bins, row_stats, mask, num_bins=B, mesh=mesh,
            shard_axis=shard_axis, bins_in_range=True,
        )

    # best split of ONE leaf from its plane. Only state-free validity
    # (min_data, feature_fraction) is applied there; per-leaf state
    # (activity, depth) is applied at selection time, so cached results
    # stay exact until the leaf's histogram changes.
    leaf_best = make_leaf_best(
        d, feature_mask, min_data_in_leaf, msh, lam, l1, cat_f,
        has_categorical, num_bins=B,
    )

    def step(k: int, state: tuple) -> tuple:
        (hist, row_leaf, leaf_depth, done,
         cache_gain, cache_feat, cache_bin, cache_catmask, prev_pair,
         rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
         rec_is_cat, rec_catmask) = state

        # hist is carried incrementally: (L, d*B, 3) cube, only the two
        # children of the previous split changed (LightGBM's
        # parent-minus-child trick). The split-search cache mirrors that:
        # re-evaluate ONLY those two leaves' planes, keep every other
        # leaf's cached best split (their histograms are untouched).
        pg, pf, pb, pcm = jax.vmap(leaf_best)(hist[prev_pair])
        cache_gain = cache_gain.at[prev_pair].set(pg)
        cache_feat = cache_feat.at[prev_pair].set(pf)
        cache_bin = cache_bin.at[prev_pair].set(pb)
        cache_catmask = cache_catmask.at[prev_pair].set(pcm)

        # selection: apply the per-leaf state masks to the cached gains
        num_active = k + 1
        leaf_ids = jnp.arange(L, dtype=jnp.int32)
        leaf_ok = leaf_ids < num_active
        if max_depth > 0:
            leaf_ok = leaf_ok & (leaf_depth < max_depth)
        sel = jnp.where(leaf_ok, cache_gain, -jnp.inf)
        bl = jnp.argmax(sel).astype(jnp.int32)
        best_gain = sel[bl]
        bf = cache_feat[bl]
        bb = cache_bin[bl]
        catmask = cache_catmask[bl]

        do_split = (~done) & (best_gain > min_gain) & jnp.isfinite(best_gain)
        new_id = jnp.int32(k + 1)
        in_leaf = row_leaf == bl
        row_bins = bins[:, bf]
        if has_categorical:
            is_cat_split = cat_f[bf]
            goes_right = in_leaf & jnp.where(
                is_cat_split, ~catmask[row_bins], row_bins > bb
            )
        else:
            is_cat_split = jnp.asarray(False)
            goes_right = in_leaf & (row_bins > bb)
        moved = do_split & goes_right
        row_leaf = jnp.where(moved, new_id, row_leaf)
        # incremental histogram update: scatter only the moved rows into the
        # right child's plane; the parent keeps (old - right)
        right_plane = plane_hist(moved.astype(jnp.float32))
        hist = hist.at[new_id].set(right_plane).at[bl].add(
            jnp.where(do_split, -right_plane, 0.0)
        )
        child_depth = leaf_depth[bl] + 1
        leaf_depth = jnp.where(
            do_split,
            leaf_depth.at[bl].set(child_depth).at[new_id].set(child_depth),
            leaf_depth,
        )
        rec_leaf = rec_leaf.at[k].set(jnp.where(do_split, bl, -1))
        rec_feature = rec_feature.at[k].set(jnp.where(do_split, bf, -1))
        rec_bin = rec_bin.at[k].set(jnp.where(do_split, bb, -1))
        rec_active = rec_active.at[k].set(do_split)
        rec_gain = rec_gain.at[k].set(jnp.where(do_split, best_gain, 0.0))
        rec_is_cat = rec_is_cat.at[k].set(do_split & is_cat_split)
        rec_catmask = rec_catmask.at[k].set(
            jnp.where(do_split & is_cat_split, catmask, False)
        )
        done = done | ~do_split
        # the two leaves whose planes changed — next step refreshes them
        prev_pair = jnp.stack([bl, new_id])
        return (hist, row_leaf, leaf_depth, done,
                cache_gain, cache_feat, cache_bin, cache_catmask, prev_pair,
                rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
                rec_is_cat, rec_catmask)

    # root histogram: the only full-data cube write of the whole tree
    hist0 = (
        jnp.zeros((L, d * B, 3), jnp.float32)
        .at[0]
        .set(plane_hist(jnp.ones((n,), jnp.float32)))
    )
    init = (
        hist0,
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((L,), jnp.int32),
        jnp.asarray(False),
        jnp.full((L,), -jnp.inf, jnp.float32),   # cache_gain
        jnp.zeros((L,), jnp.int32),              # cache_feat
        jnp.zeros((L,), jnp.int32),              # cache_bin
        jnp.zeros((L, B), bool),                 # cache_catmask
        jnp.zeros((2,), jnp.int32),              # prev_pair: root twice
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.zeros((L - 1,), bool),
        jnp.zeros((L - 1,), jnp.float32),
        jnp.zeros((L - 1,), bool),
        jnp.zeros((L - 1, B), bool),
    )
    (_, row_leaf, _, _, _, _, _, _, _,
     rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
     rec_is_cat, rec_catmask) = (
        jax.lax.fori_loop(0, L - 1, step, init)
    )

    # leaf values: -ThresholdL1(G)/(H+lambda) * lr per final leaf
    from mmlspark_tpu.ops.histogram import _rows_sharded, leaf_stat_sums

    sums = leaf_stat_sums(
        row_leaf, row_stats, L, sharded=_rows_sharded(mesh, shard_axis)
    )
    Gl, Hl, Cl = sums[:, 0], sums[:, 1], sums[:, 2]
    leaf_values = -soft(Gl) / (Hl + lambda_l2) * learning_rate
    leaf_values = jnp.where(Cl > 0, leaf_values, 0.0)
    return GrownTree(
        rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
        leaf_values, Cl.astype(jnp.int32), row_leaf,
        rec_is_cat, rec_catmask,
    )


def _range_sizes(n: int, min_size: int = 512) -> tuple:
    """Static power-of-2 row-bucket sizes for the range histogram: the
    smallest bucket covering a child's row count bounds overshoot at 2x."""
    sizes = []
    s = min(min_size, n)
    while s < n:
        sizes.append(s)
        s *= 2
    sizes.append(n)
    return tuple(sizes)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_leaves", "max_depth", "min_data_in_leaf", "has_categorical",
        "num_bins", "hist_mode",
    ),
)
def _grow_tree_partitioned(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_weight: jnp.ndarray,
    num_leaves: int,
    lambda_l2: float,
    min_gain: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,
    max_depth: int,
    min_data_in_leaf: int,
    categorical_mask: jnp.ndarray,
    has_categorical: bool,
    lambda_l1: float = 0.0,
    min_sum_hessian: float = 1e-3,
    num_bins: int = NUM_BINS,
    hist_mode: str = "",
) -> GrownTree:
    """Leaf-wise growth over data kept PARTITIONED by leaf — the TPU
    expression of LightGBM's DataPartition + histogram-subtraction core
    (the reason native LightGBM's per-split cost is O(leaf rows), not
    O(dataset rows); TrainUtils.scala:220-315 drives that C++ engine).

    Identical split semantics to :func:`_grow_tree` (same ``make_leaf_best``,
    same records); only the histogram COST model changes:

    - rows live in a permuted layout (``order``) where every leaf owns a
      contiguous [start, start+count) range; each split stable-partitions
      the parent's range in O(n) elementwise work;
    - the new histogram pass covers ONLY the smaller child's range, sliced
      to the smallest static power-of-2 bucket (``lax.switch`` keeps every
      shape static for XLA) — the larger sibling is parent - smaller
      (LightGBM's subtraction trick);
    - per tree the histogram work sums to O(n * avg_depth) cells instead
      of the masked full-pass grower's O(n * num_leaves).

    Single-device layouts only: the per-split global permutation gathers
    would become cross-device traffic under a sharded mesh (the caller
    gates on mesh size; sharded meshes keep :func:`_grow_tree`, whose
    scatter lowering GSPMD partitions + allreduces)."""
    from mmlspark_tpu.ops.histogram import plane_histogram

    n, d = bins.shape
    L = num_leaves
    B = num_bins
    bins = bins.astype(jnp.int32)
    cat_f = categorical_mask.astype(bool)
    lam = lambda_l2
    l1 = lambda_l1
    msh = min_sum_hessian
    g = grad * row_weight
    h = hess * row_weight
    cnt_w = row_weight
    row_stats = jnp.stack([g, h, cnt_w], axis=-1)  # (n, 3) original order
    sizes = _range_sizes(n)
    sizes_arr = jnp.asarray(sizes, jnp.int32)

    leaf_best = make_leaf_best(
        d, feature_mask, min_data_in_leaf, msh, lam, l1, cat_f,
        has_categorical, num_bins=B,
    )

    def step(k: int, state: tuple) -> tuple:
        (hist, order, bins_ord, stats_ord, leaf_start, leaf_count,
         leaf_depth, done,
         cache_gain, cache_feat, cache_bin, cache_catmask, prev_pair,
         rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
         rec_is_cat, rec_catmask) = state

        # refresh the two planes the previous split changed (all other
        # leaves' cached best splits are still exact)
        pg, pf, pb, pcm = jax.vmap(leaf_best)(hist[prev_pair])
        cache_gain = cache_gain.at[prev_pair].set(pg)
        cache_feat = cache_feat.at[prev_pair].set(pf)
        cache_bin = cache_bin.at[prev_pair].set(pb)
        cache_catmask = cache_catmask.at[prev_pair].set(pcm)

        num_active = k + 1
        leaf_ids = jnp.arange(L, dtype=jnp.int32)
        leaf_ok = leaf_ids < num_active
        if max_depth > 0:
            leaf_ok = leaf_ok & (leaf_depth < max_depth)
        sel = jnp.where(leaf_ok, cache_gain, -jnp.inf)
        bl = jnp.argmax(sel).astype(jnp.int32)
        best_gain = sel[bl]
        bf = cache_feat[bl]
        bb = cache_bin[bl]
        catmask = cache_catmask[bl]
        do_split = (~done) & (best_gain > min_gain) & jnp.isfinite(best_gain)
        new_id = jnp.int32(k + 1)

        s = leaf_start[bl]
        c = leaf_count[bl]
        pos = jnp.arange(n, dtype=jnp.int32)
        in_range = (pos >= s) & (pos < s + c)
        row_bins = bins_ord[:, bf]
        if has_categorical:
            is_cat_split = cat_f[bf]
            decide = jnp.where(is_cat_split, ~catmask[row_bins], row_bins > bb)
        else:
            is_cat_split = jnp.asarray(False)
            decide = row_bins > bb
        right_m = in_range & decide & do_split
        left_m = in_range & ~right_m & do_split
        c_right = right_m.sum().astype(jnp.int32)
        c_left = c - c_right

        # stable partition of the parent's range: left block then right
        # block; everything outside the range (and no-op steps) stays put
        destL = s + jnp.cumsum(left_m.astype(jnp.int32)) - 1
        destR = s + c_left + jnp.cumsum(right_m.astype(jnp.int32)) - 1
        dest = jnp.where(left_m, destL, jnp.where(right_m, destR, pos))
        inv = jnp.zeros((n,), jnp.int32).at[dest].set(pos)
        order = jnp.take(order, inv)
        bins_ord = jnp.take(bins_ord, inv, axis=0)
        stats_ord = jnp.take(stats_ord, inv, axis=0)

        # smaller child's histogram from its (now contiguous) range; the
        # switch picks the smallest static bucket covering the count
        small_left = c_left <= c_right
        s_small = jnp.where(small_left, s, s + c_left)
        c_small = jnp.where(do_split, jnp.minimum(c_left, c_right), 0)

        def mk(sz: int):
            def f(_arg: None) -> jnp.ndarray:
                st = jnp.clip(s_small, 0, n - sz)
                bsl = jax.lax.dynamic_slice_in_dim(bins_ord, st, sz, 0)
                ssl = jax.lax.dynamic_slice_in_dim(stats_ord, st, sz, 0)
                p = st + jnp.arange(sz, dtype=jnp.int32)
                m = ((p >= s_small) & (p < s_small + c_small)).astype(
                    jnp.float32
                )
                return plane_histogram(bsl, ssl, m, num_bins=B, bins_in_range=True)
            return f

        idx = jnp.sum(c_small > sizes_arr).astype(jnp.int32)
        small_plane = jax.lax.switch(idx, [mk(sz) for sz in sizes], None)
        parent_plane = hist[bl]
        big_plane = parent_plane - small_plane
        left_plane = jnp.where(small_left, small_plane, big_plane)
        right_plane = jnp.where(small_left, big_plane, small_plane)
        hist = hist.at[bl].set(
            jnp.where(do_split, left_plane, parent_plane)
        ).at[new_id].set(
            jnp.where(do_split, right_plane, hist[new_id])
        )

        leaf_start = jnp.where(
            do_split, leaf_start.at[new_id].set(s + c_left), leaf_start
        )
        leaf_count = jnp.where(
            do_split,
            leaf_count.at[bl].set(c_left).at[new_id].set(c_right),
            leaf_count,
        )
        child_depth = leaf_depth[bl] + 1
        leaf_depth = jnp.where(
            do_split,
            leaf_depth.at[bl].set(child_depth).at[new_id].set(child_depth),
            leaf_depth,
        )
        rec_leaf = rec_leaf.at[k].set(jnp.where(do_split, bl, -1))
        rec_feature = rec_feature.at[k].set(jnp.where(do_split, bf, -1))
        rec_bin = rec_bin.at[k].set(jnp.where(do_split, bb, -1))
        rec_active = rec_active.at[k].set(do_split)
        rec_gain = rec_gain.at[k].set(jnp.where(do_split, best_gain, 0.0))
        rec_is_cat = rec_is_cat.at[k].set(do_split & is_cat_split)
        rec_catmask = rec_catmask.at[k].set(
            jnp.where(do_split & is_cat_split, catmask, False)
        )
        done = done | ~do_split
        prev_pair = jnp.stack([bl, new_id])
        return (hist, order, bins_ord, stats_ord, leaf_start, leaf_count,
                leaf_depth, done,
                cache_gain, cache_feat, cache_bin, cache_catmask, prev_pair,
                rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
                rec_is_cat, rec_catmask)

    hist0 = (
        jnp.zeros((L, d * B, 3), jnp.float32)
        .at[0]
        .set(plane_histogram(bins, row_stats, num_bins=B, bins_in_range=True))
    )
    init = (
        hist0,
        jnp.arange(n, dtype=jnp.int32),          # order: position -> row id
        bins,                                     # bins_ord (starts unpermuted)
        row_stats,                                # stats_ord
        jnp.zeros((L,), jnp.int32),               # leaf_start
        jnp.zeros((L,), jnp.int32).at[0].set(n),  # leaf_count
        jnp.zeros((L,), jnp.int32),               # leaf_depth
        jnp.asarray(False),
        jnp.full((L,), -jnp.inf, jnp.float32),
        jnp.zeros((L,), jnp.int32),
        jnp.zeros((L,), jnp.int32),
        jnp.zeros((L, B), bool),
        jnp.zeros((2,), jnp.int32),
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.zeros((L - 1,), bool),
        jnp.zeros((L - 1,), jnp.float32),
        jnp.zeros((L - 1,), bool),
        jnp.zeros((L - 1, B), bool),
    )
    (_, order, _, _, leaf_start, leaf_count, _, _,
     _, _, _, _, _,
     rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
     rec_is_cat, rec_catmask) = jax.lax.fori_loop(0, L - 1, step, init)

    # position -> leaf from the final ranges (ranges tile [0, n) exactly:
    # each position lies in exactly one active leaf), then back to the
    # original row order through the permutation
    pos = jnp.arange(n, dtype=jnp.int32)[:, None]
    in_leaf = (pos >= leaf_start[None, :]) & (
        pos < (leaf_start + leaf_count)[None, :]
    )
    row_leaf_ord = jnp.argmax(in_leaf, axis=1).astype(jnp.int32)
    row_leaf = jnp.zeros((n,), jnp.int32).at[order].set(row_leaf_ord)

    from mmlspark_tpu.ops.histogram import leaf_stat_sums

    sums = leaf_stat_sums(row_leaf, row_stats, L)
    Gl, Hl, Cl = sums[:, 0], sums[:, 1], sums[:, 2]
    leaf_values = -threshold_l1(Gl, lambda_l1) / (Hl + lambda_l2) * learning_rate
    leaf_values = jnp.where(Cl > 0, leaf_values, 0.0)
    return GrownTree(
        rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
        leaf_values, Cl.astype(jnp.int32), row_leaf,
        rec_is_cat, rec_catmask,
    )


def grow_tree_depthwise(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_weight: jnp.ndarray,
    num_leaves: int,
    lambda_l2: float,
    min_gain: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,
    max_depth: int = -1,
    min_data_in_leaf: int = 20,
    categorical_mask: Optional[jnp.ndarray] = None,
    lambda_l1: float = 0.0,
    min_sum_hessian: float = 1e-3,
    num_bins: int = NUM_BINS,
    mesh: Any = None,
    shard_axis: Optional[str] = None,
) -> GrownTree:
    """Depthwise (level-wise) growth — the XGBoost-hist/SparkML-GBT grow
    policy, built for the TPU cost model: every level's leaf histograms
    come from ONE ``multi_plane_histogram`` pass over the rows, so a tree
    costs O(depth) row passes instead of lossguide's O(num_leaves). Split
    semantics (gain, min_data, L1/hessian floors, categorical subsets)
    come from the same ``make_leaf_best`` as the leaf-wise grower; output
    is the identical GrownTree record format.

    With ``max_depth`` unset, depth caps at ceil(log2(num_leaves)) — the
    balanced depth that can realize the leaf budget.

    Sibling subtraction (LightGBM's histogram-subtraction trick, on by
    default, ``MMLSPARK_TPU_GBDT_SIBLING=0`` to disable): from level 1
    on, only the RIGHT child of every pair is histogrammed and the left
    plane is derived as parent - right. The multi-plane kernel's MXU
    cost scales with the slot count, so this halves the dominant
    per-level matmul width — the per-tree histogram work drops from
    ~2*num_leaves to ~num_leaves plane-equivalents."""
    has_categorical = categorical_mask is not None
    if not has_categorical:
        categorical_mask = jnp.zeros((bins.shape[1],), bool)
    L = int(num_leaves)
    # levels beyond the leaf budget can never split anything: cap the
    # static unroll so a huge max_depth doesn't emit useless row passes
    n_levels = (
        min(int(max_depth), L - 1) if max_depth > 0
        else max(1, int(np.ceil(np.log2(L))))
    )
    sibling = os.environ.get("MMLSPARK_TPU_GBDT_SIBLING", "1") not in (
        "0", "false", ""
    )
    # vectorized level application pays on TPU (the sequential chain of
    # tiny dependent ops per split dominates wall clock there) but costs
    # ~30% on CPU (no dispatch-latency problem; full-width scatters per
    # level instead). Default by backend, env-overridable.
    env_vec = os.environ.get("MMLSPARK_TPU_GBDT_VECTOR_SPLIT")
    if env_vec is not None:
        vector = env_vec not in ("0", "false", "")
    else:
        try:
            vector = jax.default_backend() == "tpu"
        except Exception:
            vector = False
    # CPU lowering: the whole tree grows behind ONE host callback (numpy
    # split scan + pooled bincount histograms) — a per-level histogram
    # callback alone leaves ~9 ms/tree of XLA:CPU glue plus ~1 ms of
    # bridge cost per crossing, which is the difference between losing
    # and beating sklearn's OpenMP grower at bench shapes. TPU and
    # sharded meshes keep the XLA grower below.
    from mmlspark_tpu.ops.histogram import _rows_sharded, use_host_hist

    if use_host_hist() and not _rows_sharded(mesh, shard_axis):
        return _grow_tree_depthwise_hostcall(
            bins, grad, hess, row_weight,
            num_leaves=L, n_levels=n_levels, num_bins=num_bins,
            min_data_in_leaf=min_data_in_leaf, min_gain=min_gain,
            lambda_l2=lambda_l2, lambda_l1=lambda_l1,
            min_sum_hessian=min_sum_hessian, learning_rate=learning_rate,
            feature_mask=feature_mask, categorical_mask=categorical_mask,
            has_categorical=has_categorical, sibling_subtract=sibling,
        )
    from mmlspark_tpu.ops.histogram import hist_lowering

    return _grow_tree_depthwise(
        bins, grad, hess, row_weight,
        num_leaves=L, lambda_l2=lambda_l2, min_gain=min_gain,
        learning_rate=learning_rate, feature_mask=feature_mask,
        n_levels=n_levels, min_data_in_leaf=min_data_in_leaf,
        categorical_mask=categorical_mask, has_categorical=has_categorical,
        lambda_l1=lambda_l1, min_sum_hessian=min_sum_hessian,
        num_bins=num_bins, mesh=mesh, shard_axis=shard_axis,
        sibling_subtract=sibling, vector_split=vector,
        hist_mode=hist_lowering(),
    )


def _grown_tree_shapes(n: int, L: int, B: int) -> tuple:
    return (
        jax.ShapeDtypeStruct((L - 1,), jnp.int32),    # rec_leaf
        jax.ShapeDtypeStruct((L - 1,), jnp.int32),    # rec_feature
        jax.ShapeDtypeStruct((L - 1,), jnp.int32),    # rec_bin
        jax.ShapeDtypeStruct((L - 1,), jnp.bool_),    # rec_active
        jax.ShapeDtypeStruct((L - 1,), jnp.float32),  # rec_gain
        jax.ShapeDtypeStruct((L,), jnp.float32),      # leaf_values
        jax.ShapeDtypeStruct((L,), jnp.int32),        # leaf_counts
        jax.ShapeDtypeStruct((n,), jnp.int32),        # row_leaf
        jax.ShapeDtypeStruct((L - 1,), jnp.bool_),    # rec_is_cat
        jax.ShapeDtypeStruct((L - 1, B), jnp.bool_),  # rec_catmask
    )


def _grow_tree_lossguide_hostcall(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_weight: jnp.ndarray,
    *,
    num_leaves: int,
    max_depth: int,
    num_bins: int,
    min_data_in_leaf: int,
    min_gain: float,
    lambda_l2: float,
    lambda_l1: float,
    min_sum_hessian: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,
    categorical_mask: jnp.ndarray,
    has_categorical: bool,
) -> GrownTree:
    """The host leaf-wise grower (models/gbdt/hostgrow.py) behind one
    pure_callback; traceable inside jit / the scan-fused round loop."""
    from mmlspark_tpu.models.gbdt.hostgrow import grow_tree_lossguide_host

    n, d = bins.shape
    L, B = num_leaves, num_bins
    kern = functools.partial(
        grow_tree_lossguide_host,
        L, int(max_depth), B, min_data_in_leaf, has_categorical,
    )
    args = (
        jnp.float32(min_gain), jnp.float32(lambda_l2),
        jnp.float32(lambda_l1), jnp.float32(min_sum_hessian),
        jnp.float32(learning_rate),
        bins, grad, hess, row_weight, feature_mask, categorical_mask,
    )
    out_shapes = _grown_tree_shapes(n, L, B)
    from mmlspark_tpu.ops.histogram import _callback

    return GrownTree(*_callback(kern, out_shapes, *args))


def _grow_tree_depthwise_hostcall(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_weight: jnp.ndarray,
    *,
    num_leaves: int,
    n_levels: int,
    num_bins: int,
    min_data_in_leaf: int,
    min_gain: float,
    lambda_l2: float,
    lambda_l1: float,
    min_sum_hessian: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,
    categorical_mask: jnp.ndarray,
    has_categorical: bool,
    sibling_subtract: bool,
) -> GrownTree:
    """The host grower (models/gbdt/hostgrow.py) behind one
    pure_callback; traceable inside jit / the scan-fused round loop."""
    from mmlspark_tpu.models.gbdt.hostgrow import grow_tree_depthwise_host

    n, d = bins.shape
    L, B = num_leaves, num_bins
    # static structure in the partial; regularization/lr knobs ride as
    # operands — inside the scan-fused loop they are traced scalars
    kern = functools.partial(
        grow_tree_depthwise_host,
        L, n_levels, B, min_data_in_leaf, sibling_subtract, has_categorical,
    )
    out_shapes = _grown_tree_shapes(n, L, B)
    args = (
        jnp.float32(min_gain), jnp.float32(lambda_l2),
        jnp.float32(lambda_l1), jnp.float32(min_sum_hessian),
        jnp.float32(learning_rate),
        bins, grad, hess, row_weight, feature_mask, categorical_mask,
    )
    from mmlspark_tpu.ops.histogram import _callback

    return GrownTree(*_callback(kern, out_shapes, *args))


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_leaves", "n_levels", "min_data_in_leaf", "has_categorical",
        "num_bins", "mesh", "shard_axis", "sibling_subtract",
        "vector_split", "hist_mode",
    ),
)
def _grow_tree_depthwise(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    row_weight: jnp.ndarray,
    num_leaves: int,
    lambda_l2: float,
    min_gain: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,
    n_levels: int,
    min_data_in_leaf: int,
    categorical_mask: jnp.ndarray,
    has_categorical: bool,
    lambda_l1: float = 0.0,
    min_sum_hessian: float = 1e-3,
    num_bins: int = NUM_BINS,
    mesh: Any = None,
    shard_axis: Optional[str] = None,
    sibling_subtract: bool = True,
    vector_split: bool = True,
    hist_mode: str = "",
) -> GrownTree:
    del hist_mode  # jit cache key only (see grow_tree_depthwise)
    from mmlspark_tpu.ops.histogram import multi_plane_histogram

    n, d = bins.shape
    L = num_leaves
    B = num_bins
    bins = bins.astype(jnp.int32)
    cat_f = categorical_mask.astype(bool)
    g = grad * row_weight
    h = hess * row_weight
    cnt_w = row_weight
    row_stats = jnp.stack([g, h, cnt_w], axis=-1)
    leaf_best = make_leaf_best(
        d, feature_mask, min_data_in_leaf, min_sum_hessian,
        lambda_l2, lambda_l1, cat_f, has_categorical, num_bins=B,
    )

    row_slot = jnp.zeros((n,), jnp.int32)
    k = jnp.int32(0)                       # splits made so far (record cursor)
    rec_leaf = jnp.full((L - 1,), -1, jnp.int32)
    rec_feature = jnp.full((L - 1,), -1, jnp.int32)
    rec_bin = jnp.full((L - 1,), -1, jnp.int32)
    rec_active = jnp.zeros((L - 1,), bool)
    rec_gain = jnp.zeros((L - 1,), jnp.float32)
    rec_is_cat = jnp.zeros((L - 1,), bool)
    rec_catmask = jnp.zeros((L - 1, B), bool)
    # frontier of the CURRENT level: lut maps record-slot -> local plane
    # index (sentinel = not in frontier); inv maps plane index -> slot
    lut = jnp.where(jnp.arange(L) == 0, 0, L).astype(jnp.int32)
    inv = jnp.full((1,), 0, jnp.int32)     # level 0: just the root
    cube_prev = None                       # previous level's plane cube
    parent_local = None                    # pair p -> parent's plane in it

    for level in range(n_levels):
        S = int(inv.shape[0])
        local = jnp.where(row_slot < L, lut[jnp.clip(row_slot, 0, L - 1)], S)
        if sibling_subtract and level > 0:
            # LightGBM's histogram subtraction, TPU-shaped: the frontier
            # is sibling pairs at locals (2p, 2p+1); histogram only the
            # RIGHT children (matmul width P*6 instead of S*6 — the MXU
            # cost of the multi-plane kernel scales with slot count) and
            # derive left = parent - right from the previous level's cube.
            P = S // 2
            is_right = (local < 2 * P) & (local % 2 == 1)
            slot_pair = jnp.where(is_right, local // 2, P)  # P = no plane
            half = multi_plane_histogram(
                bins, row_stats, slot_pair, P, num_bins=B,
                mesh=mesh, shard_axis=shard_axis, bins_in_range=True,
            )
            ok = (parent_local >= 0)[:, None, None]
            parents = cube_prev[
                jnp.clip(parent_local, 0, cube_prev.shape[0] - 1)
            ]
            left = jnp.where(ok, parents - half, 0.0)
            right = jnp.where(ok, half, 0.0)
            inter = jnp.stack([left, right], axis=1).reshape(
                2 * P, d * B, 3
            )
            cube = (
                inter if S == 2 * P
                else jnp.zeros((S, d * B, 3), jnp.float32).at[: 2 * P].set(inter)
            )
        else:
            cube = multi_plane_histogram(
                bins, row_stats, local, S, num_bins=B,
                mesh=mesh, shard_axis=shard_axis, bins_in_range=True,
            )
        cube_prev = cube
        gains, feats, bbs, catms = jax.vmap(leaf_best)(cube)
        # budget: when fewer than S splits remain, best-gain nodes win
        order = jnp.argsort(-gains)
        S_next = min(2 * S, L)

        if vector_split:
            # ONE vectorized application of the whole level's splits.
            # The sequential fori_loop below is semantically a chain of
            # ~30 tiny dependent XLA ops per split — at 63 splits x 50
            # trees that dependency chain, not the histogram FLOPs,
            # dominated on-chip wall clock. Every split in a level
            # touches a DIFFERENT leaf, so the only cross-split coupling
            # is the budget/record ordering — reproduced exactly by a
            # cumsum over the gain-sorted valid mask (argsort is stable,
            # and the budget cuts a suffix: once k + rank hits L-1 every
            # later valid fails too, so surviving ranks are unchanged).
            slot_s = inv[order]
            gain_s = gains[order]
            ok = (
                (slot_s >= 0) & jnp.isfinite(gain_s) & (gain_s > min_gain)
            )
            rank = jnp.cumsum(ok.astype(jnp.int32)) - ok.astype(jnp.int32)
            ok = ok & (k + rank < L - 1)
            ks = k + rank                    # record index per sorted pos
            new_id = ks + 1
            bf_s, bb_s, cm_s = feats[order], bbs[order], catms[order]
            if has_categorical:
                is_cat_s = cat_f[bf_s]
            else:
                is_cat_s = jnp.zeros_like(ok)
            # record scatters; invalid positions write out-of-range (drop)
            idx = jnp.where(ok, ks, L - 1)   # rec arrays are (L-1,)
            rec_leaf = rec_leaf.at[idx].set(slot_s, mode="drop")
            rec_feature = rec_feature.at[idx].set(bf_s, mode="drop")
            rec_bin = rec_bin.at[idx].set(bb_s, mode="drop")
            rec_active = rec_active.at[idx].set(True, mode="drop")
            rec_gain = rec_gain.at[idx].set(gain_s, mode="drop")
            rec_is_cat = rec_is_cat.at[idx].set(is_cat_s, mode="drop")
            rec_catmask = rec_catmask.at[idx].set(
                jnp.where(is_cat_s[:, None], cm_s, False), mode="drop"
            )
            # next frontier: pair p (= rank) at locals (2p, 2p+1)
            lut = (
                jnp.full((L,), L, jnp.int32)
                .at[jnp.where(ok, slot_s, L)].set(2 * rank, mode="drop")
                .at[jnp.where(ok, new_id, L)].set(2 * rank + 1, mode="drop")
            )
            inv = (
                jnp.full((S_next,), -1, jnp.int32)
                .at[jnp.where(ok, 2 * rank, S_next)].set(slot_s, mode="drop")
                .at[jnp.where(ok, 2 * rank + 1, S_next)].set(
                    new_id, mode="drop"
                )
            )
            pl_n = S_next // 2
            parent_local = (
                jnp.full((pl_n,), -1, jnp.int32)
                .at[jnp.where(ok, rank, pl_n)].set(order, mode="drop")
            )
            # row routing: per ORIGINAL local j, this level's chosen split.
            # The lookup arrays are (S+1,) with slot S as the ALL-FALSE
            # pad: rows whose leaf left the frontier carry local == L,
            # which the clamped gather maps to S — so invalid sorted
            # positions must dump OUT of range (S+1, dropped), never
            # into slot S itself (that pollution rerouted frozen-leaf
            # rows by garbage split params)
            sj = jnp.where(ok, order, S + 1)  # scatter index by local
            split_ok_l = jnp.zeros((S + 1,), bool).at[sj].set(
                True, mode="drop"
            )
            split_bf_l = jnp.zeros((S + 1,), jnp.int32).at[sj].set(
                bf_s, mode="drop"
            )
            split_bb_l = jnp.zeros((S + 1,), jnp.int32).at[sj].set(
                bb_s, mode="drop"
            )
            split_new_l = jnp.zeros((S + 1,), jnp.int32).at[sj].set(
                new_id, mode="drop"
            )
            j_r = local                       # (n,) in [0, S]
            okr = split_ok_l[j_r]
            bf_r = split_bf_l[j_r]
            row_bins = jnp.take_along_axis(bins, bf_r[:, None], axis=1)[:, 0]
            if has_categorical:
                split_iscat_l = jnp.zeros((S + 1,), bool).at[sj].set(
                    is_cat_s, mode="drop"
                )
                split_cm_l = jnp.zeros((S + 1, B), bool).at[sj].set(
                    cm_s, mode="drop"
                )
                goes_right = okr & jnp.where(
                    split_iscat_l[j_r],
                    ~split_cm_l[j_r, row_bins],
                    row_bins > split_bb_l[j_r],
                )
            else:
                goes_right = okr & (row_bins > split_bb_l[j_r])
            row_slot = jnp.where(goes_right, split_new_l[j_r], row_slot)
            k = k + ok.sum(dtype=jnp.int32)
            continue

        lut_next0 = jnp.full((L,), L, jnp.int32)
        inv_next0 = jnp.full((S_next,), -1, jnp.int32)
        parent_local0 = jnp.full((S_next // 2,), -1, jnp.int32)

        def split_one(i: int, carry: tuple) -> tuple:
            (k, n_split, row_slot, lut_next, inv_next, parent_local_n,
             rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
             rec_is_cat, rec_catmask) = carry
            j = order[i]
            slot_j = inv[j]
            gain = gains[j]
            valid = (
                (slot_j >= 0)
                & jnp.isfinite(gain)
                & (gain > min_gain)
                & (k < L - 1)
            )
            bf, bb, cm = feats[j], bbs[j], catms[j]
            new_id = k + 1
            in_leaf = row_slot == slot_j
            row_bins = bins[:, bf]
            if has_categorical:
                goes_right = in_leaf & jnp.where(
                    cat_f[bf], ~cm[row_bins], row_bins > bb
                )
                is_cat_split = cat_f[bf]
            else:
                goes_right = in_leaf & (row_bins > bb)
                is_cat_split = jnp.asarray(False)
            row_slot = jnp.where(valid & goes_right, new_id, row_slot)
            ks = jnp.clip(k, 0, L - 2)
            rec_leaf = rec_leaf.at[ks].set(jnp.where(valid, slot_j, rec_leaf[ks]))
            rec_feature = rec_feature.at[ks].set(jnp.where(valid, bf, rec_feature[ks]))
            rec_bin = rec_bin.at[ks].set(jnp.where(valid, bb, rec_bin[ks]))
            rec_active = rec_active.at[ks].set(rec_active[ks] | valid)
            rec_gain = rec_gain.at[ks].set(jnp.where(valid, gain, rec_gain[ks]))
            rec_is_cat = rec_is_cat.at[ks].set(
                rec_is_cat[ks] | (valid & is_cat_split)
            )
            rec_catmask = rec_catmask.at[ks].set(
                jnp.where(valid & is_cat_split, cm, rec_catmask[ks])
            )
            # children join the next level's frontier
            both_ok = valid
            lut_next = jnp.where(
                both_ok,
                lut_next.at[slot_j].set(2 * n_split).at[new_id].set(2 * n_split + 1),
                lut_next,
            )
            inv_next = jnp.where(
                both_ok,
                inv_next.at[2 * n_split].set(slot_j).at[2 * n_split + 1].set(new_id),
                inv_next,
            )
            # pair p's parent plane lives at local j of THIS level's cube
            ps = jnp.clip(n_split, 0, parent_local_n.shape[0] - 1)
            parent_local_n = parent_local_n.at[ps].set(
                jnp.where(both_ok, j, parent_local_n[ps])
            )
            k = k + valid.astype(jnp.int32)
            n_split = n_split + valid.astype(jnp.int32)
            return (k, n_split, row_slot, lut_next, inv_next, parent_local_n,
                    rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
                    rec_is_cat, rec_catmask)

        (k, _, row_slot, lut, inv, parent_local,
         rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
         rec_is_cat, rec_catmask) = jax.lax.fori_loop(
            0, S,
            split_one,
            (k, jnp.int32(0), row_slot, lut_next0, inv_next0, parent_local0,
             rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
             rec_is_cat, rec_catmask),
        )

    from mmlspark_tpu.ops.histogram import _rows_sharded, leaf_stat_sums

    sums = leaf_stat_sums(
        row_slot, row_stats, L, sharded=_rows_sharded(mesh, shard_axis)
    )
    Gl, Hl, Cl = sums[:, 0], sums[:, 1], sums[:, 2]
    leaf_values = (
        -threshold_l1(Gl, lambda_l1) / (Hl + lambda_l2) * learning_rate
    )
    leaf_values = jnp.where(Cl > 0, leaf_values, 0.0)
    return GrownTree(
        rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
        leaf_values, Cl.astype(jnp.int32), row_slot,
        rec_is_cat, rec_catmask,
    )


# -- prediction -------------------------------------------------------------


def category_bin_slot(vals: Any, B: int = NUM_BINS, xp: Any = np):
    """Category value -> bin slot, the ONE encoding shared by training
    (identity binning in BinMapper), device prediction (predict_leaves) and
    host SHAP replay (_tree_contribs): NaN -> 0 (missing bin), value v ->
    v+1, clipped into [0, B-1]. ``xp`` selects numpy (host) or jax.numpy
    (traced)."""
    finite = xp.nan_to_num(vals, nan=-1.0)  # NaN -> -1 -> rounds to slot 0
    # clip in float first: huge values must not overflow the int cast
    slot = xp.round(xp.clip(finite, -1.0, float(B))).astype(xp.int32) + 1
    return xp.clip(xp.where(xp.isnan(vals), 0, slot), 0, B - 1)


@jax.jit
def predict_leaves(
    x: jnp.ndarray,            # (n, d) float32 raw features
    rec_leaf: jnp.ndarray,     # (T, S) int32
    rec_feature: jnp.ndarray,  # (T, S) int32
    rec_threshold: jnp.ndarray,  # (T, S) float32 (real-valued; <= goes left)
    rec_active: jnp.ndarray,   # (T, S) bool
    rec_is_cat: Optional[jnp.ndarray] = None,   # (T, S) bool
    rec_catmask: Optional[jnp.ndarray] = None,  # (T, S, B) bool; index = value+1
    rec_default_left: Optional[jnp.ndarray] = None,  # (T, S) bool; NaN direction
) -> jnp.ndarray:
    """Replay split logs for all trees at once -> (n, T) leaf indices.

    Numerical: NaN goes LEFT by default (missing-bin semantics);
    ``rec_default_left`` overrides the direction per split (LightGBM's
    decision_type default-left bit — imported default-right splits route
    NaN right). Categorical splits route by set membership — a category
    value v looks up catmask[v + 1] (identity binning; NaN -> slot 0, the
    missing category). Passing rec_is_cat/rec_default_left as None
    statically compiles that machinery OUT — the common case pays nothing
    for it (mirrors grow_tree's gating)."""
    n = x.shape[0]
    T, S = rec_leaf.shape
    B = NUM_BINS
    row_leaf = jnp.zeros((n, T), jnp.int32)
    has_cat = rec_is_cat is not None
    has_dl = rec_default_left is not None
    if has_cat and rec_catmask is None:
        rec_catmask = jnp.zeros((T, S, B), bool)

    # scan over split steps: right child id of step k is k+1
    def body(row_leaf: jnp.ndarray, inputs: tuple) -> tuple:
        it = iter(inputs)
        k, leaf, feat, thr, active = (next(it) for _ in range(5))
        if has_cat:
            is_cat, catmask = next(it), next(it)
        if has_dl:
            dleft = next(it)
        vals = jnp.take_along_axis(
            x, jnp.broadcast_to(jnp.clip(feat, 0, x.shape[1] - 1)[None, :], (n, T)), axis=1
        )
        in_leaf = row_leaf == leaf[None, :]
        if has_dl:
            right_num = jnp.where(
                jnp.isnan(vals), ~dleft[None, :], vals > thr[None, :]
            )
        else:
            right_num = (vals > thr[None, :]) & ~jnp.isnan(vals)
        if has_cat:
            vbin = category_bin_slot(vals, B, jnp)  # (n, T)
            left_cat = jnp.take_along_axis(
                jnp.broadcast_to(catmask[None], (n, T, B)), vbin[..., None], axis=2
            )[..., 0]
            decide = jnp.where(is_cat[None, :], ~left_cat, right_num)
        else:
            decide = right_num
        goes_right = in_leaf & active[None, :] & decide
        row_leaf = jnp.where(goes_right, jnp.int32(k + 1), row_leaf)
        return row_leaf, None

    ks = jnp.arange(S, dtype=jnp.int32)
    xs = (ks, rec_leaf.T, rec_feature.T, rec_threshold.T, rec_active.T)
    if has_cat:
        xs = xs + (rec_is_cat.T, jnp.moveaxis(rec_catmask, 1, 0))
    if has_dl:
        xs = xs + (rec_default_left.T,)
    row_leaf, _ = jax.lax.scan(body, row_leaf, xs)
    return row_leaf


@jax.jit
def predict_scores(
    x: jnp.ndarray,
    rec_leaf: jnp.ndarray,
    rec_feature: jnp.ndarray,
    rec_threshold: jnp.ndarray,
    rec_active: jnp.ndarray,
    leaf_values: jnp.ndarray,  # (T, L) float32
    rec_is_cat: Optional[jnp.ndarray] = None,
    rec_catmask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Sum of tree outputs -> (n,) raw score."""
    leaves = predict_leaves(
        x, rec_leaf, rec_feature, rec_threshold, rec_active, rec_is_cat, rec_catmask
    )
    per_tree = jnp.take_along_axis(
        jnp.broadcast_to(leaf_values[None], (x.shape[0], *leaf_values.shape)),
        leaves[..., None],
        axis=2,
    )[..., 0]  # (n, T)
    return per_tree.sum(axis=1)
