"""Device-side leaf-wise tree growth + prediction kernels.

The TPU replacement for LightGBM's native histogram trainer
(lightgbm/TrainUtils.scala:220-315 drives `LGBM_BoosterUpdateOneIter`,
whose C++ internally builds per-leaf histograms and allreduces them across
workers over sockets). Here:

- the WHOLE per-tree growth loop is ONE jitted XLA program
  (``lax.fori_loop`` over split steps; static shapes L-1 steps);
- histograms are scatter-adds into a (num_leaves x features x bins) cube;
  under a row-sharded mesh GSPMD turns the scatter into partial histograms
  + an ICI allreduce — exactly LightGBM's data_parallel mode
  (LightGBMConstants "data_parallel", LightGBMParams.scala:13-18) with XLA
  collectives instead of socket rings;
- prediction replays split records with ``lax.scan`` — vectorized over
  rows x trees, no pointer-chasing (TPU-friendly tree inference).

Convention: a split sends ``bin <= threshold_bin`` (and missing/NaN) LEFT;
the left child keeps the parent's leaf id, the right child gets a fresh id.
Trees are therefore fully described by the ordered split records + leaf
values — LightGBM's leaf-wise growth expressed as a replay log.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.ops.histogram import NUM_BINS  # uint8 bin space; bin 0 = missing


class GrownTree(NamedTuple):
    """Device outputs of one grown tree (fixed shapes; L = num_leaves)."""

    rec_leaf: jnp.ndarray      # (L-1,) int32 parent leaf id per split
    rec_feature: jnp.ndarray   # (L-1,) int32
    rec_bin: jnp.ndarray       # (L-1,) int32 threshold bin (<= goes left)
    rec_active: jnp.ndarray    # (L-1,) bool: split actually made
    rec_gain: jnp.ndarray      # (L-1,) float32
    leaf_values: jnp.ndarray   # (L,) float32 (shrinkage applied)
    leaf_counts: jnp.ndarray   # (L,) int32
    row_leaf: jnp.ndarray      # (n,) int32 final leaf of every row


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_leaves", "max_depth", "min_data_in_leaf",
    ),
)
def grow_tree(
    bins: jnp.ndarray,            # (n, d) uint8/int32
    grad: jnp.ndarray,            # (n,) f32
    hess: jnp.ndarray,            # (n,) f32
    row_weight: jnp.ndarray,      # (n,) f32 (bagging/validation mask; 0 = ignore)
    num_leaves: int,
    lambda_l2: float,
    min_gain: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,    # (d,) f32 1/0 (feature_fraction)
    max_depth: int = -1,
    min_data_in_leaf: int = 20,
) -> GrownTree:
    n, d = bins.shape
    L = num_leaves
    B = NUM_BINS
    bins = bins.astype(jnp.int32)
    g = grad * row_weight
    h = hess * row_weight
    cnt_w = row_weight

    # per-row (g, h, count) stats; the histogram op picks its lowering
    # (Pallas one-hot matmul on single-chip TPU, GSPMD-partitioned scatter
    # under sharded meshes / CPU) — see ops/histogram.py
    from mmlspark_tpu.ops.histogram import plane_histogram

    row_stats = jnp.stack([g, h, cnt_w], axis=-1)  # (n, 3)

    def plane_hist(mask: jnp.ndarray) -> jnp.ndarray:
        """Histogram of the rows selected by ``mask`` -> (d*B, 3)."""
        return plane_histogram(bins, row_stats, mask)

    def step(k: int, state: tuple) -> tuple:
        (hist, row_leaf, leaf_depth, done,
         rec_leaf, rec_feature, rec_bin, rec_active, rec_gain) = state

        # hist is carried incrementally: (L, d*B, 3) cube, only the two
        # children of the previous split changed (LightGBM's
        # parent-minus-child trick — one plane scatter per step instead of
        # rebuilding every leaf's histogram from all rows)
        cube = hist.reshape(L, d, B, 3)
        hg, hh, hc = cube[..., 0], cube[..., 1], cube[..., 2]
        # per-(leaf,f): cumulative left stats over threshold bins
        cg = jnp.cumsum(hg, axis=2)
        ch = jnp.cumsum(hh, axis=2)
        cc = jnp.cumsum(hc, axis=2)
        G = cg[:, :, -1:]
        H = ch[:, :, -1:]
        C = cc[:, :, -1:]
        GL, HL, CL = cg, ch, cc
        GR, HR, CR = G - GL, H - HL, C - CL
        lam = lambda_l2
        gain = (
            GL * GL / (HL + lam)
            + GR * GR / (HR + lam)
            - G * G / (H + lam)
        )
        num_active = k + 1
        leaf_ids = jnp.arange(L, dtype=jnp.int32)
        leaf_ok = (leaf_ids < num_active)[:, None, None]
        if max_depth > 0:
            leaf_ok = leaf_ok & (leaf_depth < max_depth)[:, None, None]
        valid = (
            leaf_ok
            & (CL >= min_data_in_leaf)
            & (CR >= min_data_in_leaf)
            & (feature_mask[None, :, None] > 0)
        )
        gain = jnp.where(valid, gain, -jnp.inf)
        flat = gain.reshape(-1)
        best = jnp.argmax(flat)
        best_gain = flat[best]
        bl = (best // (d * B)).astype(jnp.int32)
        bf = ((best // B) % d).astype(jnp.int32)
        bb = (best % B).astype(jnp.int32)

        do_split = (~done) & (best_gain > min_gain) & jnp.isfinite(best_gain)
        new_id = jnp.int32(k + 1)
        in_leaf = row_leaf == bl
        goes_right = in_leaf & (bins[:, bf] > bb)
        moved = do_split & goes_right
        row_leaf = jnp.where(moved, new_id, row_leaf)
        # incremental histogram update: scatter only the moved rows into the
        # right child's plane; the parent keeps (old - right)
        right_plane = plane_hist(moved.astype(jnp.float32))
        hist = hist.at[new_id].set(right_plane).at[bl].add(
            jnp.where(do_split, -right_plane, 0.0)
        )
        child_depth = leaf_depth[bl] + 1
        leaf_depth = jnp.where(
            do_split,
            leaf_depth.at[bl].set(child_depth).at[new_id].set(child_depth),
            leaf_depth,
        )
        rec_leaf = rec_leaf.at[k].set(jnp.where(do_split, bl, -1))
        rec_feature = rec_feature.at[k].set(jnp.where(do_split, bf, -1))
        rec_bin = rec_bin.at[k].set(jnp.where(do_split, bb, -1))
        rec_active = rec_active.at[k].set(do_split)
        rec_gain = rec_gain.at[k].set(jnp.where(do_split, best_gain, 0.0))
        done = done | ~do_split
        return (hist, row_leaf, leaf_depth, done,
                rec_leaf, rec_feature, rec_bin, rec_active, rec_gain)

    # root histogram: the only full-data cube write of the whole tree
    hist0 = (
        jnp.zeros((L, d * B, 3), jnp.float32)
        .at[0]
        .set(plane_hist(jnp.ones((n,), jnp.float32)))
    )
    init = (
        hist0,
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((L,), jnp.int32),
        jnp.asarray(False),
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.full((L - 1,), -1, jnp.int32),
        jnp.zeros((L - 1,), bool),
        jnp.zeros((L - 1,), jnp.float32),
    )
    (_, row_leaf, _, _, rec_leaf, rec_feature, rec_bin, rec_active, rec_gain) = (
        jax.lax.fori_loop(0, L - 1, step, init)
    )

    # leaf values: -G/(H+lambda) * lr per final leaf
    Gl = jnp.zeros((L,), jnp.float32).at[row_leaf].add(g)
    Hl = jnp.zeros((L,), jnp.float32).at[row_leaf].add(h)
    Cl = jnp.zeros((L,), jnp.float32).at[row_leaf].add(cnt_w)
    leaf_values = -Gl / (Hl + lambda_l2) * learning_rate
    leaf_values = jnp.where(Cl > 0, leaf_values, 0.0)
    return GrownTree(
        rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
        leaf_values, Cl.astype(jnp.int32), row_leaf,
    )


# -- prediction -------------------------------------------------------------


@jax.jit
def predict_leaves(
    x: jnp.ndarray,            # (n, d) float32 raw features
    rec_leaf: jnp.ndarray,     # (T, S) int32
    rec_feature: jnp.ndarray,  # (T, S) int32
    rec_threshold: jnp.ndarray,  # (T, S) float32 (real-valued; <= goes left)
    rec_active: jnp.ndarray,   # (T, S) bool
) -> jnp.ndarray:
    """Replay split logs for all trees at once -> (n, T) leaf indices.

    NaN features always go LEFT (missing bin semantics of the trainer)."""
    n = x.shape[0]
    T, S = rec_leaf.shape
    row_leaf = jnp.zeros((n, T), jnp.int32)

    # scan over split steps: right child id of step k is k+1
    def body(row_leaf: jnp.ndarray, inputs: tuple) -> tuple:
        k, leaf, feat, thr, active = inputs
        vals = jnp.take_along_axis(
            x, jnp.broadcast_to(jnp.clip(feat, 0, x.shape[1] - 1)[None, :], (n, T)), axis=1
        )
        in_leaf = row_leaf == leaf[None, :]
        goes_right = in_leaf & (vals > thr[None, :]) & ~jnp.isnan(vals) & active[None, :]
        row_leaf = jnp.where(goes_right, jnp.int32(k + 1), row_leaf)
        return row_leaf, None

    ks = jnp.arange(S, dtype=jnp.int32)
    row_leaf, _ = jax.lax.scan(
        body, row_leaf, (ks, rec_leaf.T, rec_feature.T, rec_threshold.T, rec_active.T)
    )
    return row_leaf


@jax.jit
def predict_scores(
    x: jnp.ndarray,
    rec_leaf: jnp.ndarray,
    rec_feature: jnp.ndarray,
    rec_threshold: jnp.ndarray,
    rec_active: jnp.ndarray,
    leaf_values: jnp.ndarray,  # (T, L) float32
) -> jnp.ndarray:
    """Sum of tree outputs -> (n,) raw score."""
    leaves = predict_leaves(x, rec_leaf, rec_feature, rec_threshold, rec_active)
    per_tree = jnp.take_along_axis(
        jnp.broadcast_to(leaf_values[None], (x.shape[0], *leaf_values.shape)),
        leaves[..., None],
        axis=2,
    )[..., 0]  # (n, T)
    return per_tree.sum(axis=1)
