"""Host (numpy) tree growers — the CPU lowering of
:func:`treegrow.grow_tree_depthwise` (whole-level batches) and of the
masked leaf-wise :func:`treegrow.grow_tree` (best-first splits).

Why a whole-tree host kernel and not just a host histogram: each
``pure_callback`` crossing costs ~1 ms of bridge overhead (operand/result
marshalling) on top of the kernel, and a per-LEVEL histogram callback
leaves the split search, sibling assembly and row routing as XLA:CPU ops
that cost another ~9 ms/tree — measured floor ~21 ms/tree at the bench
shape (20k x 16, 31 leaves) against sklearn's 12 ms. Growing the whole
tree behind ONE callback pays the bridge once, runs the split scan in
vectorized f64 numpy, and keeps the feature-parallel bincount pool
(ops/histpool.py) hot across levels.

Selection: only on unsharded CPU traces (``use_host_hist()``), chosen in
:func:`treegrow.grow_tree_depthwise`. TPU, sharded meshes and
``MMLSPARK_TPU_HIST_HOST=0`` keep the XLA grower. Split semantics mirror
``treegrow.make_leaf_best`` + the vectorized level application exactly
(same tie-breaks: first-max over the (d*B) plane, stable gain ordering
across a level); gains accumulate in f64 where the XLA grower uses f32,
so near-tie splits may differ by float epsilon — the same class of
divergence the Pallas/scatter lowerings already have. tests/test_gbdt_fused.py
pins host-vs-XLA grower equivalence on clean-margin fixtures.

Rows-proportional cost: level histograms cover only the SMALLER child of
every sibling pair (LightGBM's subtraction trick, generalized from the
XLA grower's right-child-only choice), and the kernel drops non-frontier
rows before counting.
"""

from __future__ import annotations

import itertools

import numpy as np

# per-callback token for the pool's write-once arena cache: object ids are
# recyclable across trees (a freed ndarray's id can be reused by the next
# round's same-shape array, which would silently serve STALE gradients), so
# every tree draws a fresh monotonic token instead
_TREE_TOKENS = itertools.count(1)

from mmlspark_tpu.ops.histogram import _host_multi_kernel


def _soft(G: np.ndarray, l1: float) -> np.ndarray:
    return np.sign(G) * np.maximum(np.abs(G) - l1, 0.0)


def _feature_blocks(d: int) -> list:
    """Contiguous feature ranges for the build/allreduce overlap
    pipeline. Elementwise sums are blocking-invariant, so the block
    count changes only WHEN bytes move, never what they sum to.

    Blocks must stay >= 16 features wide: the histogram pool stripes
    work BY FEATURE, so narrower blocks would shrink per-call worker
    parallelism — measured at the 1M x 16 bench shape, 4-feature blocks
    cost more build time than the wire time they overlapped. Narrow
    planes therefore stay whole (one block = plain build + one
    allreduce); the pipeline engages on wide planes, where both the
    payload and the per-block parallelism are large."""
    nb = max(1, min(4, d // 16))
    return [
        (i * d // nb, (i + 1) * d // nb)
        for i in range(nb)
        if (i + 1) * d // nb > i * d // nb
    ]


def _gang_summed_cube(
    blocks_fn,
    bins: np.ndarray,
    stats: np.ndarray,
    slot: np.ndarray,
    ns_hist: int,
    B: int,
) -> np.ndarray:
    """Gang-global (ns_hist, d, B, 3) cube with compute/communication
    overlap: per-feature-block histograms are handed to the reducer as
    soon as they finish, while the NEXT block is still being built
    (GangContext.allreduce_blocks double-buffers). Bit-identical to
    building the whole cube and allreducing it in one piece."""
    d = bins.shape[1]

    def build(lo: int, hi: int):
        def _go() -> np.ndarray:
            blk = np.ascontiguousarray(bins[:, lo:hi])
            return _host_multi_kernel(
                ns_hist, B, True, blk, stats, slot
            ).reshape(ns_hist, hi - lo, B, 3)

        return _go

    bounds = _feature_blocks(d)
    outs = blocks_fn([build(lo, hi) for lo, hi in bounds])
    if len(outs) == 1:
        return outs[0]
    return np.concatenate(outs, axis=1)


def _combine_candidates(
    cube: np.ndarray,        # (S, d, B, 3)
    gains: np.ndarray,       # (d, S) f64
    bbs: np.ndarray,         # (d, S) i64
    cat_f: "np.ndarray | None",
) -> tuple:
    """Cross-feature winner per slot (lowest feature on ties — together
    with feature_candidates' lowest-bin tie-break this reproduces the
    XLA grower's flat first-max exactly) + the winner's categorical
    left-set mask."""
    S = gains.shape[1]
    bf = np.argmax(gains, axis=0)                     # (S,)
    sl = np.arange(S)
    bgain = gains[bf, sl]
    bb = bbs[bf, sl]
    B = cube.shape[2]
    catmask = np.zeros((S, B), bool)
    if cat_f is not None and cat_f[bf].any():
        hsel = cube[sl, bf].astype(np.float64)        # (S, B, 3)
        with np.errstate(divide="ignore", invalid="ignore"):
            ratio = np.where(
                hsel[..., 2] > 0, hsel[..., 0] / (hsel[..., 1] + 1e-12),
                -np.inf,
            )
        order = np.argsort(-ratio, axis=1, kind="stable")
        rank = np.argsort(order, axis=1, kind="stable")
        catmask = rank <= bb[:, None]
    return bgain, bf.astype(np.int64), bb, catmask


def _voting_combine(
    cube_local: np.ndarray,     # (S, d, B, 3) member-LOCAL histograms
    local_gains: np.ndarray,    # (d, S) f64 local best gain per feature
    fm: np.ndarray,
    cat_f: "np.ndarray | None",
    min_data: float,
    msh: float,
    lam: float,
    l1: float,
    gsum,
    top_k: int,
) -> tuple:
    """PV-Tree voting exchange (LightGBM ``voting_parallel``) for the
    gang growers: instead of allreducing the full (S, d, B, 3) plane,

    1. each member votes its local top-``K`` features per slot (ballots
       derived from the ALREADY-computed local gain scan — free);
    2. one tiny (d,) vote allreduce; the top ``2K`` vote-getters (ties
       to the lower feature id, mirroring voting.py's device tie-break)
       become the refresh's candidates — identical on every member;
    3. only the candidates' histogram columns are summed
       ((S, 2K, B, 3) instead of (S, d, B, 3)) and the exact split scan
       runs on those global columns.

    Payload per exchange drops from O(d*B) to O(d + 2K*B) — the win
    voting mode exists for when features are wide. The chosen split is
    exact over the candidate set; a feature outvoted everywhere cannot
    win, which is the mode's documented quality tolerance versus full
    data-parallel (docs/gbdt-training.md)."""
    from mmlspark_tpu.ops.histpool import feature_candidates
    from mmlspark_tpu.parallel.elastic import note_vote_round

    d, S = local_gains.shape
    K = max(1, min(int(top_k), d))
    C = min(2 * K, d)
    masked = np.where(np.isfinite(local_gains), local_gains, -np.inf)
    ballots = np.zeros(d, np.float64)
    if K < d:
        idx = np.argpartition(-masked, K - 1, axis=0)[:K]       # (K, S)
        chosen = np.take_along_axis(masked, idx, axis=0)
        np.add.at(ballots, idx[np.isfinite(chosen)], 1.0)
    else:
        ballots += np.isfinite(masked).sum(axis=1)
    votes = np.asarray(gsum(ballots), np.float64)
    if C < d:
        # ties to the LOWER feature id — the same deterministic rank
        # voting.py uses on device (scores are distinct by construction)
        score = votes * np.float64(d + 1) - np.arange(d, dtype=np.float64)
        cand = np.sort(np.argpartition(-score, C - 1)[:C])
    else:
        cand = np.arange(d)
    cand_cube = np.asarray(
        gsum(np.ascontiguousarray(cube_local[:, cand]))
    )
    cat_c = cat_f[cand] if cat_f is not None else None
    gains_c, bbs_c = feature_candidates(
        cand_cube, np.asarray(fm)[cand], float(min_data), msh, lam, l1,
        cat_c,
    )
    bg, bfc, bb, cm = _combine_candidates(cand_cube, gains_c, bbs_c, cat_c)
    note_vote_round()
    return bg, cand[bfc], bb, cm


def grow_tree_depthwise_host(
    num_leaves: int,
    n_levels: int,
    num_bins: int,
    min_data_in_leaf: int,
    sibling_subtract: bool,
    has_categorical: bool,
    min_gain,
    lambda_l2,
    lambda_l1,
    min_sum_hessian,
    learning_rate,
    bins,
    grad,
    hess,
    row_weight,
    feature_mask,
    categorical_mask,
) -> tuple:
    """One depthwise tree, entirely on host. Returns the GrownTree field
    tuple (same order/dtypes as treegrow.GrownTree). The regularization
    and learning-rate knobs arrive as 0-d arrays (they are traced values
    inside the scan-fused round loop). If the worker pool dies mid-tree
    the whole tree re-runs serially (pooled and serial paths are
    bit-identical, so the retry is invisible)."""
    from mmlspark_tpu.parallel.elastic import gang_sum

    # elastic gang training: level histograms are allreduced across gang
    # members (parallel/elastic.py), which needs the serial kernel — the
    # fork pool's split scan would run on member-LOCAL cubes
    if gang_sum() is not None:
        return _grow_host(
            num_leaves, n_levels, num_bins, min_data_in_leaf,
            sibling_subtract, has_categorical, min_gain, lambda_l2,
            lambda_l1, min_sum_hessian, learning_rate, bins, grad, hess,
            row_weight, feature_mask, categorical_mask, use_pool=False,
        )
    try:
        return _grow_host(
            num_leaves, n_levels, num_bins, min_data_in_leaf,
            sibling_subtract, has_categorical, min_gain, lambda_l2,
            lambda_l1, min_sum_hessian, learning_rate, bins, grad, hess,
            row_weight, feature_mask, categorical_mask, use_pool=True,
        )
    except _PoolLost:
        return _grow_host(
            num_leaves, n_levels, num_bins, min_data_in_leaf,
            sibling_subtract, has_categorical, min_gain, lambda_l2,
            lambda_l1, min_sum_hessian, learning_rate, bins, grad, hess,
            row_weight, feature_mask, categorical_mask, use_pool=False,
        )


class _PoolLost(Exception):
    """The pool degraded after this tree already used it for a level —
    the previous level's cube lives in a dead arena, so restart serial."""


def _grow_host(
    num_leaves: int,
    n_levels: int,
    num_bins: int,
    min_data_in_leaf: int,
    sibling_subtract: bool,
    has_categorical: bool,
    min_gain,
    lambda_l2,
    lambda_l1,
    min_sum_hessian,
    learning_rate,
    bins,
    grad,
    hess,
    row_weight,
    feature_mask,
    categorical_mask,
    use_pool: bool,
) -> tuple:
    from mmlspark_tpu.ops.histpool import feature_candidates, get_pool
    from mmlspark_tpu.parallel.elastic import gang_blocks, gang_sum

    # elastic gang: sum histograms (and child-size decisions) across the
    # gang, LightGBM data-parallel style — every member then makes the
    # identical split decision from the identical global cube.
    # gblocks: the compute/communication overlap pipeline (feature
    # blocks allreduce while later blocks build). Voting-parallel never
    # reaches this grower: PV-Tree is leaf-wise, and train() rejects
    # depthwise + voting before any grower runs.
    gsum = gang_sum()
    gblocks = gang_blocks()

    min_gain = float(np.asarray(min_gain))
    lambda_l2 = float(np.asarray(lambda_l2))
    lambda_l1 = float(np.asarray(lambda_l1))
    min_sum_hessian = float(np.asarray(min_sum_hessian))
    learning_rate = float(np.asarray(learning_rate))
    # keep the caller's dtype: mapper-binned uint8 crosses the callback
    # bridge and the pool arena at a quarter of the int32 byte volume
    b = np.ascontiguousarray(np.asarray(bins))
    n, d = b.shape
    L, B = num_leaves, num_bins
    g64 = np.asarray(grad, np.float64)
    h64 = np.asarray(hess, np.float64)
    w = np.asarray(row_weight, np.float64)
    fm = np.asarray(feature_mask)
    cat_f = np.asarray(categorical_mask, bool) if has_categorical else None
    g = g64 * w
    h = h64 * w
    stats = np.stack([g, h, w], axis=1).astype(np.float32)
    s3 = np.ascontiguousarray(stats.T)
    scan = (fm, cat_f, float(min_data_in_leaf), min_sum_hessian,
            lambda_l2, lambda_l1)
    pool = get_pool() if use_pool else None
    tree_tok = next(_TREE_TOKENS)

    row_slot = np.zeros(n, np.int64)
    k = 0
    rec_leaf = np.full(L - 1, -1, np.int32)
    rec_feature = np.full(L - 1, -1, np.int32)
    rec_bin = np.full(L - 1, -1, np.int32)
    rec_active = np.zeros(L - 1, bool)
    rec_gain = np.zeros(L - 1, np.float32)
    rec_is_cat = np.zeros(L - 1, bool)
    rec_catmask = np.zeros((L - 1, B), bool)

    lut = np.full(L, L, np.int64)
    lut[0] = 0
    inv = np.zeros(1, np.int64)              # plane index -> record slot
    cube_prev: "np.ndarray | None" = None    # serial path only
    parent_local: "np.ndarray | None" = None
    pooled_any = False
    S_prev = 1
    cur = 0

    for level in range(n_levels):
        S = len(inv)
        # slots outside the frontier carry lut == L; clamp to S, the
        # all-dropped pad index (the XLA grower's clamped-gather idiom)
        local = np.minimum(lut[row_slot], S)
        sib = sibling_subtract and level > 0
        if sib:
            # histogram only the SMALLER child of each sibling pair and
            # derive the other as parent - small
            P = S // 2
            counts = np.bincount(local, minlength=S + 1)
            if gsum is not None:
                # the smaller-child choice must be the GLOBAL one or the
                # members' summed histograms would cover different children
                counts = gsum(counts.astype(np.float64))
            right_small = counts[1:2 * P:2] <= counts[0:2 * P:2]
            pairi = local >> 1
            is_small = (local < 2 * P) & (
                (local & 1).astype(bool)
                == right_small[np.minimum(pairi, P - 1)]
            )
            slot_hist = np.where(is_small, pairi, P)
            ns_hist = P
            pair_meta = (right_small, parent_local, S_prev)
        else:
            slot_hist = local
            ns_hist = S
            pair_meta = None
        # slot_hist is already clamped into [0, ns_hist] (ns_hist = the
        # trash plane), so the offsets need no range check
        base = (slot_hist * B).astype(np.int64)
        res = None
        if pool is not None:
            res = pool.grow_level(
                b, base, s3, S, B, scan, pair_meta, cur,
                bins_token=("tree", tree_tok), stats_token=("tree", tree_tok),
            )
            if res is None and pooled_any:
                raise _PoolLost()
        if res is not None:
            cube, gains, bbs = res
            pooled_any = True
        else:
            pool = None
            if gsum is not None and gblocks is not None:
                # data-parallel gang: per-feature-block histograms hand
                # off to the reducer while later blocks still build —
                # wire time hides behind compute (bit-identical to one
                # whole-plane allreduce)
                half = _gang_summed_cube(
                    gblocks, b, stats, slot_hist, ns_hist, B
                )
            else:
                half = _host_multi_kernel(
                    ns_hist, B, True, b, stats, slot_hist
                ).reshape(ns_hist, d, B, 3)
                if gsum is not None:
                    half = gsum(half)
            if sib:
                parents_ok = parent_local >= 0
                parents = cube_prev[np.maximum(parent_local, 0)]
                other = parents - half
                if not parents_ok.all():
                    bad = ~parents_ok
                    other[bad] = 0.0
                    half = half.copy()
                    half[bad] = 0.0
                rs = right_small[:, None, None, None]
                cube = np.empty((S, d, B, 3), np.float32)
                cube[0:2 * P:2] = np.where(rs, other, half)
                cube[1:2 * P:2] = np.where(rs, half, other)
                if 2 * P < S:
                    cube[2 * P:] = 0.0
            else:
                cube = half
            cube_prev = cube
            gains, bbs = feature_candidates(
                cube, fm, float(min_data_in_leaf), min_sum_hessian,
                lambda_l2, lambda_l1, cat_f,
            )
        S_prev = S
        cur = 1 - cur
        bgains, feats, bbest, catms = _combine_candidates(
            cube, gains, bbs, cat_f
        )
        # budget: best-gain slots win the remaining record slots, in the
        # same stable descending order the XLA grower uses
        order = np.argsort(-bgains, kind="stable")
        S_next = min(2 * S, L)
        slot_s = inv[order]
        gain_s = bgains[order]
        ok = (slot_s >= 0) & np.isfinite(gain_s) & (gain_s > min_gain)
        rank = np.cumsum(ok) - ok
        ok &= (k + rank) < (L - 1)
        ks = k + rank
        new_id = ks + 1
        bf_s, bb_s, cm_s = feats[order], bbest[order], catms[order]
        is_cat_s = cat_f[bf_s] if cat_f is not None else np.zeros(S, bool)
        sel = np.flatnonzero(ok)
        rec_leaf[ks[sel]] = slot_s[sel]
        rec_feature[ks[sel]] = bf_s[sel]
        rec_bin[ks[sel]] = bb_s[sel]
        rec_active[ks[sel]] = True
        rec_gain[ks[sel]] = gain_s[sel]
        rec_is_cat[ks[sel]] = is_cat_s[sel]
        rec_catmask[ks[sel]] = np.where(
            is_cat_s[sel, None], cm_s[sel], False
        )
        # next frontier: pair p (= rank) at locals (2p, 2p+1). Indices
        # past the clipped frontier drop (the XLA grower's mode='drop'):
        # a split whose odd child index would land outside S_next keeps
        # its record but leaves the frontier.
        lut = np.full(L, L, np.int64)
        inv = np.full(S_next, -1, np.int64)
        parent_local = np.full(S_next // 2, -1, np.int64)
        even = sel[2 * rank[sel] < S_next]
        odd = sel[2 * rank[sel] + 1 < S_next]
        pok = sel[rank[sel] < (S_next // 2)]
        lut[slot_s[even]] = 2 * rank[even]
        lut[new_id[odd]] = 2 * rank[odd] + 1
        inv[2 * rank[even]] = slot_s[even]
        inv[2 * rank[odd] + 1] = new_id[odd]
        parent_local[rank[pok]] = order[pok]
        # row routing: per ORIGINAL local j, this level's chosen split
        split_ok = np.zeros(S + 1, bool)
        split_bf = np.zeros(S + 1, np.int64)
        split_bb = np.zeros(S + 1, np.int64)
        split_new = np.zeros(S + 1, np.int64)
        split_ok[order[sel]] = True
        split_bf[order[sel]] = bf_s[sel]
        split_bb[order[sel]] = bb_s[sel]
        split_new[order[sel]] = new_id[sel]
        okr = split_ok[local]
        bf_r = split_bf[local]
        row_bins = b[np.arange(n), bf_r]
        if cat_f is not None:
            split_iscat = np.zeros(S + 1, bool)
            split_cm = np.zeros((S + 1, B), bool)
            split_iscat[order[sel]] = is_cat_s[sel]
            split_cm[order[sel]] = cm_s[sel]
            goes_right = okr & np.where(
                split_iscat[local],
                ~split_cm[local, row_bins],
                row_bins > split_bb[local],
            )
        else:
            goes_right = okr & (row_bins > split_bb[local])
        row_slot = np.where(goes_right, split_new[local], row_slot)
        k += int(ok.sum())

    Gl = np.bincount(row_slot, weights=g, minlength=L)[:L]
    Hl = np.bincount(row_slot, weights=h, minlength=L)[:L]
    Cl = np.bincount(row_slot, weights=w, minlength=L)[:L]
    if gsum is not None:
        Gl, Hl, Cl = gsum(np.stack([Gl, Hl, Cl]))
    with np.errstate(divide="ignore", invalid="ignore"):
        leaf_values = np.where(
            Cl > 0,
            -_soft(Gl, lambda_l1) / (Hl + lambda_l2) * learning_rate,
            0.0,
        )
    return (
        rec_leaf,
        rec_feature,
        rec_bin,
        rec_active,
        rec_gain.astype(np.float32),
        leaf_values.astype(np.float32),
        Cl.astype(np.int32),
        row_slot.astype(np.int32),
        rec_is_cat,
        rec_catmask,
    )

# -- leaf-wise (lossguide) ---------------------------------------------------


def grow_tree_lossguide_host(
    num_leaves: int,
    max_depth: int,
    num_bins: int,
    min_data_in_leaf: int,
    has_categorical: bool,
    min_gain,
    lambda_l2,
    lambda_l1,
    min_sum_hessian,
    learning_rate,
    bins,
    grad,
    hess,
    row_weight,
    feature_mask,
    categorical_mask,
) -> tuple:
    """One leaf-wise (best-first) tree on host — the masked
    :func:`treegrow._grow_tree` semantics with the DataPartition cost
    model for free: each split histograms only the SMALLER child
    (compacted rows), derives the sibling as parent - small, and
    re-scans only the two planes the split changed (the same split-search
    cache the XLA grower carries). Early exhaustion breaks the loop — the
    XLA grower's remaining steps are provable no-ops."""
    from mmlspark_tpu.ops.histogram import _host_multi_kernel as _mk
    from mmlspark_tpu.parallel.elastic import (
        gang_blocks,
        gang_sum,
        gang_voting_k,
    )

    # elastic gang: histograms summed across members (see _grow_host);
    # voting mode keeps planes LOCAL and exchanges only ballots +
    # candidate columns per refresh
    gsum = gang_sum()
    gblocks = gang_blocks()
    gv_k = gang_voting_k()

    min_gain = float(np.asarray(min_gain))
    lambda_l2 = float(np.asarray(lambda_l2))
    lambda_l1 = float(np.asarray(lambda_l1))
    min_sum_hessian = float(np.asarray(min_sum_hessian))
    learning_rate = float(np.asarray(learning_rate))
    b = np.ascontiguousarray(np.asarray(bins))
    n, d = b.shape
    L, B = num_leaves, num_bins
    g = np.asarray(grad, np.float64) * np.asarray(row_weight, np.float64)
    h = np.asarray(hess, np.float64) * np.asarray(row_weight, np.float64)
    w = np.asarray(row_weight, np.float64)
    fm = np.asarray(feature_mask)
    cat_f = np.asarray(categorical_mask, bool) if has_categorical else None
    stats = np.stack([g, h, w], axis=1).astype(np.float32)

    from mmlspark_tpu.ops.histpool import feature_candidates

    row_leaf = np.zeros(n, np.int64)
    leaf_depth = np.zeros(L, np.int64)
    rec_leaf = np.full(L - 1, -1, np.int32)
    rec_feature = np.full(L - 1, -1, np.int32)
    rec_bin = np.full(L - 1, -1, np.int32)
    rec_active = np.zeros(L - 1, bool)
    rec_gain = np.zeros(L - 1, np.float32)
    rec_is_cat = np.zeros(L - 1, bool)
    rec_catmask = np.zeros((L - 1, B), bool)
    hist = np.zeros((L, d, B, 3), np.float32)
    cache_gain = np.full(L, -np.inf)
    cache_feat = np.zeros(L, np.int64)
    cache_bin = np.zeros(L, np.int64)
    cache_cm = np.zeros((L, B), bool)

    def _gang_cube(slot: np.ndarray, ns: int) -> np.ndarray:
        """One (ns, d, B, 3) histogram, gang-summed with the feature-
        block overlap pipeline when available."""
        if gsum is not None and gv_k is None and gblocks is not None:
            return _gang_summed_cube(gblocks, b, stats, slot, ns, B)
        cube = _mk(ns, B, True, b, stats, slot).reshape(ns, d, B, 3)
        if gsum is not None and gv_k is None:
            cube = gsum(cube)
        return cube

    # root: the only full-data histogram of the tree (pool-eligible).
    # Voting mode keeps it LOCAL — the exchange happens per refresh.
    root = _gang_cube(np.zeros(n, np.int64), 1)[0]
    hist[0] = root
    prev_pair = np.array([0, 0])

    def _refresh(pair: np.ndarray) -> None:
        cube = hist[pair]                       # (2, d, B, 3)
        gains, bbs = feature_candidates(
            cube, fm, float(min_data_in_leaf), min_sum_hessian,
            lambda_l2, lambda_l1, cat_f,
        )
        if gv_k is not None and gsum is not None:
            # PV-Tree: ballots from the local scan, then an exact scan
            # over only the top-2K candidates' GLOBAL columns
            bg, bf, bb, cm = _voting_combine(
                cube, gains, fm, cat_f, float(min_data_in_leaf),
                min_sum_hessian, lambda_l2, lambda_l1, gsum, gv_k,
            )
        else:
            bg, bf, bb, cm = _combine_candidates(cube, gains, bbs, cat_f)
        cache_gain[pair] = bg
        cache_feat[pair] = bf
        cache_bin[pair] = bb
        cache_cm[pair] = cm

    for k in range(L - 1):
        _refresh(prev_pair)
        leaf_ok = np.arange(L) < (k + 1)
        if max_depth > 0:
            leaf_ok &= leaf_depth < max_depth
        sel = np.where(leaf_ok, cache_gain, -np.inf)
        bl = int(np.argmax(sel))
        best_gain = sel[bl]
        if not (np.isfinite(best_gain) and best_gain > min_gain):
            break                               # XLA path: no-op steps
        bf = int(cache_feat[bl])
        bb = int(cache_bin[bl])
        new_id = k + 1
        in_leaf = row_leaf == bl
        row_bins = b[:, bf]
        is_cat_split = bool(cat_f is not None and cat_f[bf])
        if is_cat_split:
            goes_right = in_leaf & ~cache_cm[bl][row_bins]
        else:
            goes_right = in_leaf & (row_bins > bb)
        moved = goes_right
        n_right = int(moved.sum())
        n_left = int(in_leaf.sum()) - n_right
        if gsum is not None:
            # globalize the child sizes: members must histogram the SAME
            # child of the pair or the summed planes would be incoherent
            n_left, n_right = gsum(
                np.array([n_left, n_right], np.float64)
            )
        row_leaf = np.where(moved, new_id, row_leaf)
        # histogram the smaller child over its COMPACTED rows, derive the
        # sibling as parent - small
        small_mask = moved if n_right <= n_left else (in_leaf & ~moved)
        slot = np.where(small_mask, 0, 1).astype(np.int64)  # 1 = dropped
        small = _gang_cube(slot, 1)[0]
        parent = hist[bl]
        if n_right <= n_left:
            hist[new_id] = small
            hist[bl] = parent - small
        else:
            hist[new_id] = parent - small
            hist[bl] = small
        child_depth = leaf_depth[bl] + 1
        leaf_depth[bl] = child_depth
        leaf_depth[new_id] = child_depth
        rec_leaf[k] = bl
        rec_feature[k] = bf
        rec_bin[k] = bb
        rec_active[k] = True
        rec_gain[k] = best_gain
        rec_is_cat[k] = is_cat_split
        if is_cat_split:
            rec_catmask[k] = cache_cm[bl]
        prev_pair = np.array([bl, new_id])

    Gl = np.bincount(row_leaf, weights=g, minlength=L)[:L]
    Hl = np.bincount(row_leaf, weights=h, minlength=L)[:L]
    Cl = np.bincount(row_leaf, weights=w, minlength=L)[:L]
    if gsum is not None:
        Gl, Hl, Cl = gsum(np.stack([Gl, Hl, Cl]))
    with np.errstate(divide="ignore", invalid="ignore"):
        leaf_values = np.where(
            Cl > 0,
            -_soft(Gl, lambda_l1) / (Hl + lambda_l2) * learning_rate,
            0.0,
        )
    return (
        rec_leaf,
        rec_feature,
        rec_bin,
        rec_active,
        rec_gain.astype(np.float32),
        leaf_values.astype(np.float32),
        Cl.astype(np.int32),
        row_leaf.astype(np.int32),
        rec_is_cat,
        rec_catmask,
    )

