"""GBDT objectives: gradients/hessians + prediction transforms.

Mirrors the objective surface of the reference's LightGBM params
(TrainParams.scala objective: binary/multiclass/regression/lambdarank).
All dense objectives are jitted; LambdaRank runs vectorized per group.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def binary_grad_hess(scores: jnp.ndarray, y: jnp.ndarray) -> tuple:
    p = jax.nn.sigmoid(scores)
    return p - y, p * (1.0 - p)


@jax.jit
def l2_grad_hess(scores: jnp.ndarray, y: jnp.ndarray) -> tuple:
    return scores - y, jnp.ones_like(scores)


@jax.jit
def multiclass_grad_hess(scores: jnp.ndarray, y_onehot: jnp.ndarray) -> tuple:
    """scores (n, k) -> grads/hess (n, k)."""
    p = jax.nn.softmax(scores, axis=-1)
    k = scores.shape[-1]
    factor = k / max(k - 1.0, 1.0)  # LightGBM's multiclass hessian factor
    return p - y_onehot, factor * p * (1.0 - p)


def lambdarank_grad_hess(
    scores: np.ndarray,
    relevance: np.ndarray,
    group_ids: np.ndarray,
    sigma: float = 1.0,
    truncation: int = 30,
) -> tuple:
    """LambdaRank (NDCG) gradients, host-vectorized per group.

    For each query group, pairs (i, j) with rel_i > rel_j contribute
    lambda_ij scaled by |delta NDCG|."""
    n = len(scores)
    grad = np.zeros(n, np.float64)
    hess = np.zeros(n, np.float64)
    for gid in np.unique(group_ids):
        idx = np.flatnonzero(group_ids == gid)
        if len(idx) < 2:
            continue
        s = scores[idx]
        r = relevance[idx]
        order = np.argsort(-s, kind="stable")
        ranks = np.empty(len(idx), np.int64)
        ranks[order] = np.arange(len(idx))
        gains = (2.0 ** r - 1.0)
        discounts = 1.0 / np.log2(ranks + 2.0)
        ideal = np.sort(gains)[::-1]
        idcg = (ideal / np.log2(np.arange(len(idx)) + 2.0))[:truncation].sum()
        if idcg <= 0:
            continue
        diff_r = r[:, None] - r[None, :]
        better = diff_r > 0
        sd = s[:, None] - s[None, :]
        rho = 1.0 / (1.0 + np.exp(sigma * sd))  # sigmoid(-sigma * sd)
        delta_ndcg = np.abs(
            (gains[:, None] - gains[None, :])
            * (discounts[:, None] - discounts[None, :])
        ) / idcg
        lam = sigma * rho * delta_ndcg
        lam_h = sigma * sigma * rho * (1.0 - rho) * delta_ndcg
        # pair (i better than j): grad_i -= lam_ij ; grad_j += lam_ij
        g = np.where(better, -lam, 0.0).sum(axis=1) + np.where(better.T, lam.T, 0.0).sum(axis=1)
        h = np.where(better, lam_h, 0.0).sum(axis=1) + np.where(better.T, lam_h.T, 0.0).sum(axis=1)
        grad[idx] = g
        hess[idx] = np.maximum(h, 1e-9)
    return grad.astype(np.float32), hess.astype(np.float32)


def sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def softmax(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)
