"""GBDT objectives: gradients/hessians + prediction transforms.

Mirrors the objective surface of the reference's LightGBM params
(TrainParams.scala objective: binary/multiclass/regression/lambdarank).
All dense objectives are jitted; LambdaRank runs vectorized per group.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def binary_grad_hess(scores: jnp.ndarray, y: jnp.ndarray) -> tuple:
    p = jax.nn.sigmoid(scores)
    return p - y, p * (1.0 - p)


@jax.jit
def l2_grad_hess(scores: jnp.ndarray, y: jnp.ndarray) -> tuple:
    return scores - y, jnp.ones_like(scores)


# canonical regression objective kinds (LightGBM TrainParams.scala:8-40
# objective passthrough; notebook "LightGBM - Quantile Regression for Drug
# Discovery" exercises quantile). ``p1`` is the objective's knob:
# quantile/huber -> alpha, tweedie -> tweedie_variance_power,
# poisson -> poisson_max_delta_step, fair -> fair_c.
REGRESSION_KINDS = (
    "regression", "regression_l1", "quantile", "huber", "fair",
    "poisson", "tweedie", "gamma", "mape",
)

# objectives whose raw score lives in log space: prediction applies exp
# (LightGBM's convert_output for poisson/gamma/tweedie)
LOG_LINK_KINDS = ("poisson", "tweedie", "gamma")

_OBJECTIVE_ALIASES = {
    "regression_l2": "regression", "l2": "regression", "mse": "regression",
    "mean_squared_error": "regression", "root_mean_squared_error": "regression",
    "rmse": "regression", "l2_root": "regression",
    "l1": "regression_l1", "mae": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mean_absolute_percentage_error": "mape",
}


def canonical_objective(name: str) -> str:
    """LightGBM objective aliases -> the canonical kind string."""
    return _OBJECTIVE_ALIASES.get(name, name)


def regression_grad_hess(
    kind: str, scores: jnp.ndarray, y: jnp.ndarray, p1: jnp.ndarray
) -> tuple:
    """Gradient/hessian pairs for the regression objective zoo, formula-
    matched to LightGBM's regression_objective.hpp (traced; ``kind`` is
    static at the jit boundary)."""
    r = scores - y
    one = jnp.ones_like(scores)
    if kind == "regression_l1":
        # LightGBM keeps hess=1 for l1 (leaf renewal is its refinement;
        # the Newton step with unit hessian is the same gradient boost)
        return jnp.sign(r), one
    if kind == "quantile":
        # pinball: score >= label contributes (1-alpha), else -alpha
        return jnp.where(r >= 0, 1.0 - p1, -p1), one
    if kind == "huber":
        return jnp.clip(r, -p1, p1), one
    if kind == "fair":
        a = jnp.abs(r) + p1
        return p1 * r / a, p1 * p1 / (a * a)
    if kind == "poisson":
        # scores in log space; p1 = poisson_max_delta_step stabilizes the
        # hessian exactly as LightGBM's exp(score + max_delta_step)
        return jnp.exp(scores) - y, jnp.exp(scores + p1)
    if kind == "tweedie":
        e1 = jnp.exp((1.0 - p1) * scores)
        e2 = jnp.exp((2.0 - p1) * scores)
        return -y * e1 + e2, -y * (1.0 - p1) * e1 + (2.0 - p1) * e2
    if kind == "gamma":
        ei = jnp.exp(-scores)
        return 1.0 - y * ei, y * ei
    if kind == "mape":
        w = 1.0 / jnp.maximum(1.0, jnp.abs(y))
        return jnp.sign(r) * w, w
    return r, one  # regression (l2)


def regression_loss(kind: str, s: Any, y: Any, p1: float, xp: Any = np) -> Any:
    """Pointwise loss of each regression objective — the eval metric the
    trainer reports/early-stops on (``xp``: numpy on host, jnp on device so
    the scan-fused path computes the identical number)."""
    r = s - y
    if kind == "regression_l1":
        return xp.abs(r)
    if kind == "quantile":
        return xp.maximum(p1 * (y - s), (p1 - 1.0) * (y - s))
    if kind == "huber":
        a = xp.abs(r)
        return xp.where(a <= p1, 0.5 * r * r, p1 * (a - 0.5 * p1))
    if kind == "fair":
        a = xp.abs(r)
        return p1 * p1 * (a / p1 - xp.log1p(a / p1))
    if kind == "poisson":
        return xp.exp(s) - y * s
    if kind == "tweedie":
        return -y * xp.exp((1.0 - p1) * s) / (1.0 - p1) + xp.exp(
            (2.0 - p1) * s
        ) / (2.0 - p1)
    if kind == "gamma":
        return y * xp.exp(-s) + s
    if kind == "mape":
        return xp.abs(r) / xp.maximum(1.0, xp.abs(y))
    return r * r  # l2


# objectives whose leaf values LightGBM "renews" after growth: the Newton
# step with unit hessian under-shoots the percentile these losses target,
# so leaf outputs are recomputed as the weighted alpha-percentile of the
# leaf's residuals (RegressionL1loss/QuantileLoss RenewTreeOutput)
RENEWED_KINDS = ("regression_l1", "quantile", "mape")


def leaf_quantile_renewal(
    row_leaf: jnp.ndarray,   # (n,) int32 leaf of every row
    resid: jnp.ndarray,      # (n,) f32 y - score (pre-update residuals)
    w: jnp.ndarray,          # (n,) f32 row weights (0 = excluded)
    num_leaves: int,
    alpha: Any,
) -> jnp.ndarray:
    """Weighted alpha-percentile of residuals per leaf, on device.

    Two-key stable sort (residual, then leaf) puts each leaf's rows in
    residual order; the per-leaf crossing of cumulative weight past
    alpha * total_weight is the weighted percentile — one scatter picks
    all leaves' values at once. Returns (L,) f32 (0 for empty leaves)."""
    L = num_leaves
    ord1 = jnp.argsort(resid)
    leaf1 = row_leaf[ord1]
    ord2 = jnp.argsort(leaf1, stable=True)
    order = ord1[ord2]
    leaf_s = row_leaf[order]
    r_s = resid[order]
    w_s = w[order]
    Wl = jnp.zeros((L,), jnp.float32).at[row_leaf].add(w)
    leaf_base = jnp.cumsum(Wl) - Wl                     # weight mass before leaf
    within = jnp.cumsum(w_s) - leaf_base[leaf_s]        # cum weight inside leaf
    target = jnp.maximum(alpha, 1e-12) * Wl[leaf_s]
    crossing = (w_s > 0) & (within >= target) & (within - w_s < target)
    vals = jnp.zeros((L,), jnp.float32).at[leaf_s].add(
        jnp.where(crossing, r_s, 0.0)
    )
    return jnp.where(Wl > 0, vals, 0.0)


def regression_metric_name(kind: str) -> str:
    return {
        "regression": "l2", "regression_l1": "l1", "quantile": "quantile",
        "huber": "huber", "fair": "fair", "poisson": "poisson",
        "tweedie": "tweedie", "gamma": "gamma", "mape": "mape",
    }.get(kind, "l2")


@jax.jit
def multiclass_grad_hess(scores: jnp.ndarray, y_onehot: jnp.ndarray) -> tuple:
    """scores (n, k) -> grads/hess (n, k)."""
    p = jax.nn.softmax(scores, axis=-1)
    k = scores.shape[-1]
    factor = k / max(k - 1.0, 1.0)  # LightGBM's multiclass hessian factor
    return p - y_onehot, factor * p * (1.0 - p)


def lambdarank_grad_hess(
    scores: np.ndarray,
    relevance: np.ndarray,
    group_ids: np.ndarray,
    sigma: float = 1.0,
    truncation: int = 30,
) -> tuple:
    """LambdaRank (NDCG) gradients, host-vectorized per group.

    For each query group, pairs (i, j) with rel_i > rel_j contribute
    lambda_ij scaled by |delta NDCG|."""
    n = len(scores)
    grad = np.zeros(n, np.float64)
    hess = np.zeros(n, np.float64)
    for gid in np.unique(group_ids):
        idx = np.flatnonzero(group_ids == gid)
        if len(idx) < 2:
            continue
        s = scores[idx]
        r = relevance[idx]
        order = np.argsort(-s, kind="stable")
        ranks = np.empty(len(idx), np.int64)
        ranks[order] = np.arange(len(idx))
        gains = (2.0 ** r - 1.0)
        discounts = 1.0 / np.log2(ranks + 2.0)
        ideal = np.sort(gains)[::-1]
        idcg = (ideal / np.log2(np.arange(len(idx)) + 2.0))[:truncation].sum()
        if idcg <= 0:
            continue
        diff_r = r[:, None] - r[None, :]
        better = diff_r > 0
        sd = s[:, None] - s[None, :]
        rho = 1.0 / (1.0 + np.exp(sigma * sd))  # sigmoid(-sigma * sd)
        delta_ndcg = np.abs(
            (gains[:, None] - gains[None, :])
            * (discounts[:, None] - discounts[None, :])
        ) / idcg
        lam = sigma * rho * delta_ndcg
        lam_h = sigma * sigma * rho * (1.0 - rho) * delta_ndcg
        # pair (i better than j): grad_i -= lam_ij ; grad_j += lam_ij
        g = np.where(better, -lam, 0.0).sum(axis=1) + np.where(better.T, lam.T, 0.0).sum(axis=1)
        h = np.where(better, lam_h, 0.0).sum(axis=1) + np.where(better.T, lam_h.T, 0.0).sum(axis=1)
        grad[idx] = g
        hess[idx] = np.maximum(h, 1e-9)
    return grad.astype(np.float32), hess.astype(np.float32)


def lambdarank_pad_groups(
    group_ids: np.ndarray, keep: Optional[np.ndarray] = None
) -> tuple:
    """Contiguous query groups -> padded (G, M) row-index layout.

    The device lambdarank kernel needs STATIC shapes, so groups are packed
    into a (num_groups, max_group_len) index grid once on host (the
    reference keeps the same contiguity contract: LightGBMRanker requires a
    query's rows on one partition and passes group COUNTS to the native
    trainer, LightGBMRankerParams groupCol). ``keep``: optional row filter
    (e.g. validation rows) applied before grouping — matching
    :func:`grouped_ndcg`'s mask-then-group semantics.

    Returns (pad_idx (G, M) int32 with -1 padding, valid (G, M) bool)."""
    gid = np.asarray(group_ids)
    pos = np.arange(len(gid), dtype=np.int64)
    if keep is not None:
        pos = pos[keep]
        gid = gid[keep]
    if len(gid) == 0:
        return np.full((1, 1), -1, np.int32), np.zeros((1, 1), bool)
    starts = np.flatnonzero(np.r_[True, gid[1:] != gid[:-1]])
    ends = np.r_[starts[1:], len(gid)]
    sizes = ends - starts
    G, M = len(starts), int(sizes.max())
    pad_idx = np.full((G, M), -1, np.int64)
    for i, (s0, e0) in enumerate(zip(starts, ends)):
        pad_idx[i, : e0 - s0] = pos[s0:e0]
    return pad_idx.astype(np.int32), pad_idx >= 0


def lambdarank_grad_hess_device(
    scores: jnp.ndarray,
    rel: jnp.ndarray,
    pad_idx: jnp.ndarray,
    valid: jnp.ndarray,
    sigma: float = 1.0,
    truncation: int = 30,
) -> tuple:
    """LambdaRank gradients ON DEVICE over padded groups — the traced twin
    of :func:`lambdarank_grad_hess` (formula-identical; goldens compare
    them), so ranking joins the scan-fused training path with no
    per-iteration host round-trip (TrainUtils.scala:220-315 likewise keeps
    ranking gradients inside the native booster)."""
    n = scores.shape[0]
    G, M = pad_idx.shape
    idx = jnp.clip(pad_idx, 0, n - 1)
    s = jnp.where(valid, scores[idx], -jnp.inf)
    r = jnp.where(valid, rel[idx], 0.0)
    # rank of each slot within its group by descending score (stable);
    # invalid slots (-inf) sink to the tail
    order = jnp.argsort(-s, axis=1, stable=True)
    ranks = jnp.argsort(order, axis=1)
    gains = jnp.where(valid, 2.0 ** r - 1.0, 0.0)
    disc = 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0)
    ideal = -jnp.sort(-gains, axis=1)
    pos_disc = 1.0 / jnp.log2(jnp.arange(M, dtype=jnp.float32) + 2.0)
    idcg = (ideal * pos_disc * (jnp.arange(M) < truncation)).sum(axis=1)
    better = (
        (r[:, :, None] - r[:, None, :] > 0)
        & valid[:, :, None] & valid[:, None, :]
    )
    pair = better | jnp.transpose(better, (0, 2, 1))
    sd = jnp.where(pair, s[:, :, None] - s[:, None, :], 0.0)
    rho = jax.nn.sigmoid(-sigma * sd)
    dndcg = jnp.abs(
        (gains[:, :, None] - gains[:, None, :])
        * (disc[:, :, None] - disc[:, None, :])
    ) / jnp.maximum(idcg, 1e-12)[:, None, None]
    lam = sigma * rho * dndcg
    lam_h = sigma * sigma * rho * (1.0 - rho) * dndcg
    g = -(lam * better).sum(axis=2) + (lam * better).sum(axis=1)
    h = (lam_h * better).sum(axis=2) + (lam_h * better).sum(axis=1)
    processed = (idcg > 0) & (valid.sum(axis=1) >= 2)
    g = jnp.where(processed[:, None] & valid, g, 0.0)
    h = jnp.where(processed[:, None] & valid, jnp.maximum(h, 1e-9), 0.0)
    sink = jnp.where(valid, pad_idx, n)  # padding scatters into a dead slot
    grad = jnp.zeros(n + 1, jnp.float32).at[sink.reshape(-1)].add(
        g.reshape(-1), mode="drop"
    )[:n]
    hess = jnp.zeros(n + 1, jnp.float32).at[sink.reshape(-1)].add(
        h.reshape(-1), mode="drop"
    )[:n]
    return grad, hess


def grouped_ndcg_device(
    scores: jnp.ndarray,
    rel: jnp.ndarray,
    pad_idx: jnp.ndarray,
    valid: jnp.ndarray,
    k: int = 5,
) -> jnp.ndarray:
    """Mean NDCG@k over padded groups on device — the traced twin of
    train.grouped_ndcg (same 2^rel-1 gains, all-zero-relevance groups score
    1.0), so ranking early stopping needs no host sync either."""
    n = scores.shape[0]
    G, M = pad_idx.shape
    idx = jnp.clip(pad_idx, 0, n - 1)
    s = jnp.where(valid, scores[idx], -jnp.inf)
    r = jnp.where(valid, rel[idx], 0.0)
    order = jnp.argsort(-s, axis=1, stable=True)
    ranks = jnp.argsort(order, axis=1)
    gains = jnp.where(valid, 2.0 ** r - 1.0, 0.0)
    sizes = valid.sum(axis=1)
    kk = jnp.minimum(k, sizes)[:, None]
    disc = 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0)
    dcg = (gains * disc * (ranks < kk)).sum(axis=1)
    ideal = -jnp.sort(-gains, axis=1)
    pos = jnp.arange(M)[None, :]
    pos_disc = 1.0 / jnp.log2(pos.astype(jnp.float32) + 2.0)
    idcg = (ideal * pos_disc * (pos < kk)).sum(axis=1)
    ndcg = jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-12), 1.0)
    nonempty = sizes > 0
    return (ndcg * nonempty).sum() / jnp.maximum(nonempty.sum(), 1)


def sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def softmax(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)

def binary_auc_device(
    s: "jnp.ndarray", y: "jnp.ndarray", vw: "jnp.ndarray"
) -> "jnp.ndarray":
    """ROC AUC on device over the masked validation rows — the rank
    statistic with ties averaged, formula-matched to
    :func:`mmlspark_tpu.core.metrics.binary_auc` (searchsorted average
    ranks instead of the host's tie-run walk; identical value). Lets
    ``metric="auc"`` early stopping train scan-fused with zero per-round
    host syncs. Raw scores are fine: sigmoid is strictly increasing, so
    ranks (and ties) match probability-space AUC exactly. Degenerate
    all-one-class validation sets return 0.5 (the host path returns NaN
    and disables improvement tracking; inside a fused chunk a constant
    metric achieves the same — no improvement is ever recorded)."""
    import jax.numpy as jnp

    valid = vw > 0
    # invalid rows sort to +inf: counts of (< s_i) and (<= s_i) over the
    # valid set are unaffected for finite s_i. Rank sums accumulate in
    # f32 (x64 is globally off) — exact up to ~2^24 validation rows,
    # far beyond any early-stopping eval set here
    srt = jnp.sort(jnp.where(valid, s, jnp.inf))
    lo = jnp.searchsorted(srt, s, side="left")
    hi = jnp.searchsorted(srt, s, side="right")
    avg_rank = (lo + hi + 1).astype(jnp.float32) / 2.0
    pos = jnp.where(valid, y, 0.0)
    n_pos = pos.sum()
    n_val = valid.sum().astype(jnp.float32)
    n_neg = n_val - n_pos
    rank_sum = (avg_rank * pos).sum()
    denom = jnp.maximum(n_pos * n_neg, 1.0)
    auc = (rank_sum - n_pos * (n_pos + 1) / 2.0) / denom
    return jnp.where((n_pos > 0) & (n_neg > 0), auc, 0.5)
