"""Feature quantization for histogram GBDT.

LightGBM's BinMapper equivalent: each feature is quantized to at most
``max_bin`` bins by (approximate) quantiles; training then operates on the
uint8 bin matrix. Bin 0 is reserved for missing values (NaN), matching
LightGBM's missing-bin handling (zero_as_missing=False semantics).

Upper-bound thresholds are kept in original feature space so trained trees
carry real-valued thresholds and prediction never needs the bin mapper.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

MISSING_BIN = 0


@dataclass
class BinMapper:
    # uppers[f] has length n_bins[f]-1: upper bound (inclusive) of each
    # non-missing bin except the last (which is +inf)
    uppers: list
    max_bin: int

    @property
    def num_features(self) -> int:
        return len(self.uppers)

    @staticmethod
    def fit(
        x: np.ndarray,
        max_bin: int = 255,
        sample: int = 200_000,
        seed: int = 0,
        categorical_features: tuple = (),
    ) -> "BinMapper":
        """``categorical_features``: feature indices binned by IDENTITY
        (category value v -> bin v+1, via half-integer bounds) instead of
        quantiles, so a trained categorical split's bin set corresponds 1:1
        to category values at prediction time. Categorical values must be
        integers in [0, max_bin-2]; out-of-range training values raise (a
        silent collapse would make training and prediction route the same
        row differently). Categories unseen at fit time route to the right
        child at prediction, like LightGBM's other-category default."""
        if not 2 <= max_bin <= 255:
            # bins live in a uint8 matrix (bin 0 = missing); larger values
            # would silently wrap mod 256
            raise ValueError(f"max_bin must be in [2, 255], got {max_bin}")
        n, d = x.shape
        if n > sample:
            idx = np.random.default_rng(seed).choice(n, sample, replace=False)
            xs = x[idx]
        else:
            xs = x
        cat = set(int(f) for f in categorical_features)
        uppers = []
        for f in range(d):
            if f in cat:
                # full column, not the sample: hi must cover every category
                # actually present or training bins and prediction's
                # identity mapping would diverge for the unsampled tail
                col = x[:, f]
                col = col[~np.isnan(col)]
                if len(col) and (col.min() < 0 or col.max() > max_bin - 2):
                    raise ValueError(
                        f"categorical feature {f} has values outside "
                        f"[0, {max_bin - 2}] — re-index categories first"
                    )
                hi = int(col.max()) if len(col) else 0
                uppers.append(np.arange(hi, dtype=np.float64) + 0.5)
                continue
            col = xs[:, f]
            col = col[~np.isnan(col)]
            uniq = np.unique(col)
            if len(uniq) <= 1:
                uppers.append(np.array([], dtype=np.float64))
                continue
            if len(uniq) <= max_bin - 1:
                bounds = (uniq[:-1] + uniq[1:]) / 2.0
            else:
                qs = np.linspace(0, 100, max_bin)[1:-1]
                bounds = np.unique(np.percentile(col, qs, method="linear"))
            uppers.append(bounds.astype(np.float64))
        return BinMapper(uppers=uppers, max_bin=max_bin)

    def transform(self, x: np.ndarray) -> np.ndarray:
        """(n, d) float -> (n, d) uint8 bins; NaN -> MISSING_BIN(0); real
        values start at bin 1."""
        from mmlspark_tpu.ops import native_loader

        # bin at float32 on BOTH paths so results are identical with and
        # without the native toolchain (the native kernel takes float32)
        x = np.asarray(x, np.float32)
        lib = native_loader.try_load()
        if lib is not None:
            return lib.bin_features(x, self.uppers)
        n, d = x.shape
        out = np.empty((n, d), dtype=np.uint8)
        for f in range(d):
            col = x[:, f]
            b = np.searchsorted(self.uppers[f], col, side="left") + 1
            b = np.where(np.isnan(col), MISSING_BIN, b)
            out[:, f] = b.astype(np.uint8)
        return out

    def num_bins(self, f: int) -> int:
        return len(self.uppers[f]) + 2  # missing bin + len(uppers)+1 value bins

    def threshold_value(self, f: int, bin_idx: int) -> float:
        """Upper bound of value-bin ``bin_idx`` (split 'x <= thr')."""
        u = self.uppers[f]
        i = int(bin_idx) - 1  # value bins start at 1
        if i < 0:
            return -np.inf
        if i >= len(u):
            return np.inf
        return float(u[i])
