"""Feature quantization for histogram GBDT.

LightGBM's BinMapper equivalent: each feature is quantized to at most
``max_bin`` bins by (approximate) quantiles; training then operates on the
uint8 bin matrix. Bin 0 is reserved for missing values (NaN), matching
LightGBM's missing-bin handling (zero_as_missing=False semantics).

Sparse input: ``fit``/``transform`` also accept a scipy-style CSR/CSC
matrix (anything with ``data``/``indices``/``indptr``/``shape``) — the
reference builds native datasets from dense rows OR sparse rows the same
way (LightGBMUtils.scala:211-265). Stored values are binned per column
without ever densifying the float matrix; absent entries map to the
missing bin (LightGBM's ``zero_as_missing=true``, its recommended setting
for sparse data). The bin matrix itself stays dense uint8 — 1 byte/cell is
the histogram substrate the device kernels consume.

Upper-bound thresholds are kept in original feature space so trained trees
carry real-valued thresholds and prediction never needs the bin mapper.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

MISSING_BIN = 0


def is_sparse(x: object) -> bool:
    return hasattr(x, "indptr") and hasattr(x, "indices") and hasattr(x, "data")


def densify_missing(x: object) -> np.ndarray:
    """Sparse -> dense float32 with ABSENT entries as NaN.

    Prediction-time companion of the zero_as_missing binning: a tree
    trained on sparse data routes absent entries through the missing bin,
    so scoring must present them as NaN, not 0.0."""
    n, d = x.shape
    out = np.full((n, d), np.nan, np.float32)
    xc = x.tocsc() if hasattr(x, "tocsc") else x
    indptr = np.asarray(xc.indptr)
    rows = np.asarray(xc.indices)
    data = np.asarray(xc.data, np.float32)
    for f in range(d):
        lo, hi = indptr[f], indptr[f + 1]
        if hi > lo:
            out[rows[lo:hi], f] = data[lo:hi]
    return out


def _csc_columns(x: object):
    """Yield (f, stored_values) for every column with stored entries."""
    xc = x.tocsc() if hasattr(x, "tocsc") else x
    indptr = np.asarray(xc.indptr)
    for f in range(x.shape[1]):
        lo, hi = indptr[f], indptr[f + 1]
        if hi > lo:
            yield f, np.asarray(xc.data[lo:hi], np.float64)


@dataclass
class BinMapper:
    # uppers[f] has length n_bins[f]-1: upper bound (inclusive) of each
    # non-missing bin except the last (which is +inf)
    uppers: list
    max_bin: int

    @property
    def num_features(self) -> int:
        return len(self.uppers)

    @staticmethod
    def fit(
        x: np.ndarray,
        max_bin: int = 255,
        sample: int = 200_000,
        seed: int = 0,
        categorical_features: tuple = (),
    ) -> "BinMapper":
        """``categorical_features``: feature indices binned by IDENTITY
        (category value v -> bin v+1, via half-integer bounds) instead of
        quantiles, so a trained categorical split's bin set corresponds 1:1
        to category values at prediction time. Categorical values must be
        integers in [0, max_bin-2]; out-of-range training values raise (a
        silent collapse would make training and prediction route the same
        row differently). Categories unseen at fit time route to the right
        child at prediction, like LightGBM's other-category default."""
        if not 2 <= max_bin <= 255:
            # bins live in a uint8 matrix (bin 0 = missing); larger values
            # would silently wrap mod 256
            raise ValueError(f"max_bin must be in [2, 255], got {max_bin}")
        if is_sparse(x):
            if categorical_features:
                raise ValueError(
                    "categorical features require dense input (sparse "
                    "columns have no stable category<->bin identity for "
                    "absent entries)"
                )
            return BinMapper._fit_sparse(x, max_bin, sample=sample, seed=seed)
        n, d = x.shape
        if n > sample:
            idx = np.random.default_rng(seed).choice(n, sample, replace=False)
            xs = x[idx]
        else:
            xs = x
        cat = set(int(f) for f in categorical_features)
        uppers = []
        for f in range(d):
            if f in cat:
                # full column, not the sample: hi must cover every category
                # actually present or training bins and prediction's
                # identity mapping would diverge for the unsampled tail
                col = x[:, f]
                col = col[~np.isnan(col)]
                if len(col) and (col.min() < 0 or col.max() > max_bin - 2):
                    raise ValueError(
                        f"categorical feature {f} has values outside "
                        f"[0, {max_bin - 2}] — re-index categories first"
                    )
                hi = int(col.max()) if len(col) else 0
                uppers.append(np.arange(hi, dtype=np.float64) + 0.5)
                continue
            col = xs[:, f]
            col = col[~np.isnan(col)]
            uniq = np.unique(col)
            if len(uniq) <= 1:
                uppers.append(np.array([], dtype=np.float64))
                continue
            if len(uniq) <= max_bin - 1:
                bounds = (uniq[:-1] + uniq[1:]) / 2.0
            else:
                qs = np.linspace(0, 100, max_bin)[1:-1]
                bounds = np.unique(np.percentile(col, qs, method="linear"))
            uppers.append(bounds.astype(np.float64))
        return BinMapper(uppers=uppers, max_bin=max_bin)

    @staticmethod
    def _fit_sparse(
        x: object, max_bin: int, sample: int = 200_000, seed: int = 0
    ) -> "BinMapper":
        """Quantile bounds from each column's STORED values only (capped at
        the same per-fit sampling budget as the dense path)."""
        d = x.shape[1]
        rng = np.random.default_rng(seed)
        uppers = [np.array([], dtype=np.float64)] * d
        for f, col in _csc_columns(x):
            if len(col) > sample:
                col = rng.choice(col, sample, replace=False)
            col = col[~np.isnan(col)]
            uniq = np.unique(col)
            if len(uniq) <= 1:
                continue
            if len(uniq) <= max_bin - 1:
                bounds = (uniq[:-1] + uniq[1:]) / 2.0
            else:
                qs = np.linspace(0, 100, max_bin)[1:-1]
                bounds = np.unique(np.percentile(col, qs, method="linear"))
            uppers[f] = bounds.astype(np.float64)
        return BinMapper(uppers=uppers, max_bin=max_bin)

    def _transform_sparse(self, x: object) -> np.ndarray:
        """CSR/CSC -> dense uint8 bins; absent entries stay MISSING_BIN."""
        n, d = x.shape
        out = np.zeros((n, d), dtype=np.uint8)
        xc = x.tocsc() if hasattr(x, "tocsc") else x
        indptr = np.asarray(xc.indptr)
        rows = np.asarray(xc.indices)
        data = np.asarray(xc.data, np.float32)
        for f in range(d):
            lo, hi = indptr[f], indptr[f + 1]
            if hi == lo:
                continue
            vals = data[lo:hi]
            b = np.searchsorted(self.uppers[f], vals, side="left") + 1
            b = np.where(np.isnan(vals), MISSING_BIN, b)
            out[rows[lo:hi], f] = b.astype(np.uint8)
        return out

    def transform(self, x: np.ndarray) -> np.ndarray:
        """(n, d) float -> (n, d) uint8 bins; NaN -> MISSING_BIN(0); real
        values start at bin 1."""
        if is_sparse(x):
            return self._transform_sparse(x)
        from mmlspark_tpu.ops import native_loader

        # bin at float32 on BOTH paths so results are identical with and
        # without the native toolchain (the native kernel takes float32)
        x = np.asarray(x, np.float32)
        lib = native_loader.try_load()
        if lib is not None:
            return lib.bin_features(x, self.uppers)
        n, d = x.shape
        out = np.empty((n, d), dtype=np.uint8)
        for f in range(d):
            col = x[:, f]
            b = np.searchsorted(self.uppers[f], col, side="left") + 1
            b = np.where(np.isnan(col), MISSING_BIN, b)
            out[:, f] = b.astype(np.uint8)
        return out

    def num_bins(self, f: int) -> int:
        return len(self.uppers[f]) + 2  # missing bin + len(uppers)+1 value bins

    def transform_into(
        self, x: np.ndarray, out: np.ndarray, row0: int
    ) -> None:
        """Bin a chunk straight into ``out[row0:row0+len(x)]`` — the
        out-of-core ingestion path writes uint8 rows into a preallocated
        matrix without ever holding a second float copy."""
        out[row0:row0 + len(x)] = self.transform(x)

    def threshold_value(self, f: int, bin_idx: int) -> float:
        """Upper bound of value-bin ``bin_idx`` (split 'x <= thr')."""
        u = self.uppers[f]
        i = int(bin_idx) - 1  # value bins start at 1
        if i < 0:
            return -np.inf
        if i >= len(u):
            return np.inf
        return float(u[i])


@dataclass
class BinnedDataset:
    """An already-quantized training input: the uint8 bin matrix plus
    the mapper that produced it. ``train()`` accepts one wherever it
    accepts a float matrix and skips its own fit/transform — the
    out-of-core path bins streaming chunks into this shape so the float
    matrix never exists in memory at once (docs/gbdt-training.md)."""

    bins: np.ndarray        # (n, d) uint8
    mapper: BinMapper

    def __post_init__(self) -> None:
        self.bins = np.ascontiguousarray(self.bins)
        if self.bins.dtype != np.uint8 or self.bins.ndim != 2:
            raise ValueError("BinnedDataset.bins must be a (n, d) uint8")
        if self.bins.shape[1] != self.mapper.num_features:
            raise ValueError(
                f"bins have {self.bins.shape[1]} features, mapper has "
                f"{self.mapper.num_features}"
            )

    @property
    def shape(self) -> tuple:
        return self.bins.shape
