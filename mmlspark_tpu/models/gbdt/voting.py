"""Voting-parallel tree growth — LightGBM's ``voting_parallel`` for real.

The reference exposes two distributed GBDT modes
(lightgbm/LightGBMParams.scala:13-18, LightGBMConstants.scala:22-24):
``data_parallel`` allreduces the FULL per-leaf histogram every split, while
``voting_parallel`` (PV-Tree: Meng et al., "A Communication-Efficient
Parallel Algorithm for Decision Tree", NeurIPS 2016) cuts the exchange to
two tiny rounds:

1. **local vote** — each worker ranks features by its local split gain and
   nominates its top ``top_k``;
2. **global vote** — per-feature vote counts are summed (one (d,)
   allreduce) and the top ``2 * top_k`` features become candidates;
3. **exact phase** — only the candidates' histogram columns are summed
   (a (2, 2K, B, 3) allreduce instead of (d*B, 3)), and the split is
   chosen exactly on those.

Here a worker = a mesh shard: the grower runs under ``jax.shard_map`` over
the ``data`` axis, local histograms stay shard-resident (never allreduced
in full), and the two vote rounds are explicit ``psum``s riding ICI. Bytes
on the wire per split drop from ``d*B*3`` to ``d + 2*2K*B*3`` — the win
LightGBM's voting mode exists for when ``d >> 2K``.

Same incremental design as :mod:`treegrow`: per-leaf best-split cache,
only the two changed leaves re-voted per step. Categorical features vote
with their sorted-prefix gain and split by subset membership exactly like
the single-chip grower (the reference imposes no categorical restriction
on voting mode either, LightGBMParams.scala:13-18); the catmask is derived
from the psum'd candidate histograms, so it is identical on every shard.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.models.gbdt.treegrow import GrownTree, split_gain_term, threshold_l1
from mmlspark_tpu.ops.histogram import NUM_BINS, plane_histogram
from mmlspark_tpu.parallel.compat import shard_map
from mmlspark_tpu.parallel.mesh import DATA_AXIS


def grow_tree_voting(
    bins: jnp.ndarray,            # (n, d) sharded over the data axis
    grad: jnp.ndarray,            # (n,)
    hess: jnp.ndarray,            # (n,)
    row_weight: jnp.ndarray,      # (n,)
    num_leaves: int,
    lambda_l2: float,
    min_gain: float,
    learning_rate: float,
    feature_mask: jnp.ndarray,    # (d,) f32 (replicated)
    max_depth: int = -1,
    min_data_in_leaf: int = 20,
    top_k: int = 20,
    mesh: Any = None,
    axis: str = DATA_AXIS,
    lambda_l1: float = 0.0,
    min_sum_hessian: float = 1e-3,
    num_bins: int = NUM_BINS,
    categorical_mask: Any = None,   # (d,) bool, replicated
) -> GrownTree:
    """Grow one tree with PV-Tree voting over ``mesh``'s ``axis``."""
    if mesh is None:
        from mmlspark_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
    has_categorical = categorical_mask is not None
    if not has_categorical:
        categorical_mask = jnp.zeros((bins.shape[1],), bool)
    program = _voting_program(
        mesh, axis, int(num_leaves), int(max_depth), int(min_data_in_leaf),
        int(top_k), int(num_bins), has_categorical,
    )
    return program(
        bins, grad, hess, row_weight,
        jnp.float32(lambda_l2), jnp.float32(min_gain),
        jnp.float32(learning_rate), feature_mask,
        jnp.float32(lambda_l1), jnp.float32(min_sum_hessian),
        categorical_mask,
    )


@functools.lru_cache(maxsize=None)
def _voting_program(
    mesh, axis, num_leaves, max_depth, min_data_in_leaf, top_k,
    num_bins=NUM_BINS, has_categorical=False,
):
    L = num_leaves
    B = num_bins

    def program(bins, grad, hess, row_weight, lambda_l2, min_gain,
                learning_rate, feature_mask, lambda_l1, min_sum_hessian,
                categorical_mask):
        # executes PER SHARD: shapes below are shard-local
        n, d = bins.shape
        K = min(top_k, d)
        C = min(2 * top_k, d)
        bins = bins.astype(jnp.int32)
        lam = lambda_l2
        l1 = lambda_l1
        msh = min_sum_hessian

        def soft(Gv):
            return threshold_l1(Gv, l1)

        def gscore(Gv, Hv):
            return split_gain_term(Gv, Hv, lam, l1)

        g = grad * row_weight
        h = hess * row_weight
        row_stats = jnp.stack([g, h, row_weight], axis=-1)

        def plane_hist(mask):
            # LOCAL histogram plane — stays on the shard (scatter lowering;
            # single-shard shapes, no GSPMD collectives inside shard_map;
            # allow_host=False: a host callback per shard would serialize
            # the shards on the GIL)
            return plane_histogram(
                bins, row_stats, mask, num_bins=B, allow_host=False
            )

        cat_f = categorical_mask.astype(bool)

        def _cat_prefix(hg, hh, hc):
            """Sorted-by-ratio prefix cumsums (the Fisher-optimal subset
            scan shared with treegrow.make_leaf_best). Returns
            (order, cgs, chs, ccs) over the leading axis's features."""
            ratio = jnp.where(hc > 0, hg / (hh + 1e-12), -jnp.inf)
            order = jnp.argsort(-ratio, axis=-1)
            sgs = jnp.take_along_axis(hg, order, -1)
            shs = jnp.take_along_axis(hh, order, -1)
            scs = jnp.take_along_axis(hc, order, -1)
            return (order, jnp.cumsum(sgs, -1), jnp.cumsum(shs, -1),
                    jnp.cumsum(scs, -1))

        def local_feature_gains(plane):
            """(d*B, 3) LOCAL plane -> (d,) best local gain per feature
            (the vote-phase ranking; validity from local counts)."""
            cube = plane.reshape(d, B, 3)
            hg, hh, hc = cube[..., 0], cube[..., 1], cube[..., 2]
            cg = jnp.cumsum(hg, axis=1)
            ch = jnp.cumsum(hh, axis=1)
            cc = jnp.cumsum(hc, axis=1)
            G, H, Ct = cg[:, -1:], ch[:, -1:], cc[:, -1:]
            gain = gscore(cg, ch) + gscore(G - cg, H - ch) - gscore(G, H)
            valid = (
                (feature_mask > 0)[:, None]
                & (cc >= min_data_in_leaf)
                & ((Ct - cc) >= min_data_in_leaf)
                # same hessian floor as the exact phase: a feature whose
                # splits all fail it must not win votes
                & (ch >= msh) & ((H - ch) >= msh)
            )
            best_num = jnp.where(valid, gain, -jnp.inf).max(axis=1)
            if not has_categorical:
                return best_num
            order, cgs, chs, ccs = _cat_prefix(hg, hh, hc)
            gain_cat = gscore(cgs, chs) + gscore(G - cgs, H - chs) - gscore(G, H)
            valid_cat = (
                (feature_mask > 0)[:, None]
                & (ccs >= min_data_in_leaf)
                & ((Ct - ccs) >= min_data_in_leaf)
                & (chs >= msh) & ((H - chs) >= msh)
            )
            best_cat = jnp.where(valid_cat, gain_cat, -jnp.inf).max(axis=1)
            return jnp.where(cat_f, best_cat, best_num)

        def candidate_best(cand_hist, cand_ids):
            """Exact split over the GLOBAL candidate histograms of one leaf.

            cand_hist: (C, B, 3) psum'd; cand_ids: (C,) feature ids.
            Inputs are psum results, so every shard derives the identical
            split AND catmask. Returns (gain, feature, bin/prefix, catmask).
            """
            hg, hh, hc = cand_hist[..., 0], cand_hist[..., 1], cand_hist[..., 2]
            cg = jnp.cumsum(hg, axis=1)
            ch = jnp.cumsum(hh, axis=1)
            cc = jnp.cumsum(hc, axis=1)
            G, H, Ct = cg[:, -1:], ch[:, -1:], cc[:, -1:]
            gain_num = gscore(cg, ch) + gscore(G - cg, H - ch) - gscore(G, H)
            valid = (
                (feature_mask[cand_ids] > 0)[:, None]
                & (cc >= min_data_in_leaf)
                & ((Ct - cc) >= min_data_in_leaf)
                & (ch >= msh) & ((H - ch) >= msh)
            )
            gain = jnp.where(valid, gain_num, -jnp.inf)
            if has_categorical:
                order, cgs, chs, ccs = _cat_prefix(hg, hh, hc)
                gain_cat = (
                    gscore(cgs, chs) + gscore(G - cgs, H - chs) - gscore(G, H)
                )
                valid_cat = (
                    (feature_mask[cand_ids] > 0)[:, None]
                    & (ccs >= min_data_in_leaf)
                    & ((Ct - ccs) >= min_data_in_leaf)
                    & (chs >= msh) & ((H - chs) >= msh)
                )
                gain = jnp.where(
                    cat_f[cand_ids][:, None],
                    jnp.where(valid_cat, gain_cat, -jnp.inf),
                    gain,
                )
            flat = gain.reshape(-1)
            best = jnp.argmax(flat)
            ci = (best // B).astype(jnp.int32)
            bb = (best % B).astype(jnp.int32)
            if has_categorical:
                rank = jnp.argsort(order[ci])
                catmask = (rank <= bb) & cat_f[cand_ids[ci]]
            else:
                catmask = jnp.zeros((B,), bool)
            return flat[best], cand_ids[ci], bb, catmask

        def step(k, state):
            (hist, row_leaf, leaf_depth, done,
             cache_gain, cache_feat, cache_bin, cache_catmask, prev_pair,
             rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
             rec_is_cat, rec_catmask) = state

            # -- vote phase: rank features by LOCAL gain on the two planes
            pair_planes = hist[prev_pair]                       # (2, d*B, 3)
            local_gains = jax.vmap(local_feature_gains)(pair_planes)  # (2, d)
            topv, topi = jax.lax.top_k(local_gains, K)
            ballots = jnp.zeros((2, d), jnp.float32).at[
                jnp.arange(2)[:, None], topi
            ].add(jnp.where(jnp.isfinite(topv), 1.0, 0.0))
            votes = jax.lax.psum(ballots, axis)                 # tiny: (2, d)
            # global top-C by votes, ties to the lower feature id
            score = votes * jnp.float32(d + 1) - jnp.arange(d, dtype=jnp.float32)
            _, cand = jax.lax.top_k(score, C)                   # (2, C)

            # -- exact phase: allreduce ONLY the candidates' columns
            cube = pair_planes.reshape(2, d, B, 3)
            cand_local = jnp.take_along_axis(
                cube, cand[:, :, None, None], axis=1
            )                                                   # (2, C, B, 3)
            cand_global = jax.lax.psum(cand_local, axis)
            bg, bf_, bb_, bcm_ = jax.vmap(candidate_best)(cand_global, cand)

            cache_gain = cache_gain.at[prev_pair].set(bg)
            cache_feat = cache_feat.at[prev_pair].set(bf_)
            cache_bin = cache_bin.at[prev_pair].set(bb_)
            cache_catmask = cache_catmask.at[prev_pair].set(bcm_)

            # -- selection + split (identical on every shard: inputs are
            # psum results, so the split records stay replicated)
            leaf_ids = jnp.arange(L, dtype=jnp.int32)
            leaf_ok = leaf_ids < (k + 1)
            if max_depth > 0:
                leaf_ok = leaf_ok & (leaf_depth < max_depth)
            sel = jnp.where(leaf_ok, cache_gain, -jnp.inf)
            bl = jnp.argmax(sel).astype(jnp.int32)
            best_gain = sel[bl]
            bf = cache_feat[bl]
            bb = cache_bin[bl]
            catmask = cache_catmask[bl]

            do_split = (~done) & (best_gain > min_gain) & jnp.isfinite(best_gain)
            new_id = jnp.int32(k + 1)
            in_leaf = row_leaf == bl
            row_bins = bins[:, bf]
            if has_categorical:
                is_cat_split = cat_f[bf]
                goes_right = jnp.where(
                    is_cat_split, ~catmask[row_bins], row_bins > bb
                )
            else:
                is_cat_split = jnp.asarray(False)
                goes_right = row_bins > bb
            moved = do_split & in_leaf & goes_right
            row_leaf = jnp.where(moved, new_id, row_leaf)
            right_plane = plane_hist(moved.astype(jnp.float32))  # LOCAL
            hist = hist.at[new_id].set(right_plane).at[bl].add(
                jnp.where(do_split, -right_plane, 0.0)
            )
            child_depth = leaf_depth[bl] + 1
            leaf_depth = jnp.where(
                do_split,
                leaf_depth.at[bl].set(child_depth).at[new_id].set(child_depth),
                leaf_depth,
            )
            rec_leaf = rec_leaf.at[k].set(jnp.where(do_split, bl, -1))
            rec_feature = rec_feature.at[k].set(jnp.where(do_split, bf, -1))
            rec_bin = rec_bin.at[k].set(jnp.where(do_split, bb, -1))
            rec_active = rec_active.at[k].set(do_split)
            rec_gain = rec_gain.at[k].set(jnp.where(do_split, best_gain, 0.0))
            rec_is_cat = rec_is_cat.at[k].set(do_split & is_cat_split)
            rec_catmask = rec_catmask.at[k].set(
                jnp.where(do_split & is_cat_split, catmask, False)
            )
            done = done | ~do_split
            prev_pair = jnp.stack([bl, new_id])
            return (hist, row_leaf, leaf_depth, done,
                    cache_gain, cache_feat, cache_bin, cache_catmask, prev_pair,
                    rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
                    rec_is_cat, rec_catmask)

        hist0 = (
            jnp.zeros((L, d * B, 3), jnp.float32)
            .at[0]
            .set(plane_hist(jnp.ones((n,), jnp.float32)))
        )
        init = (
            hist0,
            jnp.zeros((n,), jnp.int32),
            jnp.zeros((L,), jnp.int32),
            jnp.asarray(False),
            jnp.full((L,), -jnp.inf, jnp.float32),
            jnp.zeros((L,), jnp.int32),
            jnp.zeros((L,), jnp.int32),
            jnp.zeros((L, B), bool),
            jnp.zeros((2,), jnp.int32),
            jnp.full((L - 1,), -1, jnp.int32),
            jnp.full((L - 1,), -1, jnp.int32),
            jnp.full((L - 1,), -1, jnp.int32),
            jnp.zeros((L - 1,), bool),
            jnp.zeros((L - 1,), jnp.float32),
            jnp.zeros((L - 1,), bool),
            jnp.zeros((L - 1, B), bool),
        )
        (_, row_leaf, _, _, _, _, _, _, _,
         rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
         rec_is_cat, rec_catmask) = (
            jax.lax.fori_loop(0, L - 1, step, init)
        )

        # leaf values from GLOBAL sums (one (L,3) psum)
        sums = jnp.stack(
            [
                jnp.zeros((L,), jnp.float32).at[row_leaf].add(g),
                jnp.zeros((L,), jnp.float32).at[row_leaf].add(h),
                jnp.zeros((L,), jnp.float32).at[row_leaf].add(row_weight),
            ],
            axis=-1,
        )
        sums = jax.lax.psum(sums, axis)
        Gl, Hl, Cl = sums[:, 0], sums[:, 1], sums[:, 2]
        leaf_values = -soft(Gl) / (Hl + lam) * learning_rate
        leaf_values = jnp.where(Cl > 0, leaf_values, 0.0)
        return GrownTree(
            rec_leaf, rec_feature, rec_bin, rec_active, rec_gain,
            leaf_values, Cl.astype(jnp.int32), row_leaf,
            rec_is_cat, rec_catmask,
        )

    row = P(axis)
    rep = P()
    mapped = shard_map(
        program,
        mesh=mesh,
        in_specs=(row, row, row, row, rep, rep, rep, rep, rep, rep, rep),
        out_specs=GrownTree(
            rep, rep, rep, rep, rep,   # split records
            rep, rep,                  # leaf values/counts
            row,                       # row_leaf stays sharded
            rep, rep,                  # categorical records
        ),
        check_vma=False,
    )
    return jax.jit(mapped)
