"""Streaming quantile sketches for out-of-core bin-bound fitting.

The original :meth:`BinMapper.fit` needs the full feature matrix in one
place — in an elastic gang that meant gathering EVERY row to every host
(``GangContext.binning_rows``) before a single tree grew, which caps the
dataset at host memory and made "distributed" training need the whole
dataset resident anyway. This module replaces that gather with the
classic mergeable-sketch pattern:

- each host streams ITS OWN row slice once, counting values into a
  fixed-size per-feature histogram over the **monotone float32 key
  space** (sign-flipped IEEE bit patterns, the radix-sort trick: the
  uint32 key order equals the float order, so bucket = top ``bits`` of
  the key needs no data-dependent range pass);
- the per-host count tensors are **summed by the gang's reducer** (the
  only collective the sketch needs — counts are exact integers in f64
  far below 2^53);
- every member derives the identical bin upper bounds from the identical
  merged counts.

Determinism contract: the merged counts are a sum over rows, so they are
invariant to chunking AND to how rows are partitioned over hosts — the
fitted bins are a pure function of the global dataset, which is exactly
the world-size-invariance the elastic checkpoint contract needs (a
resumed shrunk-world run re-fits the same bins from its new slices).

Precision: with the default ``bits=16`` a bucket spans sign + exponent +
the top 7 mantissa bits, i.e. values inside one bucket agree to ~0.8%
relative — well inside the approximation LightGBM's own sampled
quantile binning already accepts (the bounds only decide histogram bin
edges, never split thresholds' correctness).
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

import numpy as np

from mmlspark_tpu.models.gbdt.binning import BinMapper


def _monotone_keys(col: np.ndarray) -> np.ndarray:
    """float32 -> uint32 keys whose unsigned order equals float order
    (NaNs must be masked out by the caller)."""
    u = col.astype(np.float32).view(np.uint32)
    neg = (u & np.uint32(0x80000000)) != 0
    return np.where(neg, ~u, u | np.uint32(0x80000000))


def _key_upper_value(bucket: np.ndarray, bits: int) -> np.ndarray:
    """Largest float32 whose key lands in ``bucket`` — the bucket's
    inclusive upper bound in value space (used as the bin threshold, so
    every value in the bucket satisfies ``x <= upper``)."""
    shift = 32 - bits
    key = ((bucket.astype(np.uint64) + 1) << shift) - 1
    key = key.astype(np.uint32)
    neg = (key & np.uint32(0x80000000)) == 0  # un-flipped sign bit
    u = np.where(neg, ~key, key & np.uint32(0x7FFFFFFF))
    vals = u.astype(np.uint32).view(np.float32).astype(np.float64)
    # keys at the very top of the space decode to inf/nan payloads —
    # clamp to +/- inf, which searchsorted handles as an open bound
    return np.where(np.isnan(vals), np.inf, vals)


class QuantileSketch:
    """Per-feature streaming value-distribution sketch.

    ``counts`` is a (d, 2**bits) f64 tensor of finite-value counts; NaNs
    are skipped (they ride the missing bin at transform time, exactly as
    in :meth:`BinMapper.fit`)."""

    def __init__(self, n_features: int, bits: int = 16):
        if not 8 <= int(bits) <= 20:
            raise ValueError(f"sketch bits must be in [8, 20], got {bits}")
        self.d = int(n_features)
        self.bits = int(bits)
        self.n_buckets = 1 << self.bits
        self.counts = np.zeros((self.d, self.n_buckets), np.float64)
        self.rows_seen = 0

    def update(self, chunk: np.ndarray) -> None:
        """Count one (n, d) float chunk (any float dtype; binning space
        is float32, matching BinMapper.transform)."""
        x = np.asarray(chunk, np.float32)
        if x.ndim != 2 or x.shape[1] != self.d:
            raise ValueError(
                f"chunk shape {x.shape} does not match d={self.d}"
            )
        self.rows_seen += x.shape[0]
        shift = 32 - self.bits
        for f in range(self.d):
            col = x[:, f]
            col = col[~np.isnan(col)]
            if not len(col):
                continue
            buckets = (_monotone_keys(col) >> np.uint32(shift)).astype(
                np.int64
            )
            self.counts[f] += np.bincount(
                buckets, minlength=self.n_buckets
            )

    def merge_counts(
        self, reduce: Optional[Callable[[np.ndarray], np.ndarray]] = None
    ) -> np.ndarray:
        """The gang-global counts: summed across hosts by ``reduce``
        (the elastic TcpReducer's allreduce — chunked through the ring)
        or returned as-is for world 1 / single-host fits."""
        if reduce is None:
            return self.counts
        return np.asarray(reduce(self.counts), np.float64)

    def to_binmapper(
        self,
        max_bin: int = 255,
        reduce: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    ) -> BinMapper:
        """Quantile-cut bin uppers from the (merged) counts — the
        streaming analogue of :meth:`BinMapper.fit`'s percentile path.
        Deterministic: identical counts -> identical bounds on every
        member at every world size."""
        if not 2 <= max_bin <= 255:
            raise ValueError(f"max_bin must be in [2, 255], got {max_bin}")
        counts = self.merge_counts(reduce)
        uppers = []
        for f in range(self.d):
            c = counts[f]
            nz = np.flatnonzero(c)
            if len(nz) <= 1:
                # constant feature (one occupied bucket): a single bin
                uppers.append(np.array([], np.float64))
                continue
            if len(nz) <= max_bin - 1:
                # few distinct buckets: a bound after each occupied
                # bucket but the last (mirrors the unique-values path)
                bounds = _key_upper_value(nz[:-1], self.bits)
            else:
                # quantile cuts over the cumulative distribution: the
                # bucket where each target fraction is crossed supplies
                # its upper value as the bound
                cum = np.cumsum(c[nz])
                total = cum[-1]
                qs = np.linspace(0, 1, max_bin)[1:-1] * total
                idx = np.searchsorted(cum, qs, side="left")
                idx = np.minimum(idx, len(nz) - 1)
                bounds = np.unique(_key_upper_value(nz[idx], self.bits))
            uppers.append(np.asarray(bounds, np.float64))
        return BinMapper(uppers=uppers, max_bin=max_bin)


def sketch_chunks(
    chunks: Iterable[np.ndarray], n_features: int, bits: int = 16
) -> QuantileSketch:
    """One pass over an (n_i, d)-chunk stream -> a fitted sketch."""
    sk = QuantileSketch(n_features, bits=bits)
    for chunk in chunks:
        sk.update(chunk)
    return sk
