"""Round-level checkpoint/resume for the GBDT boosting loop.

The reference recovers from executor loss by replaying uncommitted Spark
epochs; a preempted TPU host has nothing to replay — the booster lives in
process memory. These checkpoints make the loop preemption-safe: every
``checkpoint_every`` rounds the trainer serializes the grown trees, the
device score/bagging state (exact f32), the host RNG stream and the
early-stopping counters, and ``train(resume_from=...)`` continues from
the last completed round producing a model **bit-identical** to an
uninterrupted run (tests/test_chaos.py proves it).

On-disk layout (atomic against preemption mid-save)::

    <dir>/round-0000012/state.json     # round, rng state, counters, fingerprint
                        booster.json   # trees grown so far (model string)
                        arrays.npz     # scores, bag (unpadded first-n rows)
    <dir>/LATEST                       # name of the last COMPLETE round dir

``LATEST`` is os.replace()d only after the round dir is fully written, so
a save torn by preemption leaves the previous checkpoint loadable; stale
round dirs beyond ``keep_last`` are pruned best-effort.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import time
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from mmlspark_tpu import obs
from mmlspark_tpu.models.gbdt.booster import Booster

_FORMAT = "mmlspark_tpu_gbdt_ckpt_v1"
_LATEST = "LATEST"

_M_CKPTS = obs.counter(
    "mmlspark_gbdt_checkpoints_total", "GBDT checkpoints committed",
)
_M_CKPT_SAVE = obs.histogram(
    "mmlspark_gbdt_checkpoint_save_seconds",
    "Wall time to serialize + atomically commit one checkpoint",
)
_M_CKPT_RESTORE = obs.histogram(
    "mmlspark_gbdt_checkpoint_restore_seconds",
    "Wall time to load the LATEST checkpoint at resume",
)


def config_fingerprint(cfg: Any, n: int, d: int, k: int) -> str:
    """Hash of everything that must match for a resumed run to be the
    same run: determinism-relevant hyperparameters + data shape. Excludes
    ``num_iterations`` (resume may legitimately extend the budget) and
    the delegate (host callbacks carry no trained state)."""
    payload = {
        f.name: getattr(cfg, f.name)
        for f in dataclasses.fields(cfg)
        if f.name not in ("num_iterations", "delegate", "verbosity")
    }
    payload.update(n=int(n), d=int(d), k=int(k))
    blob = json.dumps(payload, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


@dataclass
class TrainCheckpoint:
    """Everything the boosting loop needs to continue from ``round``."""

    round: int                       # next iteration index to run
    booster: Booster                 # trees of completed rounds (new trees only)
    scores: np.ndarray               # (n,) or (n, k) f32 running scores
    bag: Optional[np.ndarray]        # (n,) f32 bagging mask carry, if bagging
    rng_state: dict                  # np.random.Generator bit_generator state
    fingerprint: str
    best_val: Optional[float] = None
    best_iter: int = -1
    rounds_no_improve: int = 0
    lr: float = 0.1


def save_checkpoint(
    ckpt_dir: str, ckpt: TrainCheckpoint, keep_last: int = 2
) -> str:
    """Write one checkpoint; returns the round directory path."""
    t0 = time.perf_counter()
    os.makedirs(ckpt_dir, exist_ok=True)
    name = f"round-{ckpt.round:07d}"
    tmp = os.path.join(ckpt_dir, f".tmp-{name}-{os.getpid()}")
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    arrays = {"scores": np.asarray(ckpt.scores, np.float32)}
    if ckpt.bag is not None:
        arrays["bag"] = np.asarray(ckpt.bag, np.float32)
    np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
    with open(os.path.join(tmp, "booster.json"), "w") as f:
        f.write(ckpt.booster.to_model_string())
    with open(os.path.join(tmp, "state.json"), "w") as f:
        json.dump(
            {
                "format": _FORMAT,
                "round": ckpt.round,
                "rng_state": ckpt.rng_state,
                "fingerprint": ckpt.fingerprint,
                "best_val": ckpt.best_val,
                "best_iter": ckpt.best_iter,
                "rounds_no_improve": ckpt.rounds_no_improve,
                "lr": ckpt.lr,
            },
            f,
        )
    final = os.path.join(ckpt_dir, name)
    shutil.rmtree(final, ignore_errors=True)
    os.replace(tmp, final)
    # the commit point: LATEST flips only once the round dir is complete
    latest_tmp = os.path.join(ckpt_dir, f".{_LATEST}-{os.getpid()}")
    with open(latest_tmp, "w") as f:
        f.write(name)
    os.replace(latest_tmp, os.path.join(ckpt_dir, _LATEST))
    if keep_last > 0:
        rounds = [e for e in os.listdir(ckpt_dir) if e.startswith("round-")]
        # newest by mtime, NOT by round number: a fresh run writing low
        # round numbers into a dir still holding a previous run's higher
        # rounds must never prune its own just-committed checkpoint (the
        # one LATEST points at) in favor of the stale leftovers
        rounds.sort(
            key=lambda e: os.path.getmtime(os.path.join(ckpt_dir, e))
        )
        keep = set(rounds[-keep_last:]) | {name}
        for stale in rounds:
            if stale not in keep:
                shutil.rmtree(
                    os.path.join(ckpt_dir, stale), ignore_errors=True
                )
    _M_CKPTS.inc()
    _M_CKPT_SAVE.observe(time.perf_counter() - t0)
    return final


def load_checkpoint(ckpt_dir: str) -> Optional[TrainCheckpoint]:
    """Load the last complete checkpoint, or None when the directory holds
    none (a fresh run). Torn saves are invisible: only round dirs named by
    ``LATEST`` are ever read."""
    latest_path = os.path.join(ckpt_dir, _LATEST)
    if not os.path.exists(latest_path):
        return None
    t0 = time.perf_counter()
    with open(latest_path) as f:
        name = f.read().strip()
    rdir = os.path.join(ckpt_dir, name)
    with open(os.path.join(rdir, "state.json")) as f:
        state = json.load(f)
    if state.get("format") != _FORMAT:
        raise ValueError(
            f"unrecognized checkpoint format {state.get('format')!r} in {rdir}"
        )
    with open(os.path.join(rdir, "booster.json")) as f:
        booster = Booster.from_model_string(f.read())
    with np.load(os.path.join(rdir, "arrays.npz")) as z:
        scores = z["scores"]
        bag = z["bag"] if "bag" in z.files else None
    _M_CKPT_RESTORE.observe(time.perf_counter() - t0)
    return TrainCheckpoint(
        round=int(state["round"]),
        booster=booster,
        scores=scores,
        bag=bag,
        rng_state=state["rng_state"],
        fingerprint=state["fingerprint"],
        best_val=state.get("best_val"),
        best_iter=int(state.get("best_iter", -1)),
        rounds_no_improve=int(state.get("rounds_no_improve", 0)),
        lr=float(state.get("lr", 0.1)),
    )
