from mmlspark_tpu.models.xla_model import XLAModel
from mmlspark_tpu.models.image_featurizer import ImageFeaturizer
from mmlspark_tpu.models import resnet
from mmlspark_tpu.models import sequence
from mmlspark_tpu.models import vit

__all__ = ["XLAModel", "ImageFeaturizer", "resnet", "sequence", "vit"]
