"""ImageFeaturizer — images to feature vectors through a zoo backbone.

Reference: image/ImageFeaturizer.scala:133-178 composes
Resize -> UnrollImage -> CNTKModel with ``cutOutputLayers`` truncating the
head so the net becomes a featurizer (:96-104); layer names come from the
model schema (:121-129).

TPU design: resize + normalize + backbone run as ONE jitted XLA program per
fixed batch shape — preprocessing fuses into the model instead of
materializing intermediate columns. ``cut_output_layers=k`` selects the
k-th entry of the schema's ``layer_names`` (0 = logits, 1 = pooled
features), and XLA prunes every head past it.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasBatchSize,
    HasInputCol,
    HasOutputCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Model
from mmlspark_tpu.core.schema import image_row_to_array
from mmlspark_tpu.downloader.zoo import ModelDownloader
from mmlspark_tpu.models.xla_model import XLAModel
from mmlspark_tpu.ops import image as image_ops


class ImageFeaturizer(Model, HasInputCol, HasOutputCol, HasBatchSize):
    # default = the zoo entry with COMMITTED TRAINED weights
    # (mmlspark_tpu/downloader/builtin/, tools/train_zoo_backbone.py);
    # the large ResNet variants stay selectable for scale benchmarking
    model_name = Param("zoo model name", default="ResNet8_Digits", type_=str)
    cut_output_layers = Param(
        "how many output layers to drop (0=logits, 1=pooled features)",
        default=1,
        type_=int,
    )
    repo_dir = Param("model repository directory", type_=str)
    drop_na = Param("drop rows whose image failed to decode", default=True, type_=bool)
    apply_fn = ComplexParam("override: jittable (variables, images_f32) -> dict")
    variables = ComplexParam("override: backbone variables")
    image_size = Param("input resolution override", type_=int)
    bgr_input = Param(
        "treat incoming channel order as BGR (reference image format)",
        default=False,
        type_=bool,
    )

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        self._inner: Optional[XLAModel] = None
        self._schema: Any = None

    # -- model assembly ------------------------------------------------------

    def _build(self) -> XLAModel:
        if self._inner is not None:
            return self._inner
        if self.is_set("apply_fn") and self.is_set("variables"):
            apply_fn, variables = self.get("apply_fn"), self.get("variables")
            layer_names = ["logits", "pool"]
            size = self.get("image_size") or 224
        else:
            repo = ModelDownloader(self.get("repo_dir")) if self.get("repo_dir") else ModelDownloader()
            module, variables, schema = repo.load(self.get("model_name"))
            self._schema = schema
            layer_names = schema.layer_names
            size = self.get("image_size") or schema.image_size

            def apply_fn(vs: Any, x: Any) -> Any:
                return module.apply(vs, x, train=False)

        cut = self.get("cut_output_layers")
        if not 0 <= cut < len(layer_names):
            raise ValueError(
                f"cut_output_layers={cut} out of range for layers {layer_names}"
            )
        node = layer_names[cut]
        bgr = self.get("bgr_input")

        def full_fn(vs: Any, x: Any) -> Any:
            # x: (N,H,W,C) float32 raw pixels 0..255; entire preprocess is
            # inside the jitted program so it fuses with the backbone
            if bgr:
                x = image_ops.bgr_to_rgb(x)
            x = image_ops.resize(x, size, size)
            x = image_ops.normalize(x)
            out = apply_fn(vs, x)
            return out[node] if isinstance(out, dict) else out

        self._inner = XLAModel(
            input_col="__pixels__",
            output_col=self.get_or_fail("output_col"),
            batch_size=self.get("batch_size"),
            # keep host dtype: uint8 pixel batches transfer 4x less and the
            # program's leading resize casts to f32 on device anyway
            input_dtype=None,
        )
        self._inner.set(apply_fn=full_fn, variables=variables)
        return self._inner

    # -- host-side image coercion -------------------------------------------

    def _coerce_images(self, col: np.ndarray) -> tuple:
        """image structs / bytes / dense tensors -> ((N,H,W,C) float32, keep mask)."""
        if col.dtype != object:
            # uint8 pixel tensors stay uint8 (device-side cast; cheaper copy)
            x = col if col.dtype == np.uint8 else col.astype(np.float32)
            if x.ndim == 2:  # unrolled vectors: roll back using model size
                size = self.get("image_size") or (
                    self._schema.image_size if self._schema else 224
                )
                # unrolled layout is always reference CHW/BGR. With
                # bgr_input=False, convert to RGB here (roll bgr=True);
                # with bgr_input=True keep BGR planes (roll bgr=False) so
                # full_fn's single bgr_to_rgb flip lands on RGB — never two.
                x = np.asarray(
                    image_ops.roll(
                        jnp.asarray(x), size, size, bgr=not self.get("bgr_input")
                    )
                )
            return x, np.ones(len(x), bool)
        rows = []
        for r in col:
            if isinstance(r, (bytes, bytearray)):
                arr = image_ops.decode_image(bytes(r))
            elif r is None:
                arr = None
            else:
                arr = image_row_to_array(r)
            rows.append(arr)
        keep = np.array([a is not None for a in rows], dtype=bool)
        if not keep.all() and not self.get("drop_na"):
            raise ValueError("undecodable image rows present and drop_na=False")
        good = [np.asarray(a) for a in rows if a is not None]
        if not good:
            return np.zeros((0, 1, 1, 3), np.float32), keep
        # decoded JPEG/PNG arrive uint8 — keep them uint8 so the batch ships
        # to the device at 1 byte/px (the program casts on device)
        if all(a.dtype == np.uint8 for a in good):
            return np.stack(good), keep
        return np.stack([a.astype(np.float32) for a in good]), keep

    def pipeline_io(self) -> tuple:
        """Column deps for the pipeline compiler."""
        return (self.get_or_fail("input_col"),), (self.get_or_fail("output_col"),)

    @property
    def pipeline_row_preserving(self) -> bool:
        # drop_na may remove undecodable rows at runtime (object inputs
        # only) — the scheduler must not reorder branches around that
        return not self.get("drop_na")

    def fusable_kernel(self) -> Any:
        """Fusable for dense (N,H,W,C) pixel batches: the whole
        preprocess+backbone program (already one jitted fn in the staged
        path) traces into the fused segment with the weights as constants.
        Object columns (bytes/structs needing host decode) and unrolled
        2-D layouts guard-fall back to the staged path.

        ``exact_capable=False``: convolution lowerings are not bit-stable
        across batch shapes, so exact-mode compilation (the default) keeps
        this stage host-bound; ``compile(exact=False)`` fuses the backbone
        into the segment at allclose-level equality."""
        from mmlspark_tpu.compiler.kernels import StageKernel

        ic = self.get_or_fail("input_col")
        oc = self.get_or_fail("output_col")
        inner = self._build()
        apply_fn = inner.get_or_fail("apply_fn")
        variables = inner.get_or_fail("variables")

        def fn(cols: dict) -> dict:
            return {oc: apply_fn(variables, cols[ic])}

        def guard(cols: dict) -> Any:
            a = np.asarray(cols.get(ic))
            if a.dtype == object:
                return "object image column (host decode path)"
            if a.ndim != 4:
                return f"image column ndim={a.ndim} (unrolled host path)"
            return None

        return StageKernel(reads=(ic,), writes=(oc,), fn=fn, guard=guard,
                           cost_hint=20.0, exact_capable=False)

    def transform(self, df: DataFrame) -> DataFrame:
        ic = self.get_or_fail("input_col")
        inner = self._build()

        def fn(p: Partition) -> Partition:
            x, keep = self._coerce_images(p[ic])
            feats = inner.apply_batch(x) if len(x) else np.zeros((0, 1), np.float32)
            q = dict(p)
            if not keep.all():  # undecodable rows dropped from every column
                q = {k: v[keep] for k, v in p.items()}
            q[self.get_or_fail("output_col")] = feats
            return q

        return df.map_partitions(fn, parallel=False)
