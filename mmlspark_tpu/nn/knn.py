"""KNN / ConditionalKNN pipeline stages.

Reference semantics (nn/KNN.scala, nn/ConditionalKNN.scala:68-102): ``fit``
captures the dataset (features + payload values + labels); the model
broadcasts the index and answers per-row top-k max-inner-product queries,
emitting an array of ``{value, distance[, label]}`` structs.

TPU-first: the index lives on device as one dense (N, d) matrix; a query
batch is a single ``scores = Q @ X.T`` matmul (MXU) + ``lax.top_k``. The
conditional variant masks scores with a per-row allowed-label mask before
top_k — branchless, so the whole batch stays one compiled program. When N
exceeds ``index_chunk_size`` the index is processed in chunks whose per-chunk
top-k results are merged by a final top-k, bounding the live (B, N) score
matrix in HBM. ``algorithm='balltree'`` falls back to the exact host tree
(mmlspark_tpu.nn.balltree).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, HasFeaturesCol, HasLabelCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.nn.balltree import BallTree, ConditionalBallTree

_NEG_INF = np.float32(-np.inf)


@partial(jax.jit, static_argnums=(2,))
def _topk_scores(q: jnp.ndarray, x: jnp.ndarray, k: int) -> tuple:
    scores = q @ x.T
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnums=(3,))
def _topk_scores_masked(q: jnp.ndarray, x: jnp.ndarray, mask: jnp.ndarray, k: int) -> tuple:
    scores = jnp.where(mask, q @ x.T, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _chunked_topk(
    q: np.ndarray, x: np.ndarray, k: int, chunk: int, mask: Optional[np.ndarray] = None
) -> tuple:
    """Top-k over the index in chunks; merges chunk winners with a final
    top-k so only (B, chunk) scores are ever live on device."""
    qd = jnp.asarray(q)
    all_sc, all_ix = [], []
    for lo in range(0, len(x), chunk):
        xc = jnp.asarray(x[lo : lo + chunk])
        kc = min(k, len(x[lo : lo + chunk]))
        if mask is None:
            sc, ix = _topk_scores(qd, xc, kc)
        else:
            sc, ix = _topk_scores_masked(qd, xc, jnp.asarray(mask[:, lo : lo + chunk]), kc)
        all_sc.append(np.asarray(sc))
        all_ix.append(np.asarray(ix) + lo)
    sc = np.concatenate(all_sc, axis=1)
    ix = np.concatenate(all_ix, axis=1)
    order = np.argsort(-sc, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(sc, order, 1), np.take_along_axis(ix, order, 1)


class _KNNParams(HasFeaturesCol, HasOutputCol):
    values_col = Param("payload column returned with each match", default="values")
    k = Param("number of matches", default=5, type_=int, validator=lambda v: v > 0)
    leaf_size = Param("ball tree leaf size (host algorithm)", default=50, type_=int)
    index_chunk_size = Param(
        "max index rows scored per device call (bounds HBM)", default=1 << 20, type_=int
    )
    algorithm = Param(
        "'brute' = device matmul top-k; 'balltree' = exact host tree",
        default="brute",
        validator=lambda v: v in ("brute", "balltree"),
    )

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "output_col" not in self._paramMap:
            self.set(output_col="matches")


class _HasConditionerCol(HasLabelCol):
    conditioner_col = Param("column of per-row allowed-label collections", default="conditioner")


class KNN(Estimator, _KNNParams):
    """Fit = capture the index; see module docstring."""

    def fit(self, df: DataFrame) -> "KNNModel":
        feats = np.asarray(df[self.get("features_col")], np.float32)
        values = df[self.get("values_col")] if self.get("values_col") in df.columns else None
        m = KNNModel(**{k: v for k, v in self._paramMap.items()})
        m.set(index_features=feats)
        if values is not None:
            m.set(index_values=np.asarray(values))
        return m


class KNNModel(Model, _KNNParams):
    index_features = ComplexParam("(N, d) index matrix")
    index_values = ComplexParam("(N,) payload values", default=None)

    _tree_cache: Any = None  # (conditional_flag, tree); cleared whenever index params change

    def set(self, *args: Any, **kw: Any) -> Any:
        names = set(kw)
        if args:
            names.add(args[0])
        if names & {"index_features", "index_labels", "leaf_size"}:
            self._tree_cache = None
        return super().set(*args, **kw)

    def _tree(self, conditional: bool = False) -> Any:
        x = self.get_or_fail("index_features")
        if self._tree_cache is None or self._tree_cache[0] != conditional:
            if conditional:
                tree = ConditionalBallTree(
                    x, self.get_or_fail("index_labels"), self.get("leaf_size")
                )
            else:
                tree = BallTree(x, self.get("leaf_size"))
            self._tree_cache = (conditional, tree)
        return self._tree_cache[1]

    def _query(self, q: np.ndarray, k: int) -> tuple:
        """Return (scores, indices) each (B, k)."""
        x = self.get_or_fail("index_features")
        k = min(k, len(x))
        if len(q) == 0 or k == 0:
            return np.zeros((len(q), 0), np.float32), np.zeros((len(q), 0), np.int64)
        if self.get("algorithm") == "balltree":
            tree = self._tree()
            idx = np.zeros((len(q), k), np.int64)
            sc = np.zeros((len(q), k), np.float32)
            for i, row in enumerate(q):
                ms = tree.find_maximum_inner_products(row, k)
                idx[i] = [m.index for m in ms]
                sc[i] = [m.distance for m in ms]
            return sc, idx
        return _chunked_topk(q, x, k, self.get("index_chunk_size"))

    def _emit(self, df: DataFrame, scores: Any, indices: Any, labels: Any = None) -> DataFrame:
        values = self.get("index_values")
        out = np.empty(len(scores), dtype=object)
        for i, (sc, ix) in enumerate(zip(scores, indices)):
            row = []
            for s, j in zip(sc, ix):
                if not np.isfinite(s):
                    continue  # masked-out candidate (conditional variant)
                match = {"distance": float(s), "index": int(j)}
                if values is not None:
                    match["value"] = values[j]
                if labels is not None:
                    match["label"] = labels[j]
                row.append(match)
            out[i] = row
        return df.with_column(self.get("output_col"), out)

    def transform(self, df: DataFrame) -> DataFrame:
        q = np.asarray(df[self.get("features_col")], np.float32)
        scores, indices = self._query(q, self.get("k"))
        return self._emit(df, scores, indices)


class ConditionalKNN(Estimator, _KNNParams, _HasConditionerCol):
    """KNN whose queries restrict candidates to per-row allowed labels
    (ConditionalKNN.scala:68-102)."""

    def fit(self, df: DataFrame) -> "ConditionalKNNModel":
        feats = np.asarray(df[self.get("features_col")], np.float32)
        labels = np.asarray(df[self.get("label_col")])
        m = ConditionalKNNModel(**{k: v for k, v in self._paramMap.items()})
        m.set(index_features=feats, index_labels=labels)
        if self.get("values_col") in df.columns:
            m.set(index_values=np.asarray(df[self.get("values_col")]))
        return m


class ConditionalKNNModel(KNNModel, _HasConditionerCol):
    index_labels = ComplexParam("(N,) index labels")

    def transform(self, df: DataFrame) -> DataFrame:
        q = np.asarray(df[self.get("features_col")], np.float32)
        labels = self.get_or_fail("index_labels")
        x = self.get_or_fail("index_features")
        k = min(self.get("k"), len(x))
        if len(q) == 0 or k == 0:
            return self._emit(
                df,
                np.zeros((len(q), 0), np.float32),
                np.zeros((len(q), 0), np.int64),
                labels=labels,
            )
        conditioners = df[self.get("conditioner_col")]

        if self.get("algorithm") == "balltree":
            tree = self._tree(conditional=True)
            scores = np.full((len(q), k), _NEG_INF, np.float32)
            indices = np.zeros((len(q), k), np.int64)
            for i, row in enumerate(q):
                ms = tree.find_maximum_inner_products(row, k, conditioners[i])
                for j, m in enumerate(ms):
                    scores[i, j], indices[i, j] = m.distance, m.index
        else:
            mask = np.stack([np.isin(labels, np.asarray(list(c))) for c in conditioners])
            scores, indices = _chunked_topk(q, x, k, self.get("index_chunk_size"), mask)
        return self._emit(df, scores, indices, labels=labels)
