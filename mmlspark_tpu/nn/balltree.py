"""Serializable ball tree with max-inner-product search.

Parity target: the reference's in-JVM ``BallTree``/``ConditionalBallTree``
(nn/BallTree.scala:32-99) — exact top-k by inner product, with the
conditional variant restricting candidates to an allowed label set.

Construction splits on the direction between two approximately-farthest
points (median projection), giving balanced leaves; search is
best-first with the standard MIP bound ``q·c + |q|·r`` per ball.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np


@dataclass
class BestMatch:
    """One search hit: index into the fitted data, inner-product score."""

    index: int
    distance: float
    value: Any = None
    label: Any = None


class _Node:
    __slots__ = ("center", "radius", "lo", "hi", "left", "right")

    def __init__(self, center: np.ndarray, radius: float, lo: int, hi: int):
        self.center = center
        self.radius = radius
        self.lo = lo  # [lo, hi) range into the permuted point array
        self.hi = hi
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class BallTree:
    """Exact max-inner-product ball tree over dense vectors."""

    def __init__(self, points: np.ndarray, leaf_size: int = 50):
        points = np.asarray(points, np.float32)
        if points.ndim != 2:
            raise ValueError(f"points must be (n, d), got {points.shape}")
        self.leaf_size = int(leaf_size)
        self.perm = np.arange(len(points))
        self.points = points.copy()
        self.root = self._build(0, len(points)) if len(points) else None

    # -- construction --------------------------------------------------------

    def _make_node(self, lo: int, hi: int) -> _Node:
        pts = self.points[lo:hi]
        center = pts.mean(axis=0)
        radius = float(np.sqrt(((pts - center) ** 2).sum(-1)).max()) if len(pts) else 0.0
        return _Node(center, radius, lo, hi)

    def _build(self, lo: int, hi: int) -> _Node:
        node = self._make_node(lo, hi)
        if hi - lo <= self.leaf_size:
            return node
        pts = self.points[lo:hi]
        # two-step farthest-point heuristic for the split direction
        a = pts[int(np.argmax(((pts - pts[0]) ** 2).sum(-1)))]
        b = pts[int(np.argmax(((pts - a) ** 2).sum(-1)))]
        proj = pts @ (b - a)
        order = np.argsort(proj, kind="stable")
        mid = (hi - lo) // 2
        take = lo + order
        self.points[lo:hi] = self.points[take]
        self.perm[lo:hi] = self.perm[take]
        node.left = self._build(lo, lo + mid)
        node.right = self._build(lo + mid, hi)
        return node

    # -- search --------------------------------------------------------------

    def _search(
        self, query: np.ndarray, k: int, allowed: Optional[np.ndarray] = None
    ) -> list[BestMatch]:
        if self.root is None or k <= 0:
            return []
        q = np.asarray(query, np.float32)
        qnorm = float(np.linalg.norm(q))
        best: list[tuple[float, int]] = []  # min-heap of (score, original index)

        def bound(node: _Node) -> float:
            return float(q @ node.center) + qnorm * node.radius

        heap = [(-bound(self.root), 0, self.root)]
        tiebreak = 1
        while heap:
            neg_ub, _, node = heapq.heappop(heap)
            if len(best) == k and -neg_ub <= best[0][0]:
                continue  # this ball cannot beat the current k-th best
            if node.left is None:  # leaf
                idx = slice(node.lo, node.hi)
                scores = self.points[idx] @ q
                orig = self.perm[idx]
                if allowed is not None:
                    keep = allowed[orig]
                    scores, orig = scores[keep], orig[keep]
                for s, i in zip(scores, orig):
                    if len(best) < k:
                        heapq.heappush(best, (float(s), int(i)))
                    elif s > best[0][0]:
                        heapq.heapreplace(best, (float(s), int(i)))
            else:
                for child in (node.left, node.right):
                    ub = bound(child)
                    if len(best) < k or ub > best[0][0]:
                        heapq.heappush(heap, (-ub, tiebreak, child))
                        tiebreak += 1
        best.sort(key=lambda t: -t[0])
        return [BestMatch(index=i, distance=s) for s, i in best]

    def find_maximum_inner_products(self, query: np.ndarray, k: int = 1) -> list[BestMatch]:
        return self._search(query, k)

    # -- persistence ---------------------------------------------------------

    def __getstate__(self) -> dict:
        # the tree is cheap to rebuild relative to (de)serializing node objects
        return {
            "points": self.points[np.argsort(self.perm)],
            "leaf_size": self.leaf_size,
        }

    def __setstate__(self, state: dict) -> None:
        self.__init__(state["points"], state["leaf_size"])


class ConditionalBallTree(BallTree):
    """Ball tree whose queries are restricted to an allowed set of labels
    (nn/ConditionalBallTree in the reference)."""

    def __init__(self, points: np.ndarray, labels: Sequence[Any], leaf_size: int = 50):
        if len(points) != len(labels):
            raise ValueError("points and labels must align")
        self.labels = np.asarray(labels)
        super().__init__(points, leaf_size)

    def find_maximum_inner_products(
        self, query: np.ndarray, k: int = 1, conditioner: Optional[Sequence[Any]] = None
    ) -> list[BestMatch]:
        allowed = None
        if conditioner is not None:
            allowed = np.isin(self.labels, np.asarray(list(conditioner)))
        out = self._search(query, k, allowed)
        for m in out:
            m.label = self.labels[m.index]
        return out

    def __getstate__(self) -> dict:
        state = super().__getstate__()
        state["labels"] = self.labels  # kept in original order (never permuted)
        return state

    def __setstate__(self, state: dict) -> None:
        self.__init__(state["points"], state["labels"], state["leaf_size"])
