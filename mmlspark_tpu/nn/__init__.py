"""Nearest-neighbor search (reference: nn/, SURVEY.md §2.13).

TPU-first design: the hot query path is a brute-force max-inner-product
matmul + ``lax.top_k`` on device (the MXU eats the (B, N) score matrix the
reference's JVM ball tree walks pointer-by-pointer). A serializable host
:class:`BallTree` / :class:`ConditionalBallTree` is kept for exact parity
with the reference's data structure (BallTree.scala:32-99) and for hosts
without an accelerator.
"""

from mmlspark_tpu.nn.balltree import BallTree, BestMatch, ConditionalBallTree
from mmlspark_tpu.nn.knn import KNN, ConditionalKNN, ConditionalKNNModel, KNNModel

__all__ = [
    "BallTree",
    "ConditionalBallTree",
    "BestMatch",
    "KNN",
    "KNNModel",
    "ConditionalKNN",
    "ConditionalKNNModel",
]
