"""SummarizeData — per-column statistics DataFrame (stages/SummarizeData.scala)."""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer


class SummarizeData(Transformer):
    counts = Param("include count stats", default=True, type_=bool)
    basic = Param("include basic stats", default=True, type_=bool)
    sample = Param("include sample stats (quantiles)", default=True, type_=bool)
    percentiles = Param("include percentile stats", default=True, type_=bool)
    error_threshold = Param("API parity; exact quantiles are used", default=0.0, type_=float)

    def transform(self, df: DataFrame) -> DataFrame:
        rows = []
        data = df.to_dict()
        n = df.count()
        for name, col in data.items():
            row: dict = {"Feature": name}
            if self.get("counts"):
                row["Count"] = float(n)
                if col.dtype == object:
                    row["Unique Value Count"] = float(len(set(map(str, col))))
                    row["Missing Value Count"] = float(sum(v is None for v in col))
                else:
                    flat = col.reshape(n, -1) if col.ndim > 1 else col
                    row["Unique Value Count"] = (
                        float(len(np.unique(flat))) if col.ndim == 1 else float("nan")
                    )
                    row["Missing Value Count"] = (
                        float(np.isnan(flat).any(axis=-1).sum())
                        if np.issubdtype(col.dtype, np.floating)
                        else 0.0
                    )
            if col.dtype != object and np.issubdtype(col.dtype, np.number) and col.ndim == 1:
                c = col.astype(np.float64)
                c = c[~np.isnan(c)]
                if self.get("basic") and len(c):
                    row.update(
                        {
                            "Max": float(c.max()),
                            "Min": float(c.min()),
                            "Mean": float(c.mean()),
                            "Variance": float(c.var(ddof=1)) if len(c) > 1 else 0.0,
                        }
                    )
                if self.get("sample") and len(c):
                    row["Sample Variance"] = row.get("Variance", 0.0)
                    row["Sample Standard Deviation"] = float(np.sqrt(row.get("Variance", 0.0)))
                    row["Sample Skewness"] = _skew(c)
                    row["Sample Kurtosis"] = _kurt(c)
                if self.get("percentiles") and len(c):
                    for q in (0.5, 1, 5, 25, 50, 75, 95, 99, 99.5):
                        row[f"P{q}"] = float(np.percentile(c, q))
                    row["Median"] = float(np.median(c))
            rows.append(row)
        keys: list = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(k)
        filled = [{k: r.get(k, float("nan")) for k in keys} for r in rows]
        return DataFrame.from_rows(filled)


def _skew(c: np.ndarray) -> float:
    if len(c) < 2 or c.std() == 0:
        return 0.0
    z = (c - c.mean()) / c.std()
    return float((z ** 3).mean())


def _kurt(c: np.ndarray) -> float:
    if len(c) < 2 or c.std() == 0:
        return 0.0
    z = (c - c.mean()) / c.std()
    return float((z ** 4).mean() - 3.0)
