"""Generic dataflow stages (reference ``stages/`` package, SURVEY.md §2.10).

Column plumbing, UDF stages, repartitioners, caching and timing — the thin
host-side stages that glue TPU compute stages into pipelines.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition, Row
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasInputCol,
    HasOutputCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer

log = logging.getLogger("mmlspark_tpu")


class DropColumns(Transformer):
    """stages/DropColumns.scala analogue."""

    cols = Param("columns to drop", default=[], type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        return df.drop(*self.get("cols"))


class SelectColumns(Transformer):
    cols = Param("columns to keep", default=[], type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        return df.select(*self.get("cols"))


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    # removes its input column — column-level deps can't express that, so
    # the pipeline compiler must plan it as a barrier
    pipeline_opaque = True

    def transform(self, df: DataFrame) -> DataFrame:
        return df.rename({self.get_or_fail("input_col"): self.get_or_fail("output_col")})


class Repartition(Transformer):
    """stages/Repartition.scala analogue."""

    n = Param("target partition count", default=1, type_=int)
    disable = Param("no-op switch", default=False, type_=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        if self.get("disable"):
            return df
        return df.repartition(self.get("n"))


class Lambda(Transformer):
    """Arbitrary DataFrame -> DataFrame function as a stage
    (stages/Lambda.scala:21-36). The callable persists via cloudpickle."""

    transform_fn = ComplexParam("DataFrame -> DataFrame function")
    transform_schema_fn = ComplexParam("optional Schema -> Schema function")

    @staticmethod
    def of(fn: Callable[[DataFrame], DataFrame]) -> "Lambda":
        t = Lambda()
        t.set(transform_fn=fn)
        return t

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_fail("transform_fn")(df)

    def transform_schema(self, schema: Any) -> Any:
        fn = self.get("transform_schema_fn")
        return fn(schema) if fn else schema


class UDFTransformer(Transformer, HasInputCol, HasOutputCol):
    """Column UDF stage (stages/UDFTransformer.scala analogue).

    ``udf`` maps one row value -> value; ``vector_udf`` maps the whole
    column array -> array (preferred: it can be vectorized/jitted)."""

    udf = ComplexParam("per-row function")
    vector_udf = ComplexParam("whole-column function (array -> array)")
    input_cols = Param("multiple input columns (passed as dict to udf)", type_=list)
    jit_compatible = Param(
        "author-declared: vector_udf is a pure jnp-traceable row-wise "
        "array fn. The staged path then runs it under jax.jit and the "
        "pipeline compiler may fuse it into adjacent stages (both sides "
        "trace the identical ops, so compiled output stays element-wise "
        "equal)", default=False, type_=bool,
    )

    def transform(self, df: DataFrame) -> DataFrame:
        oc = self.get_or_fail("output_col")
        vec = self.get("vector_udf")
        cols = self.get("input_cols")
        if vec is not None:
            ic = self.get_or_fail("input_col")
            if self.get("jit_compatible"):
                import jax

                # cache per udf object: a fresh jax.jit wrapper would
                # retrace on every transform call
                cached = getattr(self, "_jitted_udf", None)
                if cached is None or cached[0] is not vec:
                    cached = self._jitted_udf = (vec, jax.jit(vec))
                jitted = cached[1]
                return df.with_column(
                    oc, lambda p: np.asarray(jitted(np.asarray(p[ic])))
                )
            return df.with_column(oc, lambda p: vec(p[ic]))
        fn = self.get_or_fail("udf")
        if cols:
            return df.with_row_column(oc, lambda r: fn(**{c: r[c] for c in cols}))
        ic = self.get_or_fail("input_col")
        return df.with_row_column(oc, lambda r: fn(r[ic]))

    def fusable_kernel(self) -> Any:
        """Fusable only when the author set ``jit_compatible`` on a
        ``vector_udf`` (the fusability contract: pure, jit-traceable,
        row-independent along axis 0)."""
        if not self.get("jit_compatible"):
            return None
        vec = self.get("vector_udf")
        if vec is None:
            return None
        from mmlspark_tpu.compiler.kernels import StageKernel, guard_f32_safe

        ic = self.get_or_fail("input_col")
        oc = self.get_or_fail("output_col")

        def fn(cols: dict) -> dict:
            return {oc: vec(cols[ic])}

        return StageKernel(reads=(ic,), writes=(oc,), fn=fn,
                           guard=guard_f32_safe, cost_hint=0.2)


class Explode(Transformer, HasInputCol, HasOutputCol):
    """Explode an array column into one row per element."""

    # rewrites every column's rows — a planner barrier, not a column dep
    pipeline_opaque = True

    def transform(self, df: DataFrame) -> DataFrame:
        ic = self.get_or_fail("input_col")
        oc = self.get("output_col") or ic

        def fn(p: Partition) -> Partition:
            col = p[ic]
            lens = np.array([len(v) for v in col])
            idx = np.repeat(np.arange(len(col)), lens)
            out = {k: v[idx] for k, v in p.items() if k != ic or oc != ic}
            flat = np.concatenate([np.asarray(v) for v in col]) if len(col) else np.array([])
            out[oc] = flat
            return out

        return df.map_partitions(fn)


class Cacher(Transformer):
    """stages/Cacher.scala analogue. The DataFrame substrate is eager, so
    caching == materializing once; this stage is a marker/no-op that also
    coalesces object columns for cheap re-iteration."""

    disable = Param("no-op switch", default=False, type_=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        return df


class Timer(Transformer):
    """Wraps a stage and logs wall time per fit/transform
    (stages/Timer.scala:57-92)."""

    stage = ComplexParam("wrapped stage")
    log_to_scala = Param("kept for API parity; logs via python logging", default=True, type_=bool)
    disable_timer = Param("bypass timing", default=False, type_=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        inner = self.get_or_fail("stage")
        if self.get("disable_timer"):
            return inner.transform(df)
        t0 = time.perf_counter()
        out = inner.transform(df)
        log.info("%s.transform took %.3fs", type(inner).__name__, time.perf_counter() - t0)
        return out

    def fit(self, df: DataFrame) -> Any:
        inner = self.get_or_fail("stage")
        if isinstance(inner, Estimator):
            t0 = time.perf_counter()
            model = inner.fit(df)
            log.info("%s.fit took %.3fs", type(inner).__name__, time.perf_counter() - t0)
            wrapped = Timer()
            wrapped.set(stage=model, disable_timer=self.get("disable_timer"))
            return wrapped
        return self


# -- udfs.scala analogues ---------------------------------------------------


def get_value_at(col: np.ndarray, i: int) -> np.ndarray:
    """Vector column -> scalar column of element i (udfs.scala get_value_at)."""
    return np.asarray(col)[:, i]


def to_vector(col: np.ndarray) -> np.ndarray:
    """Array-of-list column -> dense 2D vector column (udfs.scala to_vector)."""
    return np.stack([np.asarray(v, dtype=np.float32) for v in col])
