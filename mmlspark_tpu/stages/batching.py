"""Minibatching stages (stages/MiniBatchTransformer.scala:14-204).

On TPU, fixed-shape batching is *load bearing*: every distinct batch shape
is a separate XLA compilation. A "batched" DataFrame here is one where each
row holds an array of the original values (dense columns become one-higher-
rank tensors; object columns become object arrays of arrays). ``FlattenBatch``
is the inverse.

``DynamicBufferedBatcher``/``TimeIntervalBatcher`` (Batchers.scala) matter
for streaming/serving where arrival time dictates batch boundaries; the
serving layer reuses ``TimeIntervalMiniBatchTransformer`` semantics.
"""

from __future__ import annotations

import time
from typing import Any, Iterator, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import HasBatchSize, Param
from mmlspark_tpu.core.pipeline import Transformer


def _batch_partition(p: Partition, sizes: Iterator[int]) -> Partition:
    n = len(next(iter(p.values()))) if p else 0
    bounds = [0]
    for s in sizes:
        if bounds[-1] >= n:
            break
        bounds.append(min(n, bounds[-1] + s))
    if bounds[-1] < n:
        bounds.append(n)
    out: Partition = {}
    for k, v in p.items():
        chunks = [v[bounds[i]: bounds[i + 1]] for i in range(len(bounds) - 1)]
        arr = np.empty(len(chunks), dtype=object)
        for i, c in enumerate(chunks):
            arr[i] = c
        out[k] = arr
    return out


class FixedMiniBatchTransformer(Transformer, HasBatchSize):
    """Group every ``batch_size`` rows into one batch row."""

    max_buffer_size = Param("API parity; unused (eager substrate)", default=2147483647, type_=int)
    buffered = Param("API parity; unused", default=False, type_=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        bs = self.get("batch_size")

        def sizes() -> Iterator[int]:
            while True:
                yield bs

        return df.map_partitions(lambda p: _batch_partition(p, sizes()))


class DynamicMiniBatchTransformer(Transformer):
    """One batch per partition (the dynamic batcher degenerates to
    'whatever is buffered now' — in the eager substrate that is the whole
    partition; max_batch_size caps it)."""

    max_batch_size = Param("maximum rows per batch", default=2147483647, type_=int)

    def transform(self, df: DataFrame) -> DataFrame:
        mx = self.get("max_batch_size")

        def sizes() -> Iterator[int]:
            while True:
                yield mx

        return df.map_partitions(lambda p: _batch_partition(p, sizes()))


class TimeIntervalMiniBatchTransformer(Transformer):
    """Batch by arrival-time windows (TimeIntervalBatcher analogue).

    Batch dataframes have no arrival times; rows carrying a ``millis_col``
    timestamp column are grouped into ``interval_ms`` windows. The serving
    layer uses the same windowing against wall-clock arrival."""

    interval_ms = Param("window length in ms", default=1000, type_=int)
    millis_col = Param("timestamp column (ms)", type_=str)
    max_batch_size = Param("cap rows per batch", default=2147483647, type_=int)

    def transform(self, df: DataFrame) -> DataFrame:
        tcol = self.get("millis_col")
        iv = self.get("interval_ms")
        mx = self.get("max_batch_size")

        def fn(p: Partition) -> Partition:
            if not p:
                return p
            n = len(next(iter(p.values())))
            if tcol and tcol in p:
                t = np.asarray(p[tcol], dtype=np.int64)
                window = (t - t.min()) // iv
            else:
                window = np.zeros(n, dtype=np.int64)
            sizes = []
            for w in np.unique(window):
                c = int((window == w).sum())
                while c > 0:
                    sizes.append(min(c, mx))
                    c -= mx
            order = np.argsort(window, kind="stable")
            q = {k: v[order] for k, v in p.items()}
            return _batch_partition(q, iter(sizes))

        return df.map_partitions(fn)


class FlattenBatch(Transformer):
    """Inverse of the minibatchers (MiniBatchTransformer.scala FlattenBatch)."""

    def transform(self, df: DataFrame) -> DataFrame:
        def fn(p: Partition) -> Partition:
            if not p:
                return p
            out: Partition = {}
            for k, v in p.items():
                if v.dtype == object:
                    parts = [np.asarray(x) for x in v]
                    out[k] = (
                        np.concatenate(parts, axis=0) if parts else np.array([])
                    )
                else:  # already-dense batched tensor: merge first two dims
                    out[k] = v.reshape(-1, *v.shape[2:])
            return out

        return df.map_partitions(fn)


class HasMiniBatcher(Transformer):
    """Mixin param carrying a batcher stage (HasMiniBatcher analogue)."""

    from mmlspark_tpu.core.params import ComplexParam as _CP

    mini_batcher = _CP("batcher stage to apply before this stage")
