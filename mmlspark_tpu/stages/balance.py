"""Label-aware partitioning and balancing stages.

- StratifiedRepartition (StratifiedRepartition.scala:44-73): spread every
  label evenly across partitions so gang-scheduled trainers see all classes.
- ClassBalancer: inverse-frequency instance weights.
- EnsembleByKey (EnsembleByKey.scala): aggregate vector/scalar columns by key.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import HasInputCol, HasLabelCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer


class StratifiedRepartition(Transformer, HasLabelCol):
    n = Param("target partition count", default=2, type_=int)
    mode = Param("equal | original | mixed", default="equal", type_=str)
    seed = Param("shuffle seed", default=0, type_=int)

    def transform(self, df: DataFrame) -> DataFrame:
        cols = df.to_dict()
        y = cols[self.get("label_col")]
        n = self.get("n")
        rng = np.random.default_rng(self.get("seed"))
        # round-robin rows of each class over partitions => every partition
        # sees every class (the reference uses a range partitioner on
        # label-grouped keys to the same end)
        assign = np.zeros(len(y), dtype=np.int64)
        for label in np.unique(y.astype(str) if y.dtype == object else y):
            mask = (y.astype(str) if y.dtype == object else y) == label
            idx = np.flatnonzero(mask)
            rng.shuffle(idx)
            assign[idx] = np.arange(len(idx)) % n
        parts = []
        for i in range(n):
            m = assign == i
            parts.append({k: v[m] for k, v in cols.items()})
        return DataFrame(parts)


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Inverse-frequency weights (stages/ClassBalancer.scala)."""

    broadcast_join = Param("API parity; unused", default=True, type_=bool)
    output_col = Param("weight output column", default="weight", type_=str)

    def fit(self, df: DataFrame) -> "ClassBalancerModel":
        y = df[self.get_or_fail("input_col")]
        key = y.astype(str) if y.dtype == object else y
        uniq, counts = np.unique(key, return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        m = ClassBalancerModel(
            input_col=self.get("input_col"), output_col=self.get("output_col")
        )
        m.set(levels=[str(u) for u in uniq], weights=weights.tolist())
        return m


class ClassBalancerModel(Model, HasInputCol, HasOutputCol):
    levels = Param("class levels", type_=list)
    weights = Param("weight per level", type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        table = dict(zip(self.get("levels"), self.get("weights")))

        def fn(p: Partition) -> Any:
            y = p[self.get_or_fail("input_col")]
            return np.array([table[str(v)] for v in y], dtype=np.float64)

        return df.with_column(self.get("output_col"), fn)


class EnsembleByKey(Transformer):
    """Aggregate columns by key (stages/EnsembleByKey.scala): strategy
    'mean' averages scalar/vector columns; collapse to one row per key."""

    keys = Param("key columns", default=[], type_=list)
    cols = Param("value columns to aggregate", default=[], type_=list)
    col_names = Param("output names (defaults to value names)", default=[], type_=list)
    strategy = Param("mean", default="mean", type_=str)
    collapse_group = Param("one row per key (else broadcast back)", default=True, type_=bool)
    vector_dims = Param("API parity; unused", default={}, type_=dict)

    def transform(self, df: DataFrame) -> DataFrame:
        keys = self.get("keys")
        cols = self.get("cols")
        names = self.get("col_names") or cols
        if self.get("strategy") != "mean":
            raise ValueError("only 'mean' strategy is supported (as in the reference)")
        if len(keys) != 1:
            # composite keys: synthesize a single key column
            data = df.to_dict()
            combo = np.array(
                ["".join(str(data[k][i]) for k in keys) for i in range(df.count())],
                dtype=object,
            )
            df = df.with_column("__key__", combo)
            key = "__key__"
        else:
            key = keys[0]

        def agg(kv: Any, grp: Partition) -> dict:
            row = {key: kv}
            for k in keys:
                row[k] = grp[k][0]
            for c, nm in zip(cols, names):
                row[nm] = np.asarray(grp[c], dtype=np.float64).mean(axis=0)
            return row

        out = df.group_apply(key, agg)
        if self.get("collapse_group"):
            return out.drop("__key__") if key == "__key__" else out
        # broadcast aggregated values back onto original rows (keyed on the
        # same — possibly synthesized — key column on both sides)
        ldata = out.to_dict()
        index = {str(v): i for i, v in enumerate(ldata[key])}
        kcol = df[key]
        for c, nm in zip(cols, names):
            vals = np.asarray(ldata[nm])
            picked = vals[[index[str(v)] for v in kcol]]
            df = df.with_column(nm, picked)
        return df.drop("__key__") if key == "__key__" else df
