"""Column-adapter stages.

Three utility stages from the reference that round out the generic stage
toolkit:
- :class:`VectorZipper` — row-wise zip of columns into one array column
  (vw/VectorZipper.scala:14-35).
- :class:`FastVectorAssembler` — concatenate numeric/vector columns into a
  single dense features vector (org/apache/spark/ml/feature/
  FastVectorAssembler.scala; "fast" there = no per-slot metadata pass,
  which this columnar substrate never needed).
- :class:`MultiColumnAdapter` — fit/apply a single-column base stage to
  each of ``input_cols`` producing ``output_cols``
  (stages/MultiColumnAdapter.scala:19-90).
"""

from __future__ import annotations

import copy
from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasInputCols,
    HasOutputCol,
    HasOutputCols,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer


class VectorZipper(Transformer, HasInputCols, HasOutputCol):
    """Combine one or more input columns into a sequence output column."""

    def transform(self, df: DataFrame) -> DataFrame:
        cols = self.get_or_fail("input_cols")
        mats = [np.asarray(df[c]) for c in cols]
        kinds = {m.dtype.kind for m in mats}
        if len(kinds) > 1 and not kinds <= {"i", "f", "u", "b"}:
            # np.stack would silently stringify numerics; the reference
            # asserts identical column types (VectorZipper.scala:26-27)
            raise ValueError(
                f"VectorZipper input columns must share a type family, got "
                f"{[m.dtype.name for m in mats]}"
            )
        return df.with_column(
            self.get_or_fail("output_col"), np.stack(mats, axis=1)
        )


class FastVectorAssembler(Transformer, HasInputCols, HasOutputCol):
    """Assemble numeric scalar/vector columns into one dense vector."""

    def transform(self, df: DataFrame) -> DataFrame:
        cols = self.get_or_fail("input_cols")
        parts = []
        for c in cols:
            a = np.asarray(df[c], np.float64)
            parts.append(a[:, None] if a.ndim == 1 else a.reshape(len(a), -1))
        return df.with_column(
            self.get_or_fail("output_col"), np.concatenate(parts, axis=1)
        )


class _AdapterBase(HasInputCols, HasOutputCols):
    def _pairs(self) -> list:
        ins = self.get_or_fail("input_cols")
        outs = self.get_or_fail("output_cols")
        if len(ins) != len(outs):
            raise ValueError("input_cols and output_cols must align")
        return list(zip(ins, outs))


class MultiColumnAdapter(Estimator, _AdapterBase):
    """Fit a copy of ``base_stage`` per column; transformers pass through
    unfitted. The base stage must expose input_col/output_col params."""

    base_stage = ComplexParam("single-column stage applied per column")

    def fit(self, df: DataFrame) -> "MultiColumnAdapterModel":
        base = self.get_or_fail("base_stage")
        if "input_col" not in base.params() or "output_col" not in base.params():
            raise ValueError(
                "base_stage needs input_col/output_col params "
                "(MultiColumnAdapter.scala:31-40 contract)"
            )
        fitted = []
        for in_c, out_c in self._pairs():
            stage = copy.deepcopy(base)
            stage.set(input_col=in_c, output_col=out_c)
            fitted.append(stage.fit(df) if isinstance(stage, Estimator) else stage)
        m = MultiColumnAdapterModel(
            input_cols=self.get("input_cols"), output_cols=self.get("output_cols")
        )
        m.set(stages=fitted)
        return m


class MultiColumnAdapterModel(Model, _AdapterBase):
    stages = ComplexParam("per-column fitted stages")

    def transform(self, df: DataFrame) -> DataFrame:
        out = df
        for stage in self.get_or_fail("stages"):
            out = stage.transform(out)
        return out
