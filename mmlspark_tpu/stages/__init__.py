from mmlspark_tpu.stages.adapters import (
    FastVectorAssembler,
    MultiColumnAdapter,
    MultiColumnAdapterModel,
    VectorZipper,
)
from mmlspark_tpu.stages.basic import (
    Cacher,
    DropColumns,
    Explode,
    Lambda,
    RenameColumn,
    Repartition,
    SelectColumns,
    Timer,
    UDFTransformer,
    get_value_at,
    to_vector,
)
from mmlspark_tpu.stages.batching import (
    DynamicMiniBatchTransformer,
    FixedMiniBatchTransformer,
    FlattenBatch,
    TimeIntervalMiniBatchTransformer,
)
from mmlspark_tpu.stages.balance import (
    ClassBalancer,
    ClassBalancerModel,
    EnsembleByKey,
    StratifiedRepartition,
)
from mmlspark_tpu.stages.summarize import SummarizeData
from mmlspark_tpu.stages.text import TextPreprocessor, UnicodeNormalize

__all__ = [
    "VectorZipper",
    "MultiColumnAdapterModel",
    "MultiColumnAdapter",
    "FastVectorAssembler",
    "DropColumns",
    "SelectColumns",
    "RenameColumn",
    "Repartition",
    "Lambda",
    "UDFTransformer",
    "Explode",
    "Cacher",
    "Timer",
    "get_value_at",
    "to_vector",
    "FixedMiniBatchTransformer",
    "DynamicMiniBatchTransformer",
    "TimeIntervalMiniBatchTransformer",
    "FlattenBatch",
    "StratifiedRepartition",
    "ClassBalancer",
    "ClassBalancerModel",
    "EnsembleByKey",
    "SummarizeData",
    "TextPreprocessor",
    "UnicodeNormalize",
]
