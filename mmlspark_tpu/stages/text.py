"""Host-side text stages: trie find/replace, unicode normalization
(stages/TextPreprocessor.scala, stages/UnicodeNormalize.scala).
"""

from __future__ import annotations

import unicodedata
from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Transformer


class _Trie:
    """Longest-match replacement trie (TextPreprocessor's Trie analogue)."""

    __slots__ = ("children", "value")

    def __init__(self) -> None:
        self.children: dict = {}
        self.value: Any = None

    def put(self, key: str, value: str) -> None:
        node = self
        for ch in key:
            node = node.children.setdefault(ch, _Trie())
        node.value = value

    def replace_all(self, text: str) -> str:
        out = []
        i, n = 0, len(text)
        while i < n:
            node, j, best, best_j = self, i, None, i
            while j < n and text[j] in node.children:
                node = node.children[text[j]]
                j += 1
                if node.value is not None:
                    best, best_j = node.value, j
            if best is not None:
                out.append(best)
                i = best_j
            else:
                out.append(text[i])
                i += 1
        return "".join(out)


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Map/replace substrings via trie with optional normalization first."""

    map = Param("substring -> replacement map", default={}, type_=dict)
    normFunc = Param("none|lower|upper (applied before matching)", default="none", type_=str)

    def transform(self, df: DataFrame) -> DataFrame:
        trie = _Trie()
        for k, v in self.get("map").items():
            trie.put(k, v)
        norm = {"none": lambda s: s, "lower": str.lower, "upper": str.upper}[
            self.get("normFunc")
        ]
        ic, oc = self.get_or_fail("input_col"), self.get_or_fail("output_col")
        return df.with_column(
            oc, lambda p: np.array([trie.replace_all(norm(str(s))) for s in p[ic]], dtype=object)
        )


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    form = Param("NFC|NFD|NFKC|NFKD", default="NFKD", type_=str)
    lower = Param("lowercase output", default=True, type_=bool)

    def transform(self, df: DataFrame) -> DataFrame:
        ic, oc = self.get_or_fail("input_col"), self.get_or_fail("output_col")
        form = self.get("form")
        lower = self.get("lower")

        def f(s: Any) -> str:
            t = unicodedata.normalize(form, str(s))
            return t.lower() if lower else t

        return df.with_column(
            oc, lambda p: np.array([f(s) for s in p[ic]], dtype=object)
        )
