"""TuneHyperparameters + FindBestModel (automl/TuneHyperparameters.scala:97-150,
automl/FindBestModel.scala).

Randomized search over one or more estimators with k-fold CV. The reference
parallelizes fits with a thread pool over the Spark cluster; here
candidate fits run sequentially against the single device mesh (each fit is
itself a compiled SPMD program — on TPU the win is keeping the chip fed,
not host threads), with a thread pool for host-bound estimators.
"""

from __future__ import annotations

import concurrent.futures as _futures
from typing import Any, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.metrics import MetricConstants, classification_metrics, regression_metrics
from mmlspark_tpu.core.params import ComplexParam, HasLabelCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.automl.hyperparams import RandomSpace


class EvaluationUtils:
    """automl/EvaluationUtils.scala analogue: metric resolution helpers
    shared by TuneHyperparameters / FindBestModel."""

    @staticmethod
    def is_higher_better(metric: str) -> bool:
        return metric in MetricConstants.HIGHER_IS_BETTER

    @staticmethod
    def default_metric(task: str) -> str:
        return (
            MetricConstants.ACCURACY
            if task in ("classification", "classifier")
            else MetricConstants.RMSE
        )

    @staticmethod
    def evaluate(df: DataFrame, label_col: str, metric: str) -> float:
        return _evaluate(df, label_col, metric)


def _evaluate(df: DataFrame, label_col: str, metric: str) -> float:
    y = df[label_col]
    pred = df["prediction"]
    if metric in MetricConstants.ALL_REGRESSION:
        return regression_metrics(y, pred)[metric]
    scores = None
    if "probability" in df.columns:
        probs = df["probability"]
        if probs.ndim == 2 and probs.shape[1] == 2:
            scores = probs[:, 1]
    return classification_metrics(y, pred, scores)[metric]


class TuneHyperparameters(Estimator, HasLabelCol):
    models = ComplexParam("estimators to search over")
    hyperparams = ComplexParam("list of (estimator_index, spaces) or shared spaces list")
    evaluation_metric = Param("metric name", default=MetricConstants.ACCURACY, type_=str)
    number_of_folds = Param("k-fold count", default=3, type_=int)
    number_of_runs = Param("random draws per estimator", default=8, type_=int)
    parallelism = Param("concurrent fits (host-bound estimators only)", default=1, type_=int)
    seed = Param("search seed", default=0, type_=int)

    def fit(self, df: DataFrame) -> "TuneHyperparametersModel":
        estimators: Sequence[Estimator] = self.get_or_fail("models")
        spaces = self.get_or_fail("hyperparams")
        metric = self.get("evaluation_metric")
        higher = metric in MetricConstants.HIGHER_IS_BETTER
        k = self.get("number_of_folds")
        folds = df.random_split([1.0] * k, seed=self.get("seed"))

        candidates: list = []
        for ei, est in enumerate(estimators):
            est_spaces = spaces[ei] if isinstance(spaces[0], list) else spaces
            draws = RandomSpace(est_spaces, seed=self.get("seed") + ei).param_maps(
                self.get("number_of_runs")
            )
            for pm in draws:
                unknown = sorted(k_ for k_ in pm if k_ not in est.params())
                if unknown:
                    raise ValueError(
                        f"hyperparameter(s) {', '.join(map(repr, unknown))} "
                        f"are not params of estimator "
                        f"{type(est).__name__}; a sampled param that the "
                        "estimator ignores silently searches nothing"
                    )
                candidates.append((est, dict(pm)))

        def cv_score(est: Estimator, pm: dict) -> float:
            scores = []
            for i in range(k):
                train = None
                for j in range(k):
                    if j == i:
                        continue
                    train = folds[j] if train is None else train.union(folds[j])
                model = est.copy(pm).fit(train)
                scores.append(_evaluate(model.transform(folds[i]), self.get("label_col"), metric))
            return float(np.nanmean(scores))

        par = self.get("parallelism")
        if par > 1:
            with _futures.ThreadPoolExecutor(max_workers=par) as pool:
                results = list(pool.map(lambda c: cv_score(*c), candidates))
        else:
            results = [cv_score(est, pm) for est, pm in candidates]

        arr = np.asarray(results, dtype=np.float64)
        if np.isnan(arr).all():
            raise ValueError(
                f"all {len(arr)} candidates scored NaN for metric "
                f"{metric!r}; check folds contain every class"
            )
        best_i = int(np.nanargmax(arr) if higher else np.nanargmin(arr))
        best_est, best_pm = candidates[best_i]
        best_model = best_est.copy(best_pm).fit(df)
        out = TuneHyperparametersModel(label_col=self.get("label_col"))
        out.set(
            best_model=best_model,
            best_metric=float(results[best_i]),
            best_params=dict(best_pm),
            all_metrics=[float(r) for r in results],
        )
        return out


class TuneHyperparametersModel(Model, HasLabelCol):
    best_model = ComplexParam("winning fitted model")
    best_metric = Param("winning CV metric", type_=float)
    best_params = Param("winning param map", default={}, type_=dict)
    all_metrics = Param("metric per candidate", default=[], type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_fail("best_model").transform(df)


class FindBestModel(Estimator):
    """Evaluate fitted models on a dataset, keep the best
    (automl/FindBestModel.scala)."""

    models = ComplexParam("fitted Transformer models to compare")
    evaluation_metric = Param("metric name", default=MetricConstants.ACCURACY, type_=str)
    label_col = Param("label column", default="label", type_=str)

    def fit(self, df: DataFrame) -> "FindBestModelResult":
        metric = self.get("evaluation_metric")
        higher = metric in MetricConstants.HIGHER_IS_BETTER
        models = self.get_or_fail("models")
        scores = [
            _evaluate(m.transform(df), self.get("label_col"), metric) for m in models
        ]
        best_i = int(np.nanargmax(scores) if higher else np.nanargmin(scores))
        out = FindBestModelResult()
        out.set(
            best_model=models[best_i],
            best_model_metrics={metric: float(scores[best_i])},
            all_model_metrics=[float(s) for s in scores],
        )
        return out


class FindBestModelResult(Model):
    best_model = ComplexParam("best fitted model")
    best_model_metrics = Param("metrics of the winner", default={}, type_=dict)
    all_model_metrics = Param("metric per candidate", default=[], type_=list)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_fail("best_model").transform(df)
