from mmlspark_tpu.automl.hyperparams import (
    DefaultHyperparams,
    DiscreteHyperParam,
    GridSpace,
    HyperparamBuilder,
    RandomSpace,
    RangeHyperParam,
)
from mmlspark_tpu.automl.tune import (
    EvaluationUtils,
    FindBestModel,
    FindBestModelResult,
    TuneHyperparameters,
)

__all__ = [
    "TuneHyperparameters",
    "FindBestModel",
    "FindBestModelResult",
    "HyperparamBuilder",
    "GridSpace",
    "RandomSpace",
    "DiscreteHyperParam",
    "RangeHyperParam",
    "DefaultHyperparams",
    "EvaluationUtils",
]
