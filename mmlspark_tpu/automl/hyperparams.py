"""Hyperparameter spaces (automl/HyperparamBuilder.scala, ParamSpace,
GridSpace/RandomSpace, DefaultHyperparams)."""

from __future__ import annotations

import itertools
import random
from typing import Any, Iterator, Sequence


class DiscreteHyperParam:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.values)

    def grid(self) -> list:
        return self.values


class RangeHyperParam:
    def __init__(self, low: Any, high: Any, is_int: bool = False, log: bool = False):
        self.low, self.high, self.is_int, self.log = low, high, is_int, log

    def sample(self, rng: random.Random) -> Any:
        import math

        if self.log:
            v = math.exp(rng.uniform(math.log(self.low), math.log(self.high)))
        else:
            v = rng.uniform(self.low, self.high)
        return int(round(v)) if self.is_int else v

    def grid(self, n: int = 3) -> list:
        step = (self.high - self.low) / max(n - 1, 1)
        vals = [self.low + i * step for i in range(n)]
        return [int(round(v)) for v in vals] if self.is_int else vals


class HyperparamBuilder:
    """Collects (param_name, space) pairs (HyperparamBuilder analogue)."""

    def __init__(self) -> None:
        self._spaces: list = []

    def add_hyperparam(self, name: str, space: Any) -> "HyperparamBuilder":
        self._spaces.append((name, space))
        return self

    def build(self) -> list:
        return list(self._spaces)


class GridSpace:
    """Cartesian product of discrete grids."""

    def __init__(self, spaces: Sequence[tuple]):
        self.spaces = list(spaces)

    def param_maps(self) -> Iterator[dict]:
        names = [n for n, _ in self.spaces]
        grids = [s.grid() if hasattr(s, "grid") else list(s) for _, s in self.spaces]
        for combo in itertools.product(*grids):
            yield dict(zip(names, combo))


class RandomSpace:
    """Random draws from each space."""

    def __init__(self, spaces: Sequence[tuple], seed: int = 0):
        self.spaces = list(spaces)
        self.seed = seed

    def param_maps(self, n: int = 10) -> Iterator[dict]:
        rng = random.Random(self.seed)
        for _ in range(n):
            yield {name: s.sample(rng) for name, s in self.spaces}


class DefaultHyperparams:
    """Per-algorithm default search ranges (automl/DefaultHyperparams.scala)."""

    @staticmethod
    def logistic_regression() -> list:
        return (
            HyperparamBuilder()
            .add_hyperparam("reg_param", RangeHyperParam(1e-5, 1e-1, log=True))
            .add_hyperparam("learning_rate", DiscreteHyperParam([0.1, 0.3, 1.0]))
            .build()
        )

    @staticmethod
    def gbdt() -> list:
        return (
            HyperparamBuilder()
            .add_hyperparam("num_leaves", DiscreteHyperParam([15, 31, 63]))
            .add_hyperparam("learning_rate", RangeHyperParam(0.02, 0.3, log=True))
            .add_hyperparam("num_iterations", DiscreteHyperParam([50, 100]))
            .build()
        )
