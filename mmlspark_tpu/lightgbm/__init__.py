"""Drop-in import location matching the reference package layout
(``com.microsoft.ml.spark.lightgbm`` -> ``mmlspark_tpu.lightgbm``)."""

from mmlspark_tpu.models.gbdt import (
    Booster as LightGBMBooster,
    LightGBMClassificationModel,
    LightGBMClassifier,
    LightGBMRanker,
    LightGBMRankerModel,
    LightGBMRegressionModel,
    LightGBMRegressor,
)

__all__ = [
    "LightGBMBooster",
    "LightGBMClassifier",
    "LightGBMClassificationModel",
    "LightGBMRegressor",
    "LightGBMRegressionModel",
    "LightGBMRanker",
    "LightGBMRankerModel",
]
