"""RecommendationIndexer: string ids -> contiguous ints and back.

Reference: recommendation/RecommendationIndexer.scala — a pair of
StringIndexers for user and item columns whose maps are shared with the
evaluator/adapter so recommendations can be decoded back to raw ids.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, Param
from mmlspark_tpu.core.pipeline import Estimator, Model


class _IndexerParams:
    user_input_col = Param("raw user id column", default="user")
    item_input_col = Param("raw item id column", default="item")
    user_output_col = Param("indexed user column", default="user_idx")
    item_output_col = Param("indexed item column", default="item_idx")
    rating_col = Param("rating column (passed through)", default="rating")


class RecommendationIndexer(Estimator, _IndexerParams):
    def fit(self, df: DataFrame) -> "RecommendationIndexerModel":
        users = sorted(set(np.asarray(df[self.get("user_input_col")]).tolist()))
        items = sorted(set(np.asarray(df[self.get("item_input_col")]).tolist()))
        m = RecommendationIndexerModel(**{k: v for k, v in self._paramMap.items()})
        m.set(user_labels=users, item_labels=items)
        return m


class RecommendationIndexerModel(Model, _IndexerParams):
    user_labels = ComplexParam("ordered raw user ids")
    item_labels = ComplexParam("ordered raw item ids")

    def transform(self, df: DataFrame) -> DataFrame:
        u_map = {v: i for i, v in enumerate(self.get_or_fail("user_labels"))}
        i_map = {v: i for i, v in enumerate(self.get_or_fail("item_labels"))}

        def fn(p: dict) -> dict:
            q = dict(p)
            q[self.get("user_output_col")] = np.array(
                [u_map[v] for v in p[self.get("user_input_col")]], np.int64
            )
            q[self.get("item_output_col")] = np.array(
                [i_map[v] for v in p[self.get("item_input_col")]], np.int64
            )
            return q

        return df.map_partitions(fn)

    def recover_user(self, idx: Any) -> Any:
        return np.asarray(self.get_or_fail("user_labels"))[np.asarray(idx)]

    def recover_item(self, idx: Any) -> Any:
        return np.asarray(self.get_or_fail("item_labels"))[np.asarray(idx)]
