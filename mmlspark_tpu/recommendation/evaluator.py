"""RankingEvaluator: NDCG/MAP/precision/recall @ k over recommendation lists.

Reference: recommendation/RankingEvaluator.scala delegates to mllib
``RankingMetrics``; same metric definitions here, computed over a DataFrame
with one row per user holding the recommended item list and the
ground-truth item list.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.params import Params


class RankingEvaluator(Params):
    k = Param("cutoff", default=10, type_=int)
    metric_name = Param(
        "ndcgAt | map | precisionAtk | recallAtK",
        default="ndcgAt",
        validator=lambda v: v in ("ndcgAt", "map", "precisionAtk", "recallAtK"),
    )
    prediction_col = Param("recommended item-list column", default="recommendations")
    label_col = Param("ground-truth item-list column", default="label")

    def _per_user(self, pred: Any, truth: Any) -> dict:
        k = self.get("k")
        pred = list(pred)[:k]
        truth_set = set(list(truth))
        if not truth_set:
            return {"ndcgAt": 0.0, "map": 0.0, "precisionAtk": 0.0, "recallAtK": 0.0}
        hits = np.array([1.0 if p in truth_set else 0.0 for p in pred])
        precision = hits.sum() / k
        recall = hits.sum() / len(truth_set)
        # NDCG@k with binary relevance
        dcg = (hits / np.log2(np.arange(2, len(hits) + 2))).sum()
        ideal_hits = min(len(truth_set), k)
        idcg = (1.0 / np.log2(np.arange(2, ideal_hits + 2))).sum()
        ndcg = dcg / idcg if idcg > 0 else 0.0
        # MAP (average precision at k, normalized by min(|truth|, k))
        cum = np.cumsum(hits)
        prec_at_i = cum / np.arange(1, len(hits) + 1)
        ap = (prec_at_i * hits).sum() / min(len(truth_set), k)
        return {"ndcgAt": ndcg, "map": ap, "precisionAtk": precision, "recallAtK": recall}

    def evaluate_all(self, df: DataFrame) -> dict:
        preds = df[self.get("prediction_col")]
        truths = df[self.get("label_col")]
        if len(preds) == 0:
            return {"ndcgAt": 0.0, "map": 0.0, "precisionAtk": 0.0, "recallAtK": 0.0}
        rows = [self._per_user(p, t) for p, t in zip(preds, truths)]
        return {m: float(np.mean([r[m] for r in rows])) for m in rows[0]}

    def evaluate(self, df: DataFrame) -> float:
        return self.evaluate_all(df)[self.get("metric_name")]

    @property
    def is_larger_better(self) -> bool:
        return True
