"""RankingTrainValidationSplit: per-user stratified split + grid search.

Reference: recommendation/RankingTrainValidationSplit.scala — splits each
user's interactions (so every user appears in both sides), fits the
estimator per param-map, scores with RankingEvaluator on the held-out
side, keeps the best model.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, Param
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.recommendation.adapter import RankingAdapter
from mmlspark_tpu.recommendation.evaluator import RankingEvaluator


def per_user_split(
    df: DataFrame, user_col: str, train_ratio: float = 0.75, min_ratings: int = 2, seed: int = 0
) -> tuple:
    """Stratified-by-user split: each qualifying user keeps ceil(ratio*n)
    rows in train and the rest in validation."""
    users = np.asarray(df[user_col], np.int64)
    rng = np.random.RandomState(seed)
    order: dict[int, list] = {}
    for pos, u in enumerate(users):
        order.setdefault(int(u), []).append(pos)
    in_train = np.ones(len(users), bool)
    for u, positions in order.items():
        if len(positions) < max(min_ratings, 2):
            continue  # too few interactions to split: keep all in train
        positions = np.array(positions)
        rng.shuffle(positions)
        # at least one row on each side so the user exists in both splits
        n_train = int(np.clip(np.ceil(len(positions) * train_ratio), 1, len(positions) - 1))
        in_train[positions[n_train:]] = False

    data = df.to_dict()
    train = {c: v[in_train] for c, v in data.items()}
    val = {c: v[~in_train] for c, v in data.items()}
    return DataFrame.from_dict(train), DataFrame.from_dict(val)


class RankingTrainValidationSplit(Estimator):
    estimator = ComplexParam("recommender estimator to tune")
    estimator_param_maps = ComplexParam("list of {param: value} dicts", default=None)
    evaluator = ComplexParam("RankingEvaluator", default=None)
    train_ratio = Param("per-user train fraction", default=0.75, type_=float)
    min_ratings_per_user = Param("users below this stay train-only", default=2, type_=int)
    k = Param("recommendations per user for evaluation", default=10, type_=int)
    seed = Param("split seed", default=0, type_=int)

    def fit(self, df: DataFrame) -> "RankingTrainValidationSplitModel":
        est = self.get_or_fail("estimator")
        grid: Sequence[dict] = self.get("estimator_param_maps") or [{}]
        evaluator: RankingEvaluator = self.get("evaluator") or RankingEvaluator(k=self.get("k"))
        user_col = est.get("user_col")
        train, val = per_user_split(
            df, user_col, self.get("train_ratio"), self.get("min_ratings_per_user"), self.get("seed")
        )

        best_metric, best_model, metrics = -np.inf, None, []
        for pm in grid:
            candidate = est.copy(extra=pm)
            adapter = RankingAdapter(
                recommender=candidate,
                k=self.get("k"),
                label_col=evaluator.get("label_col"),
                prediction_col=evaluator.get("prediction_col"),
            )
            fitted = adapter.fit(train)
            scored = fitted.transform(val)
            metric = evaluator.evaluate(scored)
            metrics.append(metric)
            if metric > best_metric:
                best_metric, best_model = metric, fitted
        m = RankingTrainValidationSplitModel()
        m.set(
            best_model=best_model,
            validation_metrics=[float(v) for v in metrics],
        )
        return m


class RankingTrainValidationSplitModel(Model):
    best_model = ComplexParam("best fitted RankingAdapterModel")
    validation_metrics = ComplexParam("metric per grid entry")

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_fail("best_model").transform(df)

    def recommend_for_all_users(self, k: int) -> DataFrame:
        return self.get_or_fail("best_model").get_or_fail("recommender_model").recommend_for_all_users(k)
