"""RankingAdapter: make a recommender evaluable by RankingEvaluator.

Reference: recommendation/RankingAdapter.scala — wraps a recommender
estimator; ``fit`` trains it, ``transform`` emits one row per user with the
top-k recommended items and the user's ground-truth items from the input
DataFrame, feeding RankingEvaluator.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, Param
from mmlspark_tpu.core.pipeline import Estimator, Model


class _AdapterParams:
    recommender = ComplexParam("wrapped recommender estimator (e.g. SAR)")
    k = Param("recommendations per user", default=10, type_=int)
    min_rating_filter = Param("keep truth items with rating >= this", default=0.0, type_=float)
    label_col = Param("emitted ground-truth list column", default="label")
    prediction_col = Param("emitted recommendation list column", default="recommendations")


class RankingAdapter(Estimator, _AdapterParams):
    def fit(self, df: DataFrame) -> "RankingAdapterModel":
        rec = self.get_or_fail("recommender")
        model = rec.fit(df)
        m = RankingAdapterModel(**{k: v for k, v in self._paramMap.items()})
        m.set(recommender_model=model)
        return m


class RankingAdapterModel(Model, _AdapterParams):
    recommender_model = ComplexParam("fitted recommender model")

    def transform(self, df: DataFrame) -> DataFrame:
        model = self.get_or_fail("recommender_model")
        recs = model.recommend_for_all_users(self.get("k"))
        user_col = model.get("user_col")
        rating_col = model.get("rating_col")
        item_col = model.get("item_col")

        users = np.asarray(df[user_col], np.int64)
        items = np.asarray(df[item_col], np.int64)
        if rating_col and rating_col in df.columns:
            keep = np.asarray(df[rating_col], np.float64) >= self.get("min_rating_filter")
        else:
            keep = np.ones(len(users), bool)

        truth: dict[int, list] = {}
        for u, i, ok in zip(users, items, keep):
            if ok:
                truth.setdefault(int(u), []).append(int(i))

        # only evaluate users actually present in the evaluation DataFrame —
        # train-only users would otherwise contribute all-zero metrics
        eval_users = set(int(u) for u in users)
        rec_users_all = np.asarray(recs[user_col], np.int64)
        keep_rows = np.array([int(u) in eval_users for u in rec_users_all], bool)
        recs = DataFrame.from_dict({c: recs[c][keep_rows] for c in recs.columns})
        rec_users = np.asarray(recs[user_col], np.int64)
        labels = np.empty(len(rec_users), dtype=object)
        for j, u in enumerate(rec_users):
            labels[j] = truth.get(int(u), [])
        out = recs.with_column(self.get("label_col"), labels)
        if self.get("prediction_col") != "recommendations":
            out = out.rename({"recommendations": self.get("prediction_col")})
        return out
