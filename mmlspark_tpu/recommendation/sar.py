"""SAR recommender — co-occurrence similarity × time-decayed affinity.

Reference semantics (recommendation/SAR.scala:66-119):
- item-item similarity from the co-occurrence matrix ``C = A^T A`` over the
  binarized user-item interaction matrix, rescaled per
  ``similarity_function``: cooccurrence (raw counts), jaccard
  ``c_ij / (c_ii + c_jj - c_ij)``, lift ``c_ij / (c_ii * c_jj)``;
  counts below ``support_threshold`` are zeroed.
- user-item affinity with exponential time decay
  ``sum_t rating * 2^(-(t_ref - t) / half_life)``.
- score(u, i) = affinity[u] · similarity[:, i]; top-k with seen items
  optionally removed.

TPU-first: C is one (I, I) matmul over the bool matrix (MXU, bf16-safe
counts), scoring is a second matmul + ``lax.top_k``; both jitted.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, Param
from mmlspark_tpu.core.pipeline import Estimator, Model


@partial(jax.jit, static_argnums=(1, 2))
def _similarity(a_bool: jnp.ndarray, function: str, support: int) -> jnp.ndarray:
    c = a_bool.T @ a_bool  # (I, I) co-occurrence counts
    c = jnp.where(c >= support, c, 0.0)
    diag = jnp.diag(c)
    if function == "jaccard":
        denom = diag[:, None] + diag[None, :] - c
        sim = jnp.where(denom > 0, c / jnp.maximum(denom, 1e-12), 0.0)
    elif function == "lift":
        denom = diag[:, None] * diag[None, :]
        sim = jnp.where(denom > 0, c / jnp.maximum(denom, 1e-12), 0.0)
    else:  # cooccurrence
        sim = c
    return sim.astype(jnp.float32)


@partial(jax.jit, static_argnums=(3,))
def _score_topk(
    affinity: jnp.ndarray, sim: jnp.ndarray, seen: jnp.ndarray, k: int
) -> tuple:
    scores = affinity @ sim
    scores = jnp.where(seen, -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


class _SARParams:
    user_col = Param("indexed user column", default="user_idx")
    item_col = Param("indexed item column", default="item_idx")
    rating_col = Param("rating column", default="rating")
    time_col = Param("event-time column (unix seconds); optional", default=None)
    similarity_function = Param(
        "cooccurrence | jaccard | lift",
        default="jaccard",
        validator=lambda v: v in ("cooccurrence", "jaccard", "lift"),
    )
    support_threshold = Param("min co-occurrence count kept", default=4, type_=int)
    time_decay_coeff = Param("affinity half-life in days", default=30.0, type_=float)
    reference_time = Param(
        "decay reference time (unix seconds; reference SAR.scala 'startTime' "
        "analogue). None decays relative to the latest training event, which "
        "keeps offline runs reproducible but does NOT age a stale dataset "
        "relative to now — pass time.time() for that.",
        default=None,
    )
    allow_seen_items = Param("keep already-seen items in recommendations", default=False, type_=bool)


class SAR(Estimator, _SARParams):
    def fit(self, df: DataFrame) -> "SARModel":
        users = np.asarray(df[self.get("user_col")], np.int64)
        items = np.asarray(df[self.get("item_col")], np.int64)
        n_users = int(users.max()) + 1 if len(users) else 0
        n_items = int(items.max()) + 1 if len(items) else 0
        rc = self.get("rating_col")
        ratings = (
            np.asarray(df[rc], np.float32)
            if rc and rc in df.columns
            else np.ones(len(users), np.float32)
        )

        weights = ratings
        tc = self.get("time_col")
        if tc and tc in df.columns:
            t = np.asarray(df[tc], np.float64)
            half_life_s = self.get("time_decay_coeff") * 86400.0
            ref = self.get("reference_time")
            t_ref = float(ref) if ref is not None else t.max()
            decay = np.exp2(-(t_ref - t) / half_life_s)
            weights = ratings * decay.astype(np.float32)

        # binarized interactions for similarity; decayed sums for affinity
        a_bool = np.zeros((n_users, n_items), np.float32)
        a_bool[users, items] = 1.0
        affinity = np.zeros((n_users, n_items), np.float32)
        np.add.at(affinity, (users, items), weights)

        sim = np.asarray(
            _similarity(
                jnp.asarray(a_bool),
                self.get("similarity_function"),
                self.get("support_threshold"),
            )
        )
        m = SARModel(**{k: v for k, v in self._paramMap.items()})
        m.set(item_similarity=sim, user_affinity=affinity, seen_items=a_bool)
        return m


class SARModel(Model, _SARParams):
    item_similarity = ComplexParam("(I, I) item-item similarity")
    user_affinity = ComplexParam("(U, I) time-decayed user-item affinity")
    seen_items = ComplexParam("(U, I) binary seen matrix")
    prediction_col = Param("output column for pair scores / recommendations", default="prediction")

    def transform(self, df: DataFrame) -> DataFrame:
        """Score (user, item) pairs — rating-prediction mode. Pairs whose
        user/item index was never seen at fit score NaN (cold start), rather
        than silently clamping to another entity's row."""
        sim = jnp.asarray(self.get_or_fail("item_similarity"))
        aff = jnp.asarray(self.get_or_fail("user_affinity"))
        users = np.asarray(df[self.get("user_col")], np.int64)
        items = np.asarray(df[self.get("item_col")], np.int64)
        known = (
            (users >= 0) & (users < aff.shape[0]) & (items >= 0) & (items < sim.shape[0])
        )
        u_safe = np.where(known, users, 0)
        i_safe = np.where(known, items, 0)
        # per-pair dot product: O(n*I) — no (n, I) score matrix materialized
        pair_scores = np.asarray(
            jnp.einsum("ni,ni->n", aff[u_safe], sim[:, i_safe].T)
        ).astype(np.float64)
        pair_scores[~known] = np.nan
        return df.with_column(self.get("prediction_col"), pair_scores)

    def recommend_for_all_users(self, k: int) -> DataFrame:
        aff = self.get_or_fail("user_affinity")
        sim = self.get_or_fail("item_similarity")
        seen = self.get_or_fail("seen_items")
        if self.get("allow_seen_items"):
            seen = np.zeros_like(seen)
        k = min(k, sim.shape[0])
        sc, ix = _score_topk(
            jnp.asarray(aff), jnp.asarray(sim), jnp.asarray(seen, bool), k
        )
        sc, ix = np.asarray(sc), np.asarray(ix)
        recs = np.empty(len(sc), dtype=object)
        ratings = np.empty(len(sc), dtype=object)
        for u in range(len(sc)):
            keep = np.isfinite(sc[u])
            recs[u] = ix[u][keep].tolist()
            ratings[u] = sc[u][keep].astype(np.float64).tolist()
        return DataFrame.from_dict(
            {
                self.get("user_col"): np.arange(len(sc), dtype=np.int64),
                "recommendations": recs,
                "ratings": ratings,
            }
        )
