"""Recommenders + ranking evaluation (reference: recommendation/, SURVEY.md §2.14).

SAR ("smart adaptive recommendations"): item-item co-occurrence similarity
with jaccard/lift variants + time-decayed user-item affinity
(SAR.scala:66-119). TPU-first: the co-occurrence count is one boolean
matmul ``A.T @ A`` on the MXU, scoring is ``affinity @ similarity`` +
``lax.top_k`` — the reference's per-user Spark joins become two device
matmuls.
"""

from mmlspark_tpu.recommendation.indexer import (
    RecommendationIndexer,
    RecommendationIndexerModel,
)
from mmlspark_tpu.recommendation.sar import SAR, SARModel
from mmlspark_tpu.recommendation.evaluator import RankingEvaluator
from mmlspark_tpu.recommendation.adapter import RankingAdapter, RankingAdapterModel
from mmlspark_tpu.recommendation.split import RankingTrainValidationSplit

__all__ = [
    "RecommendationIndexer",
    "RecommendationIndexerModel",
    "SAR",
    "SARModel",
    "RankingEvaluator",
    "RankingAdapter",
    "RankingAdapterModel",
    "RankingTrainValidationSplit",
]
