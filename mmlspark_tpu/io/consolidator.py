"""PartitionConsolidator (io/http/PartitionConsolidator.scala:19-132 analogue).

Funnels many partitions' rows through a bounded number of workers — the
pattern for rate-limited external services: regardless of upstream
parallelism, at most ``num_workers`` partitions exist downstream, so at most
``num_workers * concurrency`` requests are ever in flight.
"""

from __future__ import annotations

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer


class PartitionConsolidator(Transformer):
    num_workers = Param(
        "number of consolidated partitions (chosen workers)", default=1, type_=int,
        validator=lambda v: v >= 1,
    )

    def transform(self, df: DataFrame) -> DataFrame:
        return df.coalesce(self.get("num_workers"))
