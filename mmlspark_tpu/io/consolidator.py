"""PartitionConsolidator (io/http/PartitionConsolidator.scala:19-132 analogue).

Funnels many partitions' rows through ONE live worker — the pattern for
rate-limited external services: regardless of upstream parallelism, a
single partition performs the downstream work (e.g. HTTP calls), so the
service sees one client no matter how wide the job is.

Faithful to the reference's ``Consolidator``: partitions race to register;
the FIRST becomes the chosen worker and drains its own rows plus a shared
queue, staying alive (with a grace period) while other workers are still
feeding; every other partition forwards its rows into the queue and emits
nothing. Partition functions here run on the DataFrame thread pool
(core/dataframe._get_pool), so the chosen/forwarder race is real
concurrency, as on Spark executors.

Unlike ``coalesce`` (a static repartition), consolidation is LIVE: rows
forwarded while the chosen worker is mid-drain are still picked up, and
the chosen worker exits only after the last feeder deregisters. The
chosen role is sticky for the transform, and a post-pass sweeps any rows
enqueued after the chosen worker exited (serial execution degenerates to
exactly that sweep), so all rows land in ONE output partition on any
schedule and none are dropped.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Transformer


class Consolidator:
    """Chosen-worker queue (PartitionConsolidator.scala:19-60)."""

    def __init__(self, grace_period_s: float = 1.0):
        self.buffer: "queue.Queue[Partition]" = queue.Queue()
        self._lock = threading.Lock()
        self._working = 0
        self._chosen_taken = False
        self._grace = grace_period_s

    def _register(self) -> bool:
        with self._lock:
            # STICKY choice: the first registration ever wins. (The
            # reference re-elects when workingPartitions drops to 0, which
            # under serial scheduling would make EVERY partition chosen and
            # consolidate nothing; stickiness + the drain_leftovers sweep
            # keeps the one-live-worker guarantee on any schedule.)
            chosen = not self._chosen_taken
            self._chosen_taken = True
            self._working += 1
            return chosen

    def _deregister(self) -> None:
        with self._lock:
            self._working -= 1

    @property
    def working_partitions(self) -> int:
        with self._lock:
            return self._working

    def register_and_receive(self, part: Partition) -> list:
        """Run one partition through the funnel; returns the chunks this
        partition emits (chosen: everything; forwarders: nothing)."""
        chosen = self._register()
        if not chosen:
            self.buffer.put(part)
            self._deregister()
            return []
        # chosen worker: own rows first, then drain the queue while other
        # partitions are still feeding (hasNextHelper's recurse-once grace)
        out = [part]
        graced = False
        while True:
            try:
                out.append(self.buffer.get_nowait())
                graced = False
                continue
            except queue.Empty:
                pass
            if self.working_partitions > 1:
                time.sleep(0.002)  # feeders still registered: poll
                graced = False
                continue
            if not graced:
                time.sleep(self._grace)
                graced = True
                continue
            self._deregister()
            return out

    def drain_leftovers(self) -> list:
        """Chunks enqueued after the chosen worker exited (possible only
        under serial scheduling); the transformer sweeps these."""
        out = []
        while True:
            try:
                out.append(self.buffer.get_nowait())
            except queue.Empty:
                return out


class PartitionConsolidator(Transformer):
    grace_period_s = Param(
        "how long the chosen worker lingers for late feeders", default=0.05,
        type_=float,
    )

    def transform(self, df: DataFrame) -> DataFrame:
        cons = Consolidator(grace_period_s=self.get("grace_period_s"))

        def per_partition(part: Partition) -> Partition:
            chunks = cons.register_and_receive(part)
            if not chunks:
                return {k: v[:0] for k, v in part.items()}
            return _concat(chunks)

        out = df.map_partitions(per_partition)
        leftovers = cons.drain_leftovers()
        if leftovers:
            parts = [p for p in out._parts]
            merged = _concat([p for p in parts if p and len(next(iter(p.values())))]
                             + leftovers)
            empty = [
                {k: v[:0] for k, v in p.items()} for p in parts[1:]
            ]
            out = DataFrame([merged] + empty, metadata=df._metadata)
        return out


def _concat(chunks: list) -> Partition:
    keys = chunks[0].keys()
    return {k: np.concatenate([c[k] for c in chunks]) for k in keys}
