"""Lazily-constructed per-process shared objects.

SharedVariable/SharedSingleton analogue (io/http/SharedVariable.scala:18-60):
stage closures capture a *recipe*; the value is built once per process on
first use and shared across partition tasks (e.g. one HTTP connection pool,
one compiled XLA program). Pickling transports only the recipe.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Generic, TypeVar

T = TypeVar("T")

# process-wide cache keyed by singleton id, survives re-pickling
_SINGLETONS: dict[str, Any] = {}
_LOCK = threading.Lock()


class SharedVariable(Generic[T]):
    """Holds fn-constructed value, built lazily once per process."""

    def __init__(self, constructor: Callable[[], T]):
        self._constructor = constructor
        self._value: Any = None
        self._built = False
        self._lock = threading.Lock()

    def get(self) -> T:
        if not self._built:
            with self._lock:
                if not self._built:
                    self._value = self._constructor()
                    self._built = True
        return self._value

    def __getstate__(self) -> dict:
        return {"_constructor": self._constructor}

    def __setstate__(self, state: dict) -> None:
        self._constructor = state["_constructor"]
        self._value, self._built = None, False
        self._lock = threading.Lock()


class SharedSingleton(Generic[T]):
    """Like SharedVariable but deduplicated process-wide by key, so multiple
    deserialized copies of a stage share one instance."""

    def __init__(self, key: str, constructor: Callable[[], T]):
        self.key = key
        self._constructor = constructor

    def get(self) -> T:
        with _LOCK:
            if self.key not in _SINGLETONS:
                _SINGLETONS[self.key] = self._constructor()
            return _SINGLETONS[self.key]

    @staticmethod
    def invalidate(key: str) -> None:
        with _LOCK:
            _SINGLETONS.pop(key, None)
