"""PowerBI-style streaming-dataset writer.

PowerBIWriter analogue (io/powerbi/PowerBIWriter.scala:27-62): POST rows of
a DataFrame as JSON arrays to a push URL, in minibatches, with bounded
concurrency and retry on 429/5xx. Azure specifics don't matter — any
endpoint accepting ``[{col: val, ...}, ...]`` bodies works.
"""

from __future__ import annotations

import concurrent.futures as _futures
import json
from typing import Optional, Sequence

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.clients import AdvancedHandler
from mmlspark_tpu.io.http_schema import HTTPRequestData
from mmlspark_tpu.io.parsers import _to_jsonable


class PowerBIWriter:
    @staticmethod
    def write(
        df: DataFrame,
        url: str,
        minibatch_size: int = 100,
        concurrency: int = 4,
        headers: Optional[dict] = None,
        backoffs_ms: Sequence[int] = (100, 500, 1000),
        timeout: float = 30.0,
    ) -> list:
        """POST all rows; returns the list of response dicts (one per batch).
        Raises on any non-2xx final status."""
        rows = [dict(r) for r in df.collect()]
        batches = [
            rows[i: i + minibatch_size] for i in range(0, len(rows), minibatch_size)
        ]
        handler = AdvancedHandler(backoffs_ms=backoffs_ms, timeout=timeout)
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})

        def send(batch: list) -> dict:
            body = json.dumps([_to_jsonable(r) for r in batch])
            return handler(HTTPRequestData(url, "POST", hdrs, body))

        with _futures.ThreadPoolExecutor(max_workers=concurrency) as pool:
            resps = list(pool.map(send, batches))
        bad = [r for r in resps if r["status_code"] // 100 != 2]
        if bad:
            raise RuntimeError(
                f"PowerBIWriter: {len(bad)}/{len(resps)} batches failed, "
                f"first: {bad[0]['status_code']} {bad[0]['reason']}"
            )
        return resps
