"""Out-of-core streaming DataFrame source.

The eager ``core.dataframe.DataFrame`` materializes every column in
memory; the reference instead streams partitions from disk through its
custom file formats (io/binary/BinaryFileFormat.scala:112-149 reads
portioned binary records on demand). ``StreamingDataFrame`` is that
capability here: a re-iterable source of bounded eager CHUNKS (each a
normal DataFrame), so a fitted pipeline can score datasets far larger than
host memory — the 1M-row x 224^2 north-star image workload is launchable
through it (tools/northstar_stream.py).

Semantics:
- A chunk is a plain eager DataFrame; every existing Transformer works on
  it unchanged (``transform`` maps the stage lazily over chunks — Spark's
  microbatch model).
- The source factory is re-invocable: each traversal re-opens the
  underlying file/generator, so a StreamingDataFrame can be consumed more
  than once (like a Spark source, unlike a Python generator).
- ``fit`` on unbounded data is out of scope, as in SparkML: estimators
  need a bounded DataFrame (``materialize`` a sample for that).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame


class StreamingDataFrame:
    def __init__(self, source: Callable[[], Iterator[DataFrame]]):
        self._source = source

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_generator(
        make_chunk: Callable[[int], Optional[DataFrame]], num_chunks: Optional[int] = None
    ) -> "StreamingDataFrame":
        """``make_chunk(i)`` -> DataFrame or None (None = end of stream)."""

        def source() -> Iterator[DataFrame]:
            i = 0
            while num_chunks is None or i < num_chunks:
                chunk = make_chunk(i)
                if chunk is None:
                    return
                yield chunk
                i += 1

        return StreamingDataFrame(source)

    @staticmethod
    def from_csv(
        path: str,
        chunk_rows: int = 65536,
        header: bool = True,
        columns: Optional[Sequence[str]] = None,
        numeric_only: Optional[bool] = None,
    ) -> "StreamingDataFrame":
        """Chunked CSV: reads ~chunk_rows lines at a time, never the whole
        file. Column dtypes are inferred per chunk; pass ``numeric_only``
        explicitly for dtype stability across chunks whose string values
        appear late."""
        from mmlspark_tpu.io.csv import parse_csv_bytes, split_csv_header

        def source() -> Iterator[DataFrame]:
            with open(path, "rb") as f:
                head = b""
                if header or columns is None:
                    # header line (or first line for width discovery)
                    head = f.readline()
                _, names = split_csv_header(
                    head + b"\n" if head and not head.endswith(b"\n") else head,
                    header,
                    columns,
                )
                if not header:
                    # first line was data: hand it to the first chunk
                    carry = head
                else:
                    carry = b""
                while True:
                    lines = f.readlines(chunk_rows * 64)  # hint: avg 64 B/line
                    if not lines and not carry:
                        return
                    body = carry + b"".join(lines)
                    carry = b""
                    # a quoted field may contain newlines (write_csv emits
                    # them): an odd quote count means the chunk boundary cut
                    # a record — extend until the record closes
                    while lines and body.count(b'"') % 2 == 1:
                        more = f.readline()
                        if not more:
                            break
                        body += more
                    if not body.strip():
                        continue  # a run of blank lines is not end-of-file
                    yield parse_csv_bytes(body, names, numeric_only)

        return StreamingDataFrame(source)

    @staticmethod
    def from_binary_files(
        path: str,
        files_per_chunk: int = 256,
        recursive: bool = True,
        pattern: Optional[str] = None,
    ) -> "StreamingDataFrame":
        """Directory -> chunks of DataFrame[path, bytes]; file contents are
        read only when their chunk is consumed (BinaryFileFormat.scala's
        portioned reads)."""
        from mmlspark_tpu.io.binary import _iter_files
        import fnmatch

        def source() -> Iterator[DataFrame]:
            batch_paths: list = []
            for fp in _iter_files(path, recursive):
                if pattern and not fnmatch.fnmatch(os.path.basename(fp), pattern):
                    continue
                batch_paths.append(fp)
                if len(batch_paths) >= files_per_chunk:
                    yield _load_files(batch_paths)
                    batch_paths = []
            if batch_paths:
                yield _load_files(batch_paths)

        return StreamingDataFrame(source)

    # -- lazy transforms -----------------------------------------------------

    def map_chunks(self, fn: Callable[[DataFrame], DataFrame]) -> "StreamingDataFrame":
        src = self._source

        def source() -> Iterator[DataFrame]:
            for chunk in src():
                yield fn(chunk)

        return StreamingDataFrame(source)

    def transform(self, stage: Any) -> "StreamingDataFrame":
        """Lazily apply a fitted Transformer/PipelineModel chunk-by-chunk."""
        return self.map_chunks(stage.transform)

    # -- consumption ---------------------------------------------------------

    def iter_chunks(self) -> Iterator[DataFrame]:
        return self._source()

    def foreach_chunk(self, fn: Callable[[DataFrame], None]) -> int:
        n = 0
        for chunk in self._source():
            fn(chunk)
            n += len(chunk)
        return n

    def count(self) -> int:
        return sum(len(chunk) for chunk in self._source())

    def first(self) -> Optional[DataFrame]:
        for chunk in self._source():
            return chunk
        return None

    def materialize(self, max_rows: Optional[int] = None) -> DataFrame:
        """Concatenate chunks into an eager DataFrame; stops PULLING the
        source as soon as ``max_rows`` rows are buffered — on an
        unbounded source (an infinite feedback generator, a live ingest
        stream) the iterator is never drained past the cap. The chunk
        that crosses the cap is truncated to exactly ``max_rows`` rows.
        ``max_rows <= 0`` returns an empty frame without touching the
        source at all (no chunk is ever pulled just to be discarded).

        The online suite (tests/test_online.py) pins this contract:
        FeedbackStream's pull sources are unbounded by design, and a
        ``materialize`` that drained them would hang forever."""
        if max_rows is not None and max_rows <= 0:
            return DataFrame.from_dict({})
        chunks: list = []
        rows = 0
        src = self._source()
        for chunk in src:
            chunks.append(chunk)
            rows += len(chunk)
            if max_rows is not None and rows >= max_rows:
                # release the generator's resources eagerly (an open CSV
                # file handle, a live socket) instead of waiting for GC
                if hasattr(src, "close"):
                    src.close()
                break
        if not chunks:
            return DataFrame.from_dict({})
        cols: dict = {}
        for name in chunks[0].columns:
            cat = np.concatenate([c[name] for c in chunks])
            cols[name] = cat[:max_rows] if max_rows is not None else cat
        return DataFrame.from_dict(cols)

    def write_csv(self, path: str, header: bool = True) -> int:
        """Stream chunks to a CSV file (proper quoting); returns rows
        written."""
        import csv as _csv

        rows = 0
        with open(path, "w", newline="") as f:
            w = _csv.writer(f)
            for i, chunk in enumerate(self._source()):
                names = chunk.columns
                if i == 0 and header:
                    w.writerow(names)
                mats = [np.asarray(chunk[c]) for c in names]
                for r in range(len(chunk)):
                    w.writerow([_cell(m[r]) for m in mats])
                rows += len(chunk)
        return rows


def _cell(v: Any) -> str:
    if isinstance(v, (bytes, bytearray)):
        return v.decode("utf-8", "replace")
    if isinstance(v, (float, np.floating)) and float(v).is_integer():
        return str(int(v))
    return str(v)


def _load_files(paths: list) -> DataFrame:
    blobs = np.empty(len(paths), dtype=object)
    for i, fp in enumerate(paths):
        with open(fp, "rb") as f:
            blobs[i] = f.read()
    return DataFrame.from_dict(
        {"path": np.array(list(paths), dtype=object), "bytes": blobs}
    )
