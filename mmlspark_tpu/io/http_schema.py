"""HTTP request/response structs carried in DataFrame columns.

The reference models these as case classes with ``SparkBindings`` codecs
(io/http/HTTPSchema.scala:26-240). Here they are plain dicts (object
columns) with typed constructors — the columnar substrate stores them
directly, and JSON round-trips trivially for persistence.
"""

from __future__ import annotations

import json
from typing import Any, Optional, Union


def HTTPRequestData(
    url: str,
    method: str = "GET",
    headers: Optional[dict] = None,
    entity: Union[bytes, str, None] = None,
) -> dict:
    """Build a request row (HTTPSchema.scala HTTPRequestData analogue)."""
    if isinstance(entity, str):
        entity = entity.encode("utf-8")
    return {
        "url": url,
        "method": method.upper(),
        "headers": dict(headers or {}),
        "entity": entity,
    }


def HTTPResponseData(
    status_code: int,
    entity: Union[bytes, str, None] = None,
    reason: str = "",
    headers: Optional[dict] = None,
) -> dict:
    """Build a response row (HTTPSchema.scala HTTPResponseData analogue)."""
    if isinstance(entity, str):
        entity = entity.encode("utf-8")
    return {
        "status_code": int(status_code),
        "reason": reason,
        "headers": dict(headers or {}),
        "entity": entity,
    }


def string_to_response(text: str, code: int = 200, reason: str = "OK") -> dict:
    """HTTPSchema.string_to_response analogue (HTTPSchema.scala:191-199)."""
    return HTTPResponseData(code, text, reason, {"Content-Type": "text/plain"})


def json_to_request(obj: Any, url: str, headers: Optional[dict] = None) -> dict:
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    return HTTPRequestData(url, "POST", h, json.dumps(obj))


def entity_to_string(row: Optional[dict]) -> Optional[str]:
    if row is None:
        return None
    e = row.get("entity")
    if e is None:
        return None
    return e.decode("utf-8") if isinstance(e, (bytes, bytearray)) else str(e)


def response_to_json(row: Optional[dict]) -> Any:
    s = entity_to_string(row)
    return None if s is None or not s.strip() else json.loads(s)
