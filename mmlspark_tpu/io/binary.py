"""Binary & image file ingestion.

BinaryFileFormat/BinaryFileReader analogue (io/binary/BinaryFileFormat.
scala:112-149): walk a directory (or zip archives inside it), emit
``{path, bytes}`` rows with optional subsampling; ``read_images`` further
decodes into image rows ({height,width,channels,mode,data}; the reference's
Spark image schema, io/image/ImageUtils.scala).
"""

from __future__ import annotations

import fnmatch
import os
from typing import Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.core.utils import zip_iterator


def _iter_files(path: str, recursive: bool):
    if os.path.isfile(path):
        yield path
        return
    if recursive:
        for root, _, files in os.walk(path):
            for f in sorted(files):
                yield os.path.join(root, f)
    else:
        for f in sorted(os.listdir(path)):
            fp = os.path.join(path, f)
            if os.path.isfile(fp):
                yield fp


def read_binary_files(
    path: str,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    seed: int = 0,
    pattern: Optional[str] = None,
    inspect_zip: bool = True,
    num_partitions: int = 1,
) -> DataFrame:
    """Directory/zip -> DataFrame[path, bytes]."""
    rng = np.random.default_rng(seed)
    paths, blobs = [], []

    def keep() -> bool:
        return sample_ratio >= 1.0 or rng.random() < sample_ratio

    for fp in _iter_files(path, recursive):
        if inspect_zip and fp.endswith(".zip"):
            for name, data in zip_iterator(fp, sample_ratio=sample_ratio, seed=seed):
                if pattern and not fnmatch.fnmatch(name.split("::")[-1], pattern):
                    continue
                paths.append(name)
                blobs.append(data)
            continue
        if pattern and not fnmatch.fnmatch(os.path.basename(fp), pattern):
            continue
        if not keep():
            continue
        with open(fp, "rb") as f:
            blobs.append(f.read())
        paths.append(fp)

    data = np.empty(len(blobs), dtype=object)
    for i, b in enumerate(blobs):
        data[i] = b
    return DataFrame.from_dict(
        {"path": np.array(paths, dtype=object), "bytes": data},
        num_partitions=max(1, num_partitions),
    )


def read_images(
    path: str,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    seed: int = 0,
    drop_invalid: bool = True,
    num_partitions: int = 1,
) -> DataFrame:
    """Directory -> DataFrame[path, image] with decoded image rows."""
    from mmlspark_tpu.ops.image import decode_image

    df = read_binary_files(
        path, recursive=recursive, sample_ratio=sample_ratio, seed=seed,
        num_partitions=num_partitions,
    )

    def decode_part(p: dict) -> dict:
        imgs, keep = [], []
        for i, b in enumerate(p["bytes"]):
            arr = decode_image(b)
            if arr is None:
                if not drop_invalid:
                    imgs.append(None)
                    keep.append(i)
                continue
            imgs.append(make_image_row(arr, origin=p["path"][i]))
            keep.append(i)
        col = np.empty(len(imgs), dtype=object)
        for i, v in enumerate(imgs):
            col[i] = v
        return {"path": p["path"][keep], "image": col}

    return df.map_partitions(decode_part)
