"""CSV data loader backed by the native parser.

The reference delegates tabular ingestion to Spark's readers (JVM/native);
this is the framework's own loader: numeric matrices parse in C++
(ops/native/mmltpu.cc ``mml_parse_csv``), mixed-type files fall back to
Python's csv module. Output is a partitioned DataFrame sized for device
feeding.
"""

from __future__ import annotations

import csv as _csv
import io as _io
from typing import Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.ops import native_loader


def _parse_numeric(data: bytes) -> Optional[np.ndarray]:
    lib = native_loader.try_load()
    if lib is None:
        return None
    return lib.parse_csv(data)


def read_csv(
    path: str,
    header: bool = True,
    columns: Optional[Sequence[str]] = None,
    num_partitions: int = 1,
    numeric_only: Optional[bool] = None,
) -> DataFrame:
    """Load a CSV file into a DataFrame.

    numeric_only=True forces the native fast path (bad fields become NaN);
    None auto-detects by probing the first 20 data lines. A file whose
    string values first appear after the probe window is still caught: if
    the fast path leaves a column entirely NaN, auto-detection re-parses
    with the mixed-type parser.
    """
    with open(path, "rb") as f:
        raw = f.read()
    body, names = split_csv_header(raw, header, columns)
    return parse_csv_bytes(body, names, numeric_only, num_partitions)


def split_csv_header(
    raw: bytes, header: bool, columns: Optional[Sequence[str]]
) -> tuple:
    """(raw file bytes) -> (body bytes, column names or None)."""
    body = raw
    names = list(columns) if columns else None
    if header:
        nl = raw.find(b"\n")
        head_line = raw[: nl if nl >= 0 else len(raw)].decode("utf-8", "replace").strip()
        if names is None:
            names = [c.strip() for c in head_line.split(",")]
        body = raw[nl + 1 :] if nl >= 0 else b""
    return body, names


def parse_csv_bytes(
    body: bytes,
    names: Optional[list],
    numeric_only: Optional[bool] = None,
    num_partitions: int = 1,
) -> DataFrame:
    """Parse headerless CSV bytes (the per-chunk entry the streaming reader
    shares with read_csv)."""
    auto_detected = numeric_only is None
    if numeric_only is None:
        # probe a prefix of data lines, not just the first — a leading row
        # of empty/numeric fields must not send string columns to NaN
        probed = 0
        numeric_only = True
        for line in body.split(b"\n"):
            if not line.strip():
                continue
            if not _line_is_numeric(line):
                numeric_only = False
                break
            probed += 1
            if probed >= 20:
                break
        if probed == 0 and numeric_only:
            numeric_only = False  # no data lines

    if numeric_only:
        mat = _parse_numeric(body)
        if mat is None:  # no native toolchain: python fallback (NaN-padded
            # like the native parser, tolerating ragged rows)
            mat = _py_parse_numeric(body)
        # auto-detection guard: a column that parsed entirely NaN may mean
        # the probe window missed late-appearing strings. Re-parse with the
        # mixed-type parser only if such a column really holds unparseable
        # text (a legitimately empty numeric column keeps the fast path).
        suspects = (
            set(np.flatnonzero(np.isnan(mat).all(axis=0)))
            if auto_detected and mat.size
            else set()
        )
        if suspects and _columns_have_text(body, suspects):
            numeric_only = False
        else:
            if names is None:
                names = [f"c{i}" for i in range(mat.shape[1] if mat.ndim == 2 else 0)]
            # more data columns than header names: synthesize names, never drop
            names = list(names) + [f"c{i}" for i in range(len(names), mat.shape[1])]
            cols = {names[i]: mat[:, i] for i in range(mat.shape[1])}
            return DataFrame.from_dict(cols, num_partitions=num_partitions)

    # mixed types: python csv, column-wise type inference
    text = body.decode("utf-8", "replace")
    rows = [r for r in _csv.reader(_io.StringIO(text)) if r]
    width = max((len(r) for r in rows), default=len(names) if names else 0)
    if names is None:
        names = [f"c{i}" for i in range(width)]
    # rows wider than the header: synthesize names, never drop fields
    names = list(names) + [f"c{i}" for i in range(len(names), width)]
    cols_raw: list[list] = [[] for _ in names]
    for r in rows:
        for i in range(len(names)):
            cols_raw[i].append(r[i] if i < len(r) else "")
    out = {}
    for name, vals in zip(names, cols_raw):
        arr = _infer_column(vals)
        out[name] = arr
    return DataFrame.from_dict(out, num_partitions=num_partitions)


def _columns_have_text(body: bytes, col_idx: set) -> bool:
    """True if any of the given column indices holds a non-empty field that
    does not parse as a float (i.e. real text, not just missing values).

    Stays on bytes (no per-line decode) and splits only as far as the last
    suspect column, so the common refutation scan is cheap even for large
    files with one legitimately empty column."""
    max_idx = max(col_idx)
    for line in body.split(b"\n"):
        if not line.strip():
            continue
        fields = line.split(b",", max_idx + 1)
        for i in col_idx:
            if i < len(fields):
                field = fields[i].strip()
                if field:
                    try:
                        float(field)
                    except ValueError:
                        return True
    return False


def _py_parse_numeric(body: bytes) -> np.ndarray:
    """Pure-python numeric parse matching the native parser's semantics:
    NaN for empty/bad fields, short rows padded, extra fields dropped."""
    lines = [ln for ln in body.decode("utf-8", "replace").splitlines() if ln.strip()]
    if not lines:
        return np.zeros((0, 0), np.float64)
    n_cols = lines[0].count(",") + 1
    out = np.full((len(lines), n_cols), np.nan, np.float64)
    for r, ln in enumerate(lines):
        for c, field in enumerate(ln.split(",")[:n_cols]):
            field = field.strip()
            if field:
                try:
                    out[r, c] = float(field)
                except ValueError:
                    pass
    return out


def _line_is_numeric(line: bytes) -> bool:
    if not line.strip():
        return False
    for field in line.decode("utf-8", "replace").split(","):
        field = field.strip()
        if field == "":
            continue
        try:
            float(field)
        except ValueError:
            return False
    return True


def _infer_column(vals: list) -> np.ndarray:
    try:
        return np.array([float(v) if v.strip() else np.nan for v in vals], np.float64)
    except ValueError:
        arr = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            arr[i] = v
        return arr
