"""HTTP send + handler strategies.

Rebuilds the reference's client stack (io/http/Clients.scala:48-63,
HTTPClients.scala:64-150): a raw ``send_request``, a ``BasicHandler`` that
sends once, and an ``AdvancedHandler`` with retry/backoff on retryable
status codes. Concurrency comes from the caller (HTTPTransformer fans a
partition out over a bounded thread pool — AsyncClient analogue).
"""

from __future__ import annotations

import socket
import time
import urllib.error
import urllib.request
from typing import Callable, Optional, Sequence

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.io.http_schema import HTTPResponseData

Handler = Callable[[dict], dict]

_M_REQS = obs.counter(
    "mmlspark_io_requests_total", "Outbound HTTP requests sent",
)
_M_REQ_ERRS = obs.counter(
    "mmlspark_io_request_errors_total",
    "Outbound requests that became status-0 rows, by error kind",
    labels=("kind",),
)
_M_REQ_SECONDS = obs.histogram(
    "mmlspark_io_request_seconds", "Outbound HTTP request wall time",
)
_M_RETRIES = obs.counter(
    "mmlspark_io_retries_total",
    "AdvancedHandler re-sends after a retryable status",
)
_M_BACKOFF = obs.counter(
    "mmlspark_io_backoff_seconds_total",
    "Cumulative AdvancedHandler backoff sleep",
)


def send_request(request: dict, timeout: float = 60.0) -> dict:
    """Send one request dict, return a response dict. Network errors become
    status_code=0 responses (the reference surfaces nulls/errors as rows,
    never exceptions mid-partition).

    Fault point ``io.send_request``: an injected network error follows the
    same become-a-status-0-row path as a real one; an int payload becomes
    a synthetic response with that HTTP status (5xx storms); a rule delay
    simulates a hung connection."""
    req = urllib.request.Request(
        request["url"],
        data=request.get("entity"),
        headers=request.get("headers") or {},
        method=request.get("method", "GET"),
    )
    _M_REQS.inc()
    t0 = time.perf_counter()
    try:
        injected = faults.inject("io.send_request", context=request)
        # bool excluded: a delay-only rule returns payload True, which
        # must fall through to the REAL request (hung-connection sim),
        # not become a synthetic status_code=True response
        if isinstance(injected, int) and not isinstance(injected, bool):
            return HTTPResponseData(injected, b"", "injected fault")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return HTTPResponseData(
                resp.status, resp.read(), getattr(resp, "reason", ""), dict(resp.headers)
            )
    except urllib.error.HTTPError as e:  # non-2xx still has a response body
        return HTTPResponseData(e.code, e.read(), str(e.reason), dict(e.headers or {}))
    except (urllib.error.URLError, socket.timeout, ConnectionError, OSError) as e:
        _M_REQ_ERRS.labels(kind=type(e).__name__).inc()
        return HTTPResponseData(0, b"", f"{type(e).__name__}: {e}")
    finally:
        _M_REQ_SECONDS.observe(time.perf_counter() - t0)


def BasicHandler(timeout: float = 60.0) -> Handler:
    """HandlingUtils.basic analogue — single attempt."""
    return lambda request: send_request(request, timeout=timeout)


def AdvancedHandler(
    retry_codes: Sequence[int] = (0, 429, 500, 502, 503, 504),
    backoffs_ms: Sequence[int] = (100, 500, 1000),
    timeout: float = 60.0,
    sleep: Callable[[float], None] = time.sleep,
) -> Handler:
    """HandlingUtils.advancedUDF analogue (HTTPClients.scala:64-150):
    retries retryable codes with the given backoff schedule; honors
    Retry-After when present."""

    def handle(request: dict) -> dict:
        resp = send_request(request, timeout=timeout)
        for backoff in backoffs_ms:
            if resp["status_code"] not in retry_codes:
                return resp
            retry_after = (resp.get("headers") or {}).get("Retry-After")
            try:
                # RFC 7231 allows delta-seconds or an HTTP-date; fall back to
                # the schedule for dates rather than parsing them. Clamp so a
                # hostile/buggy server can't park a partition thread for hours.
                delay = float(retry_after) if retry_after else backoff / 1000.0
                delay = min(max(delay, 0.0), max(30.0, backoff / 1000.0))
                if delay != delay:  # NaN
                    delay = backoff / 1000.0
            except ValueError:
                delay = backoff / 1000.0
            _M_BACKOFF.inc(delay)
            sleep(delay)
            _M_RETRIES.inc()
            resp = send_request(request, timeout=timeout)
        return resp

    return handle
