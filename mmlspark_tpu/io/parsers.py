"""Request/response parser stages (io/http/Parsers.scala analogue).

``JSONInputParser`` turns a data column into HTTP request rows for a fixed
URL; ``JSONOutputParser``/``StringOutputParser`` decode response rows;
``Custom*Parser`` lift arbitrary functions. All are ordinary transformers so
they compose inside SimpleHTTPTransformer's internal pipeline.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Transformer


def _to_jsonable(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return [_to_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _to_jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    if isinstance(v, (bytes, bytearray)):
        return v.decode("utf-8", "replace")
    return v


class _ObjectColumnTransformer(Transformer):
    """Maps input_col values through ``self._map_value`` into output_col."""

    def _map_value(self, v: Any) -> Any:
        raise NotImplementedError

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")

        def col_fn(p: dict) -> np.ndarray:
            vals = [self._map_value(v) for v in p[in_col]]
            out = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                out[i] = v
            return out

        return df.with_column(out_col, col_fn)


class JSONInputParser(_ObjectColumnTransformer, HasInputCol, HasOutputCol):
    """Data column -> POST request rows with JSON bodies
    (Parsers.scala JSONInputParser analogue)."""

    url = Param("target URL for generated requests", type_=str)
    method = Param("HTTP method", default="POST", type_=str)
    headers = Param("extra headers to attach", default={}, type_=dict)

    def _map_value(self, v: Any) -> Any:
        from mmlspark_tpu.io.http_schema import HTTPRequestData

        headers = {"Content-Type": "application/json"}
        headers.update(self.get("headers") or {})
        return HTTPRequestData(
            self.get_or_fail("url"),
            self.get("method"),
            headers,
            json.dumps(_to_jsonable(v)),
        )


class JSONOutputParser(_ObjectColumnTransformer, HasInputCol, HasOutputCol):
    """Response rows -> parsed JSON values; optional ``data_type`` projects
    the given keys out of the top-level object."""

    data_type = Param("optional list of keys to project from the JSON object", type_=list)

    def _map_value(self, v: Any) -> Any:
        from mmlspark_tpu.io.http_schema import response_to_json

        obj = response_to_json(v)
        keys = self.get("data_type")
        if keys and isinstance(obj, dict):
            return {k: obj.get(k) for k in keys}
        return obj


class StringOutputParser(_ObjectColumnTransformer, HasInputCol, HasOutputCol):
    """Response rows -> entity text (Parsers.scala StringOutputParser)."""

    def _map_value(self, v: Any) -> Any:
        from mmlspark_tpu.io.http_schema import entity_to_string

        return entity_to_string(v)


class CustomInputParser(_ObjectColumnTransformer, HasInputCol, HasOutputCol):
    """UDF value -> request row (Parsers.scala CustomInputParser)."""

    udf = ComplexParam("function value -> request dict")

    def set_udf(self, fn: Callable[[Any], dict]) -> "CustomInputParser":
        return self.set(udf=fn)

    def _map_value(self, v: Any) -> Any:
        return self.get_or_fail("udf")(v)


class CustomOutputParser(_ObjectColumnTransformer, HasInputCol, HasOutputCol):
    """UDF response row -> value (Parsers.scala CustomOutputParser)."""

    udf = ComplexParam("function response dict -> value")

    def set_udf(self, fn: Callable[[dict], Any]) -> "CustomOutputParser":
        return self.set(udf=fn)

    def _map_value(self, v: Any) -> Any:
        return self.get_or_fail("udf")(v)
