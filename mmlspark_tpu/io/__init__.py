"""IO layer: HTTP-on-dataframes, binary/image ingestion, writers.

Rebuilds the reference's ``io/`` package (SURVEY.md §2.6): HTTP request/
response schema structs, async bounded-concurrency clients with retry,
`HTTPTransformer`/`SimpleHTTPTransformer`, JSON parsers,
`PartitionConsolidator`, `SharedVariable`, binary file ingestion and the
PowerBI-style POST writer.
"""

from mmlspark_tpu.io.http_schema import (
    HTTPRequestData,
    HTTPResponseData,
    string_to_response,
)
from mmlspark_tpu.io.shared import SharedSingleton, SharedVariable
from mmlspark_tpu.io.clients import AdvancedHandler, BasicHandler, send_request
from mmlspark_tpu.io.parsers import (
    CustomInputParser,
    CustomOutputParser,
    JSONInputParser,
    JSONOutputParser,
    StringOutputParser,
)
from mmlspark_tpu.io.http_transformer import HTTPTransformer, SimpleHTTPTransformer
from mmlspark_tpu.io.consolidator import PartitionConsolidator
from mmlspark_tpu.io.binary import read_binary_files, read_images
from mmlspark_tpu.io.csv import read_csv
from mmlspark_tpu.io.port_forwarding import PortForwarding, build_forward_command
from mmlspark_tpu.io.powerbi import PowerBIWriter

__all__ = [
    "HTTPRequestData",
    "HTTPResponseData",
    "string_to_response",
    "SharedVariable",
    "SharedSingleton",
    "BasicHandler",
    "AdvancedHandler",
    "send_request",
    "JSONInputParser",
    "JSONOutputParser",
    "StringOutputParser",
    "CustomInputParser",
    "CustomOutputParser",
    "HTTPTransformer",
    "SimpleHTTPTransformer",
    "PartitionConsolidator",
    "read_binary_files",
    "read_images",
    "PowerBIWriter",
    "read_csv",
    "PortForwarding",
    "build_forward_command",
]
