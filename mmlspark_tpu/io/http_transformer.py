"""HTTP-on-DataFrame transformers.

``HTTPTransformer`` (io/http/HTTPTransformer.scala:88-120 analogue): a
column of request rows is sent with bounded per-partition concurrency;
responses land in the output column. Partitions already run on the task
pool, so each partition fans its rows out over a small futures buffer —
the AsyncClient + ``AsyncUtils.bufferedAwait`` design.

``SimpleHTTPTransformer`` (io/http/SimpleHTTPTransformer.scala:111-154
analogue): assembles [optional minibatch] -> input parser -> HTTP ->
error split -> output parser -> [flatten] as one stage.
"""

from __future__ import annotations

import concurrent.futures as _futures
from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasInputCol,
    HasOutputCol,
    Param,
    Params,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.io.clients import AdvancedHandler, BasicHandler
from mmlspark_tpu.io.parsers import JSONInputParser, JSONOutputParser
from mmlspark_tpu.io.shared import SharedVariable


class _HasHandler(Params):
    """Shared handler/concurrency params (HasHandler analogue)."""

    concurrency = Param(
        "max in-flight requests per partition", default=8, type_=int,
        validator=lambda v: v > 0,
    )
    timeout = Param("per-request timeout seconds", default=60.0, type_=float)
    use_advanced_handler = Param("retry with backoff on 429/5xx", default=True, type_=bool)
    backoffs_ms = Param("retry backoff schedule (ms)", default=[100, 500, 1000], type_=list)
    custom_handler = ComplexParam("override handler fn request->response")

    def _make_handler(self) -> Any:
        if self.get("custom_handler") is not None:
            return self.get("custom_handler")
        if self.get("use_advanced_handler"):
            return AdvancedHandler(
                backoffs_ms=self.get("backoffs_ms"), timeout=self.get("timeout")
            )
        return BasicHandler(timeout=self.get("timeout"))


class HTTPTransformer(Transformer, _HasHandler, HasInputCol, HasOutputCol):
    """Request-row column -> response-row column, async per partition."""

    def pipeline_io(self) -> tuple:
        """Declared I/O for the pipeline compiler: host-bound (network),
        row-local, row-preserving — exactly the stage the critical-path
        scheduler can overlap with an independent branch."""
        return (self.get_or_fail("input_col"),), (self.get_or_fail("output_col"),)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")
        concurrency = self.get("concurrency")
        # one handler per process; closures over it stay picklable
        handler_var = SharedVariable(self._make_handler)

        def col_fn(p: dict) -> np.ndarray:
            reqs = list(p[in_col])
            handler = handler_var.get()
            out = np.empty(len(reqs), dtype=object)
            if not reqs:
                return out
            # IO-bound: a private bounded pool per partition call overlaps
            # requests without starving the partition task pool
            with _futures.ThreadPoolExecutor(max_workers=concurrency) as pool:
                for i, resp in enumerate(pool.map(
                    lambda r: None if r is None else handler(r), reqs
                )):
                    out[i] = resp
            return out

        return df.with_column(out_col, col_fn)


class SimpleHTTPTransformer(Transformer, _HasHandler, HasInputCol, HasOutputCol):
    """One-stop data->request->send->parse stage."""

    url = Param("service URL", type_=str)
    method = Param("HTTP method", default="POST", type_=str)
    headers = Param("extra request headers", default={}, type_=dict)
    input_parser = ComplexParam("stage mapping data col -> request col (default JSON POST)")
    output_parser = ComplexParam("stage mapping response col -> output col (default JSON)")
    error_col = Param("column for failed-response rows", default="", type_=str)
    flatten_output = Param(
        "explode parsed list responses back to rows (after a minibatcher)",
        default=False, type_=bool,
    )
    mini_batcher = ComplexParam("optional minibatching transformer applied first")

    def _error_col(self) -> str:
        return self.get("error_col") or f"{self.get_or_fail('output_col')}_error"

    def pipeline_io(self) -> Any:
        """Declared I/O for the pipeline compiler: reads the data column,
        writes the error column then the output column (staged insertion
        order). Declines (None -> opaque barrier) when a minibatcher or
        ``flatten_output`` changes row structure."""
        if self.get("mini_batcher") is not None or self.get("flatten_output"):
            return None
        return (
            (self.get_or_fail("input_col"),),
            (self._error_col(), self.get_or_fail("output_col")),
        )

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")
        err_col = self._error_col()

        batcher = self.get("mini_batcher")
        if batcher is not None:
            df = batcher.transform(df)

        from mmlspark_tpu.core.schema import find_unused_column

        req_col = find_unused_column("_request", df.columns)
        resp_col = find_unused_column("_response", df.columns + [req_col])

        parser_in = self.get("input_parser") or JSONInputParser(
            url=self.get_or_fail("url"),
            method=self.get("method"),
            headers=self.get("headers"),
        )
        parser_in = parser_in.copy(
            {"input_col": in_col, "output_col": req_col}
        )
        parser_out = self.get("output_parser") or JSONOutputParser()
        parser_out = parser_out.copy(
            {"input_col": resp_col, "output_col": out_col}
        )

        http = HTTPTransformer(
            input_col=req_col,
            output_col=resp_col,
            concurrency=self.get("concurrency"),
            timeout=self.get("timeout"),
            use_advanced_handler=self.get("use_advanced_handler"),
            backoffs_ms=self.get("backoffs_ms"),
        )
        if self.get("custom_handler") is not None:
            http.set(custom_handler=self.get("custom_handler"))

        out = http.transform(parser_in.transform(df))

        # error split (SimpleHTTPTransformer.scala:96-109): non-2xx responses
        # go to the error column; the parsed output is None for those rows
        def err_fn(p: dict) -> np.ndarray:
            vals = np.empty(len(p[resp_col]), dtype=object)
            for i, r in enumerate(p[resp_col]):
                vals[i] = r if (r is None or r["status_code"] // 100 != 2) else None
            return vals

        out = out.with_column(err_col, err_fn)

        def ok_fn(p: dict) -> np.ndarray:
            vals = np.empty(len(p[resp_col]), dtype=object)
            for i, r in enumerate(p[resp_col]):
                vals[i] = r if (r is not None and r["status_code"] // 100 == 2) else None
            return vals

        out = out.with_column(resp_col, ok_fn)
        out = parser_out.transform(out).drop(req_col, resp_col)

        if self.get("flatten_output"):
            from mmlspark_tpu.stages.batching import FlattenBatch

            # per-batch scalars (the error column, or a None output for a
            # failed batch) must be expanded to per-row values before
            # FlattenBatch concatenates
            def expand(p: dict) -> dict:
                q = dict(p)
                lens = [
                    len(v) if hasattr(v, "__len__") else 1 for v in p[in_col]
                ]
                for col in (out_col, err_col):
                    vals = np.empty(len(lens), dtype=object)
                    for i, (v, n) in enumerate(zip(p[col], lens)):
                        is_rowwise = (
                            col == out_col
                            and isinstance(v, (list, np.ndarray))
                            and len(v) == n
                        )
                        vals[i] = v if is_rowwise else [v] * n
                    q[col] = vals
                return q

            out = FlattenBatch().transform(out.map_partitions(expand))
        return out
