"""SSH port forwarding for serving behind NAT (io/http/PortForwarding.scala).

The reference opens jsch remote-forward sessions so an executor-local
serving port is reachable from a gateway host. Here the tunnel is an
``ssh -N -R`` child process managed with context semantics; serving's
WorkerServer can attach one per host (HTTPSourceV2.scala:657-665 analogue).
No paramiko in the image — the system ssh client is the transport.
"""

from __future__ import annotations

import shlex
import subprocess
import time
from typing import Optional


def build_forward_command(
    remote_host: str,
    remote_port: int,
    local_port: int,
    user: Optional[str] = None,
    key_file: Optional[str] = None,
    bind_address: str = "",
    ssh_options: Optional[dict] = None,
) -> list:
    """Construct the ``ssh -N -R`` argv for a remote forward
    remote_host:remote_port -> localhost:local_port."""
    spec = f"{bind_address}:{remote_port}:127.0.0.1:{local_port}" if bind_address else f"{remote_port}:127.0.0.1:{local_port}"
    cmd = ["ssh", "-N", "-R", spec]
    opts = {
        # trust-on-first-use: record unseen host keys, refuse changed ones.
        # Needs OpenSSH >= 7.6; on older clients (or to opt out) pass
        # ssh_options={"StrictHostKeyChecking": "no"}.
        "StrictHostKeyChecking": "accept-new",
        "ExitOnForwardFailure": "yes",
        "ServerAliveInterval": "30",
    }
    opts.update(ssh_options or {})
    for k, v in sorted(opts.items()):
        cmd += ["-o", f"{k}={v}"]
    if key_file:
        cmd += ["-i", key_file]
    target = f"{user}@{remote_host}" if user else remote_host
    cmd.append(target)
    return cmd


class PortForwarding:
    """Managed reverse-forward tunnel; ``with PortForwarding(...) :`` or
    explicit start/stop."""

    def __init__(
        self,
        remote_host: str,
        remote_port: int,
        local_port: int,
        user: Optional[str] = None,
        key_file: Optional[str] = None,
        **ssh_options: str,
    ):
        self.command = build_forward_command(
            remote_host, remote_port, local_port, user, key_file,
            ssh_options=ssh_options or None,
        )
        self._proc: Optional[subprocess.Popen] = None

    @property
    def running(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def start(self, settle_seconds: float = 0.5) -> "PortForwarding":
        if self.running:
            return self
        self._proc = subprocess.Popen(
            self.command, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE
        )
        time.sleep(settle_seconds)
        if self._proc.poll() is not None:  # died immediately: surface stderr
            err = (self._proc.stderr.read() if self._proc.stderr else b"").decode(
                "utf-8", "replace"
            )
            self._proc = None
            raise RuntimeError(
                f"ssh forward failed ({shlex.join(self.command)}): {err.strip()}"
            )
        # long-lived tunnel: drain stderr in the background so a chatty ssh
        # (keepalive warnings, -v) can never fill the pipe and block forwarding
        stderr = self._proc.stderr

        def _drain() -> None:
            try:
                while stderr.read(65536):
                    pass
            except (OSError, ValueError):
                pass

        import threading

        threading.Thread(target=_drain, name="ssh-stderr-drain", daemon=True).start()
        return self

    def stop(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._proc = None

    def __enter__(self) -> "PortForwarding":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()
