"""Contextual bandit learning with action-dependent features.

Rebuilds ``VowpalWabbitContextualBandit`` (vw/VowpalWabbitContextualBandit.scala)
and ``ContextualBanditMetrics`` (IPS/SNIPS) for the TPU framework.

Row layout: a shared-context sparse column plus a column whose cells are
*lists* of sparse rows (one per action — the ADF ``ExampleStack``
analogue), the 1-based chosen action, its logged probability, and the
observed cost. Training is IPS-weighted cost regression on the chosen
action's (shared + action) features — ``--cb_type ips`` semantics — run
through the same device SGD kernel as the supervised learners.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, HasFeaturesCol, HasPredictionCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.vw.featurizer import HasNumBits
from mmlspark_tpu.vw.learner import LOSS_SQUARED, predict_margin, train_sparse_sgd
from mmlspark_tpu.vw.sparse import NUM_BITS_META, concat_sparse, pad_sparse_batch


class VowpalWabbitContextualBandit(
    Estimator, HasFeaturesCol, HasNumBits
):
    shared_col = Param("shared-context sparse column", default="shared", type_=str)
    features_col = Param(
        "column of per-action sparse feature lists", default="features", type_=str
    )
    chosen_action_col = Param("1-based chosen action", default="chosen_action", type_=str)
    probability_col = Param("logged action probability", default="probability", type_=str)
    label_col = Param("observed cost of the chosen action", default="label", type_=str)
    num_passes = Param("passes over the data", default=1, type_=int)
    learning_rate = Param("initial learning rate", default=0.5, type_=float)
    l2 = Param("L2 regularization", default=0.0, type_=float)
    batch_size = Param("device minibatch size", default=64, type_=int)
    max_importance_weight = Param(
        "clip 1/p IPS weights at this value", default=100.0, type_=float
    )

    def fit(self, df: DataFrame) -> "VowpalWabbitContextualBanditModel":
        shared_c = self.get("shared_col")
        act_c = self.get("features_col")
        has_shared = shared_c in df.columns
        num_bits = (
            df.column_metadata(act_c).get(NUM_BITS_META)
            or (df.column_metadata(shared_c).get(NUM_BITS_META) if has_shared else None)
            or self.get("num_bits")
        )
        chosen = df[self.get("chosen_action_col")].astype(np.int64)
        prob = df[self.get("probability_col")].astype(np.float32)
        cost = df[self.get("label_col")].astype(np.float32)
        actions = df[act_c]
        shared = df[shared_c] if has_shared else None
        rows = []
        for r in range(len(chosen)):
            a = int(chosen[r]) - 1  # VW chosen actions are 1-based
            acts = actions[r]
            if not 0 <= a < len(acts):
                raise ValueError(f"row {r}: chosen action {a + 1} out of range")
            parts = [acts[a]] if shared is None else [shared[r], acts[a]]
            rows.append(concat_sparse(parts))
        idx, val = pad_sparse_batch(rows)
        wt = np.minimum(1.0 / np.maximum(prob, 1e-6), self.get("max_importance_weight"))
        w = train_sparse_sgd(
            idx,
            val,
            cost,
            wt.astype(np.float32),
            int(num_bits),
            loss=LOSS_SQUARED,
            num_passes=self.get("num_passes"),
            batch=self.get("batch_size"),
            lr=self.get("learning_rate"),
            l2=self.get("l2"),
        )
        m = VowpalWabbitContextualBanditModel(
            shared_col=shared_c if has_shared else "",
            features_col=act_c,
        )
        m.set(weights=w, num_bits=int(num_bits))
        return m


class VowpalWabbitContextualBanditModel(Model, HasFeaturesCol, HasPredictionCol):
    """Scores every action; prediction = argmin predicted cost (1-based)."""

    shared_col = Param("shared-context sparse column (empty = none)", default="", type_=str)
    features_col = Param("column of per-action sparse lists", default="features", type_=str)
    scores_col = Param("output per-action predicted-cost column", default="scores", type_=str)
    num_bits = Param("hashed space width", default=18, type_=int)
    weights = ComplexParam("(2^num_bits,) learned weights")

    def transform(self, df: DataFrame) -> DataFrame:
        w = np.asarray(self.get_or_fail("weights"))
        shared_c = self.get("shared_col")
        act_c = self.get("features_col")

        def fn(p: dict) -> dict:
            actions = p[act_c]
            shared = p[shared_c] if shared_c else None
            n = len(actions)
            # flatten (row, action) pairs into one padded batch -> one kernel call
            flat: list = []
            counts = np.zeros(n, np.int64)
            for r in range(n):
                for a in actions[r]:
                    parts = [a] if shared is None else [shared[r], a]
                    flat.append(concat_sparse(parts))
                counts[r] = len(actions[r])
            scores_out = np.empty(n, dtype=object)
            pred = np.zeros(n, np.float64)
            if flat:
                idx, val = pad_sparse_batch(flat)
                margins = predict_margin(idx, val, w)
                # flat is row-major: one linear split regroups per row
                for r, s in enumerate(np.split(margins, np.cumsum(counts)[:-1])):
                    scores_out[r] = s.astype(np.float64)
                    pred[r] = float(np.argmin(s)) + 1 if len(s) else 0.0
            q = dict(p)
            q[self.get("scores_col")] = scores_out
            q[self.get("prediction_col")] = pred
            return q

        return df.map_partitions(fn, parallel=False)


class ContextualBanditMetrics:
    """Offline policy-value estimators (IPS / SNIPS) — the
    ``ContextualBanditMetrics`` analogue. Accumulate logged (probability,
    cost) with the target policy's probability of the logged action."""

    def __init__(self) -> None:
        self.total_weighted_cost = 0.0
        self.total_weight = 0.0
        self.n = 0

    def add(self, target_prob: float, logged_prob: float, cost: float) -> None:
        w = float(target_prob) / max(float(logged_prob), 1e-9)
        self.total_weighted_cost += w * float(cost)
        self.total_weight += w
        self.n += 1

    def get_ips_estimate(self) -> float:
        return self.total_weighted_cost / max(self.n, 1)

    def get_snips_estimate(self) -> float:
        if self.total_weight == 0:
            return 0.0
        return self.total_weighted_cost / self.total_weight
