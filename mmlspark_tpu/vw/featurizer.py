"""VW-style feature hashing stages.

Rebuilds ``VowpalWabbitFeaturizer`` (vw/VowpalWabbitFeaturizer.scala, with
the per-type featurizers of vw/featurizer/*.scala) and
``VowpalWabbitInteractions`` (vw/VowpalWabbitInteractions.scala) for the
TPU framework: columns are hashed into a 2^num_bits index space with
MurmurHash3 (the ``VowpalWabbitMurmurWithPrefix`` analogue lives in
``ops.hashing``), producing the sparse rows consumed by the device SGD
learner in ``vw.learner``.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.core.params import HasInputCols, HasOutputCol, HasSeed, Param
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.ops.hashing import hash_strings, murmur3_bytes
from mmlspark_tpu.vw.sparse import (
    NUM_BITS_META,
    SPARSE_META,
    concat_sparse,
    make_sparse,
)

# FNV-style combine used for feature crossing (quadratic -q interactions).
_FNV_PRIME = np.int64(16777619)


class HasNumBits(HasSeed):
    num_bits = Param(
        "width of the hashed feature space in bits (vw/HasNumBits.scala)",
        default=18,
        type_=int,
        validator=lambda v: 1 <= v <= 30,
    )

    def _mask(self) -> np.int64:
        return np.int64((1 << self.get("num_bits")) - 1)


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol, HasNumBits):
    """Hash heterogeneous columns into one sparse namespace.

    Per-type behavior (vw/featurizer/*.scala parity):
    - numeric / bool column -> one feature named after the column
    - string column         -> categorical feature ``col=value`` with value 1
    - list-of-strings cell  -> one feature per token
    - dict cell             -> one feature per ``col.key`` with numeric value
    - dense vector column   -> one feature per dimension (hashes precomputed
      once per column, so wide vectors cost one hash pass, not n*d)
    - columns in ``string_split_input_cols`` -> whitespace-split tokens
    """

    output_col = Param("output sparse-features column", default="features", type_=str)
    string_split_input_cols = Param(
        "string columns to whitespace-split into token features", default=[], type_=list
    )
    sum_collisions = Param(
        "sum values of colliding hashes (vs keep one)", default=True, type_=bool
    )

    def transform(self, df: DataFrame) -> DataFrame:
        cols = list(self.get_or_fail("input_cols"))
        split_cols = list(self.get("string_split_input_cols"))
        mask = self._mask()
        seed = self.get("seed")
        dedupe = self.get("sum_collisions")
        out_col = self.get("output_col")

        def fn(p: Partition) -> Partition:
            n = len(next(iter(p.values()))) if p else 0
            # per-row accumulators
            idx_acc: list = [[] for _ in range(n)]
            val_acc: list = [[] for _ in range(n)]
            for c in cols + split_cols:
                arr = p[c]
                if arr.dtype != object and np.issubdtype(arr.dtype, np.number) and arr.ndim == 2:
                    # dense vector column: hash the d names once
                    d = arr.shape[1]
                    h = (
                        hash_strings([f"{c}_{j}" for j in range(d)], seed).astype(np.int64)
                        & mask
                    )
                    for r in range(n):
                        idx_acc[r].append(h)
                        val_acc[r].append(np.asarray(arr[r], np.float32))
                    continue
                if arr.dtype != object and (
                    np.issubdtype(arr.dtype, np.number) or arr.dtype == bool
                ):
                    h = np.int64(murmur3_bytes(c.encode("utf-8"), seed)) & mask
                    one = np.array([h], np.int64)
                    for r in range(n):
                        v = float(arr[r])
                        if v != 0.0:
                            idx_acc[r].append(one)
                            val_acc[r].append(np.array([v], np.float32))
                    continue
                # object column: strings / token lists / dicts
                is_split = c in split_cols
                names: list = []
                row_of: list = []
                vals: list = []
                for r in range(n):
                    cell = arr[r]
                    if cell is None:
                        continue
                    if isinstance(cell, str):
                        toks = cell.split() if is_split else [f"{c}={cell}"]
                        for t in toks:
                            names.append(t)
                            row_of.append(r)
                            vals.append(1.0)
                    elif isinstance(cell, dict):
                        for k, v in cell.items():
                            names.append(f"{c}.{k}")
                            row_of.append(r)
                            vals.append(float(v))
                    elif isinstance(cell, (list, tuple, np.ndarray)):
                        for t in cell:
                            names.append(str(t))
                            row_of.append(r)
                            vals.append(1.0)
                    else:
                        names.append(f"{c}={cell}")
                        row_of.append(r)
                        vals.append(1.0)
                if names:
                    h = hash_strings(names, seed).astype(np.int64) & mask
                    for j, r in enumerate(row_of):
                        idx_acc[r].append(h[j : j + 1])
                        val_acc[r].append(np.array([vals[j]], np.float32))
            out = np.empty(n, dtype=object)
            for r in range(n):
                if idx_acc[r]:
                    out[r] = make_sparse(
                        np.concatenate(idx_acc[r]),
                        np.concatenate(val_acc[r]),
                        dedupe=dedupe,
                    )
                else:
                    out[r] = make_sparse(np.zeros(0, np.int64), np.zeros(0, np.float32))
            q = dict(p)
            q[out_col] = out
            return q

        out = df.map_partitions(fn)
        return out.with_column_metadata(
            out_col, {SPARSE_META: True, NUM_BITS_META: self.get("num_bits")}
        )


class VowpalWabbitInteractions(Transformer, HasInputCols, HasOutputCol, HasNumBits):
    """-q style feature crossing: the cartesian product of the input sparse
    namespaces, indices combined with an FNV-style hash, values multiplied
    (vw/VowpalWabbitInteractions.scala)."""

    output_col = Param("output crossed-features column", default="interactions", type_=str)

    def transform(self, df: DataFrame) -> DataFrame:
        cols = list(self.get_or_fail("input_cols"))
        if len(cols) < 2:
            raise ValueError("VowpalWabbitInteractions needs >= 2 input namespaces")
        mask = self._mask()
        out_col = self.get("output_col")

        def cross(a: dict, b: dict) -> dict:
            ia, va = a["i"], a["v"]
            ib, vb = b["i"], b["v"]
            if len(ia) == 0 or len(ib) == 0:
                return make_sparse(np.zeros(0, np.int64), np.zeros(0, np.float32))
            with np.errstate(over="ignore"):
                combined = ((ia[:, None] * _FNV_PRIME) ^ ib[None, :]) & mask
            return make_sparse(combined.ravel(), np.outer(va, vb).ravel(), dedupe=False)

        def fn(p: Partition) -> Partition:
            n = len(next(iter(p.values()))) if p else 0
            out = np.empty(n, dtype=object)
            for r in range(n):
                acc = p[cols[0]][r]
                for c in cols[1:]:
                    acc = cross(acc, p[c][r])
                out[r] = make_sparse(acc["i"], acc["v"])
            q = dict(p)
            q[out_col] = out
            return q

        out = df.map_partitions(fn)
        return out.with_column_metadata(
            out_col, {SPARSE_META: True, NUM_BITS_META: self.get("num_bits")}
        )


def combine_namespaces(columns: dict, cols: list) -> np.ndarray:
    """Row-wise concatenation of several sparse columns (the VW example =
    all namespaces of the row). ``columns`` maps column name -> object array
    of sparse rows; single-column requests pass through untouched."""
    if len(cols) == 1:
        return columns[cols[0]]
    n = len(columns[cols[0]])
    out = np.empty(n, dtype=object)
    for r in range(n):
        out[r] = concat_sparse([columns[c][r] for c in cols])
    return out
