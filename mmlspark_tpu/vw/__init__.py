"""VW-equivalent online learning on TPU (SURVEY.md §2.3).

Hashed sparse features -> device SGD (AdaGrad) with per-pass weight
allreduce over the mesh, replacing VW's native train loop + spanning-tree
allreduce (vw/VowpalWabbitBase.scala).
"""

from mmlspark_tpu.vw.contextual_bandit import (
    ContextualBanditMetrics,
    VowpalWabbitContextualBandit,
    VowpalWabbitContextualBanditModel,
)
from mmlspark_tpu.vw.estimators import (
    VowpalWabbitClassificationModel,
    VowpalWabbitClassifier,
    VowpalWabbitRegressionModel,
    VowpalWabbitRegressor,
)
from mmlspark_tpu.vw.featurizer import (
    VowpalWabbitFeaturizer,
    VowpalWabbitInteractions,
)
from mmlspark_tpu.vw.sparse import concat_sparse, make_sparse, pad_sparse_batch

__all__ = [
    "ContextualBanditMetrics",
    "VowpalWabbitClassifier",
    "VowpalWabbitClassificationModel",
    "VowpalWabbitContextualBandit",
    "VowpalWabbitContextualBanditModel",
    "VowpalWabbitFeaturizer",
    "VowpalWabbitInteractions",
    "VowpalWabbitRegressor",
    "VowpalWabbitRegressionModel",
    "concat_sparse",
    "make_sparse",
    "pad_sparse_batch",
]
