"""Sparse hashed-feature representation shared by the VW-style stages.

A sparse feature row is a dict ``{"i": int64[nnz], "v": float32[nnz]}``
(indices into a 2^num_bits weight space, values). Column metadata carries
``{"sparse": True, "num_bits": b}``.

TPU-first: batches are *padded* to a static max-nnz — ``(B, K)`` index and
value matrices — so the training/scoring kernels are fixed-shape gathers
and scatter-adds the MXU/VPU pipeline without recompiles (padding values
are 0.0 so they are exact no-ops in dot products and gradients).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

SPARSE_META = "sparse"
NUM_BITS_META = "num_bits"


def make_sparse(indices: np.ndarray, values: np.ndarray, dedupe: bool = True) -> dict:
    """Build one sparse row, summing duplicate indices (VW sum-collisions)."""
    idx = np.asarray(indices, np.int64).ravel()
    val = np.asarray(values, np.float32).ravel()
    if dedupe and len(idx):
        uniq, inv = np.unique(idx, return_inverse=True)
        if len(uniq) != len(idx):
            summed = np.zeros(len(uniq), np.float32)
            np.add.at(summed, inv, val)
            idx, val = uniq, summed
    return {"i": idx, "v": val}


def empty_sparse() -> dict:
    return {"i": np.zeros(0, np.int64), "v": np.zeros(0, np.float32)}


def concat_sparse(rows: Sequence[dict]) -> dict:
    """Concatenate several namespaces of one example into one sparse row."""
    if not rows:
        return empty_sparse()
    return make_sparse(
        np.concatenate([r["i"] for r in rows]),
        np.concatenate([r["v"] for r in rows]),
        dedupe=False,
    )


def pad_sparse_batch(
    col: Sequence[dict], max_nnz: Optional[int] = None, multiple: int = 8
) -> tuple:
    """Object column of sparse rows -> padded ``(idx, val)`` dense batch.

    Pads nnz up to a multiple (fewer distinct compiled shapes) and rows with
    value 0.0 / index 0 (no-ops in every kernel)."""
    n = len(col)
    if max_nnz is None:
        max_nnz = max((len(r["i"]) for r in col), default=1)
    max_nnz = max(1, int(np.ceil(max(1, max_nnz) / multiple)) * multiple)
    idx = np.zeros((n, max_nnz), np.int64)
    val = np.zeros((n, max_nnz), np.float32)
    for r, row in enumerate(col):
        k = min(len(row["i"]), max_nnz)
        idx[r, :k] = row["i"][:k]
        val[r, :k] = row["v"][:k]
    return idx, val
