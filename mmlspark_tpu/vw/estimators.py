"""VW-style classifier / regressor estimators and models.

Facade parity with vw/VowpalWabbitClassifier.scala and
VowpalWabbitRegressor.scala; the distributed training model
(per-shard online pass + weight allreduce per pass,
VowpalWabbitBase.scala:313-429) runs in ``vw.learner`` as one SPMD XLA
program over the mesh. Per-shard training diagnostics mirror
``TrainingStats`` (VowpalWabbitBase.scala:27-46,431-457).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasWeightCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.ops.hashing import murmur3_bytes
from mmlspark_tpu.vw.featurizer import HasNumBits, combine_namespaces
from mmlspark_tpu.vw.learner import (
    LOSS_HINGE,
    LOSS_LOGISTIC,
    LOSS_POISSON,
    LOSS_QUANTILE,
    LOSS_SQUARED,
    LOSSES,
    predict_margin,
    train_sparse_sgd,
)
from mmlspark_tpu.vw.sparse import NUM_BITS_META, pad_sparse_batch


class _VowpalWabbitBase(
    Estimator, HasFeaturesCol, HasLabelCol, HasWeightCol, HasNumBits
):
    """Shared trainer params (the arg-string builder analogue of
    VowpalWabbitBase.scala:139-169 — params map 1:1 to VW flags)."""

    num_passes = Param("passes over the data (--passes)", default=1, type_=int)
    loss_function = Param(
        "logistic | squared | quantile | hinge | poisson "
        "('' = estimator default; --loss_function)", default="", type_=str,
    )
    quantile_tau = Param(
        "pinball level for loss_function=quantile (--quantile_tau)",
        default=0.5, type_=float,
    )
    pass_through_args = Param(
        "VW-style argument string (passThroughArgs, "
        "VowpalWabbitBase.scala:77-81): recognized flags (--loss_function, "
        "--quantile_tau, -l/--learning_rate, --power_t, --l2, --passes, "
        "--adaptive, -b/--bit_precision) override the matching params; "
        "unknown flags warn and are ignored",
        default="", type_=str,
    )
    learning_rate = Param("initial learning rate (-l)", default=0.5, type_=float)
    power_t = Param("lr decay exponent (--power_t)", default=0.5, type_=float)
    l2 = Param("L2 regularization (--l2)", default=0.0, type_=float)
    adaptive = Param("AdaGrad per-coordinate rates (--adaptive)", default=True, type_=bool)
    batch_size = Param(
        "device minibatch size per shard (0 = auto: 1024 on TPU, 64 "
        "elsewhere)", default=0, type_=int,
    )
    additional_features = Param(
        "extra sparse namespace columns concatenated into the example",
        default=[],
        type_=list,
    )
    initial_model = ComplexParam("continue training from these weights (array)")
    use_barrier_execution_mode = Param(
        "gang-launch flag (no-op: SPMD launch is always gang-scheduled)",
        default=False,
        type_=bool,
    )
    no_constant = Param(
        "drop VW's always-present intercept feature (--noconstant)",
        default=False, type_=bool,
    )

    _loss = LOSS_LOGISTIC

    def _resolve_args(self) -> dict:
        """Param values with the pass-through arg string folded in."""
        out = {
            "loss": self.get("loss_function") or self._loss,
            "tau": self.get("quantile_tau"),
            "lr": self.get("learning_rate"),
            "power_t": self.get("power_t"),
            "l2": self.get("l2"),
            "passes": self.get("num_passes"),
            "adaptive": self.get("adaptive"),
            "bits": None,
        }
        args = (self.get("pass_through_args") or "").split()
        i = 0
        import logging

        log = logging.getLogger("mmlspark_tpu.vw")
        flag_map = {
            "--loss_function": ("loss", str),
            "--quantile_tau": ("tau", float),
            "-l": ("lr", float), "--learning_rate": ("lr", float),
            "--power_t": ("power_t", float),
            "--l2": ("l2", float),
            "--passes": ("passes", int),
            "-b": ("bits", int), "--bit_precision": ("bits", int),
        }
        while i < len(args):
            # both VW syntaxes: "--flag value" and "--flag=value"
            a, eq, inline = args[i].partition("=")
            if a == "--adaptive":
                out["adaptive"] = True
                i += 1
            elif a == "--no_adaptive":
                out["adaptive"] = False
                i += 1
            elif a in flag_map and eq:
                if not inline:
                    raise ValueError(f"pass_through_args: {a} requires a value")
                key, conv = flag_map[a]
                out[key] = conv(inline)
                i += 1
            elif a in flag_map and i + 1 < len(args):
                key, conv = flag_map[a]
                out[key] = conv(args[i + 1])
                i += 2
            elif a in flag_map:
                # a recognized flag with no value is a semantic error, not
                # noise — silently ignoring it would train with defaults
                raise ValueError(f"pass_through_args: {a} requires a value")
            else:
                log.warning("pass_through_args: ignoring unrecognized %r", args[i])
                i += 1
        if out["loss"] not in LOSSES:
            raise ValueError(
                f"loss_function must be one of {LOSSES}, got {out['loss']!r}"
            )
        return out

    def _gather(self, df: DataFrame, bits_override: Optional[int] = None) -> tuple:
        fc = self.get("features_col")
        cols = [fc] + list(self.get("additional_features"))
        sparse_rows = combine_namespaces({c: df[c] for c in cols}, cols)
        feat_bits = int(
            df.column_metadata(fc).get(NUM_BITS_META) or self.get("num_bits")
        )
        num_bits = feat_bits
        if bits_override is not None:
            # -b/--bit_precision resizes the weight table, but features
            # were already hashed into the featurizer's space — a smaller
            # table would silently alias every overflowing index
            if bits_override < feat_bits:
                raise ValueError(
                    f"bit_precision {bits_override} is smaller than the "
                    f"featurized space ({feat_bits} bits); re-featurize "
                    "with the smaller num_bits instead"
                )
            num_bits = int(bits_override)
        idx, val = pad_sparse_batch(sparse_rows)
        if not self.get("no_constant"):
            # VW's intercept: every example carries the hashed "Constant"
            # feature with value 1 unless --noconstant (vw core behavior;
            # without it, e.g. quantile loss cannot shift its level).
            # Hashed in the FINAL bit space so training and scoring (which
            # reads the model's num_bits) agree on the slot.
            idx, val = _append_constant(idx, val, num_bits)
        y = df[self.get("label_col")].astype(np.float32)
        wc = self.get("weight_col")
        wt = df[wc].astype(np.float32) if wc else None
        return idx, val, y, wt, num_bits

    def _train_weights(self, df: DataFrame) -> tuple:
        """Returns (weights, num_bits, stats, resolved_args)."""
        if df.count() == 0:
            raise ValueError(f"{type(self).__name__}: empty training dataframe")
        args = self._resolve_args()
        idx, val, y, wt, num_bits = self._gather(df, bits_override=args["bits"])
        if args["loss"] in (LOSS_LOGISTIC, LOSS_HINGE):
            y = np.where(y > 0, 1.0, -1.0).astype(np.float32)
        t0 = time.perf_counter_ns()
        w = train_sparse_sgd(
            idx,
            val,
            y,
            wt,
            num_bits,
            loss=args["loss"],
            num_passes=args["passes"],
            batch=self.get("batch_size"),
            lr=args["lr"],
            power_t=args["power_t"],
            l2=args["l2"],
            adaptive=args["adaptive"],
            initial_weights=self.get("initial_model"),
            quantile_tau=args["tau"],
        )
        t1 = time.perf_counter_ns()
        from mmlspark_tpu.parallel.mesh import cluster_summary

        stats = DataFrame.from_dict(
            {
                "partition_id": [0],
                "rows": [int(len(y))],
                "time_total_ns": [t1 - t0],
                "time_learn_ns": [t1 - t0],
                "num_devices": [cluster_summary()["num_devices"]],
                "passes": [self.get("num_passes")],
            }
        )
        return w, num_bits, stats, args

    def _apply_common(self, m: "_VowpalWabbitBaseModel", w: np.ndarray, num_bits: int, stats: DataFrame) -> None:
        m.set(
            weights=w,
            num_bits=num_bits,
            features_col=self.get("features_col"),
            additional_features=self.get("additional_features"),
            no_constant=self.get("no_constant"),
            performance_statistics=stats,
        )


def _constant_slot(num_bits: int) -> int:
    """The hashed index of VW's intercept feature in this bit space."""
    return int(murmur3_bytes(b"Constant", 0)) & ((1 << num_bits) - 1)


def _append_constant(idx: np.ndarray, val: np.ndarray, num_bits: int) -> tuple:
    n = len(idx)
    c = np.full((n, 1), _constant_slot(num_bits), idx.dtype)
    v = np.ones((n, 1), val.dtype)
    return np.concatenate([idx, c], axis=1), np.concatenate([val, v], axis=1)


class _VowpalWabbitBaseModel(Model, HasFeaturesCol, HasPredictionCol):
    """Scoring through the jitted sparse-dot kernel
    (VowpalWabbitBaseModel.scala:28 analogue)."""

    weights = ComplexParam("(2^num_bits,) learned weights")
    num_bits = Param("hashed space width", default=18, type_=int)
    additional_features = Param("extra namespace columns", default=[], type_=list)
    no_constant = Param("intercept feature absent (--noconstant)", default=False, type_=bool)
    performance_statistics = ComplexParam("per-shard training diagnostics DataFrame")

    def get_performance_statistics(self) -> DataFrame:
        return self.get("performance_statistics")

    def get_readable_model(self) -> DataFrame:
        """Nonzero (index, weight) pairs — the --readable_model analogue."""
        w = np.asarray(self.get_or_fail("weights"))
        nz = np.nonzero(w)[0]
        return DataFrame.from_dict({"index": nz, "weight": w[nz]})

    def _margins(self, p: dict) -> np.ndarray:
        cols = [self.get("features_col")] + list(self.get("additional_features"))
        idx, val = pad_sparse_batch(combine_namespaces(p, cols))
        if not self.get("no_constant"):
            idx, val = _append_constant(idx, val, self.get("num_bits"))
        return predict_margin(idx, val, np.asarray(self.get_or_fail("weights")))


class VowpalWabbitClassifier(_VowpalWabbitBase):
    """Binary classifier, logistic loss (vw/VowpalWabbitClassifier.scala)."""

    _loss = LOSS_LOGISTIC

    def fit(self, df: DataFrame) -> "VowpalWabbitClassificationModel":
        w, num_bits, stats, args = self._train_weights(df)
        m = VowpalWabbitClassificationModel()
        self._apply_common(m, w, num_bits, stats)
        m.set(loss_function=args["loss"])
        return m


class VowpalWabbitClassificationModel(
    _VowpalWabbitBaseModel, HasProbabilityCol, HasRawPredictionCol
):
    loss_function = Param("loss the model was trained with", default="", type_=str)

    def transform(self, df: DataFrame) -> DataFrame:
        # hinge margins are NOT log-odds: sigmoid(margin) would masquerade
        # as a calibrated probability. Map them monotonically into [0, 1]
        # via the standard (margin+1)/2 clip instead (uncalibrated, like
        # VW's own hinge scores)
        hinge = self.get("loss_function") == LOSS_HINGE

        def fn(p: dict) -> dict:
            margin = self._margins(p)
            if hinge:
                prob = np.clip((margin + 1.0) / 2.0, 0.0, 1.0)
            else:
                prob = 1.0 / (1.0 + np.exp(-margin))
            q = dict(p)
            q[self.get("raw_prediction_col")] = margin.astype(np.float64)
            q[self.get("probability_col")] = prob.astype(np.float64)
            q[self.get("prediction_col")] = (margin > 0).astype(np.float64)
            return q

        return df.map_partitions(fn, parallel=False)


class VowpalWabbitRegressor(_VowpalWabbitBase):
    """Squared-loss regressor (vw/VowpalWabbitRegressor.scala)."""

    _loss = LOSS_SQUARED

    def fit(self, df: DataFrame) -> "VowpalWabbitRegressionModel":
        w, num_bits, stats, args = self._train_weights(df)
        m = VowpalWabbitRegressionModel()
        self._apply_common(m, w, num_bits, stats)
        m.set(loss_function=args["loss"])
        return m


class VowpalWabbitRegressionModel(_VowpalWabbitBaseModel):
    loss_function = Param("loss the model was trained with", default="", type_=str)

    def transform(self, df: DataFrame) -> DataFrame:
        # poisson trains in log space: predictions are rates (VW's
        # link=poisson convert-output behavior)
        exp_link = self.get("loss_function") == LOSS_POISSON

        def fn(p: dict) -> dict:
            q = dict(p)
            m = self._margins(p).astype(np.float64)
            if exp_link:
                # same clamp as the training link: rates, never inf
                m = np.exp(np.clip(m, -30.0, 30.0))
            q[self.get("prediction_col")] = m
            return q

        return df.map_partitions(fn, parallel=False)
