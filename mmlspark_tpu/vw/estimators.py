"""VW-style classifier / regressor estimators and models.

Facade parity with vw/VowpalWabbitClassifier.scala and
VowpalWabbitRegressor.scala; the distributed training model
(per-shard online pass + weight allreduce per pass,
VowpalWabbitBase.scala:313-429) runs in ``vw.learner`` as one SPMD XLA
program over the mesh. Per-shard training diagnostics mirror
``TrainingStats`` (VowpalWabbitBase.scala:27-46,431-457).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasWeightCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.vw.featurizer import HasNumBits, combine_namespaces
from mmlspark_tpu.vw.learner import (
    LOSS_LOGISTIC,
    LOSS_SQUARED,
    predict_margin,
    train_sparse_sgd,
)
from mmlspark_tpu.vw.sparse import NUM_BITS_META, pad_sparse_batch


class _VowpalWabbitBase(
    Estimator, HasFeaturesCol, HasLabelCol, HasWeightCol, HasNumBits
):
    """Shared trainer params (the arg-string builder analogue of
    VowpalWabbitBase.scala:139-169 — params map 1:1 to VW flags)."""

    num_passes = Param("passes over the data (--passes)", default=1, type_=int)
    learning_rate = Param("initial learning rate (-l)", default=0.5, type_=float)
    power_t = Param("lr decay exponent (--power_t)", default=0.5, type_=float)
    l2 = Param("L2 regularization (--l2)", default=0.0, type_=float)
    adaptive = Param("AdaGrad per-coordinate rates (--adaptive)", default=True, type_=bool)
    batch_size = Param("device minibatch size per shard", default=64, type_=int)
    additional_features = Param(
        "extra sparse namespace columns concatenated into the example",
        default=[],
        type_=list,
    )
    initial_model = ComplexParam("continue training from these weights (array)")
    use_barrier_execution_mode = Param(
        "gang-launch flag (no-op: SPMD launch is always gang-scheduled)",
        default=False,
        type_=bool,
    )

    _loss = LOSS_LOGISTIC

    def _gather(self, df: DataFrame) -> tuple:
        fc = self.get("features_col")
        cols = [fc] + list(self.get("additional_features"))
        sparse_rows = combine_namespaces({c: df[c] for c in cols}, cols)
        num_bits = df.column_metadata(fc).get(NUM_BITS_META) or self.get("num_bits")
        idx, val = pad_sparse_batch(sparse_rows)
        y = df[self.get("label_col")].astype(np.float32)
        wc = self.get("weight_col")
        wt = df[wc].astype(np.float32) if wc else None
        return idx, val, y, wt, int(num_bits)

    def _train_weights(self, df: DataFrame) -> tuple:
        if df.count() == 0:
            raise ValueError(f"{type(self).__name__}: empty training dataframe")
        idx, val, y, wt, num_bits = self._gather(df)
        if self._loss == LOSS_LOGISTIC:
            y = np.where(y > 0, 1.0, -1.0).astype(np.float32)
        t0 = time.perf_counter_ns()
        w = train_sparse_sgd(
            idx,
            val,
            y,
            wt,
            num_bits,
            loss=self._loss,
            num_passes=self.get("num_passes"),
            batch=self.get("batch_size"),
            lr=self.get("learning_rate"),
            power_t=self.get("power_t"),
            l2=self.get("l2"),
            adaptive=self.get("adaptive"),
            initial_weights=self.get("initial_model"),
        )
        t1 = time.perf_counter_ns()
        from mmlspark_tpu.parallel.mesh import cluster_summary

        stats = DataFrame.from_dict(
            {
                "partition_id": [0],
                "rows": [int(len(y))],
                "time_total_ns": [t1 - t0],
                "time_learn_ns": [t1 - t0],
                "num_devices": [cluster_summary()["num_devices"]],
                "passes": [self.get("num_passes")],
            }
        )
        return w, num_bits, stats

    def _apply_common(self, m: "_VowpalWabbitBaseModel", w: np.ndarray, num_bits: int, stats: DataFrame) -> None:
        m.set(
            weights=w,
            num_bits=num_bits,
            features_col=self.get("features_col"),
            additional_features=self.get("additional_features"),
            performance_statistics=stats,
        )


class _VowpalWabbitBaseModel(Model, HasFeaturesCol, HasPredictionCol):
    """Scoring through the jitted sparse-dot kernel
    (VowpalWabbitBaseModel.scala:28 analogue)."""

    weights = ComplexParam("(2^num_bits,) learned weights")
    num_bits = Param("hashed space width", default=18, type_=int)
    additional_features = Param("extra namespace columns", default=[], type_=list)
    performance_statistics = ComplexParam("per-shard training diagnostics DataFrame")

    def get_performance_statistics(self) -> DataFrame:
        return self.get("performance_statistics")

    def get_readable_model(self) -> DataFrame:
        """Nonzero (index, weight) pairs — the --readable_model analogue."""
        w = np.asarray(self.get_or_fail("weights"))
        nz = np.nonzero(w)[0]
        return DataFrame.from_dict({"index": nz, "weight": w[nz]})

    def _margins(self, p: dict) -> np.ndarray:
        cols = [self.get("features_col")] + list(self.get("additional_features"))
        idx, val = pad_sparse_batch(combine_namespaces(p, cols))
        return predict_margin(idx, val, np.asarray(self.get_or_fail("weights")))


class VowpalWabbitClassifier(_VowpalWabbitBase):
    """Binary classifier, logistic loss (vw/VowpalWabbitClassifier.scala)."""

    _loss = LOSS_LOGISTIC

    def fit(self, df: DataFrame) -> "VowpalWabbitClassificationModel":
        w, num_bits, stats = self._train_weights(df)
        m = VowpalWabbitClassificationModel()
        self._apply_common(m, w, num_bits, stats)
        return m


class VowpalWabbitClassificationModel(
    _VowpalWabbitBaseModel, HasProbabilityCol, HasRawPredictionCol
):
    def transform(self, df: DataFrame) -> DataFrame:
        def fn(p: dict) -> dict:
            margin = self._margins(p)
            prob = 1.0 / (1.0 + np.exp(-margin))
            q = dict(p)
            q[self.get("raw_prediction_col")] = margin.astype(np.float64)
            q[self.get("probability_col")] = prob.astype(np.float64)
            q[self.get("prediction_col")] = (margin > 0).astype(np.float64)
            return q

        return df.map_partitions(fn, parallel=False)


class VowpalWabbitRegressor(_VowpalWabbitBase):
    """Squared-loss regressor (vw/VowpalWabbitRegressor.scala)."""

    _loss = LOSS_SQUARED

    def fit(self, df: DataFrame) -> "VowpalWabbitRegressionModel":
        w, num_bits, stats = self._train_weights(df)
        m = VowpalWabbitRegressionModel()
        self._apply_common(m, w, num_bits, stats)
        return m


class VowpalWabbitRegressionModel(_VowpalWabbitBaseModel):
    def transform(self, df: DataFrame) -> DataFrame:
        def fn(p: dict) -> dict:
            q = dict(p)
            q[self.get("prediction_col")] = self._margins(p).astype(np.float64)
            return q

        return df.map_partitions(fn, parallel=False)
