"""Device SGD kernel for VW-style online learning.

The TPU rebuild of VW's native train loop + spanning-tree allreduce
(vw/VowpalWabbitBase.scala:235-266,401-429): each mesh shard runs an
in-compiler online pass over its rows (``lax.scan`` over fixed-shape
minibatches of gathered/scattered sparse features), and shards average
weights with ``pmean`` over ICI at every pass boundary — exactly VW's
"allreduce weights once per pass" semantics, minus the driver server.

Adaptive (AdaGrad) per-coordinate learning rates stand in for VW's
``--adaptive`` default; ``power_t`` scales the global schedule for the
non-adaptive path.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.parallel.collectives import shard_apply
from mmlspark_tpu.parallel.compat import pcast
from mmlspark_tpu.parallel.mesh import DATA_AXIS, get_mesh

LOSS_LOGISTIC = "logistic"
LOSS_SQUARED = "squared"
LOSS_QUANTILE = "quantile"
LOSS_HINGE = "hinge"
LOSS_POISSON = "poisson"
LOSSES = (LOSS_LOGISTIC, LOSS_SQUARED, LOSS_QUANTILE, LOSS_HINGE, LOSS_POISSON)


class SGDState(NamedTuple):
    """Full optimizer state of the VW online learner.

    Carrying ``g2`` (the AdaGrad accumulator) and ``t`` (the minibatch
    counter for the non-adaptive schedule) across calls is what makes
    incremental training *bit-identical* to one batch run over the
    concatenated rows (asserted in tests/test_online.py): warm-starting
    on weights alone would reset the per-coordinate step sizes every
    micro-batch. Fields may be numpy or jax arrays — the continuous-
    training loop keeps them device-resident between micro-batches and
    only pulls ``w`` to host at publish time."""

    w: Any    # (2^num_bits,) f32 weights
    g2: Any   # (2^num_bits,) f32 AdaGrad sum of squared gradients
    t: Any    # scalar f32: minibatches seen (power_t schedule input)


def sgd_init(num_bits: int,
             initial_weights: Optional[np.ndarray] = None) -> SGDState:
    """Fresh optimizer state for :func:`train_sparse_sgd_state`."""
    d = 1 << num_bits
    w = (
        np.zeros(d, np.float32) if initial_weights is None
        else np.asarray(initial_weights, np.float32)
    )
    if w.shape != (d,):
        raise ValueError(f"initial weights shape {w.shape} != ({d},)")
    return SGDState(w=w, g2=np.zeros(d, np.float32), t=np.float32(0.0))


def _dloss(loss: str, margin: jnp.ndarray, y: jnp.ndarray, tau: float) -> jnp.ndarray:
    """d(loss)/d(margin) — VW's loss zoo. logistic/hinge expect y in
    {-1,+1}; squared/quantile raw y; poisson log-space margins vs counts.
    ``tau`` is the pinball level (--quantile_tau; VW passes loss flags
    through its arg string, VowpalWabbitBase.scala:495-508)."""
    if loss == LOSS_LOGISTIC:
        return -y * jax.nn.sigmoid(-y * margin)
    if loss == LOSS_SQUARED:
        return margin - y
    if loss == LOSS_QUANTILE:
        return jnp.where(margin >= y, 1.0 - tau, -tau)
    if loss == LOSS_HINGE:
        return jnp.where(y * margin < 1.0, -y, 0.0)
    if loss == LOSS_POISSON:
        # clamp like VW's poisson link: an unclamped exp overflows f32 for
        # moderately scaled features and NaN-poisons the weights for good
        return jnp.exp(jnp.clip(margin, -30.0, 30.0)) - y
    raise ValueError(f"unknown loss {loss!r}")


@functools.partial(
    jax.jit,
    static_argnames=("loss", "num_passes", "batch", "adaptive", "axis"),
)
def _shard_train(
    idx: jnp.ndarray,  # (n, K) int32
    val: jnp.ndarray,  # (n, K) f32, 0-padded
    y: jnp.ndarray,  # (n,) f32
    wt: jnp.ndarray,  # (n,) f32 example weights, 0 for padding rows
    w0: jnp.ndarray,  # (D,) f32 initial weights
    g20: jnp.ndarray,  # (D,) f32 initial AdaGrad accumulator
    t0: jnp.ndarray,  # scalar f32: minibatches already seen
    tau: jnp.ndarray,  # pinball level (quantile loss only)
    *,
    loss: str,
    num_passes: int,
    batch: int,
    lr: float,
    power_t: float,
    l2: float,
    adaptive: bool,
    axis: Optional[str],
) -> tuple:
    n = idx.shape[0]
    nb = n // batch
    idx_b = idx[: nb * batch].reshape(nb, batch, -1)
    val_b = val[: nb * batch].reshape(nb, batch, -1)
    y_b = y[: nb * batch].reshape(nb, batch)
    wt_b = wt[: nb * batch].reshape(nb, batch)

    def minibatch(carry, xs):
        w, g2, t = carry
        bi, bv, by, bw = xs
        gathered = w[bi]  # (B, K) gather from HBM
        margin = (gathered * bv).sum(-1)
        dl = _dloss(loss, margin, by, tau) * bw  # (B,)
        g = dl[:, None] * bv + l2 * gathered * (bv != 0)  # (B, K)
        if adaptive:
            # the accumulator scatter runs BEFORE the denominator gather so
            # a feature repeated across the minibatch sees the whole
            # batch's g^2 — the aggressive-step blowup a fused
            # single-scatter variant suffers on duplicate-heavy data
            g2 = g2.at[bi].add(g * g)
            denom = jnp.sqrt(g2[bi]) + 1e-6
            w = w.at[bi].add(-lr * g / denom)
        else:
            step = lr * (1.0 / (1.0 + t)) ** power_t
            w = w.at[bi].add(-step * g)
        return (w, g2, t + 1.0), None

    def one_pass(carry, _):
        w, g2, t = carry
        (w, g2, t), _ = jax.lax.scan(
            minibatch, (w, g2, t), (idx_b, val_b, y_b, wt_b)
        )
        if axis is not None:
            w = jax.lax.pmean(w, axis)  # <- the per-pass allreduce
            g2 = jax.lax.pmean(g2, axis)
            # pmean output is axis-invariant; keep the carry type stable
            w = pcast(w, axis, to="varying")
            g2 = pcast(g2, axis, to="varying")
        return (w, g2, t), None

    if axis is not None:
        # carry becomes device-varying after the first shard-local update;
        # mark it so from the start (shard_map varying-axis typing)
        w0 = pcast(w0, axis, to="varying")
        g20 = pcast(g20, axis, to="varying")
    (w, g2, t), _ = jax.lax.scan(
        one_pass, (w0, g20, jnp.float32(t0)), None, length=num_passes
    )
    if axis is not None:
        # shards already hold identical pmean-ed weights; this extra pmean is
        # a no-op numerically but types the output as axis-invariant
        w = jax.lax.pmean(w, axis)
        g2 = jax.lax.pmean(g2, axis)
    return w, g2, t


def train_sparse_sgd_state(
    idx: np.ndarray,
    val: np.ndarray,
    y: np.ndarray,
    wt: Optional[np.ndarray],
    num_bits: int,
    state: Optional[SGDState] = None,
    *,
    loss: str = LOSS_LOGISTIC,
    num_passes: int = 1,
    batch: int = 0,
    lr: float = 0.5,
    power_t: float = 0.5,
    l2: float = 0.0,
    adaptive: bool = True,
    distributed: bool = True,
    quantile_tau: float = 0.5,
) -> SGDState:
    """One incremental training step: continue from ``state`` (or fresh
    zeros) over this (padded) sparse micro-batch, returning the FULL
    updated optimizer state with **device-resident** arrays.

    This is the continuous-training entry point (mmlspark_tpu/online/):
    state fields stay on device between calls — no host round-trip per
    micro-batch — and because the AdaGrad accumulator and schedule
    counter ride along, feeding rows chunk-by-chunk is bit-identical to
    one :func:`train_sparse_sgd` call over the concatenation whenever
    chunk sizes are multiples of the minibatch size (unsharded path;
    asserted in tests/test_online.py). Batch semantics, sharding and the
    per-pass ``pmean`` allreduce are exactly :func:`train_sparse_sgd`'s.
    """
    d = 1 << num_bits
    n = len(y)
    if batch <= 0:
        batch = 1024 if jax.default_backend() == "tpu" else 64
    wt = np.ones(n, np.float32) if wt is None else np.asarray(wt, np.float32)
    mesh = get_mesh()
    n_shards = mesh.shape[DATA_AXIS] if distributed else 1
    # multi-host: every process holds ITS OWN rows; local blocks join a
    # process-spanning sharded array and the same shard_map program runs
    # SPMD with the per-pass pmean crossing processes over DCN (the
    # spanning-tree-allreduce analogue, VowpalWabbitBase.scala:401-429)
    multihost = distributed and jax.process_count() > 1
    if multihost:
        from mmlspark_tpu.parallel.sharding import multihost_pad_target

        # ALL sizing must come from the allgathered target, never local n:
        # processes hold unequal row counts but must compile the same
        # static-batch SPMD program over the same global shape
        # floor of 1: if EVERY process holds zero rows the program still
        # needs one inert zero-weight chunk (matching the single-host
        # max(n, 1) path) instead of zero-length sharded arrays
        target = max(1, multihost_pad_target(n))
        ldc = jax.local_device_count()
        batch = max(1, min(batch, max(1, target // ldc)))
        gran = ldc * batch  # whole per-device minibatches per process block
        share = ((target + gran - 1) // gran) * gran
        n_pad = share
    else:
        batch = max(1, min(batch, max(1, n // max(1, n_shards))))
        chunk = n_shards * batch
        n_pad = int(np.ceil(max(n, 1) / chunk)) * chunk
    if n_pad != n:
        pad = n_pad - n
        idx = np.concatenate([idx, np.zeros((pad, idx.shape[1]), idx.dtype)])
        val = np.concatenate([val, np.zeros((pad, val.shape[1]), val.dtype)])
        y = np.concatenate([np.asarray(y, np.float32), np.zeros(pad, np.float32)])
        wt = np.concatenate([wt, np.zeros(pad, np.float32)])  # padding = no-op
    if state is None:
        state = sgd_init(num_bits)
    w0, g20, t0 = state
    if getattr(w0, "shape", None) != (d,):
        raise ValueError(
            f"state weights shape {getattr(w0, 'shape', None)} != ({d},)"
        )
    kwargs = dict(
        loss=loss,
        num_passes=num_passes,
        batch=batch,
        lr=lr,
        power_t=power_t,
        l2=l2,
        adaptive=adaptive,
    )
    tau = np.float32(quantile_tau)
    if not distributed or n_shards == 1:
        w, g2, t = _shard_train(
            jnp.asarray(idx, jnp.int32),
            jnp.asarray(val),
            jnp.asarray(y, jnp.float32),
            jnp.asarray(wt),
            jnp.asarray(w0, jnp.float32),  # no-op on a device array
            jnp.asarray(g20, jnp.float32),
            jnp.asarray(t0, jnp.float32),
            tau,
            axis=None,
            **kwargs,
        )
        return SGDState(w=w, g2=g2, t=t)

    fn = shard_apply(
        functools.partial(_shard_train, axis=DATA_AXIS, **kwargs),
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  P(), P(), P(), P()),
        out_specs=(P(), P(), P()),
    )
    if multihost:
        from mmlspark_tpu.parallel.sharding import shard_batch_multihost

        rows = shard_batch_multihost(
            (idx.astype(np.int32), val.astype(np.float32),
             np.asarray(y, np.float32), wt.astype(np.float32)),
            mesh,
        )
        # state: identical host arrays (or replicated device arrays from a
        # previous step) == replicated
        w, g2, t = jax.jit(fn)(
            *rows, np.asarray(w0, np.float32), np.asarray(g20, np.float32),
            np.float32(t0), tau,
        )
        return SGDState(w=w, g2=g2, t=t)
    w, g2, t = jax.jit(fn)(
        jnp.asarray(idx, jnp.int32),
        jnp.asarray(val),
        jnp.asarray(y, jnp.float32),
        jnp.asarray(wt),
        jnp.asarray(w0, jnp.float32),
        jnp.asarray(g20, jnp.float32),
        jnp.asarray(t0, jnp.float32),
        tau,
    )
    return SGDState(w=w, g2=g2, t=t)


def train_sparse_sgd(
    idx: np.ndarray,
    val: np.ndarray,
    y: np.ndarray,
    wt: Optional[np.ndarray],
    num_bits: int,
    *,
    loss: str = LOSS_LOGISTIC,
    num_passes: int = 1,
    batch: int = 0,
    lr: float = 0.5,
    power_t: float = 0.5,
    l2: float = 0.0,
    adaptive: bool = True,
    initial_weights: Optional[np.ndarray] = None,
    distributed: bool = True,
    quantile_tau: float = 0.5,
) -> np.ndarray:
    """Train on the (padded) sparse batch; returns the (2^num_bits,) weights.

    ``distributed=True`` shards rows over the mesh ``data`` axis via
    ``shard_map`` so every pass ends in an ICI ``pmean``.

    ``batch <= 0`` = auto: 1024 on TPU (the gather/scatter SGD step is
    latency-bound there — bigger minibatches keep the chip busy), 64
    elsewhere (closer to VW's per-example updates)."""
    state = train_sparse_sgd_state(
        idx, val, y, wt, num_bits,
        sgd_init(num_bits, initial_weights),
        loss=loss, num_passes=num_passes, batch=batch, lr=lr,
        power_t=power_t, l2=l2, adaptive=adaptive, distributed=distributed,
        quantile_tau=quantile_tau,
    )
    return np.asarray(state.w)


@functools.partial(jax.jit, static_argnames=())
def _predict_margin(idx: jnp.ndarray, val: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    return (w[idx] * val).sum(-1)


def predict_margin(idx: np.ndarray, val: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Batched sparse dot with the weight vector (scoring hot path)."""
    return np.asarray(
        _predict_margin(jnp.asarray(idx, jnp.int32), jnp.asarray(val), jnp.asarray(w))
    )
