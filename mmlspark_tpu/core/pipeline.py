"""Estimator / Transformer / Model / Pipeline abstractions.

The reference's public surface is SparkML pipeline stages (SURVEY.md L5);
this module provides the same contract for the TPU framework:

- :class:`Transformer` — ``transform(df) -> df``
- :class:`Estimator` — ``fit(df) -> Model``
- :class:`Pipeline` / :class:`PipelineModel` — stage composition
- every concrete stage auto-registers (for fuzzing coverage + binding
  codegen, the ``Wrappable`` analogue, core/contracts/Params.scala:15)
- ``save``/``load`` with complex payloads via ``core.serialize``

Stages must be constructible with no arguments; all state is params.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, Param, Params
from mmlspark_tpu.core import serialize as _ser

# Stage registry — the Wrappable analogue. Keys are class names; used by the
# fuzzing harness ("every stage must be covered") and the codegen layer.
STAGE_REGISTRY: dict[str, type] = {}


class PipelineStage(Params):
    """Base class for all stages."""

    def __init_subclass__(cls, **kw: Any) -> None:
        super().__init_subclass__(**kw)
        # abstract bases in this module are not public stages
        if not cls.__name__.startswith("_") and cls.__module__ != __name__:
            STAGE_REGISTRY[cls.__name__] = cls

    # -- persistence ---------------------------------------------------------

    def save(self, path: str, overwrite: bool = True) -> None:
        import os
        import shutil

        if os.path.exists(path):
            if not overwrite:
                raise FileExistsError(f"{path} exists; pass overwrite=True")
            shutil.rmtree(path)
        _ser.save_stage(self, path)

    @classmethod
    def load(cls, path: str) -> Any:
        stage = _ser.load_stage(path)
        if cls is not PipelineStage and not isinstance(stage, cls):
            raise TypeError(f"loaded {type(stage).__name__}, expected {cls.__name__}")
        return stage

    def transform_schema(self, schema: Any) -> Any:
        """Optional schema-level dry-run; default: identity."""
        return schema


class Transformer(PipelineStage):
    def transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Estimator(PipelineStage):
    def fit(self, df: DataFrame) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted transformer."""


def load_stage(path: str) -> PipelineStage:
    return _ser.load_stage(path)


# --------------------------------------------------------------------------


class Pipeline(Estimator):
    """Sequential composition of stages (SparkML Pipeline semantics:
    estimators are fitted on the running dataframe, transformers applied)."""

    stages = ComplexParam("ordered list of pipeline stages", default=[])

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kw: Any):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def fit(self, df: DataFrame) -> "PipelineModel":
        fitted: list[Transformer] = []
        cur = df
        stages = self.get("stages")
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(stages) - 1:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(stages) - 1:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"pipeline stage {stage!r} is neither Estimator nor Transformer")
        return PipelineModel(stages=fitted)


class PipelineModel(Model):
    stages = ComplexParam("fitted stages", default=[])

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kw: Any):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def transform(self, df: DataFrame) -> DataFrame:
        for stage in self.get("stages"):
            df = stage.transform(df)
        return df

    def compile(self, **options: Any) -> Any:
        """Compile this fitted pipeline into a
        :class:`~mmlspark_tpu.compiler.CompiledPipeline` — a drop-in
        Transformer that fuses adjacent fusable stages into single
        partitioned XLA programs and schedules independent branches by
        critical path, with output element-wise equal to staged
        execution. ``options`` forward to CompiledPipeline params
        (``exact``, ``max_bucket``, ``partition_mode``,
        ``parallel_hosts``)."""
        from mmlspark_tpu.compiler import CompiledPipeline

        return CompiledPipeline(stages=list(self.get("stages")), **options)



