"""Column schema utilities.

Plays the role of the reference's schema layer: ``SparkBindings`` row<->struct
codecs (core/schema/SparkBindings.scala:13-46), image-schema checks
(``ImageSchemaUtils``), categorical metadata (core/schema/Categoricals.scala),
and ``DatasetExtensions.findUnusedColumnName``.

Here a DataFrame column is a numpy array per partition:
- scalar column: 1-D array (float/int/bool/str-object)
- vector column: 2-D array (rows x dim) — TPU-friendly dense layout
- tensor column: N-D array (rows x ...) e.g. images as (n, H, W, C)
- object column: 1-D object array (ragged payloads, structs, bytes)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


@dataclass(frozen=True)
class ColumnInfo:
    """Shape/dtype summary of one column."""

    dtype: str          # numpy dtype name, or "object"
    shape: tuple        # per-row element shape, () for scalars
    metadata: dict = field(default_factory=dict, compare=False)

    @property
    def kind(self) -> str:
        if self.dtype == "object":
            return "object"
        if len(self.shape) == 0:
            return "scalar"
        if len(self.shape) == 1:
            return "vector"
        return "tensor"

    @staticmethod
    def of(arr: np.ndarray, metadata: Optional[dict] = None) -> "ColumnInfo":
        return ColumnInfo(
            dtype=str(arr.dtype) if arr.dtype != np.dtype("O") else "object",
            shape=tuple(arr.shape[1:]),
            metadata=metadata or {},
        )


class Schema(dict):
    """Mapping column name -> :class:`ColumnInfo` preserving insertion order."""

    def column_names(self) -> list:
        return list(self.keys())

    def __repr__(self) -> str:
        parts = ", ".join(
            f"{k}: {v.dtype}{list(v.shape) if v.shape else ''}" for k, v in self.items()
        )
        return f"Schema({parts})"


def infer_schema(partition: dict) -> Schema:
    s = Schema()
    for name, arr in partition.items():
        s[name] = ColumnInfo.of(np.asarray(arr))
    return s


def find_unused_column(base: str, existing) -> str:
    """``DatasetExtensions.findUnusedColumnName`` analogue."""
    name = base
    i = 0
    existing = set(existing)
    while name in existing:
        i += 1
        name = f"{base}_{i}"
    return name


# --------------------------------------------------------------------------
# Image schema — analogue of Spark's ImageSchema struct
# (io/image/ImageUtils.scala, core ImageSchemaUtils). An image column is a
# 1-D object array of dicts with these keys, OR a dense (n,H,W,C) uint8
# tensor column when shapes are uniform (the TPU-friendly form).
# --------------------------------------------------------------------------

IMAGE_FIELDS = ("origin", "height", "width", "nChannels", "mode", "data")


def make_image_row(
    data: np.ndarray, origin: str = "", mode: int = 16
) -> dict:
    """Build an image struct from an (H, W, C) uint8 array.

    mode 16 == CV_8UC3 (BGR), matching the reference's default
    (io/image/ImageUtils.scala)."""
    h, w = data.shape[:2]
    c = 1 if data.ndim == 2 else data.shape[2]
    return {
        "origin": origin,
        "height": int(h),
        "width": int(w),
        "nChannels": int(c),
        "mode": mode,
        "data": np.ascontiguousarray(data, dtype=np.uint8),
    }


def is_image_column(info: ColumnInfo) -> bool:
    if info.kind == "object":
        return info.metadata.get("logical_type") == "image"
    return len(info.shape) == 3 and info.dtype == "uint8"


def image_row_to_array(row: Any) -> np.ndarray:
    """Image struct (or raw array) -> (H, W, C) uint8 array."""
    if isinstance(row, dict):
        data = np.asarray(row["data"], dtype=np.uint8)
        return data.reshape(row["height"], row["width"], row["nChannels"])
    arr = np.asarray(row, dtype=np.uint8)
    return arr


# --------------------------------------------------------------------------
# Categorical metadata — CategoricalMap analogue
# (core/schema/Categoricals.scala). Levels ride in ColumnInfo.metadata so
# ValueIndexer / IndexToValue / TrainClassifier can round-trip labels.
# --------------------------------------------------------------------------

CATEGORICAL_KEY = "categorical_levels"


def with_categorical_levels(info: ColumnInfo, levels: list) -> ColumnInfo:
    md = dict(info.metadata)
    md[CATEGORICAL_KEY] = list(levels)
    return ColumnInfo(info.dtype, info.shape, md)


def get_categorical_levels(info: ColumnInfo):
    return info.metadata.get(CATEGORICAL_KEY)
