"""Deterministic fault injection: named points, seeded schedules.

The reference leans on Spark for fault tolerance (barrier execution,
uncommitted-epoch replay, ``FaultToleranceUtils.retryWithTimeout``); the
TPU rebuild proves its recovery machinery works by *injecting* the
failures those mechanisms exist for. A :class:`FaultPlan` maps named
injection points to error/latency/payload schedules; production code
calls :func:`inject` at each point unconditionally (a no-op costing one
attribute read when no plan is armed).

Injection points wired into the framework (see docs/robustness.md):

========================  ====================================================
point                     fires inside
========================  ====================================================
``io.send_request``       io/clients.send_request — network errors become
                          status-0 rows, int payloads become that HTTP status
``gateway.forward``       serving/distributed.ServingGateway pre-send — an
                          OSError here looks like a worker that died before
                          the request was delivered (re-dispatch path)
``gateway.response``      ServingGateway post-send — a TimeoutError here
                          looks like a worker hanging mid-execution
                          (at-most-once 504 path)
``parallel.barrier``      parallel/distributed.barrier — latency simulates a
                          slow/dead host for the timeout diagnostics
``gbdt.round``            models/gbdt/train.py round boundary — a
                          :class:`Preempted` here simulates host preemption
                          between boosting rounds (checkpoint/resume path)
``modelstore.load``       serving/modelstore/store.py before the loader runs
                          — latency is a slow deserialize (background loads
                          must keep serving through it), an error a corrupt
                          model artifact
``modelstore.swap``       serving/modelstore/store.py before the alias flip —
                          latency stalls only the control op while traffic
                          keeps serving the old version (the zero-downtime
                          hot-swap property the chaos suite asserts)
``admission.shed``        serving/server.py ingress admission check — a
                          truthy payload forces a 429 shed (chaos for the
                          client's Retry-After handling), delay stalls
                          admission itself
``gateway.hedge``         serving/distributed.py as a tail hedge launches —
                          an error suppresses the duplicate (the primary
                          must still win eventually)
``supervisor.restart``    serving/supervisor.py before a worker respawn —
                          an error is "the scheduler refused", retried next
                          tick; delay simulates slow node allocation
``online.ingest``         online/feedback.py per accepted micro-batch — an
                          error refuses the chunk (HTTP ingest answers 503,
                          nothing buffered), delay stalls intake
``online.publish``        online/publisher.py before the snapshot is written
                          — an error aborts the whole publication (alias
                          untouched: the rollback path), delay stalls only
                          the control path while serving continues
``autoscaler.scale``      serving/supervisor.py as an autoscale decision is
                          about to be applied — an error suppresses that
                          scale event ("the scheduler refused", retried
                          next tick), delay stalls it
``elastic.detect``        parallel/elastic.py GangContext.on_round detection
                          check — a string payload names a member to declare
                          lost WITHOUT killing anything (drives the whole
                          reshard path as chaos), an error is the detector
                          itself failing
``elastic.reshard``       parallel/elastic.py as the new-generation commit is
                          attempted — an error is "the commit refused",
                          retried each heartbeat until the plan relents
``train.round_abort``     parallel/elastic.py as an in-flight round is
                          abandoned after a gang change — delay stalls the
                          abort -> reshard turnaround (visible in recovery
                          timings), an error kills the trainer (the
                          supervisor-restart recovery path)
``artifact.put``          serving/artifacts.py before an artifact is stored
                          — an error is a refused push (producers degrade
                          to shared-dir semantics or retry)
``artifact.fetch``        serving/artifacts.py per transfer attempt — an
                          error fails that peer (failover), delay is a slow
                          network; a mid-stream death leaves a partial the
                          next attempt resumes by Range
``artifact.verify``       serving/artifacts.py as a local blob is hash-
                          checked — a truthy payload forces the failure
                          verdict (quarantine + re-fetch-elsewhere path)
                          without corrupting anything
``registry.commit_cas``   serving/registry.py as a generation CAS commit is
                          evaluated — an error refuses the commit (503, a
                          missing ack toward the caller's quorum), delay
                          stalls the commit endpoint
``elastic.park``          parallel/elastic.py as a member parks (lost the
                          registry quorum or the generation CAS) — delay
                          stalls the stop-training transition, an error
                          kills the trainer mid-park
``publish.fence``         serving/modelstore/dispatch.py as a stale-epoch
                          publication is refused — delay stalls the 409,
                          an error kills the control op instead of
                          answering (the publisher retry path)
``obs.watchdog_dump``     obs/watchdog.py as a stall dump is about to be
                          spooled — an error is a failed dump write (the
                          stall is still counted: losing the forensics
                          must never lose the signal), delay stalls only
                          the dump, not the monitor
========================  ====================================================

Schedules are **seeded and step-indexed**: a rule fires by absolute step
index (``at=(5,)``), by stride (``after=/every=``), or by a Bernoulli
draw whose rng is keyed on ``(seed, point, step)`` — the same plan
replays the same failures, so chaos tests are reproducible bit-for-bit.
"""

from __future__ import annotations

import contextlib
import json
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from mmlspark_tpu import obs

# chaos observability: every fire is counted, so a live fleet under an
# armed plan shows its injected faults on /metrics and chaos tests can
# assert schedule counts == observed counts (tests/test_obs.py)
_M_INJECTED = obs.counter(
    "mmlspark_faults_injected_total",
    "Faults fired by the armed FaultPlan, by injection point",
    labels=("point",),
)


class FaultError(Exception):
    """Base class for errors whose only cause is an armed FaultPlan."""


class Preempted(FaultError):
    """Injected host preemption (the SIGTERM/spot-reclaim analogue)."""


# error specs resolvable from JSON plans (tools/deploy smoke --fault-plan)
_ERROR_NAMES = {
    "ConnectionError": ConnectionError,
    "ConnectionResetError": ConnectionResetError,
    "TimeoutError": TimeoutError,
    "OSError": OSError,
    "Preempted": Preempted,
    "FaultError": FaultError,
}


@dataclass
class FaultRule:
    """One scheduled fault at one injection point.

    ``error`` — exception instance or class raised when the rule fires;
    ``delay_s`` — sleep before erroring/returning (hang/slow-host sim);
    ``payload`` — returned to the injection site when no error is set
    (sites interpret it, e.g. an int HTTP status for ``io.send_request``);
    ``at`` — fire exactly at these step indices; otherwise ``after``/
    ``every`` stride. ``probability`` thins eligible steps with a draw
    seeded on (plan seed, point, step). ``max_fires`` caps total fires.
    """

    error: Any = None
    delay_s: float = 0.0
    payload: Any = None
    at: Optional[frozenset] = None
    after: int = 0
    every: int = 1
    probability: float = 1.0
    max_fires: int = -1
    fired: int = 0

    def matches(self, step: int, seed: int, point: str) -> bool:
        if self.max_fires >= 0 and self.fired >= self.max_fires:
            return False
        if self.at is not None:
            if step not in self.at:
                return False
        else:
            if step < self.after or (step - self.after) % max(self.every, 1):
                return False
        if self.probability >= 1.0:
            return True
        # deterministic per (seed, point, step): replaying the plan
        # replays the exact same failure schedule
        return (
            random.Random(f"{seed}:{point}:{step}").random() < self.probability
        )

    def raise_or_payload(self) -> Any:
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        if self.error is not None:
            e = self.error
            if isinstance(e, type):
                e = e(f"injected fault (fire #{self.fired})")
            raise e
        return self.payload if self.payload is not None else True


class FaultPlan:
    """A process-global registry of named injection points -> schedules.

    >>> plan = FaultPlan(seed=7).on("gbdt.round", at=(5,), error=Preempted)
    >>> with plan.armed():
    ...     train(...)  # raises Preempted entering round 5
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rules: dict[str, list[FaultRule]] = {}
        self._hits: dict[str, int] = {}
        self._lock = threading.Lock()
        self.log: list[tuple[str, int]] = []  # (point, step) of every fire

    def on(
        self,
        point: str,
        *,
        error: Any = None,
        delay_s: float = 0.0,
        payload: Any = None,
        at: Optional[tuple] = None,
        after: int = 0,
        every: int = 1,
        probability: float = 1.0,
        max_fires: int = -1,
    ) -> "FaultPlan":
        if isinstance(error, str):
            # resolve JSON-plan error names EAGERLY: a typo'd name must
            # fail the plan load, not surface as a mystery FaultError from
            # inside the injected call site
            if error not in _ERROR_NAMES:
                raise ValueError(
                    f"unknown fault error name {error!r}; known: "
                    f"{sorted(_ERROR_NAMES)}"
                )
            error = _ERROR_NAMES[error]
        self._rules.setdefault(point, []).append(
            FaultRule(
                error=error, delay_s=delay_s, payload=payload,
                at=frozenset(at) if at is not None else None,
                after=after, every=every, probability=probability,
                max_fires=max_fires,
            )
        )
        return self

    def points(self) -> list:
        return sorted(self._rules)

    def rules(self, point: str) -> list:
        """The :class:`FaultRule` list installed at ``point`` (a copy —
        callers inspect schedules, e.g. the smoke containment gate
        deciding whether a plan guarantees a breaker-tripping burst)."""
        return list(self._rules.get(point, ()))

    def fires(self, point: Optional[str] = None) -> list:
        with self._lock:
            return [f for f in self.log if point is None or f[0] == point]

    # -- the hot path ---------------------------------------------------------

    def check(self, point: str, step: Optional[int] = None) -> Any:
        """Called by :func:`inject` for the armed plan. Returns the firing
        rule's payload (or raises its error); None when nothing fires.

        The rule's delay/raise runs OUTSIDE the plan lock — an injected
        hang must stall only the injected call site, not every other
        thread consulting the plan."""
        rules = self._rules.get(point)
        if not rules:
            return None
        with self._lock:
            idx = self._hits.get(point, 0)
            self._hits[point] = idx + 1
            s = idx if step is None else step
            fire = None
            for rule in rules:
                if rule.matches(s, self.seed, point):
                    rule.fired += 1
                    self.log.append((point, s))
                    fire = rule
                    break
        if fire is None:
            return None
        _M_INJECTED.labels(point=point).inc()
        # every fire also lands in the flight recorder, so a post-incident
        # dump shows the injected faults interleaved with the requests
        # they broke — and the chaos smoke can gate recorded == injected
        from mmlspark_tpu.obs import flightrec

        flightrec.record("fault", path=point, detail=f"step={s}")
        return fire.raise_or_payload()

    # -- arming ---------------------------------------------------------------

    def install(self) -> "FaultPlan":
        global _ACTIVE
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None

    @contextlib.contextmanager
    def armed(self) -> Iterator["FaultPlan"]:
        self.install()
        try:
            yield self
        finally:
            self.uninstall()

    # -- JSON round-trip (docker-compose / CLI chaos smoke) -------------------

    @staticmethod
    def from_spec(spec: Any) -> "FaultPlan":
        """Build a plan from a dict / JSON string / path to a JSON file::

            {"seed": 0, "rules": [
              {"point": "io.send_request", "error": "ConnectionError",
               "at": [2, 5]},
              {"point": "io.send_request", "payload": 503,
               "probability": 0.2}]}
        """
        if isinstance(spec, str):
            s = spec.strip()
            if not s.startswith("{"):
                with open(spec) as f:
                    s = f.read()
            spec = json.loads(s)
        plan = FaultPlan(seed=int(spec.get("seed", 0)))
        for r in spec.get("rules", ()):
            r = dict(r)
            point = r.pop("point")
            if "at" in r and r["at"] is not None:
                r["at"] = tuple(r["at"])
            plan.on(point, **r)
        return plan


_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def install(plan: FaultPlan) -> FaultPlan:
    return plan.install()


def clear() -> None:
    global _ACTIVE
    _ACTIVE = None


def inject(point: str, step: Optional[int] = None, context: Any = None) -> Any:
    """The hook production code calls at a named injection point.

    No plan armed: returns None at the cost of one global read — safe to
    leave in hot paths. Plan armed: consults the point's schedule; may
    sleep (latency fault), raise (error fault), or return the rule's
    payload for the site to interpret. ``step`` pins schedule indexing to
    a domain counter (e.g. boosting round); otherwise each call at the
    point advances a per-point hit counter. ``context`` is unused by the
    scheduler but keeps call sites self-describing."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan.check(point, step=step)
