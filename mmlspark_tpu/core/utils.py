"""Core utilities: timing, bounded-concurrency async, managed resources.

Analogue of core/utils/{StopWatch,AsyncUtils}.scala and core/env/
{StreamUtilities,FileUtilities}.scala in the reference.
"""

from __future__ import annotations

import concurrent.futures as _futures
import contextlib
import time
import zipfile
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence, TypeVar

from mmlspark_tpu import obs

T = TypeVar("T")

_M_RETRY_ATTEMPTS = obs.counter(
    "mmlspark_core_retry_attempts_total",
    "retry_with_backoff attempts (first try included)",
)
_M_RETRY_DEADLINE = obs.counter(
    "mmlspark_core_retry_deadline_hits_total",
    "retry_with_backoff budgets exhausted (deadline_s reached)",
)
_M_RETRY_BACKOFF = obs.counter(
    "mmlspark_core_retry_backoff_seconds_total",
    "Cumulative retry_with_backoff sleep",
)


class StopWatch:
    """ns-resolution stopwatch (core/utils/StopWatch.scala:6)."""

    def __init__(self) -> None:
        self.elapsed_ns = 0
        self._start: Optional[int] = None

    def start(self) -> None:
        self._start = time.perf_counter_ns()

    def stop(self) -> None:
        if self._start is not None:
            self.elapsed_ns += time.perf_counter_ns() - self._start
            self._start = None

    def restart(self) -> None:
        self.elapsed_ns = 0
        self.start()

    def measure(self, fn: Callable[[], T]) -> T:
        self.start()
        try:
            return fn()
        finally:
            self.stop()

    @property
    def elapsed_s(self) -> float:
        return self.elapsed_ns / 1e9


def buffered_await(
    tasks: Iterable[Callable[[], T]],
    max_concurrency: int,
    executor: Optional[_futures.Executor] = None,
) -> Iterator[T]:
    """Run thunks with bounded concurrency, yielding results in input order.

    ``AsyncUtils.bufferedAwait`` analogue (core/utils/AsyncUtils.scala):
    keeps at most ``max_concurrency`` in flight; yields as the *head* task
    completes, so memory stays bounded and order is preserved.
    """
    own = executor is None
    pool = executor or _futures.ThreadPoolExecutor(max_workers=max_concurrency)
    try:
        pending: list[_futures.Future] = []
        it = iter(tasks)
        exhausted = False
        while True:
            while not exhausted and len(pending) < max_concurrency:
                try:
                    thunk = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.append(pool.submit(thunk))
            if not pending:
                break
            yield pending.pop(0).result()
    finally:
        if own:
            pool.shutdown(wait=True)


@contextlib.contextmanager
def using(*resources: Any) -> Iterator[Sequence[Any]]:
    """StreamUtilities.using/usingMany analogue."""
    try:
        yield resources
    finally:
        for r in reversed(resources):
            close = getattr(r, "close", None)
            if close is not None:
                with contextlib.suppress(Exception):
                    close()


def zip_iterator(path: str, sample_ratio: float = 1.0, seed: int = 0) -> Iterator[tuple]:
    """Iterate (filename, bytes) over a zip archive with optional subsampling
    (StreamUtilities.ZipIterator analogue, used by BinaryFileFormat)."""
    import random

    rng = random.Random(seed)
    with zipfile.ZipFile(path) as z:
        for info in z.infolist():
            if info.is_dir():
                continue
            if sample_ratio >= 1.0 or rng.random() < sample_ratio:
                yield f"{path}::{info.filename}", z.read(info)


def retry_with_backoff(
    fn: Callable[[], T],
    backoffs_ms: Sequence[int] = (100, 500, 1000),
    retryable: Callable[[Exception], bool] = lambda e: True,
    jitter: bool = True,
    deadline_s: Optional[float] = None,
    rng: Optional[Any] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> T:
    """FaultToleranceUtils.retryWithTimeout / RESTHelpers.retry analogue
    (ModelDownloader.scala:37-47, RESTHelpers.scala:35-47).

    ``jitter`` (default on): each wait is uniform in [0, backoff] — full
    jitter, so a fleet of workers retrying the same dead dependency
    desynchronizes instead of hammering it in lockstep every 100/500/
    1000 ms. ``deadline_s``: overall budget — no sleep extends past it and
    no attempt starts after it, so a retried call cannot overshoot its
    caller's own timeout; on expiry the last error is raised. ``rng``/
    ``sleep``/``clock`` are injectable for deterministic tests."""
    import random as _random

    draw = (rng or _random).uniform
    start = clock()
    last: Optional[Exception] = None
    for wait_ms in [0, *backoffs_ms]:
        if wait_ms:
            delay = draw(0.0, wait_ms / 1000.0) if jitter else wait_ms / 1000.0
            if deadline_s is not None and (
                delay >= deadline_s - (clock() - start)
            ):
                # the next attempt would start at/after the deadline
                _M_RETRY_DEADLINE.inc()
                break
            _M_RETRY_BACKOFF.inc(delay)
            sleep(delay)
        try:
            _M_RETRY_ATTEMPTS.inc()
            return fn()
        except Exception as e:  # noqa: BLE001 - retry boundary
            if not retryable(e):
                raise
            last = e
            if deadline_s is not None and clock() - start >= deadline_s:
                _M_RETRY_DEADLINE.inc()
                break
    assert last is not None
    raise last
