"""Metric names + computation (core/metrics/MetricConstants.scala:9-83 and
train/ComputeModelStatistics.scala metric math).

Metric math is vectorized numpy/JAX over full prediction columns.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class MetricConstants:
    # classification
    ACCURACY = "accuracy"
    PRECISION = "precision"
    RECALL = "recall"
    AUC = "AUC"
    F1 = "f1_score"
    # regression
    MSE = "mean_squared_error"
    RMSE = "root_mean_squared_error"
    R2 = "R^2"
    MAE = "mean_absolute_error"

    ALL_CLASSIFICATION = [ACCURACY, PRECISION, RECALL, AUC, F1]
    ALL_REGRESSION = [MSE, RMSE, R2, MAE]
    HIGHER_IS_BETTER = {ACCURACY, PRECISION, RECALL, AUC, F1, R2}


def confusion_matrix(y_true: np.ndarray, y_pred: np.ndarray, n_classes: Optional[int] = None) -> np.ndarray:
    y_true = np.asarray(y_true, dtype=np.int64)
    y_pred = np.asarray(y_pred, dtype=np.int64)
    # rows with unknown labels/predictions (encoded -1) are excluded, not
    # silently wrapped onto the last class
    valid = (y_true >= 0) & (y_pred >= 0)
    y_true, y_pred = y_true[valid], y_pred[valid]
    n = n_classes or int(max(y_true.max(initial=0), y_pred.max(initial=0)) + 1)
    cm = np.zeros((n, n), dtype=np.int64)
    np.add.at(cm, (y_true, y_pred), 1)
    return cm


def binary_auc(y_true: np.ndarray, scores: np.ndarray) -> float:
    """ROC AUC via the rank statistic (ties averaged)."""
    y = np.asarray(y_true).astype(np.float64)
    s = np.asarray(scores).astype(np.float64)
    n_pos = y.sum()
    n_neg = len(y) - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), dtype=np.float64)
    ranks[order] = np.arange(1, len(s) + 1)
    # average ranks over ties
    sorted_s = s[order]
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
            j += 1
        if j > i:
            avg = (i + j) / 2.0 + 1.0
            ranks[order[i: j + 1]] = avg
        i = j + 1
    return float((ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def roc_curve(y_true: np.ndarray, scores: np.ndarray) -> dict:
    y = np.asarray(y_true).astype(np.int64)
    s = np.asarray(scores).astype(np.float64)
    order = np.argsort(-s, kind="mergesort")
    y = y[order]
    tps = np.cumsum(y)
    fps = np.cumsum(1 - y)
    n_pos = max(int(tps[-1]) if len(tps) else 0, 1)
    n_neg = max(int(fps[-1]) if len(fps) else 0, 1)
    return {
        "false_positive_rate": np.concatenate([[0.0], fps / n_neg]),
        "true_positive_rate": np.concatenate([[0.0], tps / n_pos]),
        "thresholds": np.concatenate([[np.inf], s[order]]),
    }


def classification_metrics(
    y_true: np.ndarray,
    y_pred: np.ndarray,
    scores: Optional[np.ndarray] = None,
) -> dict:
    y_true = np.asarray(y_true, dtype=np.int64)
    y_pred = np.asarray(y_pred, dtype=np.int64)
    cm = confusion_matrix(y_true, y_pred)
    n = cm.sum()
    acc = float(np.trace(cm) / n) if n else float("nan")
    # macro-averaged precision/recall (binary: positive-class values, as in
    # the reference's evaluator for binary)
    with np.errstate(divide="ignore", invalid="ignore"):
        prec_k = np.diag(cm) / cm.sum(axis=0)
        rec_k = np.diag(cm) / cm.sum(axis=1)
    if cm.shape[0] == 2:
        precision = float(np.nan_to_num(prec_k[1]))
        recall = float(np.nan_to_num(rec_k[1]))
    else:
        precision = float(np.nanmean(np.nan_to_num(prec_k)))
        recall = float(np.nanmean(np.nan_to_num(rec_k)))
    f1 = 2 * precision * recall / (precision + recall) if precision + recall else 0.0
    out = {
        MetricConstants.ACCURACY: acc,
        MetricConstants.PRECISION: precision,
        MetricConstants.RECALL: recall,
        MetricConstants.F1: f1,
    }
    if scores is not None and cm.shape[0] <= 2:
        out[MetricConstants.AUC] = binary_auc(y_true, scores)
    return out


def regression_metrics(y_true: np.ndarray, y_pred: np.ndarray) -> dict:
    y = np.asarray(y_true, dtype=np.float64)
    p = np.asarray(y_pred, dtype=np.float64)
    err = y - p
    mse = float((err ** 2).mean()) if len(y) else float("nan")
    var = float(((y - y.mean()) ** 2).mean()) if len(y) else float("nan")
    return {
        MetricConstants.MSE: mse,
        MetricConstants.RMSE: float(np.sqrt(mse)),
        MetricConstants.R2: 1.0 - mse / var if var else float("nan"),
        MetricConstants.MAE: float(np.abs(err).mean()) if len(y) else float("nan"),
    }
