"""Typed parameter system for pipeline stages.

Rebuilds the capability of the reference's SparkML ``Params`` layer —
shared column-name traits (core/contracts/Params.scala:15-217), the typed
param zoo (org/apache/spark/ml/param/*.scala) and ``ComplexParam``
persistence for non-JSON payloads (core/serialize/ComplexParam.scala:13-34)
— as Python descriptors on pipeline stages.

Design notes (TPU-first, not a translation):
- Params are class-level descriptors; values live per-instance, split into
  user-set vs default, mirroring SparkML semantics so ``explain_params`` and
  persistence behave the same way.
- ``ComplexParam`` values (model weights, pytrees, DataFrames, callables)
  are serialized to their own subdirectory by the machinery in
  ``mmlspark_tpu.core.serialize`` instead of JSON metadata.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Generic, Iterator, Optional, TypeVar

T = TypeVar("T")

_NO_DEFAULT = object()


class Param(Generic[T]):
    """A named, documented, validated parameter (descriptor).

    JSON-serializable values only; use :class:`ComplexParam` for payloads.
    """

    is_complex = False

    def __init__(
        self,
        doc: str = "",
        default: Any = _NO_DEFAULT,
        validator: Optional[Callable[[Any], bool]] = None,
        type_: Optional[type] = None,
    ):
        self.doc = doc
        self.default = default
        self.validator = validator
        self.type_ = type_
        self.name: str = ""  # filled by __set_name__

    def __set_name__(self, owner: type, name: str) -> None:
        self.name = name

    def __get__(self, obj: Any, objtype: Optional[type] = None) -> Any:
        if obj is None:
            return self
        return obj.get(self.name)

    def __set__(self, obj: Any, value: Any) -> None:
        obj.set(self.name, value)

    def has_default(self) -> bool:
        return self.default is not _NO_DEFAULT

    def validate(self, value: Any) -> Any:
        import numpy as _np

        if isinstance(value, _np.generic):  # numpy scalars from df columns
            value = value.item()
        if self.type_ is not None and value is not None:
            if self.type_ in (int, float) and isinstance(value, bool):
                raise TypeError(
                    f"param {self.name}: expected {self.type_.__name__}, got bool"
                )
            if self.type_ is float and isinstance(value, int):
                value = float(value)
            if not isinstance(value, self.type_):
                raise TypeError(
                    f"param {self.name}: expected {self.type_.__name__}, "
                    f"got {type(value).__name__}"
                )
        if self.validator is not None and value is not None:
            if not self.validator(value):
                raise ValueError(f"param {self.name}: invalid value {value!r}")
        return value


class ComplexParam(Param):
    """A param whose value is a structured payload (arrays, pytrees,
    DataFrames, fitted models, callables) persisted outside JSON metadata.

    Mirrors the role of the reference's ``ComplexParam``
    (core/serialize/ComplexParam.scala:13-34) + its typed zoo
    (TransformerParam, UDFParam, DataFrameParam, ByteArrayParam, ...).
    The concrete codec is chosen at save time by
    ``mmlspark_tpu.core.serialize.write_complex_value``.
    """

    is_complex = True


class Params:
    """Base for anything with params. Subclasses declare ``Param`` class
    attributes; instances carry user-set values and defaults separately."""

    def __init__(self, **kwargs: Any):
        self._paramMap: dict[str, Any] = {}
        self.set(**kwargs)

    # -- declaration helpers -------------------------------------------------

    @classmethod
    def params(cls) -> dict[str, Param]:
        out: dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[k] = v
        return out

    @classmethod
    def param(cls, name: str) -> Param:
        p = cls.params().get(name)
        if p is None:
            raise KeyError(f"{cls.__name__} has no param {name!r}")
        return p

    # -- get/set -------------------------------------------------------------

    def set(self, *args: Any, **kwargs: Any) -> "Params":
        if args:
            if len(args) != 2:
                raise TypeError("set() positional form is set(name, value)")
            kwargs = {args[0]: args[1], **kwargs}
        for name, value in kwargs.items():
            p = self.param(name)
            self._paramMap[name] = p.validate(value)
        return self

    def get(self, name: str, default: Any = _NO_DEFAULT) -> Any:
        p = self.param(name)
        if name in self._paramMap:
            return self._paramMap[name]
        if p.has_default():
            # copy mutable defaults so instances don't share state
            d = p.default
            return copy.copy(d) if isinstance(d, (list, dict, set)) else d
        if default is not _NO_DEFAULT:
            return default
        return None

    def is_set(self, name: str) -> bool:
        self.param(name)
        return name in self._paramMap

    def is_defined(self, name: str) -> bool:
        return self.is_set(name) or self.param(name).has_default()

    def get_or_fail(self, name: str) -> Any:
        if not self.is_defined(name):
            raise ValueError(
                f"{type(self).__name__}: required param {name!r} is not set"
            )
        return self.get(name)

    def clear(self, name: str) -> "Params":
        self._paramMap.pop(name, None)
        return self

    def copy(self, extra: Optional[dict[str, Any]] = None) -> "Params":
        other = copy.copy(self)
        other._paramMap = dict(self._paramMap)
        if extra:
            other.set(**extra)
        return other

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self.params().items()):
            cur = self._paramMap.get(name, "undefined" if not p.has_default() else p.default)
            lines.append(f"{name}: {p.doc} (current: {cur!r})")
        return "\n".join(lines)

    def iter_set_params(self) -> Iterator[tuple[str, Param, Any]]:
        for name, value in self._paramMap.items():
            yield name, self.param(name), value

    def __repr__(self) -> str:
        simple = {
            k: v for k, v in self._paramMap.items() if not self.param(k).is_complex
        }
        return f"{type(self).__name__}({', '.join(f'{k}={v!r}' for k, v in simple.items())})"


# --------------------------------------------------------------------------
# Shared column traits (HasInputCol / HasOutputCol / ... of
# core/contracts/Params.scala:15-217)
# --------------------------------------------------------------------------


class HasInputCol(Params):
    input_col = Param("name of the input column", type_=str)


class HasOutputCol(Params):
    output_col = Param("name of the output column", type_=str)


class HasInputCols(Params):
    input_cols = Param("names of the input columns", type_=list)


class HasOutputCols(Params):
    output_cols = Param("names of the output columns", type_=list)


class HasLabelCol(Params):
    label_col = Param("name of the label column", default="label", type_=str)


class HasFeaturesCol(Params):
    features_col = Param("name of the features column", default="features", type_=str)


class HasPredictionCol(Params):
    prediction_col = Param("name of the prediction column", default="prediction", type_=str)


class HasProbabilityCol(Params):
    probability_col = Param(
        "name of the predicted class-probability column", default="probability", type_=str
    )


class HasRawPredictionCol(Params):
    raw_prediction_col = Param(
        "name of the raw prediction (margin) column", default="raw_prediction", type_=str
    )


class HasWeightCol(Params):
    weight_col = Param("name of the instance-weight column", type_=str)


class HasValidationIndicatorCol(Params):
    validation_indicator_col = Param(
        "boolean column marking validation rows", type_=str
    )


class HasInitScoreCol(Params):
    init_score_col = Param("name of the initial-score (margin) column", type_=str)


class HasGroupCol(Params):
    group_col = Param("name of the query/group column (ranking)", type_=str)


class HasBatchSize(Params):
    batch_size = Param(
        "fixed minibatch size (static shapes keep XLA from recompiling)",
        default=64,
        type_=int,
        validator=lambda v: v > 0,
    )


class HasSeed(Params):
    seed = Param("random seed", default=0, type_=int)
