"""Profiling/tracing (SURVEY.md §5.1: the reference has StopWatch timers +
the Timer pipeline stage; the TPU equivalent adds device-level tracing).

- :func:`trace` wraps ``jax.profiler.trace`` — XLA/TPU timeline capture
  viewable in TensorBoard/Perfetto.
- :func:`annotate` marks host spans so stage boundaries show up inside the
  device trace (the log-per-stage analogue of stages/Timer.scala:57-92).
- :class:`ProfiledRun` collects per-stage wall times for a pipeline the
  way VW's TrainingStats DataFrame reports per-partition timings. Stage
  timings ride the obs span API (``mmlspark_tpu.obs``), so each stage
  lands in the process metrics registry as
  ``mmlspark_trace_span_seconds{span="pipeline.<Stage>"}`` AND nests into
  any active ``jax.profiler`` capture — the same numbers show up on
  ``/metrics`` and in Perfetto.
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator, Optional

import jax

from mmlspark_tpu import obs
from mmlspark_tpu.core.dataframe import DataFrame


import time as _time

# Device-time attribution: one counter splits where wall time actually
# goes across the staged-dispatch path — phase=compile (first-call XLA
# lowering+compile, blocked to completion), phase=execute (compiled
# computation dispatch+run), phase=host_callback (pure_callback host
# kernels running INSIDE a device computation — host time the device
# waits out). Per-stage label = fused segment / pipeline stage name.
# The first honest compile-vs-run split ahead of the Pallas/TPU arc.
_M_DEVICE_SECONDS = obs.counter(
    "mmlspark_device_seconds_total",
    "Wall seconds at the compile/execute/host_callback boundaries, "
    "by phase and pipeline stage / fused segment",
    labels=("phase", "stage"),
)


@contextlib.contextmanager
def device_phase(phase: str, stage: str) -> Iterator[None]:
    """Attribute the wall time of a compile/execute/host_callback
    boundary to ``mmlspark_device_seconds_total{phase,stage}``. Near-free
    when the registry is disabled (one attribute read + perf_counter)."""
    if not _M_DEVICE_SECONDS._on:
        yield
        return
    t0 = _time.perf_counter()
    try:
        yield
    finally:
        _M_DEVICE_SECONDS.labels(phase=phase, stage=stage).inc(
            _time.perf_counter() - t0
        )


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device+host profiler trace into ``log_dir``."""
    with jax.profiler.trace(log_dir, create_perfetto_link=create_perfetto_link):
        yield


def annotate(name: str) -> Any:
    """Named host span that nests into the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)


def _pipeline_stages(pipeline_model: Any) -> list:
    """The stage list of a PipelineModel, or [model] for a single
    transformer. Must not raise on plain transformers: anything without a
    ``params()`` classmethod / ``get`` accessor (a bare function wrapper,
    a duck-typed stage) profiles as one stage."""
    try:
        params = type(pipeline_model).params()
    except Exception:  # noqa: BLE001 — params() is a Params-API contract
        return [pipeline_model]
    if "stages" not in params:
        return [pipeline_model]
    try:
        return list(pipeline_model.get("stages"))
    except Exception:  # noqa: BLE001 — declared but unreadable
        return [pipeline_model]


class ProfiledRun:
    """Time each stage of a pipeline transform; emit a stats DataFrame.

    >>> prof = ProfiledRun()
    >>> out = prof.transform(pipeline_model, df)
    >>> prof.stats().head()   # stage, seconds
    """

    def __init__(self) -> None:
        self.records: list = []

    def transform(
        self, pipeline_model: Any, df: DataFrame,
        trace_id: Optional[str] = None,
    ) -> DataFrame:
        cur = df
        with obs.span("pipeline.transform", trace_id=trace_id):
            for stage in _pipeline_stages(pipeline_model):
                name = type(stage).__name__
                with obs.span(f"pipeline.{name}") as sp:
                    with device_phase("execute", name):
                        cur = stage.transform(cur)
                self.records.append((name, sp.duration_ns))
        return cur

    def stats(self) -> DataFrame:
        import numpy as np

        return DataFrame.from_dict(
            {
                "stage": np.array([r[0] for r in self.records], dtype=object),
                "seconds": np.array([r[1] / 1e9 for r in self.records]),
            }
        )
