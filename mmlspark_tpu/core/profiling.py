"""Profiling/tracing (SURVEY.md §5.1: the reference has StopWatch timers +
the Timer pipeline stage; the TPU equivalent adds device-level tracing).

- :func:`trace` wraps ``jax.profiler.trace`` — XLA/TPU timeline capture
  viewable in TensorBoard/Perfetto.
- :func:`annotate` marks host spans so stage boundaries show up inside the
  device trace (the log-per-stage analogue of stages/Timer.scala:57-92).
- :class:`ProfiledRun` collects per-stage wall times for a pipeline the
  way VW's TrainingStats DataFrame reports per-partition timings.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Iterator, Optional

import jax

from mmlspark_tpu.core.dataframe import DataFrame


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device+host profiler trace into ``log_dir``."""
    with jax.profiler.trace(log_dir, create_perfetto_link=create_perfetto_link):
        yield


def annotate(name: str) -> Any:
    """Named host span that nests into the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)


class ProfiledRun:
    """Time each stage of a pipeline transform; emit a stats DataFrame.

    >>> prof = ProfiledRun()
    >>> out = prof.transform(pipeline_model, df)
    >>> prof.stats().head()   # stage, seconds
    """

    def __init__(self) -> None:
        self.records: list = []

    def transform(self, pipeline_model: Any, df: DataFrame) -> DataFrame:
        stages = (
            pipeline_model.get("stages")
            if "stages" in type(pipeline_model).params()
            else [pipeline_model]
        )
        cur = df
        for stage in stages:
            name = type(stage).__name__
            t0 = time.perf_counter_ns()
            with annotate(name):
                cur = stage.transform(cur)
            self.records.append((name, time.perf_counter_ns() - t0))
        return cur

    def stats(self) -> DataFrame:
        import numpy as np

        return DataFrame.from_dict(
            {
                "stage": np.array([r[0] for r in self.records], dtype=object),
                "seconds": np.array([r[1] / 1e9 for r in self.records]),
            }
        )
