"""Partitioned columnar DataFrame — the dataflow substrate.

The reference is a library on top of Spark DataFrames; this framework brings
its own lightweight substrate designed for feeding TPUs:

- A DataFrame is a list of *partitions*; a partition is a dict of
  column-name -> numpy array (all arrays share axis-0 length).
- Vector/tensor columns are dense ND arrays (not arrays-of-objects), so a
  partition can be handed to ``jax.device_put`` / ``pjit`` with no host-side
  row marshalling — the analogue of the reference's per-partition native
  eval loops (cntk/CNTKModel.scala:515-520) without the row<->native copy.
- ``map_partitions`` is the SPMD primitive (Spark ``mapPartitions``
  analogue); partitions execute on a shared thread pool (numpy/JAX release
  the GIL in the hot paths; HTTP stages overlap I/O).

This is deliberately eager: XLA is the lazy/optimizing layer for compute;
re-creating Catalyst on the host would buy nothing for TPU throughput.
"""

from __future__ import annotations

import concurrent.futures as _futures
import os
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence, Union

import numpy as np

from mmlspark_tpu.core.schema import ColumnInfo, Schema, infer_schema

Partition = dict  # dict[str, np.ndarray]


class Row(dict):
    """A single row: dict with attribute access."""

    def __getattr__(self, item: str) -> Any:
        try:
            return self[item]
        except KeyError as e:
            raise AttributeError(item) from e


def _as_column(values: Any) -> np.ndarray:
    """Coerce python data to a column array (object fallback for ragged)."""
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    if values and isinstance(values[0], np.ndarray):
        shapes = {v.shape for v in values}
        if len(shapes) == 1:
            return np.stack(values)
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = v
        return out
    if values and isinstance(values[0], (dict, bytes, list, tuple)):
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = v
        return out
    arr = np.asarray(values)
    if arr.dtype.kind in ("U", "S"):
        arr = arr.astype(object)
    return arr


_pool: Optional[_futures.ThreadPoolExecutor] = None


def _get_pool() -> _futures.ThreadPoolExecutor:
    global _pool
    if _pool is None:
        n = int(os.environ.get("MMLSPARK_TPU_TASKS", str(min(16, (os.cpu_count() or 2) * 4))))
        _pool = _futures.ThreadPoolExecutor(max_workers=n, thread_name_prefix="mml-task")
    return _pool


class DataFrame:
    """Immutable partitioned columnar dataset."""

    def __init__(self, partitions: Sequence[Partition], metadata: Optional[dict] = None):
        parts = []
        names: Optional[list] = None
        for p in partitions:
            p = {k: _as_column(v) for k, v in p.items()}
            lens = {len(v) for v in p.values()}
            if len(lens) > 1:
                raise ValueError(f"ragged partition column lengths: { {k: len(v) for k, v in p.items()} }")
            if p:
                if names is None:
                    names = list(p.keys())
                elif set(p.keys()) != set(names):
                    raise ValueError(
                        f"partition columns {sorted(p.keys())} != {sorted(names)}"
                    )
                elif list(p.keys()) != names:
                    p = {k: p[k] for k in names}  # normalize order
            parts.append(p)
        if not parts:
            parts = [{}]
        # empty marker partitions adopt the shared column set (zero-length)
        if names is not None:
            proto = next(p for p in parts if p)
            empty = {k: proto[k][:0] for k in names}
            parts = [p if p else dict(empty) for p in parts]
        self._parts: list[Partition] = parts
        # per-column metadata (e.g. categorical levels), survives transforms
        self._metadata: dict = dict(metadata or {})

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_dict(data: dict, num_partitions: int = 1, metadata: Optional[dict] = None) -> "DataFrame":
        cols = {k: _as_column(v) for k, v in data.items()}
        lens = {k: len(v) for k, v in cols.items()}
        if len(set(lens.values())) > 1:
            raise ValueError(f"ragged column lengths: {lens}")
        n = len(next(iter(cols.values()))) if cols else 0
        num_partitions = max(1, min(num_partitions, max(n, 1)))
        bounds = np.linspace(0, n, num_partitions + 1).astype(int)
        parts = [
            {k: v[bounds[i]: bounds[i + 1]] for k, v in cols.items()}
            for i in range(num_partitions)
        ]
        return DataFrame(parts, metadata=metadata)

    @staticmethod
    def from_rows(rows: Iterable[dict], num_partitions: int = 1) -> "DataFrame":
        rows = list(rows)
        if not rows:
            return DataFrame([{}])
        cols = {k: [r[k] for r in rows] for k in rows[0].keys()}
        return DataFrame.from_dict(cols, num_partitions)

    @staticmethod
    def from_pandas(pdf: Any, num_partitions: int = 1) -> "DataFrame":
        return DataFrame.from_dict({c: pdf[c].to_numpy() for c in pdf.columns}, num_partitions)

    # -- basic properties ----------------------------------------------------

    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    @property
    def partitions(self) -> list:
        return self._parts

    @property
    def columns(self) -> list:
        for p in self._parts:
            if p:
                return list(p.keys())
        return []

    @property
    def schema(self) -> Schema:
        def merged(p: Partition) -> Schema:
            s = infer_schema(p)
            for name, info in s.items():
                md = self._metadata.get(name)
                if md:
                    s[name] = ColumnInfo(info.dtype, info.shape, dict(md))
            return s

        for p in self._parts:
            if p and len(next(iter(p.values()))):
                return merged(p)
        return merged(self._parts[0]) if self._parts[0] else Schema()

    def count(self) -> int:
        return sum(len(next(iter(p.values()))) if p else 0 for p in self._parts)

    def __len__(self) -> int:
        return self.count()

    def column_metadata(self, name: str) -> dict:
        return self._metadata.get(name, {})

    def with_column_metadata(self, name: str, md: dict) -> "DataFrame":
        new_md = dict(self._metadata)
        new_md[name] = dict(md)
        return DataFrame(self._parts, metadata=new_md)

    # -- column access -------------------------------------------------------

    def column(self, name: str) -> np.ndarray:
        """Materialize one column across all partitions."""
        arrs = [p[name] for p in self._parts if p]
        arrs = [a for a in arrs if len(a)]
        if not arrs:
            return np.array([])
        return np.concatenate(arrs, axis=0)

    def __getitem__(self, name: str) -> np.ndarray:
        return self.column(name)

    def to_dict(self) -> dict:
        return {c: self.column(c) for c in self.columns}

    def collect(self) -> list:
        out = []
        for p in self._parts:
            if not p:
                continue
            n = len(next(iter(p.values())))
            for i in range(n):
                out.append(Row({k: v[i] for k, v in p.items()}))
        return out

    def head(self, n: int = 5) -> list:
        out = []
        for p in self._parts:
            if not p:
                continue
            m = len(next(iter(p.values())))
            for i in range(m):
                out.append(Row({k: v[i] for k, v in p.items()}))
                if len(out) >= n:
                    return out
        return out

    # -- transformations -----------------------------------------------------

    def map_partitions(
        self,
        fn: Callable[[Partition], Partition],
        parallel: bool = True,
    ) -> "DataFrame":
        parts = self._run(fn, parallel)
        return DataFrame(parts, metadata=self._metadata)

    def _run(self, fn: Callable[[Partition], Partition], parallel: bool = True) -> list:
        live = self._parts
        import threading

        # nested map_partitions (a partition fn using DataFrame ops) must not
        # re-enter the bounded pool: all workers could block waiting for
        # inner tasks that can never be scheduled -> deadlock. Pool workers
        # carry the "mml-task" thread-name prefix; inside one, run serially.
        in_worker = threading.current_thread().name.startswith("mml-task")
        if parallel and len(live) > 1 and not in_worker:
            return list(_get_pool().map(fn, live))
        return [fn(p) for p in live]

    def select(self, *names: str) -> "DataFrame":
        names = list(names)
        return DataFrame([{k: p[k] for k in names} for p in self._parts], metadata=self._metadata)

    def drop(self, *names: str) -> "DataFrame":
        drop = set(names)
        return DataFrame(
            [{k: v for k, v in p.items() if k not in drop} for p in self._parts],
            metadata=self._metadata,
        )

    def rename(self, mapping: dict) -> "DataFrame":
        return DataFrame(
            [{mapping.get(k, k): v for k, v in p.items()} for p in self._parts],
            metadata={mapping.get(k, k): v for k, v in self._metadata.items()},
        )

    def with_column(
        self, name: str, value: Union[np.ndarray, Callable[[Partition], Any]]
    ) -> "DataFrame":
        """Add/replace a column. ``value`` is a full-length array or a
        function partition -> column array."""
        if callable(value):
            def fn(p: Partition) -> Partition:
                q = dict(p)
                q[name] = _as_column(value(p))
                return q
            return self.map_partitions(fn)
        arr = _as_column(value)
        parts, off = [], 0
        for p in self._parts:
            n = len(next(iter(p.values()))) if p else 0
            q = dict(p)
            q[name] = arr[off: off + n]
            off += n
            parts.append(q)
        if off != len(arr):
            raise ValueError(f"column length {len(arr)} != dataframe length {off}")
        return DataFrame(parts, metadata=self._metadata)

    def with_row_column(self, name: str, fn: Callable[[Row], Any]) -> "DataFrame":
        """Per-row UDF column (convenience; prefer vectorized with_column)."""
        def part_fn(p: Partition) -> Partition:
            n = len(next(iter(p.values()))) if p else 0
            vals = [fn(Row({k: v[i] for k, v in p.items()})) for i in range(n)]
            q = dict(p)
            q[name] = _as_column(vals) if vals else np.array([])
            return q
        return self.map_partitions(part_fn)

    def filter(self, mask_fn: Callable[[Partition], np.ndarray]) -> "DataFrame":
        def fn(p: Partition) -> Partition:
            mask = np.asarray(mask_fn(p), dtype=bool)
            return {k: v[mask] for k, v in p.items()}
        return self.map_partitions(fn)

    def drop_na(self, cols: Optional[Sequence[str]] = None) -> "DataFrame":
        def fn(p: Partition) -> Partition:
            if not p:
                return p
            n = len(next(iter(p.values())))
            mask = np.ones(n, dtype=bool)
            for k in (cols or p.keys()):
                v = p[k]
                if v.dtype == object:
                    mask &= np.array([x is not None for x in v])
                elif v.dtype.kind == "f":
                    ax = tuple(range(1, v.ndim))
                    mask &= ~np.isnan(v).any(axis=ax) if v.ndim > 1 else ~np.isnan(v)
            return {k: v[mask] for k, v in p.items()}
        return self.map_partitions(fn)

    # -- partitioning --------------------------------------------------------

    def repartition(self, n: int) -> "DataFrame":
        """Round-robin-ish even split into n partitions (Repartition stage)."""
        cols = self.to_dict()
        return DataFrame.from_dict(cols, num_partitions=n, metadata=self._metadata)

    def coalesce(self, n: int) -> "DataFrame":
        if n < 1:
            raise ValueError(f"coalesce: n must be >= 1, got {n}")
        if n >= self.num_partitions:
            return self
        # contiguous runs preserve global row order
        bounds = np.linspace(0, len(self._parts), n + 1).astype(int)
        groups: list[list[Partition]] = [
            self._parts[bounds[i]: bounds[i + 1]] for i in range(n)
        ]
        parts = []
        for g in groups:
            g = [p for p in g if p]
            if not g:
                parts.append({})
                continue
            names = list(g[0].keys())
            parts.append({k: np.concatenate([p[k] for p in g], axis=0) for k in names})
        return DataFrame(parts, metadata=self._metadata)

    def union(self, other: "DataFrame") -> "DataFrame":
        my_cols = self.columns or other.columns
        if other.columns and set(other.columns) != set(my_cols):
            raise ValueError(
                f"union: column mismatch {sorted(my_cols)} vs {sorted(other.columns)}"
            )
        other_parts = [{k: p[k] for k in my_cols} for p in other._parts if p]
        md = {**other._metadata, **self._metadata}
        return DataFrame(self._parts + other_parts, metadata=md)

    def random_split(self, weights: Sequence[float], seed: int = 0) -> list:
        w = np.asarray(weights, dtype=float)
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        cols = self.to_dict()
        n = self.count()
        assign = rng.choice(len(w), size=n, p=w)
        out = []
        for i in range(len(w)):
            mask = assign == i
            out.append(
                DataFrame([{k: v[mask] for k, v in cols.items()}], metadata=self._metadata)
            )
        return out

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        rng = np.random.default_rng(seed)
        def fn(p: Partition) -> Partition:
            if not p:
                return p
            n = len(next(iter(p.values())))
            mask = rng.random(n) < fraction
            return {k: v[mask] for k, v in p.items()}
        return self.map_partitions(fn, parallel=False)

    def sort(self, by: str, ascending: bool = True) -> "DataFrame":
        cols = self.to_dict()
        order = np.argsort(cols[by], kind="stable")
        if not ascending:
            order = order[::-1]
        return DataFrame([{k: v[order] for k, v in cols.items()}], metadata=self._metadata)

    # -- aggregation ---------------------------------------------------------

    def group_apply(
        self, key: str, fn: Callable[[Any, Partition], dict]
    ) -> "DataFrame":
        """Group all rows by ``key`` column and apply fn(key_value, group) ->
        dict of scalar/array outputs (one row per group)."""
        cols = self.to_dict()
        keys = cols[key]
        uniq, inv = np.unique(keys.astype(str) if keys.dtype == object else keys, return_inverse=True)
        rows = []
        for gi, kv in enumerate(uniq):
            mask = inv == gi
            group = {c: v[mask] for c, v in cols.items()}
            rows.append(fn(kv, group))
        return DataFrame.from_rows(rows)

    # -- sugar (FluentAPI analogue: core/spark/FluentAPI.scala:25-30) --------

    def ml_transform(self, *stages: Any) -> "DataFrame":
        df = self
        for s in stages:
            df = s.transform(df)
        return df

    def ml_fit(self, estimator: Any) -> Any:
        return estimator.fit(self)

    def __repr__(self) -> str:
        return (
            f"DataFrame[{self.count()} rows x {len(self.columns)} cols, "
            f"{self.num_partitions} partitions]({', '.join(self.columns[:8])}"
            + ("..." if len(self.columns) > 8 else "") + ")"
        )
