"""Persistence machinery for stages, params and DataFrames.

Rebuilds the reference's ``ComplexParamsWritable``/``Serializer`` capability
(core/serialize/ComplexParam.scala:13-34, org/apache/spark/ml/Serializer.scala:53-60):
every stage — including ones holding native payloads (model weights/pytrees,
inner DataFrames, fitted sub-stages, callables) — must round-trip
``save(path)`` / ``load(path)``, including when nested inside a Pipeline.
SerializationFuzzing (tests/fuzzing.py) is the forcing function, as in the
reference.

On-disk layout of a saved stage::

    path/
      metadata.json          # {class, version, params: {...simple json...}}
      complex/<param>/       # one dir per set ComplexParam
        kind.txt             # codec name
        value.*              # codec-specific payload

Codec dispatch (the ``Serializer.typeToSerializer`` analogue):
ndarray -> .npy | jax array -> .npy | pytree of arrays -> msgpack (flax) |
DataFrame -> partition npz + pickled object columns | stage / list of
stages -> nested dirs | bytes -> raw | everything else (UDFs, lambdas) ->
cloudpickle (so inline lambdas persist, the UDFParam analogue).
"""

from __future__ import annotations

import importlib
import json
import os
import pickle
from typing import Any

import cloudpickle
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame

FORMAT_VERSION = 1


def _full_class_name(obj: Any) -> str:
    cls = type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


def _import_class(name: str) -> type:
    module, _, cls = name.rpartition(".")
    return getattr(importlib.import_module(module), cls)


def _is_pytree_of_arrays(v: Any) -> bool:
    if isinstance(v, dict):
        # msgpack strict_map_key only round-trips str keys; other key types
        # (ints, tuples, numpy scalars) must take the pickle path
        return all(
            isinstance(k, str) and _is_pytree_of_arrays(x) for k, x in v.items()
        )
    if isinstance(v, (list, tuple)):
        return all(_is_pytree_of_arrays(x) for x in v)
    return isinstance(v, (np.ndarray, float, int)) or type(v).__module__.startswith("jax")


# -- DataFrame codec --------------------------------------------------------


def write_dataframe(df: DataFrame, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    meta = {"num_partitions": df.num_partitions, "metadata": {}}
    for name, md in ((n, df.column_metadata(n)) for n in df.columns):
        if md:
            meta["metadata"][name] = _jsonable(md)
    for i, p in enumerate(df.partitions):
        dense = {k: v for k, v in p.items() if v.dtype != object}
        objs = {k: list(v) for k, v in p.items() if v.dtype == object}
        np.savez(os.path.join(path, f"part_{i}.npz"), **dense)
        if objs:
            with open(os.path.join(path, f"part_{i}.objs.pkl"), "wb") as f:
                pickle.dump(objs, f)
        meta.setdefault("columns", list(p.keys()))
    with open(os.path.join(path, "dataframe.json"), "w") as f:
        json.dump(meta, f)


def read_dataframe(path: str) -> DataFrame:
    with open(os.path.join(path, "dataframe.json")) as f:
        meta = json.load(f)
    parts = []
    for i in range(meta["num_partitions"]):
        with np.load(os.path.join(path, f"part_{i}.npz"), allow_pickle=False) as z:
            p = {k: z[k] for k in z.files}
        objp = os.path.join(path, f"part_{i}.objs.pkl")
        if os.path.exists(objp):
            with open(objp, "rb") as f:
                for k, v in pickle.load(f).items():
                    arr = np.empty(len(v), dtype=object)
                    for j, x in enumerate(v):
                        arr[j] = x
                    p[k] = arr
        cols = meta.get("columns")
        if cols:
            p = {k: p[k] for k in cols if k in p}
        parts.append(p)
    return DataFrame(parts, metadata=meta.get("metadata") or None)


# -- complex value dispatch -------------------------------------------------


def write_complex_value(value: Any, path: str) -> None:
    from mmlspark_tpu.core.pipeline import PipelineStage  # cycle-free at call time

    os.makedirs(path, exist_ok=True)

    def mark(kind: str) -> None:
        with open(os.path.join(path, "kind.txt"), "w") as f:
            f.write(kind)

    if isinstance(value, PipelineStage):
        mark("stage")
        save_stage(value, os.path.join(path, "value.stage"))
    elif (
        isinstance(value, (list, tuple))
        and value
        and all(isinstance(s, PipelineStage) for s in value)
    ):
        mark("stage_list")
        sl = os.path.join(path, "value.stages")
        os.makedirs(sl, exist_ok=True)
        with open(os.path.join(sl, "n.json"), "w") as f:
            json.dump(len(value), f)
        for i, s in enumerate(value):
            save_stage(s, os.path.join(sl, f"stage_{i}"))
    elif isinstance(value, DataFrame):
        mark("dataframe")
        write_dataframe(value, os.path.join(path, "value.df"))
    elif isinstance(value, bytes):
        mark("bytes")
        with open(os.path.join(path, "value.bin"), "wb") as f:
            f.write(value)
    elif isinstance(value, np.ndarray) and value.dtype != object:
        mark("ndarray")
        np.save(os.path.join(path, "value.npy"), value)
    elif type(value).__module__.startswith("jax"):
        mark("ndarray")
        np.save(os.path.join(path, "value.npy"), np.asarray(value))
    elif isinstance(value, (dict, list, tuple)) and _is_pytree_of_arrays(value):
        mark("pytree")
        from flax import serialization as _fser

        with open(os.path.join(path, "value.msgpack"), "wb") as f:
            f.write(_fser.msgpack_serialize(_np_tree(value)))
    else:
        mark("pickle")
        with open(os.path.join(path, "value.pkl"), "wb") as f:
            cloudpickle.dump(value, f)


def _np_tree(v: Any) -> Any:
    if isinstance(v, dict):
        # msgpack strict_map_key rejects numpy scalar keys; use python scalars
        return {
            (k.item() if isinstance(k, np.generic) else k): _np_tree(x)
            for k, x in v.items()
        }
    if isinstance(v, (list, tuple)):
        return [_np_tree(x) for x in v]
    if type(v).__module__.startswith("jax"):
        return np.asarray(v)
    return v


def read_complex_value(path: str) -> Any:
    with open(os.path.join(path, "kind.txt")) as f:
        kind = f.read().strip()
    if kind == "stage":
        return load_stage(os.path.join(path, "value.stage"))
    if kind == "stage_list":
        sl = os.path.join(path, "value.stages")
        with open(os.path.join(sl, "n.json")) as f:
            n = json.load(f)
        return [load_stage(os.path.join(sl, f"stage_{i}")) for i in range(n)]
    if kind == "dataframe":
        return read_dataframe(os.path.join(path, "value.df"))
    if kind == "bytes":
        with open(os.path.join(path, "value.bin"), "rb") as f:
            return f.read()
    if kind == "ndarray":
        return np.load(os.path.join(path, "value.npy"))
    if kind == "pytree":
        from flax import serialization as _fser

        with open(os.path.join(path, "value.msgpack"), "rb") as f:
            return _fser.msgpack_restore(f.read())
    if kind == "pickle":
        with open(os.path.join(path, "value.pkl"), "rb") as f:
            return pickle.load(f)
    raise ValueError(f"unknown complex param kind {kind!r} at {path}")


# -- stage save/load --------------------------------------------------------


def save_stage(stage: Any, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    simple, complexes = {}, {}
    for name, p, value in stage.iter_set_params():
        if p.is_complex:
            complexes[name] = value
        else:
            simple[name] = _jsonable(value)
    meta = {
        "class": _full_class_name(stage),
        "format_version": FORMAT_VERSION,
        "params": simple,
        "complex_params": sorted(complexes.keys()),
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=1)
    for name, value in complexes.items():
        write_complex_value(value, os.path.join(path, "complex", name))
    # allow stages to persist extra payloads (e.g. PipelineModel stages)
    extra = getattr(stage, "_save_extra", None)
    if extra is not None:
        extra(path)


def _jsonable(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return v


def load_stage(path: str) -> Any:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = _import_class(meta["class"])
    stage = cls()  # stages are constructible with no args (SparkML convention)
    stage.set(**meta["params"])
    for name in meta.get("complex_params", []):
        stage.set(name, read_complex_value(os.path.join(path, "complex", name)))
    extra = getattr(stage, "_load_extra", None)
    if extra is not None:
        extra(path)
    return stage
