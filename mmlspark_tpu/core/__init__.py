from mmlspark_tpu.core.dataframe import DataFrame, Row
from mmlspark_tpu.core.params import (
    ComplexParam,
    Param,
    Params,
)
from mmlspark_tpu.core.pipeline import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    STAGE_REGISTRY,
    Transformer,
    load_stage,
)
from mmlspark_tpu.core.faults import FaultPlan, Preempted
from mmlspark_tpu.core.schema import ColumnInfo, Schema
from mmlspark_tpu.core.utils import StopWatch

__all__ = [
    "FaultPlan",
    "Preempted",
    "DataFrame",
    "Row",
    "Param",
    "ComplexParam",
    "Params",
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "STAGE_REGISTRY",
    "load_stage",
    "ColumnInfo",
    "Schema",
    "StopWatch",
]
