"""ChaosProxy: a transparent, seeded, deterministic TCP chaos proxy.

Every fault-injection point so far (core/faults.py) fires *inside* our
own functions; a production network lies at a layer none of them reach —
flipped bytes, slow-dripped headers, asymmetric partitions, mid-frame
resets. This proxy makes the fabric itself the adversary: point any
fleet link (client->gateway, gateway->worker, gang member<->member,
artifact fetch, registry heartbeats) at a :class:`ChaosProxy` and give
it :class:`WireRule` schedules.

Rule kinds (:data:`RULE_KINDS`; docs/chaos.md has the full table):

==============  ==============================================================
``latency``     delay each stream window by ``delay_ms`` plus a seeded
                jitter draw in ``[0, jitter_ms]``
``throttle``    cap the direction's forwarding rate at ``bytes_per_s``
``flip``        XOR the byte at absolute stream ``at_offset`` with
                ``xor_mask`` (``every_bytes`` > 0 repeats the flip at
                ``at_offset + k*every_bytes``)
``truncate_rst``  forward the stream up to ``at_offset`` bytes, then RST
                both sides of the connection (SO_LINGER 0)
``slowdrip``    forward in ``drip_bytes`` chunks with
                ``drip_interval_ms`` sleeps — the proxy *becomes* a
                slowloris client toward the upstream
``blackhole``   silently swallow the direction's bytes (the peer's sends
                succeed; nothing arrives). One direction only =
                asymmetric partition: A->B dead while B->A lives
==============  ==============================================================

**Determinism contract.** The fault *schedule* is a pure function of
``(seed, link name, connection index, direction, stream byte offset)``
— never of wall-clock time or TCP chunk boundaries. Byte-positioned
rules (flip, truncate) land on exact offsets; latency jitter draws per
fixed 64 KiB stream window. Every applied fault is journaled as a
``(conn, direction, kind, offset, value)`` tuple and
:meth:`ChaosProxy.schedule_digest` hashes the sorted journal: replaying
the same seed against the same byte streams (and connection arrival
order) reproduces the identical digest — chaos tests are reproducible,
bit-for-bit, the same property core/faults.py gives code-level plans.

Rules can be swapped live with :meth:`ChaosProxy.set_rules` (the
conductor's timed-scenario hook); in-flight connections pick the new
rules up at their next chunk.
"""

from __future__ import annotations

import hashlib
import random
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from mmlspark_tpu import obs

_M_CONNS = obs.counter(
    "mmlspark_chaos_conns_total",
    "Connections accepted by a chaos proxy, per link",
    labels=("link",),
)
_M_FAULTS = obs.counter(
    "mmlspark_chaos_faults_total",
    "Wire faults applied by a chaos proxy, per link and rule kind",
    labels=("link", "kind"),
)
_M_BYTES = obs.counter(
    "mmlspark_chaos_bytes_total",
    "Bytes forwarded through a chaos proxy, per link and direction",
    labels=("link", "direction"),
)
_M_DROPPED = obs.counter(
    "mmlspark_chaos_dropped_bytes_total",
    "Bytes swallowed by blackhole rules, per link",
    labels=("link",),
)

# the rule vocabulary; tools/lint_fault_points.py greps this tuple and
# requires every kind to be named by at least one test (an untested wire
# fault is an adversary nobody has ever watched the fleet survive)
RULE_KINDS = (
    "latency",
    "throttle",
    "flip",
    "truncate_rst",
    "slowdrip",
    "blackhole",
)

DIRECTIONS = ("c2s", "s2c", "both")

# latency jitter draws once per this many stream bytes (schedule keyed on
# the window index, so TCP chunking cannot perturb the draw sequence)
LAT_WINDOW = 65536

_BUFSIZE = 65536


class _Truncated(Exception):
    """Internal: a truncate_rst rule fired — RST and stop pumping."""


@dataclass(frozen=True)
class WireRule:
    """One scheduled wire fault on one link direction.

    ``direction``: ``c2s`` (client->server bytes), ``s2c``, or ``both``.
    ``conns``: restrict to these connection indices (accept order,
    0-based); ``after_conn``: apply only from that index on. Offsets are
    absolute per-connection per-direction stream byte offsets."""

    kind: str
    direction: str = "both"
    delay_ms: float = 0.0          # latency: base added delay per window
    jitter_ms: float = 0.0         # latency: seeded uniform extra
    bytes_per_s: float = 0.0       # throttle
    at_offset: int = 0             # flip / truncate_rst
    xor_mask: int = 0xFF           # flip
    every_bytes: int = 0           # flip: 0 = once, else repeat stride
    drip_bytes: int = 1            # slowdrip chunk size
    drip_interval_ms: float = 20.0  # slowdrip inter-chunk sleep
    conns: Optional[frozenset] = None
    after_conn: int = 0

    def __post_init__(self):
        if self.kind not in RULE_KINDS:
            raise ValueError(
                f"unknown wire rule kind {self.kind!r}; known: {RULE_KINDS}"
            )
        if self.direction not in DIRECTIONS:
            raise ValueError(
                f"unknown direction {self.direction!r}; known: {DIRECTIONS}"
            )

    def applies(self, conn: int, direction: str) -> bool:
        if self.direction != "both" and self.direction != direction:
            return False
        if conn < self.after_conn:
            return False
        return self.conns is None or conn in self.conns

    @staticmethod
    def from_dict(d: dict) -> "WireRule":
        d = dict(d)
        if "conns" in d and d["conns"] is not None:
            d["conns"] = frozenset(d["conns"])
        return WireRule(**d)


@dataclass
class JournalEntry:
    """One applied fault — the deterministic schedule record. ``value``
    is the fault's drawn/derived parameter (jitter ms, flipped mask, RST
    offset, ...), never a wall-clock time."""

    conn: int
    direction: str
    kind: str
    offset: int
    value: Any = None

    def key(self) -> tuple:
        return (self.conn, self.direction, self.kind, self.offset,
                repr(self.value))


class ChaosProxy:
    """Transparent TCP proxy applying a seeded :class:`WireRule` schedule.

    >>> proxy = ChaosProxy("127.0.0.1", worker_port, seed=7, name="gw-w1",
    ...                    rules=[WireRule("flip", at_offset=100)])
    >>> proxy.start()
    >>> # point the client at ("127.0.0.1", proxy.port) instead
    """

    def __init__(
        self,
        target_host: str,
        target_port: int,
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        rules: Any = (),
        seed: int = 0,
        name: str = "link",
        connect_timeout_s: float = 10.0,
    ):
        self.target = (target_host, int(target_port))
        self.listen_host = listen_host
        self._listen_port = int(listen_port)
        self.seed = int(seed)
        self.name = name
        self.connect_timeout_s = connect_timeout_s
        self._rules: tuple = tuple(
            r if isinstance(r, WireRule) else WireRule.from_dict(r)
            for r in rules
        )
        self._lock = threading.Lock()
        self._journal: list = []
        self._conn_counter = 0
        self._stop = threading.Event()
        self._lsock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._open_socks: set = set()
        self.port: int = 0
        self._m_conns = _M_CONNS.labels(link=name)
        self._m_bytes = {
            d: _M_BYTES.labels(link=name, direction=d) for d in ("c2s", "s2c")
        }
        self._m_dropped = _M_DROPPED.labels(link=name)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "ChaosProxy":
        self._lsock = socket.create_server(
            (self.listen_host, self._listen_port)
        )
        self._lsock.settimeout(0.25)
        self.port = self._lsock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"chaos-{self.name}", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass
        with self._lock:
            socks = list(self._open_socks)
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(2.0)

    @property
    def url(self) -> str:
        return f"http://{self.listen_host}:{self.port}"

    # -- rule management (live-swappable by the conductor) --------------------

    def set_rules(self, rules: Any) -> None:
        with self._lock:
            self._rules = tuple(
                r if isinstance(r, WireRule) else WireRule.from_dict(r)
                for r in rules
            )

    def clear_rules(self) -> None:
        self.set_rules(())

    def rules(self) -> tuple:
        with self._lock:
            return self._rules

    # -- the deterministic schedule record ------------------------------------

    def journal(self) -> list:
        with self._lock:
            return list(self._journal)

    def schedule_digest(self) -> str:
        """sha256 over the sorted journal keys — identical for identical
        (seed, byte streams, connection order); the determinism pin."""
        entries = sorted(e.key() for e in self.journal())
        h = hashlib.sha256()
        for e in entries:
            h.update(repr(e).encode())
        return h.hexdigest()

    def _record(self, entry: JournalEntry) -> None:
        with self._lock:
            self._journal.append(entry)
        if _M_FAULTS._on:
            _M_FAULTS.labels(link=self.name, kind=entry.kind).inc()

    def _rng(self, conn: int, direction: str, kind: str, idx: int):
        return random.Random(
            f"{self.seed}:{self.name}:{conn}:{direction}:{kind}:{idx}"
        )

    # -- data plane -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                conn_id = self._conn_counter
                self._conn_counter += 1
            if self._m_conns._on:
                self._m_conns.inc()
            threading.Thread(
                target=self._serve_conn, args=(conn_id, client),
                name=f"chaos-{self.name}-{conn_id}", daemon=True,
            ).start()

    def _serve_conn(self, conn_id: int, client: socket.socket) -> None:
        try:
            upstream = socket.create_connection(
                self.target, timeout=self.connect_timeout_s
            )
        except OSError:
            try:
                client.close()
            except OSError:
                pass
            return
        upstream.settimeout(None)
        client.settimeout(None)
        for s in (client, upstream):
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        with self._lock:
            self._open_socks.update((client, upstream))
        t1 = threading.Thread(
            target=self._pump, args=(conn_id, client, upstream, "c2s"),
            daemon=True,
        )
        t2 = threading.Thread(
            target=self._pump, args=(conn_id, upstream, client, "s2c"),
            daemon=True,
        )
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        with self._lock:
            self._open_socks.discard(client)
            self._open_socks.discard(upstream)
        for s in (client, upstream):
            try:
                s.close()
            except OSError:
                pass

    @staticmethod
    def _rst(sock: socket.socket) -> None:
        """Close with SO_LINGER 0 so the peer sees ECONNRESET, not FIN —
        the mid-frame reset a dying kernel or middlebox produces. The
        SHUT_RD first unblocks the sibling pump's recv on this socket:
        close() alone would leave that thread parked in the syscall and
        the kernel would never actually tear the connection down (no
        RST ever leaves — measured, not theory)."""
        try:
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0),
            )
        except OSError:
            pass
        try:
            sock.shutdown(socket.SHUT_RD)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _pump(self, conn_id: int, src: socket.socket, dst: socket.socket,
              direction: str) -> None:
        offset = 0
        # per-connection one-shot journal flags (throttle/blackhole/
        # slowdrip are stream-wide modes, journaled once at first byte;
        # latency is journaled once per stream window per rule)
        noted: set = set()
        m_bytes = self._m_bytes[direction]
        try:
            while not self._stop.is_set():
                drip = next(
                    (
                        r for r in self.rules()
                        if r.kind == "slowdrip"
                        and r.applies(conn_id, direction)
                    ),
                    None,
                )
                bufsize = max(1, drip.drip_bytes) if drip else _BUFSIZE
                try:
                    data = src.recv(bufsize)
                except OSError:
                    break
                # the rule snapshot is taken AFTER recv returns: the
                # pump parks in recv for arbitrarily long, and a rule
                # set swapped in meanwhile (the conductor's timed
                # scenario) must apply to THIS chunk, not the next one
                rules = [
                    r for r in self.rules() if r.applies(conn_id, direction)
                ]
                if not data:
                    # half-close: propagate the FIN but keep the reverse
                    # pump alive (a one-sided shutdown is not a teardown
                    # — the response may still be in flight), and do NOT
                    # close src: the reverse pump writes to it
                    try:
                        dst.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    return
                data, offset = self._apply(
                    conn_id, direction, rules, data, offset, noted, dst,
                )
                if data is None:
                    continue  # blackholed: swallowed, keep reading
                try:
                    dst.sendall(data)
                except OSError:
                    break
                if m_bytes._on:
                    m_bytes.inc(len(data))
        except _Truncated:
            # the mid-frame reset must be visible on BOTH sides: _apply
            # already RST the destination; reset the source too
            self._rst(src)
            return
        # error teardown (dead socket either side): close both so the
        # reverse pump unblocks instead of waiting on a zombie stream
        for s in (src, dst):
            try:
                s.close()
            except OSError:
                pass

    def _apply(
        self, conn_id: int, direction: str, rules: list, data: bytes,
        offset: int, noted: set, dst: socket.socket,
    ) -> tuple:
        """Run one chunk through the rule set; returns ``(bytes-or-None,
        new_offset)``. Raises :class:`_Truncated` after a truncate_rst.
        Offsets advance by the bytes CONSUMED from the source stream, so
        byte-positioned schedules stay exact under any TCP chunking."""
        n = len(data)
        for r in rules:
            if r.kind == "blackhole":
                if "blackhole" not in noted:
                    noted.add("blackhole")
                    self._record(JournalEntry(
                        conn_id, direction, "blackhole", offset
                    ))
                if self._m_dropped._on:
                    self._m_dropped.inc(n)
                return None, offset + n
        # the earliest truncate point in this chunk bounds which flips
        # exist AT ALL: flips strictly before it still mutate the
        # forwarded prefix, flips at/after it target bytes that are
        # never delivered. Resolving the bound FIRST keeps the applied
        # schedule identical under any TCP chunking — checking
        # truncate_rst before flipping used to silently skip a flip
        # whose offset shared a recv chunk with the cut
        rst_at = None
        for r in rules:
            if r.kind != "truncate_rst":
                continue
            if offset <= r.at_offset < offset + n and (
                rst_at is None or r.at_offset < rst_at
            ):
                rst_at = r.at_offset
        end = offset + n if rst_at is None else rst_at
        if end > offset:
            out = bytearray(data)
            mutated = False
            for r in rules:
                if r.kind != "flip":
                    continue
                # normalize ONCE so the journal records exactly the mask
                # applied (a multiple-of-256 xor_mask falls back to 0xFF,
                # and the entry must say so or the digest lies)
                mask = (r.xor_mask & 0xFF) or 0xFF
                for fo in self._flip_offsets(r, offset, end):
                    out[fo - offset] ^= mask
                    mutated = True
                    self._record(JournalEntry(
                        conn_id, direction, "flip", fo, value=mask,
                    ))
            if mutated:
                data = bytes(out)
        if rst_at is not None:
            keep = rst_at - offset
            if keep:
                try:
                    dst.sendall(data[:keep])
                except OSError:
                    pass
            self._record(JournalEntry(
                conn_id, direction, "truncate_rst", rst_at
            ))
            self._rst(dst)
            raise _Truncated()
        for r in rules:
            if r.kind == "latency":
                # one draw per fixed stream window per rule: chunk
                # boundaries cannot perturb the schedule (a chunk that
                # spans K windows pays all K entries)
                for w in range(
                    offset // LAT_WINDOW, (offset + n - 1) // LAT_WINDOW + 1
                ):
                    key = ("latency", r, w)
                    if key in noted:
                        continue
                    noted.add(key)
                    jitter = (
                        self._rng(conn_id, direction, "latency", w).random()
                        * r.jitter_ms
                        if r.jitter_ms > 0 else 0.0
                    )
                    delay = (r.delay_ms + jitter) / 1e3
                    self._record(JournalEntry(
                        conn_id, direction, "latency", w * LAT_WINDOW,
                        value=round(r.delay_ms + jitter, 3),
                    ))
                    if delay > 0:
                        time.sleep(delay)
            elif r.kind == "throttle" and r.bytes_per_s > 0:
                if "throttle" not in noted:
                    noted.add("throttle")
                    self._record(JournalEntry(
                        conn_id, direction, "throttle", offset,
                        value=r.bytes_per_s,
                    ))
                time.sleep(n / r.bytes_per_s)
            elif r.kind == "slowdrip":
                if "slowdrip" not in noted:
                    noted.add("slowdrip")
                    self._record(JournalEntry(
                        conn_id, direction, "slowdrip", offset,
                        value=r.drip_bytes,
                    ))
                time.sleep(r.drip_interval_ms / 1e3)
        return data, offset + n

    @staticmethod
    def _flip_offsets(r: WireRule, lo: int, hi: int) -> list:
        """Absolute flip offsets of rule ``r`` within ``[lo, hi)``."""
        if r.every_bytes and r.every_bytes > 0:
            first_k = max(0, -(-(lo - r.at_offset) // r.every_bytes))
            out = []
            fo = r.at_offset + first_k * r.every_bytes
            while fo < hi:
                if fo >= lo:
                    out.append(fo)
                fo += r.every_bytes
            return out
        return [r.at_offset] if lo <= r.at_offset < hi else []


__all__ = [
    "ChaosProxy",
    "DIRECTIONS",
    "JournalEntry",
    "LAT_WINDOW",
    "RULE_KINDS",
    "WireRule",
]
