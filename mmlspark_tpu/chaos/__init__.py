"""Hostile-wire chaos engineering: the fabric itself as the adversary.

Three layers (docs/chaos.md):

- :mod:`mmlspark_tpu.chaos.wire` — :class:`ChaosProxy`, a transparent
  seeded TCP proxy any fleet link can be pointed through, with per-link
  :class:`WireRule` fault schedules (latency/jitter, bandwidth throttle,
  byte-flip at offset, truncate-then-RST, slowloris drip, asymmetric
  blackhole). Same seed => byte-identical fault schedule.
- :mod:`mmlspark_tpu.chaos.conductor` — :class:`ChaosConductor`, a timed
  scenario runner driving wire faults + process signals against a live
  fleet, journaling every action (``fleet chaos``).
- :mod:`mmlspark_tpu.chaos.invariants` — :class:`InvariantChecker`, a
  conservation-law checker over every role's ``/metrics``: nothing the
  fleet accepted may go unaccounted, no matter what the wire did.
"""

from mmlspark_tpu.chaos.conductor import ChaosConductor, Scenario
from mmlspark_tpu.chaos.invariants import InvariantChecker, Violation
from mmlspark_tpu.chaos.wire import RULE_KINDS, ChaosProxy, WireRule

__all__ = [
    "ChaosConductor",
    "ChaosProxy",
    "InvariantChecker",
    "RULE_KINDS",
    "Scenario",
    "Violation",
    "WireRule",
]
