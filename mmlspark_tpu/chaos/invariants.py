"""Fleet-wide conservation-law checker: nothing accepted goes unaccounted.

A hostile wire (chaos/wire.py) may flip, drop, drip or reset anything —
the fleet's contract is not "no errors", it is **accounting**: every
request the gateway accepted was answered (forwarded or failed, never
lost), every worker reply matches a worker accept, every ingested online
example is trained, buffered, shed or poisoned — never silently gone —
and control-plane state (breakers, refcounts, quarantine) stays sane.

:class:`InvariantChecker` scrapes every role's ``/metrics`` (the same
Prometheus text any external scraper reads) and evaluates the invariant
catalogue (docs/chaos.md):

==========================  ==================================================
``gateway_conservation``    gateway accepted == forwarded + failed (final;
                            ``>=`` while traffic is still in flight)
``fleet_conservation``      sum(worker accepted) >= gateway forwarded —
                            every answered forward was accepted by SOME
                            worker (retries/hedges only ever inflate the
                            worker side); skipped when any worker's
                            /metrics is unreachable, and DISABLED for the
                            checker's lifetime once any worker churns: a
                            previously-seen URL gone from the roster
                            (SIGKILL then TTL-prune/scale-in takes its
                            accepted counter with it) or an accepted
                            counter going BACKWARD at a same-port URL (a
                            supervisor respawn restarts the counter while
                            gateway forwarded spans both eras) — either
                            way the cross-era sum can never balance, and
                            a conservative skip beats a false red
``worker_conservation``     per role: the ingress in-flight gauge (accepted
                            requests not yet replied — the routing table)
                            drains to zero (final)
``modelstore_refs_drain``   in-flight version refcounts drain to zero
                            (final) — hot-swap/continuous-batching leaks
                            show up here
``admission_drain``         admission in-flight gauge drains to zero (final)
``online_conservation``     ingested + spill-replayed examples == trained
                            + buffered + shed + poisoned (replay re-enters
                            a fresh process whose ingested counter died
                            with the previous incarnation)
``breaker_sane``            every breaker-state gauge is 0/1/2
``retry_budget_sane``       retry-budget-remaining gauge is in [0, 1]
``generation_monotonic``    every registry's committed ``<service>-gen``
                            record only moves forward across checker
                            passes — a backward step is a resurrected,
                            superseded world (split-brain rollback)
``single_writer``           across trainer status files, no two members
                            claim to have committed the same generation
                            (``committed_gens`` join) — commit makes a
                            member the epoch's writer, so a double claim
                            is split-brain made visible
``experiment_conservation`` per experiment-controller status file:
                            trials_spawned == completed + demoted +
                            rescheduled + running — a trial the
                            controller spawned but lost track of is an
                            orphan process burning fleet capacity
``single_promotion``        across every controller status file of the
                            same experiment, at most ONE promoted set
                            per rung — two controllers promoting
                            different survivors is the tuning-plane
                            flavour of split-brain, which the rung
                            records' write-once generation CAS exists
                            to forbid
``artifact_quarantine``     every failed verification quarantined
                            (verify_failures == quarantines, final only:
                            the failure counter lands before the
                            quarantine's disk work, so a mid-soak scrape
                            can see the gap); with a live
                            :class:`~mmlspark_tpu.serving.artifacts.
                            ArtifactStore` handle, no quarantined digest is
                            advertised or servable
==========================  ==================================================

``check(final=False)`` (DURING a soak) evaluates only the inequality
forms; ``check(final=True)`` (after traffic drains) demands equalities.
Used by tests/test_chaos_wire.py, ``tools/deploy/smoke.py --chaos-wire``
and ``fleet chaos``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from mmlspark_tpu import obs

_M_CHECKS = obs.counter(
    "mmlspark_chaos_invariant_checks_total",
    "Invariant-checker passes, by verdict (green / violated)",
    labels=("verdict",),
)
_M_VIOLATIONS = obs.gauge(
    "mmlspark_chaos_invariant_violations_count",
    "Violations found by the most recent invariant-checker pass",
)


@dataclass
class Violation:
    """One broken conservation law."""

    name: str
    where: str
    detail: str

    def __str__(self) -> str:
        return f"{self.name}@{self.where}: {self.detail}"


def _sum(parsed: dict, name: str, match: Optional[dict] = None) -> float:
    return obs.sum_samples(parsed, name, match)


def _series(parsed: dict, name: str) -> list:
    """Every (labels, value) sample of a family."""
    return [
        (dict(labels), v)
        for (n, labels), v in parsed.items()
        if n == name
    ]


class InvariantChecker:
    """Scrape-and-verify. ``scrape`` is injectable for unit tests (takes
    a base URL, returns parsed samples or None)."""

    def __init__(
        self,
        gateway_url: Optional[str] = None,
        worker_urls: Any = (),
        online_url: Optional[str] = None,
        registry_url: Optional[str] = None,
        service_name: str = "serving",
        scrape: Optional[Callable] = None,
        stores: Any = (),
        tolerance: int = 0,
        status_files: Any = (),
        experiment_status_files: Any = (),
    ):
        """``stores``: live ArtifactStore handles for the in-process
        never-serve-quarantined check (metrics alone cannot prove it).
        ``tolerance``: absolute slack allowed on equality checks (for
        counters read while a scrape races a reply). ``status_files``:
        elastic-trainer status JSON paths — when given, the
        ``single_writer`` law joins their ``committed_gens`` claims.
        ``experiment_status_files``: experiment-controller status JSON
        paths — when given, the ``experiment_conservation`` and
        ``single_promotion`` laws join them."""
        from mmlspark_tpu.serving import fleet as fleet_mod

        self.gateway_url = gateway_url
        self.worker_urls = list(worker_urls or ())
        self.online_url = online_url
        self.registry_url = registry_url
        self.service_name = service_name
        self.stores = list(stores or ())
        self.tolerance = int(tolerance)
        self.status_files = list(status_files or ())
        self.experiment_status_files = list(experiment_status_files or ())
        # per (registry_url, record) committed-gen high-water across
        # check() passes: a registry whose generation record goes
        # BACKWARD resurrected a superseded world — the exact rollback
        # the quorum CAS exists to forbid
        self._gen_high: dict = {}
        self._scrape = scrape or fleet_mod.scrape_metrics
        # every worker URL any check() has resolved: a worker that later
        # vanishes from the roster (TTL-pruned after a SIGKILL) must not
        # silently shrink the fleet_conservation sum
        self._known_workers: set = set()
        # per-URL high-water accepted counter: a counter that goes
        # BACKWARD is a restarted process re-registered at the same URL
        # — its pre-restart accepts died with it, so the cross-era
        # fleet sum can never balance again for this checker's lifetime
        self._accepted_high: dict = {}
        self._fleet_sound = True

    # -- role resolution ------------------------------------------------------

    def _workers(self) -> list:
        urls = list(self.worker_urls)
        if self.registry_url:
            from mmlspark_tpu.serving.fleet import worker_urls_from_registry

            try:
                for u in worker_urls_from_registry(
                    self.registry_url, self.service_name
                ):
                    if u not in urls:
                        urls.append(u)
            except Exception:  # noqa: BLE001 — check what is reachable
                pass
        return urls

    # -- the catalogue --------------------------------------------------------

    def check(self, final: bool = True) -> list:
        """Evaluate every applicable invariant; returns the violations
        (empty == green). ``final=True`` demands the equality forms —
        call it only after traffic has drained."""
        violations: list = []
        tol = self.tolerance
        svc = self.service_name

        gw = self._scrape(self.gateway_url) if self.gateway_url else None
        if self.gateway_url and gw is None:
            violations.append(Violation(
                "scrape", self.gateway_url, "gateway /metrics unreachable"
            ))
        forwarded = 0.0
        if gw is not None:
            accepted = _sum(
                gw, "mmlspark_serving_requests_total",
                {"server": f"{svc}-gateway"},
            )
            forwarded = _sum(gw, "mmlspark_gateway_requests_total")
            failed = _sum(gw, "mmlspark_gateway_failures_total")
            answered = forwarded + failed
            if final:
                if abs(accepted - answered) > tol:
                    violations.append(Violation(
                        "gateway_conservation", self.gateway_url,
                        f"accepted {accepted:.0f} != forwarded "
                        f"{forwarded:.0f} + failed {failed:.0f}",
                    ))
            elif answered - accepted > tol:
                violations.append(Violation(
                    "gateway_conservation", self.gateway_url,
                    f"answered {answered:.0f} > accepted {accepted:.0f}",
                ))
            if final:
                infl = _sum(
                    gw, "mmlspark_serving_inflight_requests",
                    {"server": f"{svc}-gateway"},
                )
                if infl > 0:
                    violations.append(Violation(
                        "worker_conservation", self.gateway_url,
                        f"{infl:.0f} accepted request(s) never replied",
                    ))
            for labels, v in _series(gw, "mmlspark_gateway_breaker_state"):
                if v not in (0.0, 1.0, 2.0):
                    violations.append(Violation(
                        "breaker_sane", self.gateway_url,
                        f"breaker {labels.get('backend')} state {v}",
                    ))
            for _labels, v in _series(
                gw, "mmlspark_gateway_retry_budget_remaining_ratio"
            ):
                if not 0.0 <= v <= 1.0:
                    violations.append(Violation(
                        "retry_budget_sane", self.gateway_url,
                        f"retry budget remaining {v}",
                    ))
            violations.extend(self._artifact_checks(gw, self.gateway_url, final))

        worker_accepted = 0.0
        worker_urls = self._workers()
        # no workers known at all (no registry, no explicit URLs): the
        # cross-role sum is vacuously zero — skipping beats reporting a
        # false violation against every healthy gateway-only check
        all_workers_seen = bool(worker_urls)
        # a worker seen by an earlier check() but gone from the roster
        # now (SIGKILLed, then TTL-pruned by the registry) took its
        # accepted counter with it — the sum can never balance again,
        # so the law is disabled for this checker's lifetime (a
        # conservative skip beats a false red; same for scale-in)
        if self._known_workers - set(worker_urls):
            self._fleet_sound = False
        self._known_workers.update(worker_urls)
        for u in worker_urls:
            parsed = self._scrape(u)
            if parsed is None:
                # a down worker is the chaos's doing, not an accounting
                # hole — but its accepted counter is now invisible, so
                # the cross-role sum below would be PARTIAL: skip the
                # fleet law rather than report a false violation
                all_workers_seen = False
                continue
            accepted = _sum(
                parsed, "mmlspark_serving_requests_total", {"server": svc}
            )
            # counter went backward: same URL, NEW process (supervisor
            # respawn on a fixed port) — pre-restart accepts are gone
            # while the gateway's forwarded counter spans both eras
            if accepted + 0.5 < self._accepted_high.get(u, 0.0):
                self._fleet_sound = False
            self._accepted_high[u] = max(
                self._accepted_high.get(u, 0.0), accepted
            )
            worker_accepted += accepted
            if final:
                infl = _sum(
                    parsed, "mmlspark_serving_inflight_requests",
                    {"server": svc},
                )
                if infl > 0:
                    violations.append(Violation(
                        "worker_conservation", u,
                        f"{infl:.0f} accepted request(s) never replied",
                    ))
                refs = _sum(
                    parsed, "mmlspark_modelstore_version_refs_count"
                )
                if refs > 0:
                    violations.append(Violation(
                        "modelstore_refs_drain", u,
                        f"{refs:.0f} version refcount(s) still held",
                    ))
                infl = _sum(
                    parsed, "mmlspark_admission_inflight_requests",
                    {"server": svc},
                )
                if infl > 0:
                    violations.append(Violation(
                        "admission_drain", u,
                        f"{infl:.0f} admission slot(s) still held",
                    ))
            violations.extend(self._artifact_checks(parsed, u, final))

        if (
            gw is not None and all_workers_seen and self._fleet_sound
            and worker_accepted + tol < forwarded
        ):
            violations.append(Violation(
                "fleet_conservation", self.gateway_url,
                f"workers accepted {worker_accepted:.0f} < gateway "
                f"forwarded {forwarded:.0f}",
            ))

        if self.online_url:
            parsed = self._scrape(self.online_url)
            if parsed is None:
                violations.append(Violation(
                    "scrape", self.online_url, "online /metrics unreachable"
                ))
            else:
                # spill-replayed examples re-enter THIS process's buffer
                # but were pushed (and counted ingested) by a previous
                # incarnation whose counters died with it — they belong
                # on the ingested side or every post-restart check reads
                # a false violation for exactly the kill-and-recover
                # path the checker exists to bless
                ingested = _sum(
                    parsed, "mmlspark_online_ingested_total"
                ) + _sum(parsed, "mmlspark_online_spill_replayed_total")
                trained = _sum(parsed, "mmlspark_online_examples_total")
                buffered = _sum(
                    parsed, "mmlspark_online_buffered_examples_count"
                )
                shed = _sum(parsed, "mmlspark_online_shed_examples_total")
                poisoned = _sum(
                    parsed, "mmlspark_online_poisoned_examples_total"
                )
                accounted = trained + buffered + shed + poisoned
                bad = (
                    abs(ingested - accounted) > tol if final
                    else accounted - ingested > tol
                )
                if bad:
                    violations.append(Violation(
                        "online_conservation", self.online_url,
                        f"ingested+replayed {ingested:.0f} != trained "
                        f"{trained:.0f} + buffered {buffered:.0f} + shed "
                        f"{shed:.0f} + poisoned {poisoned:.0f}",
                    ))
                violations.extend(
                    self._artifact_checks(parsed, self.online_url, final)
                )

        violations.extend(self._generation_checks())
        violations.extend(self._writer_checks())
        violations.extend(self._experiment_checks())

        for store in self.stores:
            violations.extend(self._store_checks(store))

        _M_CHECKS.labels(
            verdict="green" if not violations else "violated"
        ).inc()
        _M_VIOLATIONS.set(len(violations))
        return violations

    def _generation_checks(self) -> list:
        """``generation_monotonic``: every registry's committed
        generation record (``<service>-gen``) only ever moves FORWARD
        across this checker's passes. A backward step means a
        superseded world was resurrected — a restarted registry that
        anti-entropy failed to reconcile, or a last-writer-wins commit
        the CAS endpoint exists to reject. Unreachable registries are
        skipped (blindness is chaos's doing, not a rollback)."""
        if not self.registry_url:
            return []
        import json as json_mod

        from mmlspark_tpu.io.clients import send_request
        from mmlspark_tpu.io.http_schema import HTTPRequestData
        from mmlspark_tpu.serving.fleet import split_registry_urls

        out: list = []
        for url in split_registry_urls(self.registry_url):
            try:
                resp = send_request(
                    HTTPRequestData(url.rstrip("/") + "/", "GET"),
                    timeout=5.0,
                )
                if resp["status_code"] != 200:
                    continue
                roster = json_mod.loads(resp["entity"])
            except Exception:  # noqa: BLE001 — blind registry: skip
                continue
            for name, entries in roster.items():
                if not name.endswith("-gen"):
                    continue
                gens = [
                    int(e.get("port") or 0) for e in entries
                    if isinstance(e, dict)
                ]
                if not gens:
                    continue
                gen = max(gens)
                key = (url, name)
                high = self._gen_high.get(key, 0)
                if gen < high:
                    out.append(Violation(
                        "generation_monotonic", url,
                        f"{name} rolled back: committed gen {gen} after "
                        f"this checker saw gen {high}",
                    ))
                self._gen_high[key] = max(high, gen)
        return out

    def _writer_checks(self) -> list:
        """``single_writer``: across every trainer status file, no two
        members claim to have COMMITTED the same generation — commit is
        what makes a member that epoch's writer, so a doubly-claimed gen
        is split-brain made visible (both halves of a partition fenced
        off the same epoch number)."""
        if not self.status_files:
            return []
        import json as json_mod

        out: list = []
        claimed: dict = {}  # gen -> (member, path) that claimed it first
        for path in self.status_files:
            try:
                with open(path) as f:
                    st = json_mod.load(f)
            except (OSError, ValueError):
                continue  # not written yet / mid-rewrite: no claim
            member = st.get("name") or path
            for gen in st.get("committed_gens", ()):
                prev = claimed.get(gen)
                if prev is not None and prev[0] != member:
                    out.append(Violation(
                        "single_writer", path,
                        f"gen {gen} committed by both {prev[0]!r} "
                        f"({prev[1]}) and {member!r}",
                    ))
                else:
                    claimed[gen] = (member, path)
        return out

    def _experiment_checks(self) -> list:
        """``experiment_conservation`` + ``single_promotion`` across
        experiment-controller status files. Conservation holds in EVERY
        snapshot, not just the final one: the controller's accounting is
        membership-based (a charge is "running" from spawn until it is
        classified exactly once), so a mid-experiment read is as bound
        by the law as a final one. Promotion agreement is joined across
        controllers of the same experiment — a restarted (or split)
        controller must adopt the incumbent rung records, never mint
        rival ones."""
        if not self.experiment_status_files:
            return []
        import json as json_mod

        out: list = []
        # (experiment, rung) -> (promoted tuple, path) first seen
        promoted_by_rung: dict = {}
        for path in self.experiment_status_files:
            try:
                with open(path) as f:
                    st = json_mod.load(f)
            except (OSError, ValueError):
                continue  # not written yet / mid-rewrite: no claim
            spawned = int(st.get("trials_spawned", 0))
            accounted = (
                int(st.get("completed", 0)) + int(st.get("demoted", 0))
                + int(st.get("rescheduled", 0)) + int(st.get("running", 0))
            )
            if spawned != accounted:
                out.append(Violation(
                    "experiment_conservation", path,
                    f"trials_spawned {spawned} != completed "
                    f"{st.get('completed', 0)} + demoted "
                    f"{st.get('demoted', 0)} + rescheduled "
                    f"{st.get('rescheduled', 0)} + running "
                    f"{st.get('running', 0)}",
                ))
            exp = st.get("experiment") or path
            for rung, promoted in (st.get("rungs") or {}).items():
                key = (exp, str(rung))
                claim = tuple(sorted(promoted or ()))
                prev = promoted_by_rung.get(key)
                if prev is not None and prev[0] != claim:
                    out.append(Violation(
                        "single_promotion", path,
                        f"experiment {exp!r} rung {rung}: promoted "
                        f"{list(claim)} but {prev[1]} promoted "
                        f"{list(prev[0])}",
                    ))
                else:
                    promoted_by_rung[key] = (claim, path)
        return out

    @staticmethod
    def _artifact_checks(parsed: dict, where: str, final: bool) -> list:
        out: list = []
        vfail = _sum(parsed, "mmlspark_artifact_verify_failures_total")
        quar = _sum(parsed, "mmlspark_artifact_quarantines_total")
        # equality demanded only once traffic drains: the failure
        # counter increments BEFORE quarantine()'s disk work lands, so
        # a mid-soak scrape can legitimately see vfail == quar + 1
        if final and vfail > quar:
            out.append(Violation(
                "artifact_quarantine", where,
                f"{vfail:.0f} verify failure(s) but only {quar:.0f} "
                "quarantine(s) — corrupt bytes may still be servable",
            ))
        return out

    @staticmethod
    def _store_checks(store: Any) -> list:
        """In-process: a quarantined digest must be invisible to both
        advertisement and the ranged-GET handler."""
        out: list = []
        quarantined = set(getattr(store, "_quarantined", ()))
        refs = store.refs()
        for d in quarantined:
            if any(r.endswith("@" + d) for r in refs):
                out.append(Violation(
                    "artifact_quarantine", store.root,
                    f"quarantined digest {d[:12]}… still advertised",
                ))
            code, _body, _hdrs = store.handle_http(
                f"/artifacts/{d}", {}
            )
            if code != 404:
                out.append(Violation(
                    "artifact_quarantine", store.root,
                    f"quarantined digest {d[:12]}… served with {code}",
                ))
        return out

    def report(self, violations: list) -> str:
        if not violations:
            return "invariants: green"
        lines = [f"invariants: {len(violations)} violation(s)"]
        lines += [f"  - {v}" for v in violations]
        return "\n".join(lines)


__all__ = ["InvariantChecker", "Violation"]
