"""ChaosConductor: timed hostile-wire scenarios against a live fleet.

One scenario = one seeded, replayable storm: at declared offsets it
swaps :class:`~mmlspark_tpu.chaos.wire.WireRule` sets on named
:class:`~mmlspark_tpu.chaos.wire.ChaosProxy` links, sends process
signals (SIGKILL / SIGSTOP / SIGCONT / SIGTERM) to named fleet pids,
and finally runs the :class:`~mmlspark_tpu.chaos.invariants.
InvariantChecker`. Every action is journaled with its wall-clock time
and a trace id, and mirrored into the PR 4 flight recorder — an
incident found in a soak correlates with ``fleet trace`` / flight
dumps the same way a production incident would.

Scenario JSON (inline or a file path; ``fleet chaos --scenario``)::

    {"seed": 7, "steps": [
      {"at_s": 0.0, "action": "rules", "link": "gw",
       "rules": [{"kind": "latency", "delay_ms": 5, "jitter_ms": 5}]},
      {"at_s": 2.0, "action": "signal", "target": "worker-1",
       "signal": "SIGSTOP"},
      {"at_s": 4.0, "action": "signal", "target": "worker-1",
       "signal": "SIGCONT"},
      {"at_s": 5.0, "action": "clear", "link": "gw"},
      {"at_s": 6.0, "action": "check"}
    ]}

``partition`` / ``heal`` are the split-brain macro: ``{"action":
"partition", "links": ["reg-b", "ar-a"]}`` expands to a symmetric
``blackhole`` rule (both directions) on EVERY named link, and ``heal``
clears those links — one step cuts a member off from the registry and
its peers at once, the drill docs/chaos.md builds on.

Steps run in ``at_s`` order against one monotonic clock, so the same
scenario against the same fleet replays the same storm; the wire-level
schedule inside each window is the proxy's own seeded contract
(chaos/wire.py). Unknown links/targets fail the scenario LOAD, not the
run — a typo'd chaos plan must not silently do nothing.
"""

from __future__ import annotations

import json
import signal as signal_mod
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.chaos.wire import WireRule

_M_ACTIONS = obs.counter(
    "mmlspark_chaos_actions_total",
    "Conductor scenario actions executed, by action kind",
    labels=("action",),
)

_ACTIONS = (
    "rules", "clear", "signal", "check", "sleep", "mark",
    "partition", "heal",
)
_SIGNALS = {
    "SIGKILL": signal_mod.SIGKILL,
    "SIGSTOP": signal_mod.SIGSTOP,
    "SIGCONT": signal_mod.SIGCONT,
    "SIGTERM": signal_mod.SIGTERM,
    "SIGUSR1": signal_mod.SIGUSR1,
}


@dataclass
class Scenario:
    """A validated chaos scenario: seed + time-ordered steps."""

    seed: int = 0
    steps: list = field(default_factory=list)

    @staticmethod
    def from_spec(spec: Any) -> "Scenario":
        """Dict / JSON string / path to a JSON file -> Scenario."""
        if isinstance(spec, str):
            s = spec.strip()
            if not s.startswith("{"):
                with open(spec) as f:
                    s = f.read()
            spec = json.loads(s)
        steps = []
        for raw in spec.get("steps", ()):
            step = dict(raw)
            action = step.get("action")
            if action not in _ACTIONS:
                raise ValueError(
                    f"unknown scenario action {action!r}; known: {_ACTIONS}"
                )
            if action == "signal" and step.get("signal") not in _SIGNALS:
                raise ValueError(
                    f"unknown signal {step.get('signal')!r}; known: "
                    f"{sorted(_SIGNALS)}"
                )
            if action == "rules":
                # validate eagerly: a typo'd rule kind must fail the load
                step["rules"] = [
                    r if isinstance(r, WireRule) else WireRule.from_dict(r)
                    for r in step.get("rules", ())
                ]
            if action in ("partition", "heal"):
                # normalize: a partition names the SET of links it cuts
                # (``links``; bare ``link`` accepted for a 1-link cut)
                links = step.get("links")
                if links is None:
                    links = [step["link"]] if step.get("link") else []
                if not links:
                    raise ValueError(
                        f"{action} step needs 'links' (or 'link'): the "
                        f"set of proxy links to cut/restore"
                    )
                step["links"] = list(links)
                step.pop("link", None)
            step["at_s"] = float(step.get("at_s", 0.0))
            steps.append(step)
        steps.sort(key=lambda s: s["at_s"])
        return Scenario(seed=int(spec.get("seed", 0)), steps=steps)


class ChaosConductor:
    """Drive one :class:`Scenario` against named proxies and pids.

    ``proxies``: name -> :class:`ChaosProxy` (already started).
    ``pids``: name -> pid (or a callable returning the CURRENT pid, for
    supervised charges whose pid changes across restarts).
    ``checker``: an :class:`~mmlspark_tpu.chaos.invariants.
    InvariantChecker` the ``check`` action runs (optional)."""

    def __init__(
        self,
        scenario: Scenario,
        proxies: Optional[dict] = None,
        pids: Optional[dict] = None,
        checker: Any = None,
    ):
        self.scenario = scenario
        self.proxies = dict(proxies or {})
        self.pids = dict(pids or {})
        self.checker = checker
        self.journal: list = []
        self.violations: list = []
        for step in scenario.steps:
            link = step.get("link")
            if step["action"] in ("rules", "clear") and \
                    link not in self.proxies:
                raise ValueError(
                    f"scenario names unknown link {link!r}; known: "
                    f"{sorted(self.proxies)}"
                )
            if step["action"] in ("partition", "heal"):
                for ln in step["links"]:
                    if ln not in self.proxies:
                        raise ValueError(
                            f"scenario names unknown link {ln!r}; known: "
                            f"{sorted(self.proxies)}"
                        )
            if step["action"] == "signal" and \
                    step.get("target") not in self.pids:
                raise ValueError(
                    f"scenario names unknown target "
                    f"{step.get('target')!r}; known: {sorted(self.pids)}"
                )

    def _journal_action(self, step: dict, t_rel: float, **extra) -> None:
        trace_id = obs.new_trace_id()
        entry = {
            "t_wall": time.time(),
            "t_rel_s": round(t_rel, 4),
            "trace_id": trace_id,
            "action": step["action"],
            **{
                k: v for k, v in step.items()
                if k not in ("action", "rules") and v is not None
            },
            **extra,
        }
        if "rules" in step:
            entry["rules"] = [r.kind for r in step["rules"]]
        self.journal.append(entry)
        _M_ACTIONS.labels(action=step["action"]).inc()
        # mirror into the flight recorder: a chaos action interleaves
        # with the requests it broke in any post-incident dump
        from mmlspark_tpu.obs import flightrec

        flightrec.record(
            "chaos", trace_id=trace_id, path=step["action"],
            detail=json.dumps(
                {k: v for k, v in entry.items()
                 if k in ("link", "target", "signal", "rules", "note")}
            ),
        )

    def _pid_of(self, target: str) -> int:
        p = self.pids[target]
        return int(p() if callable(p) else p)

    def run(self) -> list:
        """Execute the scenario; returns the journal. ``self.violations``
        accumulates EVERY ``check`` action's invariant violations — a
        mid-soak red followed by a green final check must still fail
        the run (docs/chaos.md: exit 1 when a check found violations)."""
        import os

        t0 = time.monotonic()
        for step in self.scenario.steps:
            delay = step["at_s"] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            t_rel = time.monotonic() - t0
            action = step["action"]
            if action == "rules":
                self.proxies[step["link"]].set_rules(step["rules"])
                self._journal_action(step, t_rel)
            elif action == "clear":
                self.proxies[step["link"]].clear_rules()
                self._journal_action(step, t_rel)
            elif action == "signal":
                pid = self._pid_of(step["target"])
                try:
                    os.kill(pid, _SIGNALS[step["signal"]])
                    self._journal_action(step, t_rel, pid=pid)
                except ProcessLookupError:
                    self._journal_action(
                        step, t_rel, pid=pid, error="no such process"
                    )
            elif action == "check":
                if self.checker is not None:
                    found = self.checker.check(
                        final=bool(step.get("final", False))
                    )
                    self.violations.extend(found)
                    self._journal_action(
                        step, t_rel, violations=len(found)
                    )
                else:
                    self._journal_action(step, t_rel, skipped=True)
            elif action == "partition":
                # a symmetric partition is the paired blackhole: every
                # named link swallows BOTH directions — connects still
                # succeed (the proxy accepts), bytes never arrive, the
                # exact shape under which both halves suspect the other
                for ln in step["links"]:
                    self.proxies[ln].set_rules(
                        [WireRule(kind="blackhole", direction="both")]
                    )
                self._journal_action(step, t_rel)
            elif action == "heal":
                for ln in step["links"]:
                    self.proxies[ln].clear_rules()
                self._journal_action(step, t_rel)
            elif action == "sleep":
                self._journal_action(step, t_rel)
            elif action == "mark":
                self._journal_action(step, t_rel)
        return self.journal


def run_chaos_cli(
    scenario_spec: str,
    proxy_specs: list,
    pid_specs: list,
    gateway_url: Optional[str] = None,
    registry_url: Optional[str] = None,
    service_name: str = "serving",
    seed: Optional[int] = None,
    status_files: Any = (),
) -> int:
    """``fleet chaos`` entrypoint: build proxies from ``name=listen_port:
    target_host:target_port`` specs, pids from ``name=PID``, run the
    scenario, print the journal JSON. Exit code 1 when a ``check``
    action found violations."""
    from mmlspark_tpu.chaos.invariants import InvariantChecker
    from mmlspark_tpu.chaos.wire import ChaosProxy

    scenario = Scenario.from_spec(scenario_spec)
    if seed is not None:
        scenario.seed = seed
    proxies: dict = {}
    try:
        for spec in proxy_specs:
            name, _, rest = spec.partition("=")
            parts = rest.split(":")
            if len(parts) != 3:
                raise ValueError(
                    f"--proxy wants name=listen_port:target_host:"
                    f"target_port, got {spec!r}"
                )
            proxies[name] = ChaosProxy(
                parts[1], int(parts[2]), listen_port=int(parts[0]),
                seed=scenario.seed, name=name,
            ).start()
        pids = {}
        for spec in pid_specs:
            name, _, pid = spec.partition("=")
            pids[name] = int(pid)
        checker = None
        if gateway_url or registry_url or status_files:
            checker = InvariantChecker(
                gateway_url=gateway_url, registry_url=registry_url,
                service_name=service_name, status_files=status_files,
            )
        conductor = ChaosConductor(
            scenario, proxies=proxies, pids=pids, checker=checker
        )
        journal = conductor.run()
        print(json.dumps({
            "journal": journal,
            "violations": [str(v) for v in conductor.violations],
            "schedules": {
                name: p.schedule_digest() for name, p in proxies.items()
            },
        }, indent=2), flush=True)
        return 1 if conductor.violations else 0
    finally:
        for p in proxies.values():
            p.stop()


__all__ = ["ChaosConductor", "Scenario", "run_chaos_cli"]
