"""Pipeline compiler: fitted pipelines -> partitioned, scheduled XLA programs.

The reference executes a fitted ``PipelineModel`` stage by stage — a zoo
of independent transformers, each paying its own dispatch and
materializing every intermediate column on the host. This package turns
that zoo into (close to) one partitioned XLA program per pipeline:

- :mod:`planner`     — stage DAG from column I/O + fusability classes;
- :mod:`kernels`     — the ``StageKernel`` fusability contract;
- :mod:`fuser`       — maximal fusable runs -> single jitted programs with
  bounded compile-cache buckets;
- :mod:`partitioner` — Automap-style NamedSharding propagation with search
  only at conflict points (arXiv:2112.02958);
- :mod:`scheduler`   — critical-path ordering of independent branches
  (arXiv:1711.01912) + overlapped host segments;
- :mod:`compiled`    — :class:`CompiledPipeline`, the drop-in Transformer
  (``PipelineModel.compile()``).

Correctness contract: compiled output is element-wise equal to staged
execution (tests/test_compiler.py goldens), with graceful per-call
fallback to staged execution whenever a segment cannot run an input.
"""

from mmlspark_tpu.compiler.compiled import CompiledPipeline
from mmlspark_tpu.compiler.fuser import FusedSegment, HostSegment, build_segments
from mmlspark_tpu.compiler.kernels import (
    StageKernel,
    guard_dense_numeric,
    pairwise_sum,
    stage_kernel,
)
from mmlspark_tpu.compiler.partitioner import ShardingPlan, plan_sharding
from mmlspark_tpu.compiler.planner import PipelinePlan, plan_pipeline, stage_io
from mmlspark_tpu.compiler.scheduler import (
    CostModel,
    ScheduledExecutor,
    critical_path,
    schedule_order,
    segment_deps,
)

__all__ = [
    "CompiledPipeline",
    "CostModel",
    "FusedSegment",
    "HostSegment",
    "PipelinePlan",
    "ScheduledExecutor",
    "ShardingPlan",
    "StageKernel",
    "build_segments",
    "critical_path",
    "guard_dense_numeric",
    "pairwise_sum",
    "plan_pipeline",
    "plan_sharding",
    "schedule_order",
    "segment_deps",
    "stage_io",
    "stage_kernel",
]
