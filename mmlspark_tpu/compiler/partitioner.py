"""Partitioner: propagate NamedShardings through fused segments.

Automap (arXiv:2112.02958) observes that most sharding decisions in an ML
program are *forced* by their neighbours — annotations propagate through
elementwise/row-wise ops unambiguously, and search is only needed at the
few points where propagation meets a conflicting constraint. The fused
segments here are exactly that easy case made explicit: every
:class:`~mmlspark_tpu.compiler.kernels.StageKernel` declares whether it is
row-wise (batch axis 0 flows through untouched) and which inputs it needs
replicated. So:

1. **Propagate**: union-find columns that must share a spec (all reads +
   writes of a row-wise kernel form one group — the batch axis flows
   through). A group nobody constrains resolves to the default
   ``data``-axis batch sharding; a group with one consistent demand
   resolves to that demand. No search.
2. **Search at conflicts**: a group carrying *both* batch-preferring uses
   and replication demands (a non-row-wise kernel, or
   ``needs_replicated``) is ambiguous. Enumerate the candidate specs and
   score each: choosing ``batch`` pays one resharding (allgather) per
   replication demand; choosing ``replicated`` pays duplicated
   compute/placement for every batch-preferring use. Pick the minimum —
   the conflict set is tiny, so exhaustive scoring is exact.
3. **Fall back to replicated** when the mesh cannot batch-shard at all —
   one device, a CPU backend in ``auto`` mode, or a bucket the mesh size
   does not divide.

The result feeds ``jax.jit(..., in_shardings=...)`` on the fused program;
XLA/GSPMD inserts the collectives.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

BATCH = "batch"
REPLICATED = "replicated"


class _UnionFind:
    def __init__(self) -> None:
        self.parent: dict = {}

    def find(self, x: Any) -> Any:
        p = self.parent.setdefault(x, x)
        if p != x:
            p = self.parent[x] = self.find(p)
        return p

    def union(self, a: Any, b: Any) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[rb] = ra


@dataclass
class ShardingPlan:
    """Per-column spec decisions for one fused segment."""

    decisions: dict                      # col -> BATCH | REPLICATED
    searched: list = field(default_factory=list)  # groups resolved by search
    mesh: Any = None
    data_axis: str = "data"

    def in_shardings(self, cols: dict) -> Optional[dict]:
        """NamedSharding pytree for the segment's (bucketed) input columns,
        or None when everything is replicated on a trivial mesh (let jit
        use default placement). Called per compile bucket: a batch-destined
        column whose *actual* leading dim the mesh does not divide (a small
        pow2 bucket on a larger mesh) degrades to replicated for that
        bucket — sharding it would ValueError inside jit."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        size = int(self.mesh.devices.size)
        out = {}
        for name, arr in cols.items():
            if (
                self.decisions.get(name) == BATCH
                and arr.ndim
                and arr.shape[0] % size == 0
            ):
                spec = P(self.data_axis, *([None] * (arr.ndim - 1)))
            else:
                spec = P()
            out[name] = NamedSharding(self.mesh, spec)
        return out


def plan_sharding(
    kernels: list,
    mesh: Any = None,
    bucket: Optional[int] = None,
    mode: str = "auto",
) -> ShardingPlan:
    """Assign a spec to every column a run of kernels touches.

    ``mode``: ``auto`` (batch-shard on a real accelerator mesh, replicate
    on CPU), ``batch`` (force batch sharding when divisible — used by
    tests and by callers who know their CPU mesh is the deployment), or
    ``replicated``.
    """
    cols: list = []
    uf = _UnionFind()
    batch_pref: dict = {}   # col -> count of batch-preferring uses
    repl_demand: dict = {}  # col -> count of replication demands
    for k in kernels:
        touched = list(k.reads) + list(k.writes)
        for c in touched:
            if c not in batch_pref:
                cols.append(c)
                batch_pref[c] = 0
                repl_demand[c] = 0
        if k.row_wise:
            # batch axis flows through: all touched columns share a spec
            for c in touched[1:]:
                uf.union(touched[0], c)
            for c in touched:
                batch_pref[c] += 1
        else:
            for c in touched:
                repl_demand[c] += 1
        for c in k.needs_replicated:
            repl_demand[c] = repl_demand.get(c, 0) + 1

    mesh_size = int(mesh.devices.size) if mesh is not None else 1
    divisible = bucket is None or (mesh_size > 0 and bucket % mesh_size == 0)
    platform = ""
    if mesh is not None and mesh_size:
        platform = mesh.devices.reshape(-1)[0].platform
    can_batch = (
        mesh is not None and mesh_size > 1 and divisible
        and mode != "replicated"
        and (mode == "batch" or platform not in ("", "cpu"))
    )

    groups: dict = {}
    for c in cols:
        groups.setdefault(uf.find(c), []).append(c)

    decisions: dict = {}
    searched: list = []
    for members in groups.values():
        prefs = sum(batch_pref[c] for c in members)
        demands = sum(repl_demand[c] for c in members)
        if not can_batch:
            spec = REPLICATED
        elif demands == 0:
            spec = BATCH            # unambiguous propagation
        elif prefs == 0:
            spec = REPLICATED       # unambiguous propagation
        else:
            # conflict point: score the candidates (Automap's search step).
            # batch   -> one reshard (allgather) per replication demand;
            # replicated -> duplicated compute for each batch use, scaled
            # by the fraction of the mesh doing redundant work.
            cost_batch = float(demands)
            cost_repl = prefs * (1.0 - 1.0 / mesh_size)
            spec = BATCH if cost_batch <= cost_repl else REPLICATED
            searched.append({
                "columns": sorted(members),
                "chosen": spec,
                "cost_batch": cost_batch,
                "cost_replicated": round(cost_repl, 3),
            })
        for c in members:
            decisions[c] = spec
    return ShardingPlan(
        decisions=decisions,
        searched=searched,
        mesh=mesh if can_batch else None,
    )
