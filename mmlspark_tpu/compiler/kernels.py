"""The fusability contract between pipeline stages and the compiler.

A stage opts into jit-fusion by implementing ``fusable_kernel()`` and
returning a :class:`StageKernel` — a *pure array→array* description of its
transform: which columns it reads, which it writes, and a jit-traceable
function mapping input column arrays to output column arrays. The fuser
(:mod:`mmlspark_tpu.compiler.fuser`) merges runs of adjacent kernels into
one XLA program; the partitioner propagates shardings through them.

The correctness contract a kernel author signs (docs/compiler.md):

- ``fn`` run under ``jax.jit`` on the declared reads must produce, for
  every row, exactly the values the stage's own ``transform`` would —
  including dtype-cast behaviour. Mirror the staged path's casts inside
  the kernel (and declare host-side output dtypes via ``out_dtypes`` for
  values the staged path materializes beyond float32, e.g. ``float64``
  prediction columns: with x64 disabled those casts must happen on host).
- ``fn`` must be row-independent along axis 0 (``row_wise=True``): the
  fuser pads batches to power-of-two buckets and slices the pad back off,
  which is only sound when one row's output never depends on another row.
  Declare ``row_wise=False`` for cross-row kernels — the partitioner then
  treats the kernel's columns as a replication demand (a sharding
  conflict point) and the fuser never pads through it.
- ``guard`` (optional) inspects the *host* input columns before tracing
  and returns a reason string when the kernel cannot handle them (object
  dtype, unrolled layouts, ...); the fused segment then falls back to
  staged execution for that DataFrame, recorded in
  ``mmlspark_compiler_fallback_total{reason=...}``.
- ``finalize`` (optional) is a **host epilogue**: with x64 disabled the
  device cannot bit-match every host op the staged path uses (libm
  ``exp`` in a sigmoid/softmax, float64 arithmetic). A kernel whose
  staged transform ends in such ops declares ``device_writes`` (the raw
  device outputs, e.g. summed tree scores) and a ``finalize(host_cols)
  -> {col: array}`` that replays the staged path's *exact numpy
  epilogue* on the fetched device arrays. The heavy array math stays in
  the one fused XLA program; the epilogue is the same host code staged
  execution runs, so equality is by construction. The fuser closes a
  fusion run after a finalize kernel (its outputs live on host).

Floating-point summation is the other exactness trap: ``np.sum`` uses
pairwise summation, XLA reduces in a different order, and float32 adds do
not associate. :func:`pairwise_sum` reproduces numpy's exact association
order with jnp ops (IEEE adds in a fixed order are deterministic on both
sides), so a kernel can sum on device and still bit-match a staged
``np.sum`` — verified in tests/test_compiler.py.

Stages that are NOT fusable but know their column I/O can implement
``pipeline_io() -> (reads, writes)`` so the planner still gets an exact
DAG edge set (e.g. ``SimpleHTTPTransformer`` declares its output *and*
error columns); stages declaring neither are planned as opaque barriers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np


@dataclass
class StageKernel:
    """Jit-fusable description of one stage's transform."""

    reads: tuple
    writes: tuple
    # jit-traceable: dict[col -> array] (reads) -> dict[col -> array] (writes)
    fn: Callable[[dict], dict]
    # host-side np dtype per output column, applied AFTER device fetch —
    # for columns the staged path materializes as float64/ints while the
    # device program (x64 disabled) computes float32/int32
    out_dtypes: dict = field(default_factory=dict)
    # host-side pre-check: dict[col -> np array] -> None (ok) | reason str
    guard: Optional[Callable[[dict], Optional[str]]] = None
    # relative cost estimate used by the scheduler before real timings exist
    cost_hint: float = 1.0
    # row-independent along axis 0 (padding-safe); False is a sharding
    # conflict point (replication demand) for the partitioner
    row_wise: bool = True
    # input columns that must be fully replicated on the mesh regardless of
    # batch sharding (e.g. a lookup table column) — a partitioner demand
    needs_replicated: tuple = ()
    # False: this kernel's ops are not bit-stable across batch shapes /
    # shardings (convolution lowerings), so exact-mode compilation plans
    # the stage host-bound and only ``exact=False`` fuses it
    exact_capable: bool = True
    # host epilogue: fn's device outputs are the ``device_writes`` keys;
    # finalize(fetched host arrays, sliced to the true row count) returns
    # the final ``writes`` columns by replaying the staged path's numpy
    # tail ops (libm transcendentals, float64 casts) bit-for-bit
    finalize: Optional[Callable[[dict], dict]] = None
    device_writes: tuple = ()  # defaults to ``writes`` when finalize is None

    @property
    def fn_outputs(self) -> tuple:
        """The columns ``fn`` actually returns from the device program."""
        if self.finalize is not None and self.device_writes:
            return self.device_writes
        return self.writes


def stage_kernel(stage: Any) -> Optional[StageKernel]:
    """The stage's kernel, or None for host-bound stages. Never raises:
    a kernel constructor that fails (missing weights, unsupported plan)
    classifies the stage host-bound rather than failing compilation."""
    getter = getattr(stage, "fusable_kernel", None)
    if getter is None:
        return None
    try:
        k = getter()
    except Exception:  # noqa: BLE001 — unfusable, not an error
        return None
    if k is None:
        return None
    if not isinstance(k, StageKernel):
        raise TypeError(
            f"{type(stage).__name__}.fusable_kernel() returned "
            f"{type(k).__name__}, expected StageKernel or None"
        )
    return k


def guard_dense_numeric(cols: dict) -> Optional[str]:
    """Common guard: every input column must be a dense numeric array."""
    for name, arr in cols.items():
        a = np.asarray(arr)
        if a.dtype == object:
            return f"object column {name!r}"
        if a.dtype.kind not in ("f", "i", "u", "b"):
            return f"non-numeric column {name!r} ({a.dtype})"
    return None


def guard_f32_safe(cols: dict) -> Optional[str]:
    """Guard for kernels whose staged path computes float32 (possibly via a
    float64 upcast): dtypes where jax's 32-bit canonicalization yields the
    same single rounding the staged ``astype`` chain does — floats, bool,
    and ints that fit 32 bits (int64 would wrap through jax's x64-disabled
    world instead of rounding like the host cast)."""
    for name, arr in cols.items():
        a = np.asarray(arr)
        if a.dtype == object:
            return f"object column {name!r}"
        if a.dtype.kind == "f" or a.dtype.kind == "b":
            continue
        if a.dtype.kind in ("i", "u") and a.dtype.itemsize <= 4:
            continue
        return f"dtype {a.dtype} column {name!r}"
    return None


# width at which numpy's pairwise summation switches from the 8-accumulator
# block loop to recursive halving (numpy's PW_BLOCKSIZE)
_PW_BLOCKSIZE = 128


def pairwise_sum(a: Any):
    """Sum a 2-D array over axis 1 in **numpy's exact association order**.

    ``np.sum`` on float32 uses pairwise summation (sequential under 8
    elements; 8 interleaved accumulators tree-combined up to 128; recursive
    halving above) while XLA's ``reduce`` associates differently — so a
    device sum is *not* bit-equal to the staged path's host sum. This
    helper emits the same adds in the same order as jnp ops: each add is
    an IEEE float32 add on both sides and XLA does not re-associate floats,
    so the jitted result matches ``np.sum(a, axis=1)`` bitwise. Cost is
    O(T) unrolled adds for T columns — negligible against the traversal or
    matmul that produced them.

    Works under ``jax.jit`` tracing (shape is static) and on plain numpy
    arrays (the ops are identical), which is how the tests pin it.
    """
    import jax.numpy as jnp

    n = a.shape[1]
    zeros = (jnp if not isinstance(a, np.ndarray) else np).zeros
    if n == 0:
        return zeros(a.shape[:1], np.float32)
    if n < 8:
        res = a[:, 0]
        for i in range(1, n):
            res = res + a[:, i]
        return res
    if n <= _PW_BLOCKSIZE:
        r = [a[:, j] for j in range(8)]
        i = 8
        while i < n - (n % 8):
            for j in range(8):
                r[j] = r[j] + a[:, i + j]
            i += 8
        res = ((r[0] + r[1]) + (r[2] + r[3])) + ((r[4] + r[5]) + (r[6] + r[7]))
        while i < n:
            res = res + a[:, i]
            i += 1
        return res
    n2 = (n // 2) - ((n // 2) % 8)
    return pairwise_sum(a[:, :n2]) + pairwise_sum(a[:, n2:])
