"""Planner: fitted stage list -> column-dependency DAG + fusability classes.

The reference executes a fitted ``PipelineModel`` strictly stage-by-stage
(core/pipeline.py:124); but stages declare their column I/O (the shared
``HasInputCol``/``HasOutputCol`` traits and ``transform_schema``), so the
true execution constraints are *data* dependencies: stage B depends on
stage A only when B reads a column A writes (or a write-write / read-write
ordering hazard links them). The planner recovers that DAG and classifies
every stage:

- ``fused``  — exposes a :class:`~mmlspark_tpu.compiler.kernels.StageKernel`
  (pure array→array): eligible for jit-fusion with adjacent fusable stages.
- ``host``   — known column I/O but host-bound work (HTTP transformers,
  io clients, native link functions): scheduled, never fused.
- ``opaque`` — declares no column I/O (``Lambda``, ``Repartition``,
  ``SummarizeData``...): planned as a barrier — it depends on every prior
  stage and every later stage depends on it, which is exactly the staged
  semantics for a stage that may touch anything.

Column I/O resolution order (first match wins):

1. ``stage.pipeline_opaque`` (class attr, True) — forced opaque: the
   stage drops/renames columns or rewrites rows wholesale (``Explode``,
   ``RenameColumn``) so column-level dependencies cannot describe it;
2. ``stage.pipeline_io() -> (reads, writes) | None`` — explicit
   declaration (None = opaque for this configuration);
3. the stage's kernel ``reads``/``writes``;
4. declared column params: reads from ``input_col``/``input_cols``/
   ``features_col``, writes from ``output_col``/``output_cols``/
   ``prediction_col``/``probability_col``/``raw_prediction_col``.

Declared-I/O stages sign a **row-locality contract**: output row k
depends only on input row k plus fitted state. Stages that may *drop*
rows (``ImageFeaturizer`` with ``drop_na`` on undecodable images) set
``pipeline_row_preserving = False``; the scheduler then pins execution to
original stage order (fusion still applies) because reordering around a
row-filter is only sound when no other branch exists.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from mmlspark_tpu.compiler.kernels import StageKernel, stage_kernel

_READ_PARAMS = ("input_col", "features_col")
_READ_LIST_PARAMS = ("input_cols",)
_WRITE_PARAMS = (
    "output_col", "prediction_col", "probability_col", "raw_prediction_col",
)
_WRITE_LIST_PARAMS = ("output_cols",)


_UNRESOLVED = object()


def stage_io(stage: Any, kernel: Any = _UNRESOLVED) -> tuple:
    """(reads, writes, known) for one stage; ``known=False`` means opaque.
    ``kernel`` lets the planner pass an already-constructed kernel so
    heavyweight kernel builds (tree stacking, weight capture) happen once.
    """
    if getattr(stage, "pipeline_opaque", False):
        return (), (), False
    explicit = getattr(stage, "pipeline_io", None)
    if explicit is not None:
        try:
            io = explicit()
            if io is None:  # this configuration declines to declare
                return (), (), False
            reads, writes = io
            return tuple(reads), tuple(writes), True
        except Exception:  # noqa: BLE001 — a broken declaration plans opaque
            return (), (), False
    if kernel is _UNRESOLVED:
        kernel = stage_kernel(stage)
    if kernel is not None:
        return tuple(kernel.reads), tuple(kernel.writes), True
    reads: list = []
    writes: list = []
    try:
        params = type(stage).params()
    except Exception:  # noqa: BLE001 — not a Params stage: opaque
        return (), (), False
    def val(name: str) -> Any:
        return stage.get(name) if name in params else None
    for p in _READ_PARAMS:
        v = val(p)
        if isinstance(v, str) and v:
            reads.append(v)
    for p in _READ_LIST_PARAMS:
        v = val(p)
        if isinstance(v, (list, tuple)):
            reads.extend(str(c) for c in v)
    for p in _WRITE_PARAMS:
        v = val(p)
        if isinstance(v, str) and v:
            writes.append(v)
    for p in _WRITE_LIST_PARAMS:
        v = val(p)
        if isinstance(v, (list, tuple)):
            writes.extend(str(c) for c in v)
    if not reads and not writes:
        return (), (), False
    # de-dup preserving order
    return (
        tuple(dict.fromkeys(reads)), tuple(dict.fromkeys(writes)), True
    )


@dataclass
class StageNode:
    """One stage in the plan."""

    index: int
    stage: Any
    name: str
    reads: tuple
    writes: tuple
    kernel: Optional[StageKernel]
    opaque: bool
    row_preserving: bool = True
    deps: set = field(default_factory=set)       # node indices this waits on
    dependents: set = field(default_factory=set)

    @property
    def kind(self) -> str:
        if self.opaque:
            return "opaque"
        return "fused" if self.kernel is not None else "host"


class PipelinePlan:
    """The stage DAG + classification for one fitted pipeline."""

    def __init__(self, nodes: list, external_inputs: tuple):
        self.nodes = nodes
        self.external_inputs = external_inputs

    @property
    def all_row_preserving(self) -> bool:
        """False when any non-opaque stage may drop rows — the scheduler
        then keeps original stage order (opaque stages are already
        barriers, so only declared-I/O row-filters matter)."""
        return all(n.opaque or n.row_preserving for n in self.nodes)

    def topo_order(self) -> list:
        """Original-index order is always a valid topological order (deps
        only ever point backwards)."""
        return list(self.nodes)

    def final_columns(self, input_columns: list) -> list:
        """Column order staged execution would produce for this input —
        the scheduler restores it after any reordering."""
        cols = list(input_columns)
        for n in self.nodes:
            if n.opaque:
                return []  # an opaque stage may drop/rename: order unknowable
            for w in n.writes:
                if w not in cols:
                    cols.append(w)
        return cols

    def explain(self) -> str:
        lines = []
        for n in self.nodes:
            dep = ",".join(str(d) for d in sorted(n.deps)) or "-"
            lines.append(
                f"[{n.index}] {n.name} kind={n.kind} "
                f"reads={list(n.reads)} writes={list(n.writes)} deps={dep}"
            )
        if self.external_inputs:
            lines.append(f"external inputs: {list(self.external_inputs)}")
        return "\n".join(lines)


def plan_pipeline(stages: list) -> PipelinePlan:
    """Derive the DAG. Dependencies per column, staged-semantics faithful:

    - read-after-write: a reader depends on the LAST writer of the column;
    - write-after-read: a writer depends on every reader since the last
      write (it would otherwise clobber the value they expect);
    - write-after-write: a writer depends on the previous writer.
    """
    nodes: list = []
    for i, stage in enumerate(stages):
        kernel = stage_kernel(stage)
        reads, writes, known = stage_io(stage, kernel=kernel)
        nodes.append(StageNode(
            index=i,
            stage=stage,
            name=type(stage).__name__,
            reads=reads,
            writes=writes,
            kernel=kernel if known else None,
            opaque=not known,
            row_preserving=bool(
                getattr(stage, "pipeline_row_preserving", True)
            ),
        ))

    last_writer: dict = {}
    readers_since: dict = {}
    external: list = []
    barrier: Optional[int] = None  # most recent opaque stage
    for n in nodes:
        if n.opaque:
            # barrier: after everything before it...
            n.deps.update(range(n.index))
            barrier = n.index
            # ...and it invalidates column tracking (may rewrite anything)
            last_writer.clear()
            readers_since.clear()
            continue
        if barrier is not None:
            n.deps.add(barrier)
        for c in n.reads:
            w = last_writer.get(c)
            if w is not None:
                n.deps.add(w)
            elif barrier is None and c not in external:
                external.append(c)
            readers_since.setdefault(c, set()).add(n.index)
        for c in n.writes:
            w = last_writer.get(c)
            if w is not None:
                n.deps.add(w)
            for r in readers_since.get(c, ()):
                if r != n.index:
                    n.deps.add(r)
            last_writer[c] = n.index
            readers_since[c] = set()
        n.deps.discard(n.index)
    for n in nodes:
        for d in n.deps:
            nodes[d].dependents.add(n.index)
    return PipelinePlan(nodes, tuple(external))
