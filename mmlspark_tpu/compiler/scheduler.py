"""Scheduler: order segments by critical path; overlap independent hosts.

Per the TF partitioning/scheduling paper (arXiv:1711.01912), once a
program is partitioned the remaining lever is the *schedule*: the makespan
of a DAG of tasks is bounded below by its critical path, and
longest-remaining-path list scheduling is the classic near-optimal
heuristic. Pipeline DAGs here are small (tens of segments), so exact
critical-path priorities are cheap to recompute every run.

Cost model: the first transform measures every segment with the obs span
substrate (``core/profiling.py`` rides the same API) and feeds an EWMA per
segment; later transforms schedule against measured reality instead of
``cost_hint`` guesses. The first fused-segment sample includes its XLA
compile — the EWMA washes that out after a couple of runs, which is
exactly the cadence at which the schedule can usefully change.

Execution is host-sequential except for one genuinely concurrent case:
when two or more *host-bound* segments (HTTP transformers, io clients)
are ready at the same instant on independent branches, they run
overlapped on a thread pool — their wall time is I/O wait, so the overlap
is the whole win the critical-path argument promises. Device segments
never overlap (one mesh) and opaque stages are plan-level barriers, so
neither can be co-ready with anything.

Safety: any reordering (or overlap) of independent branches is only sound
when every declared-I/O stage preserves row count (see planner docstring);
a plan carrying a row-dropping stage degrades to original stage order,
fusion still applied.
"""

from __future__ import annotations

import concurrent.futures as _futures
import time
from typing import Any, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.compiler.fuser import HostSegment
from mmlspark_tpu.core.dataframe import DataFrame

_M_SCHED_REORDERS = obs.counter(
    "mmlspark_compiler_schedule_overlaps_total",
    "Host segments executed concurrently by the critical-path scheduler",
)

_DEFAULT_HOST_COST = 10.0   # host stages (HTTP, io) dominate until measured
_DEFAULT_OPAQUE_COST = 1.0


class CostModel:
    """Per-segment cost estimates: kernel hints until measured, EWMA after."""

    def __init__(self, alpha: float = 0.5):
        self.alpha = alpha
        self.measured: dict = {}   # segment name -> seconds

    def observe(self, name: str, seconds: float) -> None:
        prev = self.measured.get(name)
        self.measured[name] = (
            seconds if prev is None
            else self.alpha * seconds + (1 - self.alpha) * prev
        )

    def cost(self, segment: Any) -> float:
        m = self.measured.get(segment.name)
        if m is not None:
            return m
        if isinstance(segment, HostSegment):
            return _DEFAULT_OPAQUE_COST if segment.opaque else _DEFAULT_HOST_COST
        return sum(k.cost_hint for k in segment.kernels)


def segment_deps(segments: list, plan: Any) -> list:
    """Per-segment dependency sets, projected from the stage DAG."""
    seg_of: dict = {}
    for si, seg in enumerate(segments):
        for n in seg.nodes:
            seg_of[n.index] = si
    deps: list = [set() for _ in segments]
    for si, seg in enumerate(segments):
        for n in seg.nodes:
            for d in n.deps:
                ds = seg_of[d]
                if ds != si:
                    deps[si].add(ds)
    return deps


def critical_path(segments: list, deps: list, cost_model: CostModel) -> list:
    """Longest cost path from each segment to any sink (inclusive)."""
    dependents: list = [set() for _ in segments]
    for si, ds in enumerate(deps):
        for d in ds:
            dependents[d].add(si)
    prio = [0.0] * len(segments)
    # reverse index order is reverse-topological: deps only point backwards
    for si in range(len(segments) - 1, -1, -1):
        down = max((prio[d] for d in dependents[si]), default=0.0)
        prio[si] = cost_model.cost(segments[si]) + down
    return prio


def schedule_order(segments: list, deps: list, cost_model: CostModel) -> list:
    """List schedule: among ready segments, longest remaining path first
    (original index breaks ties, keeping the schedule deterministic)."""
    prio = critical_path(segments, deps, cost_model)
    remaining = set(range(len(segments)))
    done: set = set()
    order: list = []
    while remaining:
        ready = [s for s in remaining if deps[s] <= done]
        ready.sort(key=lambda s: (-prio[s], s))
        nxt = ready[0]
        order.append(nxt)
        remaining.discard(nxt)
        done.add(nxt)
    return order


class ScheduledExecutor:
    """Run the segment DAG over a DataFrame under staged-equality rules."""

    def __init__(
        self,
        segments: list,
        plan: Any,
        cost_model: Optional[CostModel] = None,
        parallel_hosts: bool = True,
    ):
        self.segments = segments
        self.plan = plan
        self.cost_model = cost_model or CostModel()
        self.deps = segment_deps(segments, plan)
        # reordering/overlap requires every declared stage row-preserving
        self.reorderable = plan.all_row_preserving
        self.parallel_hosts = parallel_hosts and self.reorderable

    # -- schedule ------------------------------------------------------------

    def order(self) -> list:
        if not self.reorderable:
            return list(range(len(self.segments)))
        return schedule_order(self.segments, self.deps, self.cost_model)

    def explain(self) -> str:
        prio = critical_path(self.segments, self.deps, self.cost_model)
        lines = []
        for pos, si in enumerate(self.order()):
            seg = self.segments[si]
            dep = ",".join(str(d) for d in sorted(self.deps[si])) or "-"
            lines.append(
                f"{pos}. [{si}] {seg.name} cost={self.cost_model.cost(seg):.4g}s "
                f"critical_path={prio[si]:.4g}s deps={dep}"
            )
        if not self.reorderable:
            lines.append("(row-dropping stage present: original order pinned)")
        return "\n".join(lines)

    # -- execution -----------------------------------------------------------

    def _apply_one(self, seg: Any, df: DataFrame) -> DataFrame:
        t0 = time.perf_counter()
        out = seg.apply(df)
        self.cost_model.observe(seg.name, time.perf_counter() - t0)
        return out

    def _overlap_hosts(self, batch: list, df: DataFrame) -> DataFrame:
        """Run independent ready host segments concurrently on the same df
        snapshot; merge each one's declared written columns back. Sound
        because co-ready segments have disjoint writes (write-write hazards
        are plan edges) and every stage here is row-preserving."""
        m = _M_SCHED_REORDERS
        if m._on:
            m.inc(len(batch))
        with obs.span("compiler.schedule.host_overlap"):
            with _futures.ThreadPoolExecutor(max_workers=len(batch)) as pool:
                outs = list(pool.map(
                    lambda seg: self._apply_one(seg, df), batch
                ))
        for seg, out in zip(batch, outs):
            for c in seg.writes:
                df = df.with_column(c, out[c])
                md = out.column_metadata(c)
                if md:
                    df = df.with_column_metadata(c, md)
        return df

    def run(self, df: DataFrame) -> DataFrame:
        order = self.order()
        done: set = set()
        i = 0
        while i < len(order):
            si = order[i]
            seg = self.segments[si]
            # gather the run of consecutively-scheduled segments that are
            # ALL ready now and all host-bound: those overlap
            batch = [si]
            if self.parallel_hosts and isinstance(seg, HostSegment) and not seg.opaque:
                j = i + 1
                while j < len(order):
                    nj = order[j]
                    sj = self.segments[nj]
                    if (
                        isinstance(sj, HostSegment)
                        and not sj.opaque
                        and self.deps[nj] <= done
                    ):
                        batch.append(nj)
                        j += 1
                    else:
                        break
            if len(batch) > 1:
                df = self._overlap_hosts([self.segments[b] for b in batch], df)
            else:
                df = self._apply_one(seg, df)
            done.update(batch)
            i += len(batch)
        return df
