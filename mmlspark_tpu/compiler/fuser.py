"""Fuser: merge runs of adjacent fusable stages into single XLA programs.

Stage-by-stage execution of a fitted pipeline dispatches one jitted
program per stage per partition, materializing every intermediate column
on the host between stages. The fuser instead traces the stage kernels of
a maximal run of adjacent fusable stages into ONE ``jax.jit`` program:
intermediates stay on device, dispatch overhead is paid once, and XLA
sees the whole segment.

Two load-bearing design points:

- **Exactness.** The compiled pipeline's contract is element-wise
  equality with staged execution. Cross-stage XLA fusion can legally
  change the lowering of an op (e.g. fuse a featurization chain into a
  dot's operand and pick a different accumulation strategy — observed on
  CPU: ~1 ulp logit drift). In ``exact`` mode (the default) the fuser
  therefore pins stage boundaries with ``jax.lax.optimization_barrier``
  around every kernel's inputs: each stage's ops lower exactly as they
  would standalone, while the segment still runs as one program (single
  dispatch, device-resident intermediates). ``exact=False`` drops the
  barriers and lets XLA fuse across stages freely — faster, but only
  allclose-level equal.
- **Bounded compile cache.** Batches are padded to power-of-two buckets
  (the ``_bucket`` idiom from ``serving/query.py``) capped at
  ``max_bucket``, so a segment compiles at most ``log2(max_bucket)+1``
  programs per distinct feature shape no matter what partition sizes
  arrive. Row-wise kernels make pad-and-slice sound.

A segment that cannot run a given DataFrame (an object-dtype input, a
kernel guard refusal) falls back to staged execution for that call —
recorded in ``mmlspark_compiler_fallback_total{reason=...}`` — so
compiled pipelines never fail where the staged pipeline would not.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from mmlspark_tpu import obs
from mmlspark_tpu.compiler.partitioner import ShardingPlan, plan_sharding
from mmlspark_tpu.core.dataframe import DataFrame, Partition
from mmlspark_tpu.serving.query import _bucket

_M_COMPILE = obs.histogram(
    "mmlspark_compiler_compile_seconds",
    "Wall time of a fused segment's first call per bucket (trace+compile)",
    labels=("segment",),
    buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0),
)
_M_BUCKET_COMPILES = obs.counter(
    "mmlspark_compiler_bucket_compiles_total",
    "Fused-program compilations (one per new bucket/shape per segment)",
    labels=("segment",),
)
_M_SEG_LATENCY = obs.histogram(
    "mmlspark_compiler_segment_latency_seconds",
    "Per-call latency of compiled-pipeline segments",
    labels=("segment",),
)
_M_FALLBACK = obs.counter(
    "mmlspark_compiler_fallback_total",
    "Fused segments that fell back to staged execution",
    labels=("reason",),
)

_DEVICE_PHASE = None


def _device_phase(phase: str, stage: str):
    """core.profiling.device_phase, imported lazily — that module pulls
    jax eagerly and this one must stay importable without it."""
    global _DEVICE_PHASE
    if _DEVICE_PHASE is None:
        from mmlspark_tpu.core.profiling import device_phase

        _DEVICE_PHASE = device_phase
    return _DEVICE_PHASE(phase, stage)


class Segment:
    """Base: one schedulable unit of a compiled pipeline."""

    name: str = "segment"
    nodes: list = []

    @property
    def stage_names(self) -> list:
        return [n.name for n in self.nodes]

    @property
    def reads(self) -> tuple:
        out: list = []
        produced: set = set()
        for n in self.nodes:
            out.extend(c for c in n.reads if c not in produced)
            produced.update(n.writes)
        return tuple(dict.fromkeys(out))

    @property
    def writes(self) -> tuple:
        out: list = []
        for n in self.nodes:
            out.extend(n.writes)
        return tuple(dict.fromkeys(out))

    def apply(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError


class HostSegment(Segment):
    """A single host-bound (or opaque) stage, executed via its own
    ``transform`` — per-stage fallback is the *plan* for these, not an
    error path."""

    def __init__(self, node: Any, name: str):
        self.nodes = [node]
        self.name = name
        self.opaque = node.opaque

    def apply(self, df: DataFrame) -> DataFrame:
        t0 = time.perf_counter()
        out = self.nodes[0].stage.transform(df)
        m = _M_SEG_LATENCY.labels(segment=self.name)
        if m._on:
            m.observe(time.perf_counter() - t0)
        return out


class FusedSegment(Segment):
    """A maximal run of adjacent fusable stages compiled as one program."""

    def __init__(
        self,
        nodes: list,
        name: str,
        exact: bool = True,
        max_bucket: int = 1024,
        mesh: Any = None,
        partition_mode: str = "auto",
    ):
        self.nodes = nodes
        self.name = name
        self.exact = exact
        self.max_bucket = max(1, int(max_bucket))
        self.mesh = mesh
        self.partition_mode = partition_mode
        self.kernels = [n.kernel for n in nodes]
        # a cross-row kernel would see padded lanes in its reductions, so
        # pad-and-slice bucketing is only sound when every kernel is row-wise;
        # otherwise the segment compiles per exact batch shape instead
        self.row_wise = all(k.row_wise for k in self.kernels)
        self._jit_cache: dict = {}
        self._sharding: Optional[ShardingPlan] = None
        self.last_fallback_error: Optional[str] = None

    # -- planning ------------------------------------------------------------

    @property
    def sharding(self) -> ShardingPlan:
        if self._sharding is None:
            self._sharding = plan_sharding(
                self.kernels,
                mesh=self.mesh,
                bucket=self.max_bucket,
                mode=self.partition_mode,
            )
        return self._sharding

    # -- program construction ------------------------------------------------

    @property
    def device_outputs(self) -> tuple:
        """Columns the fused program returns: plain kernels' writes plus
        finalize kernels' raw device outputs (their final writes are
        produced on host by the epilogue)."""
        out: list = []
        for k in self.kernels:
            out.extend(k.fn_outputs)
        return tuple(dict.fromkeys(out))

    def _traced_fn(self):
        kernels = list(self.kernels)
        outputs = list(self.device_outputs)
        exact = self.exact

        def fn(cols: dict) -> dict:
            import jax

            env = dict(cols)
            for k in kernels:
                ins = {c: env[c] for c in k.reads}
                if exact:
                    # pin the stage boundary: the kernel's ops see opaque
                    # operands, exactly like the staged jit saw host arrays,
                    # so XLA cannot re-lower them via cross-stage fusion
                    ins = jax.lax.optimization_barrier(ins)
                env.update(k.fn(ins))
            return {c: env[c] for c in outputs}

        return fn

    def _compiled(self, key: tuple, cols: dict, bucket: int):
        entry = self._jit_cache.get(key)
        if entry is None:
            import jax

            in_sh = self.sharding.in_shardings(cols)
            if in_sh is not None:
                fn = jax.jit(self._traced_fn(), in_shardings=(in_sh,))
            else:
                fn = jax.jit(self._traced_fn())
            self._jit_cache[key] = entry = {"fn": fn, "compiled": False}
        return entry

    # -- execution -----------------------------------------------------------

    def _guard(self, part: Partition) -> Optional[str]:
        for k in self.kernels:
            if k.guard is None:
                continue
            ins = {c: part[c] for c in k.reads if c in part}
            reason = k.guard(ins)
            if reason:
                return reason
        for c in self.reads:
            arr = part.get(c)
            if arr is None:
                return f"missing column {c!r}"
            if np.asarray(arr).dtype == object:
                return f"object column {c!r}"
        return None

    def _staged(self, df: DataFrame, reason: str) -> DataFrame:
        m = _M_FALLBACK.labels(reason=reason[:60])
        if m._on:
            m.inc()
        for n in self.nodes:
            df = n.stage.transform(df)
        return df

    def apply(self, df: DataFrame) -> DataFrame:
        # guard on the first non-empty partition; the whole call either
        # runs fused or falls back (partitions must agree on dtypes)
        probe = next((p for p in df.partitions if p), None)
        if probe is not None:
            reason = self._guard(probe)
            if reason is not None:
                return self._staged(df, reason)
        t0 = time.perf_counter()
        with obs.span(f"compiler.segment.{self.name}"):
            try:
                out = df.map_partitions(self._apply_partition, parallel=False)
            except Exception as e:  # noqa: BLE001 — never fail where staged wouldn't
                # label stays bounded (exception class); the free-form
                # message would mint a metric series per distinct shape/
                # value it quotes — detail goes to explain()/introspection
                self.last_fallback_error = f"{type(e).__name__}: {e}"
                return self._staged(df, f"error:{type(e).__name__}")
        m = _M_SEG_LATENCY.labels(segment=self.name)
        if m._on:
            m.observe(time.perf_counter() - t0)
        return out

    def _apply_partition(self, part: Partition) -> Partition:
        reads = self.reads
        cols: dict = {}
        n = 0
        for c in reads:
            arr = np.asarray(part[c])
            n = max(n, arr.shape[0] if arr.ndim else 0)
            cols[c] = arr
        b = _bucket(max(n, 1), cap=self.max_bucket) if self.row_wise else max(n, 1)
        padded: dict = {}
        for c, arr in cols.items():
            padded[c] = _pad_rows(arr, b)
        key = (b,) + tuple(
            (c, padded[c].shape[1:], str(padded[c].dtype)) for c in reads
        )
        entry = self._compiled(key, padded, b)
        t0 = time.perf_counter()
        chunks = [padded]
        if n > b:  # oversized partition: run in bucket-size chunks
            chunks = []
            for start in range(0, n, b):
                chunk = {c: _pad_rows(arr[start:start + b], b) for c, arr in cols.items()}
                chunks.append(chunk)
        outs: list = []
        rest = chunks
        if not entry["compiled"]:
            # first call on this bucket pays trace+compile: block it to
            # completion so the compile/execute attribution is honest
            # (dispatching an already-compiled fn never blocks here)
            with _device_phase("compile", self.name):
                out0 = entry["fn"](chunks[0])
                for v in out0.values():
                    getattr(v, "block_until_ready", lambda: None)()
            outs.append(out0)
            rest = chunks[1:]
            dt = time.perf_counter() - t0
            entry["compiled"] = True
            mc = _M_COMPILE.labels(segment=self.name)
            if mc._on:
                mc.observe(dt)
            mb = _M_BUCKET_COMPILES.labels(segment=self.name)
            if mb._on:
                mb.inc()
        with _device_phase("execute", self.name):
            for chunk in rest:
                outs.append(entry["fn"](chunk))
        q = dict(part)
        merged: dict = {}
        for c in self.device_outputs:
            vals = [np.asarray(o[c]) for o in outs]
            merged[c] = np.concatenate(vals, axis=0)[:n] if len(vals) > 1 else vals[0][:n]
        for k in self.kernels:
            if k.finalize is not None:
                # host epilogue: replay the staged path's numpy tail on the
                # fetched device outputs (sliced to true rows already)
                host_cols = {c: merged[c] for c in k.fn_outputs}
                q.update(k.finalize(host_cols))
                continue
            for c in k.writes:
                v = merged[c]
                dt_ = k.out_dtypes.get(c)
                q[c] = v.astype(dt_) if dt_ is not None and v.dtype != dt_ else v
        return q


def _pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Pad axis 0 up to ``bucket`` rows (repeat row 0 — a real row keeps
    padded lanes NaN/inf-free); zero-rows when the array is empty."""
    n = arr.shape[0]
    if n == bucket:
        return arr
    if n == 0:
        return np.zeros((bucket,) + arr.shape[1:], arr.dtype)
    if n > bucket:
        return arr[:bucket]
    reps = np.repeat(arr[:1], bucket - n, axis=0)
    return np.concatenate([arr, reps], axis=0)


def build_segments(
    plan: Any,
    exact: bool = True,
    max_bucket: int = 1024,
    mesh: Any = None,
    partition_mode: str = "auto",
) -> list:
    """Partition the plan's nodes into segments: maximal runs of adjacent
    fusable stages become one :class:`FusedSegment`; everything else is a
    :class:`HostSegment` of its own."""
    segments: list = []
    run: list = []

    def flush() -> None:
        if not run:
            return
        idx = len(segments)
        name = f"s{idx}:" + "+".join(n.name for n in run)
        segments.append(FusedSegment(
            list(run), name, exact=exact, max_bucket=max_bucket,
            mesh=mesh, partition_mode=partition_mode,
        ))
        run.clear()

    for n in plan.nodes:
        if n.kind == "fused" and exact and not n.kernel.exact_capable:
            # the kernel cannot promise bit-equality (conv lowerings vary
            # with batch shape): exact mode runs the stage host-bound
            flush()
            segments.append(HostSegment(n, f"s{len(segments)}:{n.name}"))
        elif n.kind == "fused":
            run.append(n)
            if n.kernel.finalize is not None:
                # a finalize kernel's outputs live on host after its
                # epilogue — nothing later can read them on device, so it
                # always ends its fusion run
                flush()
        else:
            flush()
            segments.append(HostSegment(n, f"s{len(segments)}:{n.name}"))
    flush()
    return segments
