"""CompiledPipeline — a fitted pipeline as one scheduled, partitioned program.

``PipelineModel.compile()`` returns this drop-in :class:`Transformer`:
the planner derives the stage DAG, the fuser merges adjacent fusable
stages into single jitted programs, the partitioner assigns NamedShardings
over the default mesh, and the scheduler orders independent branches by
critical path. The correctness contract is **element-wise equality with
staged execution** — every representative pipeline, including chunked
scoring through ``StreamingDataFrame.transform`` (a CompiledPipeline is a
plain Transformer, so the streaming path needs no special case; the
bounded bucket cache absorbs varying chunk sizes).

Build is lazy (first ``transform``) and also exposed as
:meth:`CompiledPipeline.build` so serving loaders can pay planning before
a model version turns ready. Persistence: only the fitted stages and the
compile options are saved (``save``/``load`` via the Params machinery);
plans, jit caches and measured costs are runtime state, rebuilt on load.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

from mmlspark_tpu import obs
from mmlspark_tpu.compiler.fuser import FusedSegment, build_segments
from mmlspark_tpu.compiler.planner import PipelinePlan, plan_pipeline
from mmlspark_tpu.compiler.scheduler import CostModel, ScheduledExecutor
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, Param
from mmlspark_tpu.core.pipeline import Model

_M_PIPE_COMPILE = obs.histogram(
    "mmlspark_compiler_plan_seconds",
    "Wall time of plan+fuse+partition+schedule for one pipeline "
    "(excludes per-bucket XLA compiles, which land in "
    "mmlspark_compiler_compile_seconds)",
    buckets=(0.001, 0.01, 0.05, 0.25, 1.0, 5.0),
)
_M_STAGES_FUSED = obs.counter(
    "mmlspark_compiler_stages_fused_total",
    "Stages merged into fused segments across pipeline compiles",
)
_M_SEGMENTS = obs.counter(
    "mmlspark_compiler_segments_total",
    "Segments produced by pipeline compiles", labels=("kind",),
)
_M_SEARCHES = obs.counter(
    "mmlspark_compiler_sharding_search_total",
    "Sharding groups resolved by search (Automap conflict points) "
    "rather than propagation",
)


class CompiledPipeline(Model):
    """Drop-in Transformer executing a fitted pipeline as fused segments."""

    stages = ComplexParam("fitted stages of the source pipeline", default=[])
    exact = Param(
        "pin per-stage lowering with optimization barriers so compiled "
        "output is element-wise equal to staged execution (False lets XLA "
        "fuse across stage boundaries: faster, allclose-level equal)",
        default=True, type_=bool,
    )
    max_bucket = Param(
        "power-of-two batch-bucket cap bounding compiles per segment to "
        "log2(cap)+1 per feature shape", default=1024, type_=int,
    )
    partition_mode = Param(
        "auto (batch-shard on accelerator meshes, replicate on CPU) | "
        "batch (force batch sharding) | replicated",
        default="auto", type_=str,
    )
    parallel_hosts = Param(
        "overlap independent ready host-bound segments on threads",
        default=True, type_=bool,
    )

    def __init__(self, stages: Optional[Sequence[Any]] = None, **kw: Any):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))
        self._plan: Optional[PipelinePlan] = None
        self._segments: Optional[list] = None
        self._executor: Optional[ScheduledExecutor] = None
        self._cost_model = CostModel()

    # -- build ---------------------------------------------------------------

    def build(self, mesh: Any = None) -> "CompiledPipeline":
        """Plan + fuse + partition + schedule (idempotent). ``mesh``
        defaults to the process mesh; the partitioner falls back to
        replicated on CPU/single-device meshes in ``auto`` mode."""
        if self._executor is not None:
            return self
        t0 = time.perf_counter()
        with obs.span("compiler.compile"):
            if mesh is None and self.get("partition_mode") != "replicated":
                from mmlspark_tpu.parallel.mesh import get_mesh

                mesh = get_mesh()
            plan = plan_pipeline(list(self.get("stages")))
            segments = build_segments(
                plan,
                exact=self.get("exact"),
                max_bucket=self.get("max_bucket"),
                mesh=mesh,
                partition_mode=self.get("partition_mode"),
            )
            self._plan = plan
            self._segments = segments
            self._executor = ScheduledExecutor(
                segments, plan,
                cost_model=self._cost_model,
                parallel_hosts=self.get("parallel_hosts"),
            )
        if obs.REGISTRY.enabled:
            _M_PIPE_COMPILE.observe(time.perf_counter() - t0)
            fused = [s for s in segments if isinstance(s, FusedSegment)]
            _M_STAGES_FUSED.inc(sum(len(s.nodes) for s in fused))
            _M_SEGMENTS.labels(kind="fused").inc(len(fused))
            _M_SEGMENTS.labels(kind="host").inc(len(segments) - len(fused))
            _M_SEARCHES.inc(sum(len(s.sharding.searched) for s in fused))
        return self

    # -- introspection -------------------------------------------------------

    @property
    def plan(self) -> PipelinePlan:
        self.build()
        return self._plan

    @property
    def segments(self) -> list:
        self.build()
        return self._segments

    @property
    def fused_segments(self) -> list:
        return [s for s in self.segments if isinstance(s, FusedSegment)]

    @property
    def num_fused_stages(self) -> int:
        return sum(len(s.nodes) for s in self.fused_segments)

    def explain(self) -> str:
        """Plan, segments, sharding decisions and schedule, one report."""
        self.build()
        parts = ["== plan ==", self._plan.explain(), "", "== segments =="]
        for s in self._segments:
            kind = "fused" if isinstance(s, FusedSegment) else "host"
            parts.append(f"{s.name} kind={kind} stages={s.stage_names}")
            if isinstance(s, FusedSegment):
                sh = s.sharding
                if sh.decisions:
                    parts.append(f"  sharding: {sh.decisions}")
                for g in sh.searched:
                    parts.append(f"  searched: {g}")
                if s.last_fallback_error:
                    parts.append(f"  last fallback: {s.last_fallback_error}")
        parts += ["", "== schedule =="]
        parts.append(self._executor.explain())
        return "\n".join(parts)

    # -- execution -----------------------------------------------------------

    def transform(self, df: DataFrame) -> DataFrame:
        self.build()
        with obs.span("compiler.pipeline.transform"):
            out = self._executor.run(df)
        # staged execution fixes the output column order; reordering-capable
        # schedules restore it so compiled output is indistinguishable
        final = self._plan.final_columns(df.columns)
        if final and set(final) == set(out.columns) and out.columns != final:
            out = out.select(*final)
        return out
