"""Image transformer stages.

``ImageTransformer`` mirrors the reference's stage-list design
(opencv/ImageTransformer.scala:41-110): the transform is configured as an
ordered list of op descriptors (dicts), built fluently::

    ImageTransformer(input_col="image").resize(224, 224).flip().blur(5, 1.5)

Execution is batched: each partition groups images by shape, stacks each
group into one (N, H, W, C) array, and runs the whole op list as device
programs from ``mmlspark_tpu.ops.image``.

``UnrollImage`` flattens to the reference's CHW/BGR vector layout
(image/UnrollImage.scala:40-51), ``ResizeImageTransformer`` is the
OpenCV-free resize (image/ResizeImageTransformer.scala), and
``ImageSetAugmenter`` emits flip-augmented copies
(image/ImageSetAugmenter.scala).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.ops import image as ops


def _as_image(v: Any) -> np.ndarray:
    img = np.asarray(v, np.float32)
    if img.ndim == 2:
        img = img[..., None]
    return img


def _apply_grouped(images: np.ndarray, fn: Any) -> np.ndarray:
    """Group an object array of (H,W,C) images by shape, run ``fn`` on each
    stacked group as one batch, scatter results back row-aligned."""
    if isinstance(images, np.ndarray) and images.dtype != object:
        return np.asarray(fn(jnp.asarray(images, jnp.float32)))
    groups: dict[tuple, list[int]] = {}
    imgs = [_as_image(v) for v in images]
    for i, img in enumerate(imgs):
        groups.setdefault(img.shape, []).append(i)
    out = np.empty(len(imgs), dtype=object)
    for shape, idxs in groups.items():
        batch = jnp.stack([jnp.asarray(imgs[i]) for i in idxs])
        res = np.asarray(fn(batch))
        for j, i in enumerate(idxs):
            out[i] = res[j]
    return out


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Ordered list of image ops applied on device (see module docstring)."""

    stages = Param("ordered op descriptors", default=[])

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "input_col" not in self._paramMap:
            self.set(input_col="image")
        if "output_col" not in self._paramMap:
            self.set(output_col=self.get("input_col"))

    # -- fluent builders (reference python wrapper style) --------------------

    def _add(self, **stage: Any) -> "ImageTransformer":
        self.set(stages=self.get("stages") + [stage])
        return self

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="resize", height=height, width=width)

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add(op="crop", x=x, y=y, height=height, width=width)

    def color_format(self, format: str) -> "ImageTransformer":
        return self._add(op="color_format", format=format)

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        return self._add(op="flip", flip_code=flip_code)

    def blur(self, ksize: int, sigma: float) -> "ImageTransformer":
        return self._add(op="blur", ksize=ksize, sigma=sigma)

    def threshold(self, threshold: float, max_val: float = 255.0) -> "ImageTransformer":
        return self._add(op="threshold", threshold=threshold, max_val=max_val)

    def gaussian_kernel(self, aperture_size: int, sigma: float) -> "ImageTransformer":
        return self._add(op="blur", ksize=aperture_size, sigma=sigma)

    def normalize(
        self,
        mean: tuple = (0.485, 0.456, 0.406),
        std: tuple = (0.229, 0.224, 0.225),
        scale: float = 1.0 / 255.0,
    ) -> "ImageTransformer":
        return self._add(op="normalize", mean=list(mean), std=list(std), scale=scale)

    # -- execution -----------------------------------------------------------

    @staticmethod
    def _stage_fn(stage: dict) -> Any:
        op = stage["op"]
        if op == "resize":
            return lambda b: ops.resize(b, stage["height"], stage["width"])
        if op == "crop":
            return lambda b: ops.crop(
                b, stage["x"], stage["y"], stage["height"], stage["width"]
            )
        if op == "color_format":
            fmt = stage["format"].lower()
            if fmt in ("gray", "grey", "grayscale"):
                return lambda b: ops.to_grayscale(b)
            if fmt in ("bgr2rgb", "rgb2bgr"):
                return lambda b: ops.bgr_to_rgb(b)
            raise ValueError(f"unknown color format {fmt!r}")
        if op == "flip":
            return lambda b: ops.flip(b, horizontal=stage.get("flip_code", 1) >= 1)
        if op == "blur":
            return lambda b: ops.gaussian_blur(b, stage["ksize"], stage["sigma"])
        if op == "threshold":
            return lambda b: ops.threshold(b, stage["threshold"], stage.get("max_val", 255.0))
        if op == "normalize":
            return lambda b: ops.normalize(
                b, tuple(stage["mean"]), tuple(stage["std"]), stage["scale"]
            )
        raise ValueError(f"unknown image op {op!r}")

    def transform(self, df: DataFrame) -> DataFrame:
        fns = [self._stage_fn(s) for s in self.get("stages")]

        def pipeline(batch: jnp.ndarray) -> jnp.ndarray:
            for f in fns:
                batch = f(batch)
            return batch

        ic, oc = self.get("input_col"), self.get("output_col")

        def fn(p: dict) -> dict:
            q = dict(p)
            q[oc] = _apply_grouped(p[ic], pipeline)
            return q

        return df.map_partitions(fn, parallel=False)


class ResizeImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Standalone resize (image/ResizeImageTransformer.scala:105 analogue)."""

    height = Param("target height", type_=int)
    width = Param("target width", type_=int)

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "input_col" not in self._paramMap:
            self.set(input_col="image")
        if "output_col" not in self._paramMap:
            self.set(output_col=self.get("input_col"))

    def transform(self, df: DataFrame) -> DataFrame:
        h, w = self.get_or_fail("height"), self.get_or_fail("width")

        def fn(p: dict) -> dict:
            q = dict(p)
            out = _apply_grouped(p[self.get("input_col")], lambda b: ops.resize(b, h, w))
            if isinstance(out, np.ndarray) and out.dtype == object:
                # uniform output shapes: stack into a dense tensor column
                out = np.stack(list(out))
            q[self.get("output_col")] = out
            return q

        return df.map_partitions(fn, parallel=False)


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """Image -> flat CHW/BGR vector (image/UnrollImage.scala:40-51)."""

    bgr = Param("convert RGB input to BGR plane order like the reference", default=True, type_=bool)

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "input_col" not in self._paramMap:
            self.set(input_col="image")
        if "output_col" not in self._paramMap:
            self.set(output_col="unrolled")

    def transform(self, df: DataFrame) -> DataFrame:
        def fn(p: dict) -> dict:
            q = dict(p)
            out = _apply_grouped(
                p[self.get("input_col")], lambda b: ops.unroll(b, self.get("bgr"))
            )
            if isinstance(out, np.ndarray) and out.dtype == object:
                lens = {v.shape for v in out}
                if len(lens) == 1:
                    out = np.stack(list(out))
            q[self.get("output_col")] = out
            return q

        return df.map_partitions(fn, parallel=False)


class UnrollBinaryImage(UnrollImage):
    """Encoded image bytes -> decode -> unroll (UnrollBinaryImage analogue)."""

    def transform(self, df: DataFrame) -> DataFrame:
        ic = self.get("input_col")

        def decode(p: dict) -> dict:
            data = p[ic]
            out = np.empty(len(data), dtype=object)
            for i, blob in enumerate(data):
                img = ops.decode_image(bytes(blob)) if blob is not None else None
                out[i] = np.zeros((1, 1, 3), np.float32) if img is None else np.asarray(img, np.float32)
            q = dict(p)
            q[ic] = out
            return q

        return super().transform(df.map_partitions(decode, parallel=False))


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Emit augmented copies of every image (image/ImageSetAugmenter.scala:73):
    original + optional horizontal/vertical flips, multiplying row count."""

    flip_left_right = Param("add horizontal flips", default=True, type_=bool)
    flip_up_down = Param("add vertical flips", default=False, type_=bool)

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "input_col" not in self._paramMap:
            self.set(input_col="image")
        if "output_col" not in self._paramMap:
            self.set(output_col=self.get("input_col"))

    def transform(self, df: DataFrame) -> DataFrame:
        ic, oc = self.get("input_col"), self.get("output_col")

        def fn(p: dict) -> dict:
            variants: list[np.ndarray] = [p[ic]]
            if self.get("flip_left_right"):
                variants.append(_apply_grouped(p[ic], lambda b: ops.flip(b, True)))
            if self.get("flip_up_down"):
                variants.append(_apply_grouped(p[ic], lambda b: ops.flip(b, False)))
            q: dict = {}
            for c, v in p.items():
                if c == ic:
                    continue
                q[c] = np.concatenate([v] * len(variants))
            merged = np.empty(sum(len(v) for v in variants), dtype=object)
            pos = 0
            for v in variants:
                for x in v:
                    merged[pos] = x
                    pos += 1
            q[oc] = merged
            return q

        return df.map_partitions(fn, parallel=False)
