"""Image pipeline stages (reference: opencv/ + image/, SURVEY.md §2.5).

The reference drives OpenCV through JNI for decode/resize/crop/flip/blur;
here every pixel op is a batched jitted program from
``mmlspark_tpu.ops.image`` — images with a common shape inside a partition
are stacked and processed as one (N, H, W, C) device batch.
"""

from mmlspark_tpu.image.transformer import (
    ImageSetAugmenter,
    ImageTransformer,
    ResizeImageTransformer,
    UnrollBinaryImage,
    UnrollImage,
)

__all__ = [
    "ImageTransformer",
    "UnrollImage",
    "UnrollBinaryImage",
    "ResizeImageTransformer",
    "ImageSetAugmenter",
]
