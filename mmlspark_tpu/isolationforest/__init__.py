"""Isolation Forest anomaly detection (reference: isolationforest/, SURVEY.md §2.15).

The reference wraps ``com.linkedin.isolation-forest``
(IsolationForest.scala:17-60). This is a native rebuild: trees are grown on
the host (cheap: T×psi subsamples), stored as dense perfect-binary-tree
arrays, and scored on device — path traversal is a fixed-depth ``lax.scan``
over gathers vmapped across trees, so scoring N rows × T trees is one
jitted program with no per-row Python.
"""

from mmlspark_tpu.isolationforest.forest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
