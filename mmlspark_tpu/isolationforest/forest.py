"""Isolation forest: host tree growth, device batch scoring.

Algorithm (Liu et al. 2008, as shipped by the reference's linkedin
estimator): T trees each grown on a psi-row subsample by recursively
picking a random feature and a random split between the reaching data's
min/max until isolation or the depth cap ceil(log2(psi)); anomaly score
``s(x) = 2^(-E[h(x)] / c(psi))`` where h adds ``c(n)`` at unsplit leaves.

Device layout: perfect binary tree of depth D as flat arrays
``feature/threshold/is_leaf/path_len`` of width 2^(D+1)-1 per tree;
traversal is D gather steps (no branches), vmapped over trees.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import ComplexParam, HasFeaturesCol, HasPredictionCol, Param
from mmlspark_tpu.core.pipeline import Estimator, Model


def _avg_path_length(n: np.ndarray) -> np.ndarray:
    """c(n): average BST unsuccessful-search path length (the h(x) correction)."""
    n = np.asarray(n, np.float64)
    out = np.zeros_like(n)
    big = n > 2
    out[big] = 2.0 * (np.log(n[big] - 1.0) + np.euler_gamma) - 2.0 * (n[big] - 1.0) / n[big]
    out[n == 2] = 1.0
    return out


def _grow_tree(
    x: np.ndarray, rng: np.random.RandomState, depth_cap: int, feat_subset: np.ndarray
) -> dict:
    """Grow one tree into perfect-binary-tree arrays of depth depth_cap."""
    n_nodes = 2 ** (depth_cap + 1) - 1
    feature = np.zeros(n_nodes, np.int32)
    threshold = np.zeros(n_nodes, np.float32)
    is_leaf = np.ones(n_nodes, bool)
    path_len = np.zeros(n_nodes, np.float32)

    # stack of (node_id, row_indices, depth)
    stack = [(0, np.arange(len(x)), 0)]
    while stack:
        node, rows, depth = stack.pop()
        xs = x[rows]
        if depth >= depth_cap or len(rows) <= 1:
            path_len[node] = depth + _avg_path_length(np.array([len(rows)]))[0]
            continue
        # random feature with spread; give up (leaf) if all are constant
        cand = feat_subset[rng.permutation(len(feat_subset))]
        lo = hi = None
        f_pick = -1
        for f in cand:
            flo, fhi = xs[:, f].min(), xs[:, f].max()
            if fhi > flo:
                f_pick, lo, hi = int(f), flo, fhi
                break
        if f_pick < 0:
            path_len[node] = depth + _avg_path_length(np.array([len(rows)]))[0]
            continue
        thr = rng.uniform(lo, hi)
        is_leaf[node] = False
        feature[node] = f_pick
        threshold[node] = thr
        mask = xs[:, f_pick] < thr
        stack.append((2 * node + 1, rows[mask], depth + 1))
        stack.append((2 * node + 2, rows[~mask], depth + 1))
    return {
        "feature": feature,
        "threshold": threshold,
        "is_leaf": is_leaf,
        "path_len": path_len,
    }


@partial(jax.jit, static_argnums=(5,))
def _batch_path_lengths(
    x: jnp.ndarray,
    feature: jnp.ndarray,
    threshold: jnp.ndarray,
    is_leaf: jnp.ndarray,
    path_len: jnp.ndarray,
    depth_cap: int,
) -> jnp.ndarray:
    """(N, d) rows × (T, nodes) trees -> (N, T) path lengths."""

    def one_tree(feat: jnp.ndarray, thr: jnp.ndarray, leaf: jnp.ndarray, plen: jnp.ndarray) -> jnp.ndarray:
        def step(idx: jnp.ndarray, _: Any) -> tuple:
            go_left = x[jnp.arange(x.shape[0]), feat[idx]] < thr[idx]
            child = jnp.where(go_left, 2 * idx + 1, 2 * idx + 2)
            idx = jnp.where(leaf[idx], idx, child)  # stop at leaves
            return idx, None

        idx0 = jnp.zeros((x.shape[0],), jnp.int32)
        idx, _ = jax.lax.scan(step, idx0, None, length=depth_cap)
        return plen[idx]

    return jax.vmap(one_tree, in_axes=(0, 0, 0, 0), out_axes=1)(
        feature, threshold, is_leaf, path_len
    )


class _IFParams(HasFeaturesCol, HasPredictionCol):
    num_estimators = Param("number of trees", default=100, type_=int)
    max_samples = Param("subsample rows per tree (psi)", default=256, type_=int)
    max_features = Param("fraction of features per tree", default=1.0, type_=float)
    bootstrap = Param("sample rows with replacement", default=False, type_=bool)
    contamination = Param(
        "expected outlier fraction; 0 means fixed 0.5 score threshold",
        default=0.0,
        type_=float,
    )
    score_col = Param("anomaly score output column", default="outlierScore")
    random_seed = Param("rng seed", default=1, type_=int)


class IsolationForest(Estimator, _IFParams):
    def fit(self, df: DataFrame) -> "IsolationForestModel":
        x = np.asarray(df[self.get("features_col")], np.float32)
        if x.ndim != 2 or len(x) == 0:
            raise ValueError(f"IsolationForest needs (n, d) features, got {x.shape}")
        rng = np.random.RandomState(self.get("random_seed"))
        t = self.get("num_estimators")
        psi = min(self.get("max_samples"), len(x))
        depth_cap = max(1, int(np.ceil(np.log2(max(psi, 2)))))
        n_feat = max(1, int(round(self.get("max_features") * x.shape[1])))

        trees = []
        for _ in range(t):
            if self.get("bootstrap"):
                rows = rng.randint(0, len(x), psi)
            else:
                rows = rng.choice(len(x), psi, replace=False)
            feat_subset = rng.choice(x.shape[1], n_feat, replace=False)
            trees.append(_grow_tree(x[rows], rng, depth_cap, feat_subset))

        m = IsolationForestModel(**{k: v for k, v in self._paramMap.items()})
        m.set(
            features=np.stack([tr["feature"] for tr in trees]),
            thresholds=np.stack([tr["threshold"] for tr in trees]),
            leaves=np.stack([tr["is_leaf"] for tr in trees]),
            path_lens=np.stack([tr["path_len"] for tr in trees]),
            depth_cap=depth_cap,
            subsample_size=psi,
        )
        if self.get("contamination") > 0.0:
            scores = m._scores(x)
            m.set(score_threshold=float(np.quantile(scores, 1.0 - self.get("contamination"))))
        return m


class IsolationForestModel(Model, _IFParams):
    features = ComplexParam("(T, nodes) split feature ids")
    thresholds = ComplexParam("(T, nodes) split thresholds")
    leaves = ComplexParam("(T, nodes) leaf mask")
    path_lens = ComplexParam("(T, nodes) leaf path lengths (depth + c(n))")
    depth_cap = Param("tree depth", type_=int)
    subsample_size = Param("psi used at fit", type_=int)
    score_threshold = Param("score above this = outlier", default=0.5, type_=float)

    def _scores(self, x: np.ndarray) -> np.ndarray:
        lengths = _batch_path_lengths(
            jnp.asarray(x, jnp.float32),
            jnp.asarray(self.get_or_fail("features")),
            jnp.asarray(self.get_or_fail("thresholds")),
            jnp.asarray(self.get_or_fail("leaves")),
            jnp.asarray(self.get_or_fail("path_lens")),
            self.get_or_fail("depth_cap"),
        )
        e_h = np.asarray(lengths).mean(axis=1)
        c = _avg_path_length(np.array([self.get_or_fail("subsample_size")]))[0]
        return np.power(2.0, -e_h / max(c, 1e-9))

    def transform(self, df: DataFrame) -> DataFrame:
        def fn(p: dict) -> dict:
            x = np.asarray(p[self.get("features_col")], np.float32)
            q = dict(p)
            if len(x) == 0:
                q[self.get("score_col")] = np.zeros(0, np.float64)
                q[self.get("prediction_col")] = np.zeros(0, np.float64)
                return q
            scores = self._scores(x)
            q[self.get("score_col")] = scores.astype(np.float64)
            q[self.get("prediction_col")] = (
                scores >= self.get("score_threshold")
            ).astype(np.float64)
            return q

        return df.map_partitions(fn, parallel=False)
