"""ExperimentController: ASHA over the fleet (``fleet tune``).

The controller owns no truth. Every decision it makes — who reported
what, who advances, who won — is derived from registry records and
committed back as a write-once generation-CAS record, so a SIGKILLed
controller restarted cold resumes the experiment mid-rung from registry
state alone, and a split-brain twin derives the identical promotion set
(pure ASHA math, seeded ties) and simply adopts the CAS incumbent.

What it DOES own: processes and bytes. Trials are supervisor charges
(:class:`~mmlspark_tpu.serving.supervisor.WorkerCharge`) spawned through
the same pluggable ``--spawn-cmd`` template the supervisor uses, so
placement is an operator concern; the controller respawns charges that
die unclassified (SIGKILL, wedge) and reaps the demoted. And it
replicates every reported checkpoint/model artifact into its OWN store
as soon as the report lands — trial processes exit, their artifact
servers with them, but the controller keeps advertising the bytes a
rescheduled trial (or the winner publication) will need.

Accounting is per-controller and classified exactly once per charge
death, which is what makes the invariant law exact::

    trials_spawned == completed + demoted + rescheduled + running
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.experiments import asha, records
from mmlspark_tpu.experiments.trial import (
    EXIT_DEMOTED,
    params_json,
)

_M_SPAWNS = obs.counter(
    "mmlspark_experiments_trials_spawned_total",
    "Trial charges spawned (incarnations, not distinct trials)",
)
_M_PROMOTIONS = obs.counter(
    "mmlspark_experiments_promotions_total",
    "Rung promotion records by result (committed | adopted)",
    labels=("result",),
)
_M_DEMOTIONS = obs.counter(
    "mmlspark_experiments_demotions_total",
    "Trial charges classified demoted (self-exited or reaped)",
)
_M_RESCHEDULES = obs.counter(
    "mmlspark_experiments_reschedules_total",
    "Trial charges that died unclassified and were respawned",
)
_M_RUNGS = obs.gauge(
    "mmlspark_experiments_rungs_committed_count",
    "Rung promotion records visible in the registry",
)
_M_EXPERIMENT_S = obs.histogram(
    "mmlspark_experiments_experiment_seconds",
    "Wall-clock of one full experiment (first spawn to winner)",
    buckets=(1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0),
)


class ExperimentError(Exception):
    """The experiment cannot make progress (reschedule budget spent,
    wall-clock deadline passed)."""


def default_space() -> list:
    """The stock GBDT search space (restricted to trial-legal params)."""
    from mmlspark_tpu.automl.hyperparams import (
        DiscreteHyperParam,
        RangeHyperParam,
    )

    return [
        ("num_leaves", DiscreteHyperParam([7, 15, 31])),
        ("learning_rate", RangeHyperParam(0.05, 0.3, log=True)),
        ("min_data_in_leaf", DiscreteHyperParam([5, 10, 20])),
    ]


def space_from_json(obj: dict) -> list:
    """CLI search-space JSON -> ``RandomSpace`` pairs: a list is a
    :class:`DiscreteHyperParam`, ``{"low", "high", "log"?, "int"?}`` a
    :class:`RangeHyperParam`."""
    from mmlspark_tpu.automl.hyperparams import (
        DiscreteHyperParam,
        RangeHyperParam,
    )

    out: list = []
    for name, spec in sorted(obj.items()):
        if isinstance(spec, list):
            out.append((name, DiscreteHyperParam(spec)))
        elif isinstance(spec, dict) and "low" in spec and "high" in spec:
            out.append((name, RangeHyperParam(
                spec["low"], spec["high"],
                is_int=bool(spec.get("int")), log=bool(spec.get("log")),
            )))
        else:
            raise ValueError(
                f"space entry {name!r}: want a value list or "
                '{"low": .., "high": .., "log"?: bool, "int"?: bool}'
            )
    return out


def sample_trials(space: list, n_trials: int, seed: int) -> dict:
    """``{trial_name: param_map}`` — pure in (space, n, seed), so a
    restarted controller regenerates the byte-identical spawn argvs."""
    from mmlspark_tpu.automl.hyperparams import RandomSpace

    draws = list(RandomSpace(space, seed=seed).param_maps(n_trials))
    return {f"t{i:03d}": dict(pm) for i, pm in enumerate(draws)}


class ExperimentController:
    def __init__(
        self,
        registry_url: Any,
        experiment: str,
        n_trials: int = 6,
        space: Optional[list] = None,
        data: str = "synth:512x8:1",
        valid: str = "synth:256x8:99",
        min_iters: int = 2,
        max_iters: int = 8,
        eta: int = 2,
        seed: int = 0,
        higher_is_better: bool = True,
        workdir: Optional[str] = None,
        spawn_cmd: Optional[str] = None,
        placement: Any = None,
        python: Optional[str] = None,
        tick_s: float = 0.25,
        heartbeat_s: float = 0.5,
        poll_s: float = 0.25,
        decision_timeout_s: float = 120.0,
        partitions: int = 4,
        max_reschedules: int = 5,
        publish_model: Optional[str] = None,
        publish_service: str = "serving",
        publish_epoch: Optional[int] = None,
        status_file: Optional[str] = None,
        deadline_s: float = 600.0,
    ):
        from mmlspark_tpu.serving.fleet import split_registry_urls
        from mmlspark_tpu.serving.supervisor import (
            placement_from_spec,
            spawn_from_template,
        )

        if n_trials < 1:
            raise ValueError("n_trials must be >= 1")
        self.urls = split_registry_urls(registry_url)
        if not self.urls:
            raise ValueError("fleet tune needs --registry")
        self.experiment = experiment
        self.boundaries = asha.rung_boundaries(min_iters, max_iters, eta)
        self.min_iters, self.max_iters, self.eta = (
            int(min_iters), int(max_iters), int(eta),
        )
        self.seed = int(seed)
        self.higher_is_better = bool(higher_is_better)
        self.params = sample_trials(
            space if space is not None else default_space(),
            n_trials, self.seed,
        )
        self.trials = sorted(self.params)
        self.data, self.valid = data, valid
        self.workdir = workdir or os.path.join(
            os.getcwd(), f".experiments-{experiment}"
        )
        # trial placement mirrors the supervisor's hook: a
        # PlacementProvider (or its --placement spec string) decides
        # where trial processes land — remotely-placed trials publish
        # rung reports and model bytes through the artifact plane, so
        # the controller never needs to share a filesystem with them
        if isinstance(placement, str):
            placement = placement_from_spec(placement)
        if placement is not None:
            self._spawn_fn = placement.spawn
        else:
            self._spawn_fn = (
                spawn_from_template(spawn_cmd) if spawn_cmd
                else lambda argv: subprocess.Popen(argv)
            )
        self.python = python
        self.tick_s = tick_s
        self.heartbeat_s = heartbeat_s
        self.poll_s = poll_s
        self.decision_timeout_s = decision_timeout_s
        self.partitions = int(partitions)
        self.max_reschedules = int(max_reschedules)
        self.publish_model = publish_model
        self.publish_service = publish_service
        self.publish_epoch = publish_epoch
        self.status_file = status_file
        self.deadline_s = float(deadline_s)
        # charge bookkeeping (per-controller, per the conservation law)
        self.charges: dict = {}       # trial -> WorkerCharge (latest)
        self.incarnations: dict = {}  # trial -> spawn count
        self.spawned = 0
        self.completed = 0
        self.demoted = 0
        self.rescheduled = 0
        self.published = False
        self._publisher: Any = None
        self._store: Any = None
        self._server: Any = None

    # -- infrastructure -------------------------------------------------------

    def _ensure_artifact_plane(self) -> None:
        from mmlspark_tpu.serving.artifacts import ArtifactServer, ArtifactStore

        if self._store is None:
            os.makedirs(self.workdir, exist_ok=True)
            self._store = ArtifactStore(
                os.path.join(self.workdir, "controller-artifacts")
            )
            self._server = ArtifactServer(
                self._store, registry_urls=self.urls,
                service=f"{self.experiment}-artifacts",
                heartbeat_s=self.heartbeat_s,
            )

    def close(self) -> None:
        for charge in self.charges.values():
            if charge.alive():
                charge.proc.kill()
                charge.proc.wait()
        if self._server is not None:
            self._server.stop()
            self._server = None

    # -- charges --------------------------------------------------------------

    def _trial_argv(self, trial: str, incarnation: int) -> list:
        argv = [
            self.python or sys.executable, "-m",
            "mmlspark_tpu.serving.fleet", "trial",
            "--registry", ",".join(self.urls),
            "--experiment", self.experiment,
            "--trial", trial,
            "--params", params_json(self.params[trial]),
            "--data", self.data,
            "--valid", self.valid,
            "--workdir", os.path.join(
                self.workdir, trial, f"i{incarnation:02d}"
            ),
            "--min-iters", str(self.min_iters),
            "--max-iters", str(self.max_iters),
            "--eta", str(self.eta),
            "--seed", str(self.seed),
            "--heartbeat-s", str(self.heartbeat_s),
            "--poll-s", str(self.poll_s),
            "--decision-timeout-s", str(self.decision_timeout_s),
            "--partitions", str(self.partitions),
        ]
        if not self.higher_is_better:
            argv.append("--lower-is-better")
        return argv

    def _spawn(self, trial: str) -> None:
        from mmlspark_tpu.serving.supervisor import WorkerCharge

        faults.inject(
            "experiment.spawn",
            context={"experiment": self.experiment, "trial": trial},
        )
        inc = self.incarnations.get(trial, 0) + 1
        if inc - 1 > self.max_reschedules:
            raise ExperimentError(
                f"trial {trial} exhausted its reschedule budget "
                f"({self.max_reschedules})"
            )
        self.incarnations[trial] = inc
        charge = WorkerCharge(
            self._trial_argv(trial, inc),
            name=f"{self.experiment}-{trial}-i{inc:02d}",
        )
        charge.proc = self._spawn_fn(charge.argv)
        charge.started_at = time.monotonic()
        self.charges[trial] = charge
        self.spawned += 1
        _M_SPAWNS.inc()

    def _is_live_elsewhere(
        self, trial: str, state: records.ExperimentState
    ) -> bool:
        """A fresh liveness heartbeat from an incarnation we do not hold
        (an orphan of a previous controller) — never double-spawn it."""
        entry = state.live.get(trial)
        if entry is None:
            return False
        ts = float(entry.get("ts") or 0.0)
        return time.time() - ts < max(3.0 * self.heartbeat_s, 2.0)

    def _classify_dead(
        self, trial: str, rc: Optional[int],
        state: records.ExperimentState,
    ) -> str:
        final = len(self.boundaries) - 1
        if (trial, final) in state.reports:
            return "completed"
        if rc == EXIT_DEMOTED or asha.is_demoted(
            trial, len(self.boundaries), state.rungs
        ):
            return "demoted"
        return "rescheduled"

    def _reap_and_respawn(self, state: records.ExperimentState) -> None:
        for trial in self.trials:
            charge = self.charges.get(trial)
            if charge is not None and not charge.alive():
                rc = charge.proc.poll() if charge.proc else None
                del self.charges[trial]
                kind = self._classify_dead(trial, rc, state)
                if kind == "completed":
                    self.completed += 1
                elif kind == "demoted":
                    self.demoted += 1
                    _M_DEMOTIONS.inc()
                else:
                    self.rescheduled += 1
                    _M_RESCHEDULES.inc()
            if trial in self.charges:
                continue  # alive
            if asha.next_rung(
                trial, state.reports, self.boundaries
            ) is None:
                continue  # experiment-complete for this trial
            if asha.is_demoted(trial, len(self.boundaries), state.rungs):
                continue
            if self._is_live_elsewhere(trial, state):
                continue  # an orphan incarnation is still working
            self._spawn(trial)

    def _reap_demoted(self, state: records.ExperimentState) -> None:
        """Stop live charges of demoted trials; classification happens
        at the next reap pass (their registry state says demoted)."""
        for trial, charge in self.charges.items():
            if charge.alive() and asha.is_demoted(
                trial, len(self.boundaries), state.rungs
            ):
                charge.proc.terminate()

    # -- artifacts ------------------------------------------------------------

    def _replicate(self, state: records.ExperimentState) -> None:
        """Pull every reported checkpoint/model blob we do not yet hold
        into the controller store. Trial servers are ephemeral; this
        store is what outlives them (reschedule + winner publication)."""
        from mmlspark_tpu.serving.artifacts import registry_peers

        self._ensure_artifact_plane()
        for (trial, rung), rec in sorted(state.reports.items()):
            for key, suffix in (("ckpt", "-ckpt"), ("model", ".gbdt.json")):
                digest = rec.get(key)
                if not digest or self._store.has(digest):
                    continue
                peers = [
                    p for p in registry_peers(self.urls, digest)
                    if p != self._server.url
                ]
                if not peers:
                    continue  # advertiser gone; re-derived on reschedule
                try:
                    self._store.fetch(
                        digest, peers, name=f"{trial}-r{rung}{suffix}",
                        timeout_s=10.0,
                    )
                except Exception:  # noqa: BLE001 — retried next tick
                    pass

    def _recover_winner(self, state: records.ExperimentState) -> None:
        """The PR 17 stranded-winner residual, closed: a successor
        controller that finds ``<exp>-winner-gen`` committed but holds
        none of the model bytes re-pulls them by digest — the record's
        spec hints first (they name the holders that confirmed at commit
        time), then every registry-advertised peer. Only when NOBODY
        advertises the digest does it fall back to the deterministic
        retrain: respawn the winner trial, whose same params + seed
        re-derive the byte-identical model under the exact committed
        digest (experiments/trial.py re-runs the final rung when it is
        the unadvertised committed winner)."""
        if state.winner is None or self._store is None:
            return
        digest = state.winner.get("model")
        if not digest or self._store.has(digest):
            return
        own = self._server.url if self._server is not None else None
        hints: list = []
        tail = (state.winner.get("spec") or "").rsplit("@", 1)[-1]
        if tail.startswith("http"):
            hints = [u for u in tail.split(",") if u and u != own]
        from mmlspark_tpu.serving.artifacts import registry_peers

        peers = hints + [
            p for p in registry_peers(self.urls, digest)
            if p != own and p not in hints
        ]
        if peers:
            try:
                self._store.fetch(
                    digest, peers,
                    name=f"{state.winner.get('trial', 'winner')}.gbdt.json",
                    timeout_s=10.0,
                )
                self._server.heartbeat()  # advertise the recovered copy
                return
            except Exception:  # noqa: BLE001 — every peer gone: retrain
                pass
        trial = state.winner.get("trial")
        if (
            trial and trial in self.params
            and trial not in self.charges
            and not self._is_live_elsewhere(trial, state)
        ):
            self._spawn(trial)

    # -- decisions ------------------------------------------------------------

    def _survivors(self, rung: int, state: records.ExperimentState) -> list:
        trials = list(self.trials)
        for r in range(rung):
            rec = state.rungs.get(r)
            if rec is None:
                return []  # earlier rung undecided: nobody is at `rung`
            trials = [t for t in trials if t in rec.get("promoted", ())]
        return trials

    def _promote_ready_rungs(self, state: records.ExperimentState) -> None:
        for rung in range(len(self.boundaries)):
            if rung in state.rungs:
                continue
            survivors = self._survivors(rung, state)
            if not survivors:
                return
            metrics = state.rung_metrics(survivors, rung)
            if set(metrics) != set(survivors):
                return  # reports still outstanding; nothing deeper ready
            faults.inject(
                "experiment.promote",
                context={"experiment": self.experiment, "rung": rung},
            )
            promoted, board = asha.promote(
                metrics, self.eta, self.seed, self.higher_is_better
            )
            rec = asha.rung_record(
                rung, promoted, board, self.eta, self.seed
            )
            committed, current = records.cas_commit(
                self.urls, records.rung_record_name(self.experiment, rung),
                rec,
            )
            _M_PROMOTIONS.labels(
                result="committed" if committed else "adopted"
            ).inc()
            state.rungs[rung] = rec if committed else current
            return  # one decision per tick; reaping runs before the next

    def _commit_winner(self, state: records.ExperimentState) -> None:
        final = len(self.boundaries) - 1
        frec = state.rungs.get(final)
        if frec is None or state.winner is not None:
            return
        winner = frec["promoted"][0]
        report = state.reports.get((winner, final))
        if report is None:
            return
        if self._store is not None and not self._store.has(
            report["model"]
        ):
            # the winner record is only committed once WE hold the model
            # bytes: the winner trial lingers (advertising them) until
            # the record appears, so committing first would tear down
            # the last advertiser before replication — retried next tick
            return
        # replicate-before-commit: push the winner bytes to every other
        # rostered artifact plane (serving workers, lingering trials)
        # BEFORE the record lands, and bake the confirmed holders into
        # the record's spec hints — a controller SIGKILLed right after
        # this commit strands nothing a successor (or a worker's own
        # resolve path) cannot re-pull. Best-effort by design: with no
        # other holders on the roster our store + the lingering trial
        # still cover the normal path, and the successor's
        # deterministic-retrain fallback covers the rest.
        confirmed: list = []
        if self._store is not None:
            from mmlspark_tpu.serving.artifacts import registry_holders

            own = [self._server.url] if self._server is not None else []
            try:
                # exclude the experiment's own ephemeral plane: a
                # replica confirmed on a lingering trial (or this very
                # controller) dies with the experiment — only DURABLE
                # holders (serving workers, gang members) count
                holders = registry_holders(
                    self.urls, exclude=own,
                    exclude_services=[f"{self.experiment}-artifacts"],
                )
                if holders:
                    confirmed = self._store.replicate(
                        report["model"], holders,
                        need=min(1, len(holders)), timeout_s=10.0,
                    )
            except Exception:  # noqa: BLE001 — below quorum: commit
                confirmed = []  # proceeds on the local + trial copies
        spec = (
            f"artifact:gbdt:{winner}-r{final}.gbdt.json@{report['model']}"
        )
        hints = [self._server.url] if self._server is not None else []
        hints += [u for u in confirmed if u not in hints]
        if hints:
            spec += "@" + ",".join(hints)
        rec = {
            "trial": winner,
            "metric": float(report["metric"]),
            "model": report["model"],
            "params": dict(report.get("params") or {}),
            "spec": spec,
        }
        committed, current = records.cas_commit(
            self.urls, records.winner_record_name(self.experiment), rec,
        )
        state.winner = rec if committed else current

    def _publish_winner(self, state: records.ExperimentState) -> None:
        if (
            self.published or state.winner is None
            or not self.publish_model
        ):
            return
        from mmlspark_tpu.online.publisher import Publisher, PublishError

        if self._publisher is None:
            self._publisher = Publisher(
                model=self.publish_model,
                registry_url=",".join(self.urls),
                service_name=self.publish_service,
                epoch=self.publish_epoch,
            )
        spec = state.winner["spec"]
        if self._server is not None and not spec.rsplit(
            "@", 1
        )[-1].startswith("http"):
            # a winner record adopted from a dead controller hints that
            # controller's (gone) ingress — re-hint our own replica
            if self._store is not None and self._store.has(
                state.winner["model"]
            ):
                spec += f"@{self._server.url}"
        try:
            self._publisher.publish_spec(spec)
            self.published = True
        except PublishError:
            pass  # workers may still be warming; retried next tick

    # -- status ---------------------------------------------------------------

    def running(self) -> int:
        """Spawned and not yet classified — NOT process-alive: a charge
        that died microseconds ago still counts as running until the
        reap pass classifies it, which is what keeps the conservation
        law exact in every status snapshot (every spawn adds exactly one
        charge entry, every classification removes exactly one)."""
        return len(self.charges)

    def status(self, state: Optional[records.ExperimentState]) -> dict:
        rungs = dict(state.rungs) if state is not None else {}
        return {
            "experiment": self.experiment,
            "seed": self.seed,
            "eta": self.eta,
            "boundaries": list(self.boundaries),
            "trials": len(self.trials),
            "trials_spawned": self.spawned,
            "completed": self.completed,
            "demoted": self.demoted,
            "rescheduled": self.rescheduled,
            "running": self.running(),
            "rungs": {
                str(r): list(rec.get("promoted", ()))
                for r, rec in sorted(rungs.items())
            },
            "winner": (
                dict(state.winner)
                if state is not None and state.winner else None
            ),
            "published": self.published,
            "ts": time.time(),
        }

    def _write_status(self, state: Optional[records.ExperimentState]) -> None:
        if not self.status_file:
            return
        tmp = self.status_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.status(state), f, sort_keys=True)
        os.replace(tmp, self.status_file)

    # -- the loop -------------------------------------------------------------

    def tick(self) -> Optional[records.ExperimentState]:
        """One reconcile pass; returns the state it acted on (None when
        no registry answered — nothing was decided this tick)."""
        try:
            state = records.read_state(self.urls, self.experiment)
        except records.ExperimentWireError:
            self._write_status(None)
            return None
        self._replicate(state)
        self._promote_ready_rungs(state)
        _M_RUNGS.set(len(state.rungs))
        self._reap_demoted(state)
        self._reap_and_respawn(state)
        self._recover_winner(state)
        self._commit_winner(state)
        self._publish_winner(state)
        self._write_status(state)
        return state

    def done(self, state: Optional[records.ExperimentState]) -> bool:
        if state is None or state.winner is None:
            return False
        if self.publish_model and not self.published:
            return False
        return self.running() == 0

    def run(self) -> dict:
        """Drive the experiment to a published winner; returns the final
        status dict (plus the canonical leaderboard bytes digest)."""
        import hashlib

        t0 = time.monotonic()
        deadline = t0 + self.deadline_s
        self._ensure_artifact_plane()
        state: Optional[records.ExperimentState] = None
        with obs.span(
            "experiment.run",
            attrs={
                "experiment": self.experiment,
                "trials": len(self.trials),
            },
        ):
            while True:
                state = self.tick()
                if self.done(state):
                    break
                if time.monotonic() > deadline:
                    self.close()
                    raise ExperimentError(
                        f"experiment {self.experiment} missed its "
                        f"{self.deadline_s:.0f}s deadline"
                    )
                time.sleep(self.tick_s)
        _M_EXPERIMENT_S.observe(time.monotonic() - t0)
        out = self.status(state)
        out["leaderboard_sha256"] = hashlib.sha256(
            asha.leaderboard_bytes(state.rungs)
        ).hexdigest()
        print(
            f"tune: {self.experiment} winner {out['winner']['trial']} "
            f"metric {out['winner']['metric']:.4f} "
            f"leaderboard sha256 {out['leaderboard_sha256']}",
            flush=True,
        )
        return out


__all__ = [
    "ExperimentController",
    "ExperimentError",
    "default_space",
    "sample_trials",
    "space_from_json",
]
