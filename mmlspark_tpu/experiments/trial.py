"""One ASHA trial: a single continuous process spanning rung boundaries.

A trial is a supervisor-charge-shaped process (``fleet trial``) the
controller spawns through the same pluggable ``--spawn-cmd`` hook every
other charge uses — placement is the supervisor's business, not ours.
Per rung it runs the REAL ``fleet train`` machinery (an elastic gang of
world size 1 by default) to the rung's cumulative iteration boundary,
evaluates on the held-out spec, packs its checkpoint dir and model
string into its own content-addressed store, CAS-reports
``(metric, ckpt digest, model digest)`` to the registry, then polls for
the rung's promotion record: promoted → train on to the next boundary
in-process; demoted → exit cleanly; record never arrives → exit with
the reschedule code and let the controller decide.

Rescheduling is digest-deep: a respawned trial (fresh workdir, possibly
a different host) finds its last report in the registry, fetches that
rung's checkpoint artifact from whoever advertises it, unpacks it into
its empty checkpoint dir, and trains on — checkpoint restore is exact,
so the rescheduled trial reproduces the booster (and therefore the
metric) the uninterrupted trial would have reported. That determinism
is what makes the chaos drill's byte-identical-leaderboard claim true
rather than hopeful.

Exit codes (the controller's classification input):
``0`` completed (final rung reported) · ``4`` demoted (self-reaped) ·
``3`` rung decision never arrived (controller restarts or reaps).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional

import numpy as np

from mmlspark_tpu import obs
from mmlspark_tpu.experiments import asha, records

EXIT_COMPLETED = 0
EXIT_NO_DECISION = 3
EXIT_DEMOTED = 4

# hyperparameters a search space may legally bind — everything else in a
# sampled param map is a spawn-argv bug, rejected loudly (same contract
# TuneHyperparameters.fit enforces on estimator params)
TRAIN_PARAMS = (
    "num_leaves", "learning_rate", "min_data_in_leaf", "num_iterations",
)

_M_REPORTS = obs.counter(
    "mmlspark_experiments_reports_total",
    "Trial rung reports by result (committed | adopted | error)",
    labels=("result",),
)
_M_RUNG_SECONDS = obs.histogram(
    "mmlspark_experiments_rung_train_seconds",
    "Wall-clock of one trial's train-to-rung-boundary step",
    buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0),
)


def holdout_metric(booster: Any, x: np.ndarray, y: np.ndarray) -> float:
    """Validation accuracy — deterministic in (model, data), which the
    drill's leaderboard-equivalence property requires. ``predict`` gives
    raw margins for the binary objective; the decision boundary is 0."""
    margin = np.asarray(booster.predict(x), dtype=np.float64)
    return float(np.mean((margin > 0.0) == (np.asarray(y) > 0.5)))


def _live_loop(
    urls: list, exp: str, trial: str, stop: threading.Event,
    heartbeat_s: float,
) -> None:
    info = {
        "name": records.live_service_name(exp),
        "host": trial,
        "port": os.getpid(),
    }
    while not stop.is_set():
        records.register(urls, info, timeout=2.0)
        stop.wait(heartbeat_s)


def _report_with_retry(
    urls: list, exp: str, trial: str, rung: int, metric: float,
    ckpt_digest: str, model_digest: str, iters: int, params: dict,
    attempts: int = 5, backoff_s: float = 0.2,
) -> Optional[dict]:
    """Rung reports must land: retry through injected faults and wire
    loss (the ``experiment.report`` chaos drill arms exactly this path).
    Returns the durable record, or None when every attempt failed."""
    for i in range(attempts):
        try:
            rec = records.report_trial(
                urls, exp, trial, rung, metric,
                ckpt_digest, model_digest, iters, params,
            )
            _M_REPORTS.labels(
                result="committed" if rec.get("ckpt") == ckpt_digest
                else "adopted"
            ).inc()
            return rec
        except Exception:  # noqa: BLE001 — injected or real, retry either
            _M_REPORTS.labels(result="error").inc()
            time.sleep(backoff_s * (i + 1))
    return None


def run_trial(
    registry_url: Any,
    experiment: str,
    trial: str,
    params: dict,
    data: str,
    valid: str,
    workdir: str,
    min_iters: int = 2,
    max_iters: int = 8,
    eta: int = 2,
    seed: int = 0,
    higher_is_better: bool = True,
    heartbeat_s: float = 0.5,
    poll_s: float = 0.25,
    decision_timeout_s: float = 120.0,
    partitions: int = 4,
    status_file: Optional[str] = None,
) -> int:
    """``fleet trial``: run one trial across every rung it survives."""
    from mmlspark_tpu.parallel.elastic import load_training_data
    from mmlspark_tpu.serving.artifacts import (
        ArtifactServer,
        ArtifactStore,
        registry_peers,
        unpack_dir,
    )
    from mmlspark_tpu.serving.fleet import run_train, split_registry_urls

    bad = sorted(k for k in params if k not in TRAIN_PARAMS)
    if bad:
        raise ValueError(
            f"trial {trial}: sampled param(s) {bad} are not train "
            f"hyperparameters {list(TRAIN_PARAMS)}"
        )
    urls = split_registry_urls(registry_url)
    obs.set_process_label(f"{experiment}-{trial}")
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, "ckpt")
    store = ArtifactStore(os.path.join(workdir, "artifacts"))
    server = ArtifactServer(
        store, registry_urls=urls,
        service=f"{experiment}-artifacts", heartbeat_s=heartbeat_s,
    )
    stop = threading.Event()
    threading.Thread(
        target=_live_loop, args=(urls, experiment, trial, stop, heartbeat_s),
        name=f"{trial}-live", daemon=True,
    ).start()
    try:
        return _run_rungs(
            urls, experiment, trial, params, data, valid, ckpt_dir,
            store, server, min_iters, max_iters, eta, seed,
            higher_is_better, heartbeat_s, poll_s, decision_timeout_s,
            partitions, status_file, registry_peers, unpack_dir,
            run_train, load_training_data,
        )
    finally:
        from mmlspark_tpu.obs import watchdog

        watchdog.disarm("experiment.rung")
        stop.set()
        server.stop()


def _run_rungs(
    urls, experiment, trial, params, data, valid, ckpt_dir, store,
    server, min_iters, max_iters, eta, seed, higher_is_better,
    heartbeat_s, poll_s, decision_timeout_s, partitions, status_file,
    registry_peers, unpack_dir, run_train, load_training_data,
) -> int:
    boundaries = asha.rung_boundaries(min_iters, max_iters, eta)
    state = _read_state_retry(urls, experiment, decision_timeout_s, poll_s)
    if state is None:
        return EXIT_NO_DECISION
    rung = asha.next_rung(trial, state.reports, boundaries)
    if rung is None:
        # a twin already finished this trial — but if WE are the
        # committed winner and the model bytes have no live advertiser
        # (a controller died between winner-commit and publish, taking
        # its store with it; a successor respawned us to recover), the
        # bytes must be RE-DERIVED: re-run the final rung — training is
        # deterministic (same params, same seed), so the byte-identical
        # model lands back under the exact committed digest — and
        # linger until a replica lands elsewhere (docs/robustness.md).
        w = state.winner
        if (
            w is not None and w.get("trial") == trial and w.get("model")
            and not [
                p for p in registry_peers(urls, w["model"])
                if p != server.url
            ]
        ):
            rung = len(boundaries) - 1
        else:
            return EXIT_COMPLETED
    if asha.is_demoted(trial, rung, state.rungs):
        return EXIT_DEMOTED
    if rung > 0 and not os.path.exists(os.path.join(ckpt_dir, "LATEST")):
        # rescheduled incarnation: pull our own last rung checkpoint by
        # digest from whoever advertises it and train on from there
        prev = state.reports[(trial, rung - 1)]
        try:
            blob = store.fetch(
                prev["ckpt"], registry_peers(urls, prev["ckpt"]),
                name=f"{trial}-r{rung - 1}-ckpt",
                timeout_s=decision_timeout_s,
            )
            unpack_dir(blob, ckpt_dir)
        except Exception:  # noqa: BLE001 — nobody advertises the bytes
            # retrain from round 0 to the boundary instead: checkpointed
            # training is deterministic, so the rung metric (and the
            # leaderboard) is unchanged — only wall-clock suffers
            pass
    xv, yv = load_training_data(valid)
    from mmlspark_tpu.obs import watchdog
    while rung is not None:
        t0 = time.monotonic()
        # stall forensics: a rung whose report never lands (wedged train
        # gang, dead controller) auto-dumps all-thread stacks well after
        # the controller's own decision timeout would have fired
        watchdog.tick(
            "experiment.rung", deadline_s=max(
                watchdog.DEFAULT_DEADLINE_S, 3 * decision_timeout_s,
            ),
        )
        with obs.span(
            "experiment.rung",
            attrs={"experiment": experiment, "trial": trial, "rung": rung},
        ):
            booster = run_train(
                ",".join(urls), trial, data, ckpt_dir,
                partitions=partitions, world_size=1,
                service_name=f"{experiment}-{trial}",
                num_iterations=int(boundaries[rung]),
                checkpoint_every=1, heartbeat_s=heartbeat_s,
                seed=seed, status_file=status_file,
                # a twin incarnation (controller respawn racing a live
                # orphan) must NEVER grow into this gang: two members
                # co-training would change the model and break the
                # leaderboard's same-seed determinism
                allow_growback=False,
                **{k: v for k, v in params.items()
                   if k != "num_iterations"},
            )
        _M_RUNG_SECONDS.observe(time.monotonic() - t0)
        metric = holdout_metric(booster, xv, yv)
        ck = store.put(ckpt_dir, name=f"{trial}-r{rung}-ckpt")
        model = store.put_bytes(
            booster.to_model_string().encode(),
            name=f"{trial}-r{rung}.gbdt.json",
        )
        server.heartbeat()  # advertise the new digests before reporting
        rec = _report_with_retry(
            urls, experiment, trial, rung, metric,
            ck.digest, model.digest, int(boundaries[rung]), params,
        )
        if rec is None:
            return EXIT_NO_DECISION
        if rung == len(boundaries) - 1:
            # linger until the winner record lands: the controller must
            # replicate this trial's model bytes from OUR artifact server
            # before it commits the winner (exiting now would strand the
            # digest with no advertiser — the publish path would starve)
            _await_winner(urls, experiment, poll_s, decision_timeout_s)
            # and if the committed winner names OUR bytes, hold the
            # server open until some OTHER peer advertises the digest —
            # exiting while we are the only advertiser re-opens the
            # stranded-winner window this linger exists to close
            _await_replica(
                urls, experiment, trial, model.digest, server,
                registry_peers, poll_s, decision_timeout_s,
            )
            return EXIT_COMPLETED
        verdict = _await_decision(
            urls, experiment, trial, rung, poll_s, decision_timeout_s,
        )
        if verdict is None:
            return EXIT_NO_DECISION
        if not verdict:
            return EXIT_DEMOTED
        rung += 1
    return EXIT_COMPLETED


def _read_state_retry(
    urls: list, exp: str, timeout_s: float, poll_s: float
) -> Optional[records.ExperimentState]:
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            return records.read_state(urls, exp)
        except records.ExperimentWireError:
            if time.monotonic() > deadline:
                return None
            time.sleep(poll_s)


def _await_winner(
    urls: list, exp: str, poll_s: float, timeout_s: float
) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            if records.read_state(urls, exp).winner is not None:
                return
        except records.ExperimentWireError:
            pass
        time.sleep(poll_s)


def _await_replica(
    urls: list, exp: str, trial: str, digest: str, server: Any,
    registry_peers: Any, poll_s: float, timeout_s: float,
) -> None:
    """Linger while this process is the committed winner's ONLY
    advertiser: return once another peer (the controller's store, a
    worker, a successor controller) advertises ``digest`` — or the
    bounded timeout passes. A controller killed between winner-commit
    and publish leaves a successor that must re-pull these exact bytes;
    this window is what it pulls through."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            state = records.read_state(urls, exp)
        except records.ExperimentWireError:
            state = None
        if state is None or state.winner is None:
            return  # record gone / unreadable: nothing left to guard
        if (
            state.winner.get("trial") != trial
            or state.winner.get("model") != digest
        ):
            return  # not our bytes: not our guard
        try:
            others = [
                p for p in registry_peers(urls, digest) if p != server.url
            ]
        except Exception:  # noqa: BLE001 — registry blinked; poll again
            others = []
        if others:
            return
        server.heartbeat()  # keep the advertisement fresh meanwhile
        time.sleep(poll_s)


def _await_decision(
    urls: list, exp: str, trial: str, rung: int,
    poll_s: float, timeout_s: float,
) -> Optional[bool]:
    """Poll for rung ``rung``'s promotion record: True promoted, False
    demoted, None when no decision landed inside the timeout."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            state = records.read_state(urls, exp)
        except records.ExperimentWireError:
            state = None
        if state is not None:
            rec = state.rungs.get(rung)
            if rec is not None:
                return trial in rec.get("promoted", ())
        if time.monotonic() > deadline:
            return None
        time.sleep(poll_s)


def params_json(params: dict) -> str:
    """Canonical argv form of a sampled param map — byte-stable, so a
    restarted controller rebuilds the identical spawn command."""
    return json.dumps(params, sort_keys=True, separators=(",", ":"))


__all__ = [
    "EXIT_COMPLETED",
    "EXIT_DEMOTED",
    "EXIT_NO_DECISION",
    "TRAIN_PARAMS",
    "holdout_metric",
    "params_json",
    "run_trial",
]
