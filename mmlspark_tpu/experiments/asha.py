"""ASHA successive-halving rung math, as pure functions.

Everything the controller decides — rung boundaries, promotion sets,
leaderboards — lives here with no I/O, no clocks and no randomness
beyond an explicit seed, because the split-brain story depends on it:
two controllers (or one controller restarted mid-experiment) that see
the same registry records MUST derive byte-identical decisions, so the
generation-CAS commit is the only arbiter ever needed. Ties are broken
by a seeded hash of the trial name, not dict order or float whims.
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional


def rung_boundaries(min_iters: int, max_iters: int, eta: int) -> list:
    """Cumulative training-iteration boundaries of each rung.

    Geometric schedule ``min_iters * eta^k`` capped by ``max_iters``;
    when the budget is not a power of eta the final rung lands at
    ``max_iters`` itself (the budget is spent, not rounded away):
    ``(2, 8, 2) -> [2, 4, 8]``, ``(2, 20, 3) -> [2, 6, 18, 20]``.
    """
    min_iters, max_iters, eta = int(min_iters), int(max_iters), int(eta)
    if min_iters < 1 or max_iters < min_iters:
        raise ValueError(
            f"bad rung budget min_iters={min_iters} max_iters={max_iters}"
        )
    if eta < 2:
        raise ValueError(f"eta must be >= 2, got {eta}")
    bounds: list = []
    b = min_iters
    while b < max_iters:
        bounds.append(b)
        b *= eta
    bounds.append(max_iters)
    return bounds


def n_promote(n_survivors: int, eta: int) -> int:
    """How many of ``n_survivors`` advance: top ``1/eta``, floor 1 —
    a rung never strands the experiment with zero survivors."""
    if n_survivors < 1:
        raise ValueError("a rung needs at least one survivor")
    return max(1, int(n_survivors) // int(eta))


def _tiebreak(seed: int, trial: str) -> str:
    """Deterministic seeded tiebreak token: equal metrics rank by this
    hash, so the promotion set is a pure function of (reports, seed) —
    never of dict iteration order or report arrival order."""
    return hashlib.sha256(f"{seed}:{trial}".encode()).hexdigest()


def leaderboard(
    metrics: dict, seed: int, higher_is_better: bool = True
) -> list:
    """Rank ``{trial: metric}`` into ``[[trial, metric], ...]``, best
    first. Ties break by the seeded trial-name hash (then the name
    itself, for the astronomically unlikely hash tie)."""
    sign = -1.0 if higher_is_better else 1.0
    return [
        [t, float(m)]
        for t, m in sorted(
            metrics.items(),
            key=lambda kv: (
                sign * float(kv[1]), _tiebreak(seed, kv[0]), kv[0],
            ),
        )
    ]


def promote(
    metrics: dict, eta: int, seed: int, higher_is_better: bool = True
) -> tuple:
    """One rung's decision: ``(promoted_trials, leaderboard)``.

    ``promoted_trials`` is the top ``n_promote`` of the leaderboard, in
    rank order — deterministic under seeded ties, so any two controllers
    with the same reports CAS-write the identical record."""
    board = leaderboard(metrics, seed, higher_is_better)
    return [t for t, _ in board[: n_promote(len(board), eta)]], board


def rung_record(
    rung: int, promoted: list, board: list, eta: int, seed: int,
) -> dict:
    """The canonical promotion record CAS-committed for one rung. Field
    order is fixed here so the registry-stored record — and therefore a
    resumed controller's adopted copy — is byte-stable."""
    return {
        "rung": int(rung),
        "promoted": list(promoted),
        "leaderboard": [list(row) for row in board],
        "eta": int(eta),
        "seed": int(seed),
    }


def leaderboard_bytes(rungs: dict) -> bytes:
    """Canonical serialization of every committed rung's leaderboard —
    the byte string the chaos drill compares between a disturbed and an
    undisturbed same-seed run."""
    canon = {
        str(r): {
            "promoted": rec.get("promoted"),
            "leaderboard": rec.get("leaderboard"),
        }
        for r, rec in sorted(rungs.items(), key=lambda kv: int(kv[0]))
    }
    return json.dumps(canon, sort_keys=True, separators=(",", ":")).encode()


def next_rung(
    trial: str, reports: dict, boundaries: list
) -> Optional[int]:
    """The first rung index ``trial`` has not reported, or None when its
    final rung is already in. ``reports`` is keyed ``(trial, rung)``."""
    for r in range(len(boundaries)):
        if (trial, r) not in reports:
            return r
    return None


def is_demoted(trial: str, rung: int, rungs: dict) -> bool:
    """Whether a committed rung record below ``rung`` excludes ``trial``
    — the self-reaping check a waiting trial (and the controller's
    charge reaper) both run against the same registry state."""
    for r in range(int(rung)):
        rec = rungs.get(r)
        if rec is not None and trial not in rec.get("promoted", ()):
            return True
    return False


__all__ = [
    "is_demoted",
    "leaderboard",
    "leaderboard_bytes",
    "n_promote",
    "next_rung",
    "promote",
    "rung_boundaries",
    "rung_record",
]
