"""Experiment state on the registry wire: write-once CAS records.

Every durable fact of an experiment — a trial's rung report, a rung's
promotion set, the winner — is a generation-CAS record (the PR 16
``/generation/commit`` endpoint shape): named ``...-gen``, committed at
``gen=1`` with ``expected_gen=0``, so the FIRST writer wins and every
later attempt gets a 409 carrying the winning record to adopt. That one
property is the whole coordination story: reports from a rescheduled
trial, promotions from a restarted (or twin) controller, and the winner
stamp all converge without locks, and the records are TTL-exempt and
anti-entropy-merged like any other generation record — an experiment
survives registry restarts and partitions exactly as gangs do.

Record names under experiment ``<exp>``::

    <exp>-trial-<trial>-r<rung>-gen   one trial's rung report
    <exp>-rung-<rung>-gen             one rung's promotion record
    <exp>-winner-gen                  the published winner

Trial liveness rides plain (TTL-governed) roster entries under
``<exp>-trials-live`` keyed by trial name — presence means a trial
process is heartbeating somewhere, which is all the controller needs to
avoid double-spawning an orphan it did not itself start.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Optional

from mmlspark_tpu.core import faults


class ExperimentWireError(Exception):
    """No registry majority answered — the caller retries next tick."""


def _post(url: str, path: str, body: dict, timeout: float) -> tuple:
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    resp = send_request(HTTPRequestData(
        url.rstrip("/") + path, "POST",
        {"Content-Type": "application/json"}, json.dumps(body),
    ), timeout=timeout)
    try:
        payload = json.loads(resp["entity"]) if resp["entity"] else {}
    except ValueError:
        payload = {}
    return resp["status_code"], payload


def cas_commit(
    registry_urls: Any,
    name: str,
    record: dict,
    gen: int = 1,
    expected_gen: int = 0,
    timeout: float = 5.0,
) -> tuple:
    """Commit ``record`` under ``name`` on a strict majority of
    registries. Returns ``(committed, current)``: ``(True, None)`` when
    this write won, ``(False, winner_record)`` when an earlier commit
    holds the name (adopt it — by construction it is what a same-seed
    peer derived from the same reports). Raises
    :class:`ExperimentWireError` when no majority of registries
    acknowledged either way (partition/registry loss: retry)."""
    from mmlspark_tpu.serving.fleet import split_registry_urls

    urls = split_registry_urls(registry_urls)
    need = len(urls) // 2 + 1
    acks = 0
    current: Optional[dict] = None
    body = {
        "name": name, "gen": int(gen), "expected_gen": int(expected_gen),
        "record": record,
    }
    for url in urls:
        try:
            code, payload = _post(url, "/generation/commit", body, timeout)
        except Exception:  # noqa: BLE001 — a dead registry is a missing ack
            continue
        if code == 200 and payload.get("committed"):
            acks += 1
        elif code == 409:
            acks += 1  # a definitive answer IS an ack — the name is taken
            if current is None and payload.get("current"):
                current = dict(payload["current"])
    if acks < need:
        raise ExperimentWireError(
            f"{name}: only {acks}/{len(urls)} registries answered "
            f"(need {need})"
        )
    return current is None, current


def register(
    registry_urls: Any, info: dict, timeout: float = 5.0
) -> int:
    """Plain roster POST of ``info`` to every registry; returns how many
    acknowledged (liveness heartbeats — best-effort by design)."""
    from mmlspark_tpu.serving.fleet import split_registry_urls

    ok = 0
    for url in split_registry_urls(registry_urls):
        try:
            code, _ = _post(url, "/", info, timeout)
            ok += code == 200
        except Exception:  # noqa: BLE001 — registry may be restarting
            pass
    return ok


def fetch_roster(registry_urls: Any, timeout: float = 5.0) -> dict:
    """The first answering registry's roster dump (registry HA: the
    anti-entropy loop keeps generation records converged across peers,
    and generation records are all the experiment state there is)."""
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData
    from mmlspark_tpu.serving.fleet import split_registry_urls

    last: Optional[Exception] = None
    for url in split_registry_urls(registry_urls):
        try:
            resp = send_request(
                HTTPRequestData(url.rstrip("/") + "/", "GET"),
                timeout=timeout,
            )
            if resp["status_code"] == 200:
                return json.loads(resp["entity"])
        except Exception as e:  # noqa: BLE001 — try the next registry
            last = e
    raise ExperimentWireError(f"no registry answered a roster read: {last}")


# -- record naming ------------------------------------------------------------


def trial_record_name(exp: str, trial: str, rung: int) -> str:
    return f"{exp}-trial-{trial}-r{int(rung)}-gen"


def rung_record_name(exp: str, rung: int) -> str:
    return f"{exp}-rung-{int(rung)}-gen"


def winner_record_name(exp: str) -> str:
    return f"{exp}-winner-gen"


def live_service_name(exp: str) -> str:
    return f"{exp}-trials-live"


# -- reconstruction -----------------------------------------------------------


@dataclass
class ExperimentState:
    """Everything a controller needs, reconstructed from one roster
    read — the resume-from-registry contract: a restarted controller
    calling :func:`read_state` continues exactly where the records say
    the experiment is, with no local state at all."""

    reports: dict = field(default_factory=dict)  # (trial, rung) -> record
    rungs: dict = field(default_factory=dict)    # rung -> record
    winner: Optional[dict] = None
    live: dict = field(default_factory=dict)     # trial -> roster entry

    def rung_metrics(self, trials: list, rung: int) -> dict:
        """``{trial: metric}`` over the trials that reported ``rung`` —
        the input of :func:`~mmlspark_tpu.experiments.asha.promote`."""
        out = {}
        for t in trials:
            rec = self.reports.get((t, rung))
            if rec is not None:
                out[t] = float(rec["metric"])
        return out


def state_from_roster(exp: str, roster: dict) -> ExperimentState:
    """Pure reconstruction of :class:`ExperimentState` from a registry
    roster dump — separated from the wire read so the resume-equivalence
    property (state built incrementally == state reconstructed) is
    testable without a registry."""
    st = ExperimentState()
    trial_re = re.compile(
        re.escape(exp) + r"-trial-(.+)-r(\d+)-gen$"
    )
    rung_re = re.compile(re.escape(exp) + r"-rung-(\d+)-gen$")
    for name, entries in roster.items():
        if not entries:
            continue
        m = trial_re.match(name)
        if m:
            st.reports[(m.group(1), int(m.group(2)))] = dict(entries[0])
            continue
        m = rung_re.match(name)
        if m:
            st.rungs[int(m.group(1))] = dict(entries[0])
            continue
        if name == winner_record_name(exp):
            st.winner = dict(entries[0])
        elif name == live_service_name(exp):
            for e in entries:
                st.live[str(e.get("host"))] = dict(e)
    return st


def read_state(
    registry_urls: Any, exp: str, timeout: float = 5.0
) -> ExperimentState:
    return state_from_roster(exp, fetch_roster(registry_urls, timeout))


def report_trial(
    registry_urls: Any,
    exp: str,
    trial: str,
    rung: int,
    metric: float,
    ckpt_digest: str,
    model_digest: str,
    iters: int,
    params: dict,
    timeout: float = 5.0,
) -> dict:
    """CAS-commit one trial's rung report; returns the DURABLE record —
    this write's on a win, the incumbent's on a lose (first report wins:
    a rescheduled trial re-deriving the same deterministic metric simply
    adopts its earlier self). Fault point ``experiment.report``: an
    injected error aborts the report before the wire (retried by the
    trial loop); a delay stalls it."""
    faults.inject(
        "experiment.report",
        context={"experiment": exp, "trial": trial, "rung": int(rung)},
    )
    record = {
        "trial": trial,
        "rung": int(rung),
        "metric": float(metric),
        "ckpt": ckpt_digest,
        "model": model_digest,
        "iters": int(iters),
        "params": dict(params),
    }
    committed, current = cas_commit(
        registry_urls, trial_record_name(exp, trial, rung), record,
        timeout=timeout,
    )
    return record if committed else current


__all__ = [
    "ExperimentState",
    "ExperimentWireError",
    "cas_commit",
    "fetch_roster",
    "live_service_name",
    "read_state",
    "register",
    "report_trial",
    "rung_record_name",
    "state_from_roster",
    "trial_record_name",
    "winner_record_name",
]
