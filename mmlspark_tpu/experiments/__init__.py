"""Fleet-scale experiment orchestration (docs/experiments.md).

ASHA successive halving driven across the fleet: an
:class:`~mmlspark_tpu.experiments.controller.ExperimentController`
(``fleet tune``) samples a seeded search space, schedules each trial as
a supervisor charge running the ``fleet train`` machinery to a rung
boundary, checkpoints through the content-addressed artifact plane, and
promotes the top 1/eta per rung with write-once generation-CAS records
— so two controllers can never both promote, and a restarted controller
resumes the experiment from registry state alone. The winner is
auto-published into serving through the epoch-fenced Publisher path.
"""

from mmlspark_tpu.experiments.asha import (
    leaderboard,
    n_promote,
    promote,
    rung_boundaries,
)
from mmlspark_tpu.experiments.records import (
    ExperimentState,
    cas_commit,
    read_state,
)

__all__ = [
    "ExperimentController",
    "ExperimentState",
    "cas_commit",
    "leaderboard",
    "n_promote",
    "promote",
    "read_state",
    "run_trial",
    "rung_boundaries",
]


def __getattr__(name: str):
    # the controller/trial entry points drag in the serving stack —
    # loaded lazily so `from mmlspark_tpu.experiments import asha` stays
    # import-light for the pure-math consumers (lint tools, tests)
    if name == "ExperimentController":
        from mmlspark_tpu.experiments.controller import ExperimentController

        return ExperimentController
    if name == "run_trial":
        from mmlspark_tpu.experiments.trial import run_trial

        return run_trial
    raise AttributeError(name)
