"""Binding/codegen layer (reference: codegen/, SURVEY.md §2.17).

The reference reflects over every ``Wrappable`` stage in the jar to
generate PySpark/SparklyR wrapper classes and wrapper smoke tests
(WrapperGenerator.scala:22-117). This framework is Python-native, so the
equivalent deliverables are:

- :func:`reflect_stage` / :func:`generate_manifest` — a machine-readable
  API surface (stage -> module, kind, params with docs/defaults/types),
  the wrapper-metadata analogue, consumed by doc generation and smoke
  tests and exported for external binding writers.
- :func:`generate_api_docs` — per-package markdown API reference.
- :func:`generate_smoke_tests` — a pytest file instantiating every
  registered stage with defaults and asserting param integrity (the
  PySparkWrapperTest analogue).

Like the reference (which runs codegen inside the build), these run in the
test suite: tests/test_codegen.py regenerates everything and asserts the
registry is fully covered.
"""

from __future__ import annotations

import importlib
import inspect
import json
import os
import pkgutil
from typing import Any, Optional

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import (
    STAGE_REGISTRY,
    Estimator,
    Model,
    PipelineStage,
    Transformer,
)


def import_all_packages() -> None:
    """Import every mmlspark_tpu module so STAGE_REGISTRY is complete."""
    import mmlspark_tpu

    root = os.path.dirname(mmlspark_tpu.__file__)
    for mod in pkgutil.walk_packages([root], prefix="mmlspark_tpu."):
        name = mod.name
        if ".native" in name or name.endswith("__main__"):
            continue
        try:
            importlib.import_module(name)
        except Exception:
            # optional modules (native toolchains etc.) must not break codegen
            pass


def _stage_kind(cls: type) -> str:
    if issubclass(cls, Model):
        return "model"
    if issubclass(cls, Estimator):
        return "estimator"
    if issubclass(cls, Transformer):
        return "transformer"
    return "stage"


def reflect_stage(cls: type) -> dict:
    """One stage's wrapper metadata."""
    params = {}
    for name, p in cls.params().items():
        params[name] = {
            "doc": p.doc,
            "complex": bool(p.is_complex),
            "type": p.type_.__name__ if p.type_ is not None else None,
            "has_default": p.has_default(),
            "default": (
                p.default
                if p.has_default() and isinstance(p.default, (int, float, str, bool, type(None), list))
                else ("<complex>" if p.has_default() else None)
            ),
        }
    return {
        "name": cls.__name__,
        "module": cls.__module__,
        "kind": _stage_kind(cls),
        "doc": inspect.getdoc(cls) or "",
        "params": params,
    }


def generate_manifest() -> dict:
    """Full API manifest over the (fully imported) stage registry."""
    import_all_packages()
    stages = {
        name: reflect_stage(cls)
        for name, cls in sorted(STAGE_REGISTRY.items())
        # library stages only — the registry may also hold test-local stages
        if not name.startswith("_") and cls.__module__.startswith("mmlspark_tpu.")
    }
    from mmlspark_tpu.version import __version__

    return {"version": __version__, "stages": stages}


def _group_by_package(manifest: dict) -> dict:
    """stage infos grouped by their top-level mmlspark_tpu subpackage —
    the one grouping rule docs and R bindings must share."""
    by_pkg: dict[str, list] = {}
    for info in manifest["stages"].values():
        pkg = info["module"].split(".")[1] if "." in info["module"] else info["module"]
        by_pkg.setdefault(pkg, []).append(info)
    return by_pkg


def generate_api_docs(out_dir: str, manifest: Optional[dict] = None) -> list:
    """Write one markdown file per package; returns written paths."""
    manifest = manifest or generate_manifest()
    by_pkg = _group_by_package(manifest)

    os.makedirs(out_dir, exist_ok=True)
    written = []
    for pkg, stages in sorted(by_pkg.items()):
        path = os.path.join(out_dir, f"{pkg}.md")
        lines = [f"# `mmlspark_tpu.{pkg}`", ""]
        for info in sorted(stages, key=lambda s: s["name"]):
            lines.append(f"## {info['name']}  *({info['kind']})*")
            lines.append("")
            if info["doc"]:
                lines.append(info["doc"])
                lines.append("")
            if info["params"]:
                lines.append("| param | type | default | doc |")
                lines.append("|---|---|---|---|")
                for pname, p in sorted(info["params"].items()):
                    t = p["type"] or ("complex" if p["complex"] else "any")
                    d = repr(p["default"]) if p["has_default"] else "required"
                    doc = (p["doc"] or "").replace("|", "\\|")
                    lines.append(f"| `{pname}` | {t} | {d} | {doc} |")
                lines.append("")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        written.append(path)
    index = os.path.join(out_dir, "README.md")
    with open(index, "w") as f:
        f.write("# mmlspark_tpu API reference (generated)\n\n")
        f.write(f"{len(manifest['stages'])} stages.\n\n")
        for pkg in sorted(by_pkg):
            f.write(f"- [{pkg}]({pkg}.md) ({len(by_pkg[pkg])} stages)\n")
    written.append(index)
    return written


def generate_smoke_tests(out_path: str, manifest: Optional[dict] = None) -> str:
    """Emit a pytest module that default-constructs every stage and checks
    params round-trip through explain_params (PySparkWrapperTest analogue)."""
    manifest = manifest or generate_manifest()
    lines = [
        '"""GENERATED by mmlspark_tpu.codegen - do not edit."""',
        "import importlib",
        "import pytest",
        "",
        "CASES = [",
    ]
    for name, info in sorted(manifest["stages"].items()):
        lines.append(f"    ({info['module']!r}, {name!r}),")
    lines += [
        "]",
        "",
        "",
        "@pytest.mark.parametrize('module,name', CASES)",
        "def test_stage_surface(module, name):",
        "    cls = getattr(importlib.import_module(module), name)",
        "    stage = cls()  # every stage must be default-constructible",
        "    assert stage.explain_params() is not None",
        "    for pname, p in cls.params().items():",
        "        assert p.name == pname",
        "",
    ]
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        f.write("\n".join(lines))
    return out_path


def write_manifest(out_path: str, manifest: Optional[dict] = None) -> str:
    manifest = manifest or generate_manifest()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(manifest, f, indent=1, default=str)
    return out_path


def _r_name(stage_name: str) -> str:
    """CamelCase stage -> mt_snake_case R constructor (the reference's
    SparklyRWrapper emits ml_/ft_-prefixed R functions the same way)."""
    import re as _re

    # acronym-aware camel -> snake: LightGBMClassifier -> light_gbm_classifier
    s = _re.sub(
        r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])", "_", stage_name
    ).lower()
    return f"mt_{s}"


def _r_default(p: dict) -> str:
    """Python param default -> R literal. Ints carry the L suffix so
    reticulate passes Python ints (a bare 0 is an R double -> float, which
    int-typed Params reject); non-scalar defaults (recorded by
    reflect_stage as the "<complex>" placeholder) become NULL so the
    python-side default applies."""
    if not p["has_default"] or p["complex"]:
        return "NULL"
    v = p["default"]
    if v is None or v == "<complex>":
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, int):
        return f"{v}L"
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (list, tuple)):
        if not v:
            return "list()"
        return "list(" + ", ".join(_r_default({"has_default": True, "complex": False, "default": x}) for x in v) + ")"
    return "NULL"


def generate_r_package(out_dir: str, manifest: Optional[dict] = None) -> list:
    """Generate an R binding package (reticulate-backed) from the manifest.

    The reference generates its R wrappers from Scala reflection
    (SparklyRWrapper.scala:22-117); here the SAME stage registry that
    feeds the manifest and docs emits one R constructor per stage:

        model <- mt_lightgbm_classifier(num_iterations = 50L)$fit(df)

    Each function imports the stage's python module through reticulate and
    forwards its (defaulted) arguments; NULL arguments are dropped so
    python defaults apply. Returns the written paths."""
    manifest = manifest or generate_manifest()
    os.makedirs(os.path.join(out_dir, "R"), exist_ok=True)
    written = []

    with open(os.path.join(out_dir, "DESCRIPTION"), "w") as f:
        f.write(
            "Package: mmlsparktpu\n"
            "Type: Package\n"
            "Title: R bindings for the mmlspark_tpu framework\n"
            f"Version: {manifest['version']}\n"
            "Description: Generated reticulate-backed wrappers for every\n"
            "    registered pipeline stage (one constructor per stage).\n"
            "Imports: reticulate\n"
            "License: MIT\n"
        )
    written.append(os.path.join(out_dir, "DESCRIPTION"))
    with open(os.path.join(out_dir, "NAMESPACE"), "w") as f:
        f.write('exportPattern("^mt_")\nexport(mt_data_frame)\n')
    written.append(os.path.join(out_dir, "NAMESPACE"))

    core = [
        "# Generated by mmlspark_tpu.codegen.generate_r_package — do not edit.",
        "",
        "#' Build a mmlspark_tpu DataFrame from a named list of vectors/arrays",
        "#' @export",
        "mt_data_frame <- function(columns, num_partitions = NULL) {",
        '  core <- reticulate::import("mmlspark_tpu")',
        "  if (is.null(num_partitions)) core$DataFrame$from_dict(columns)",
        "  else core$DataFrame$from_dict(columns, num_partitions = as.integer(num_partitions))",
        "}",
        "",
        "#' Transform a DataFrame with a (fitted) stage",
        "#' (src/main/R/ml_utils.R sdf_transform analogue)",
        "#' @export",
        "mt_transform <- function(stage, df, ...) {",
        "  stage$transform(df, ...)",
        "}",
        "",
        "#' Fit an estimator on a DataFrame, returning the fitted model",
        "#' (src/main/R/ml_utils.R sdf_fit analogue)",
        "#' @export",
        "mt_fit <- function(estimator, df, ...) {",
        "  estimator$fit(df, ...)",
        "}",
        "",
        "#' Model zoo downloader (src/main/R/model_downloader.R",
        "#' smd_model_downloader analogue). Without server_url: the local",
        "#' repo client ($list_models(), $download_by_name(name)); with",
        "#' server_url: a RemoteRepository syncing into that local repo.",
        "#' @export",
        "mt_model_downloader <- function(local_path, server_url = NULL) {",
        '  d <- reticulate::import("mmlspark_tpu.downloader")',
        "  local <- d$ModelDownloader(local_path)",
        "  if (is.null(server_url)) local",
        "  else d$RemoteRepository(server_url, local)",
        "}",
        "",
    ]
    with open(os.path.join(out_dir, "R", "core.R"), "w") as f:
        f.write("\n".join(core))
    written.append(os.path.join(out_dir, "R", "core.R"))

    by_pkg = _group_by_package(manifest)
    for pkg, stages in sorted(by_pkg.items()):
        lines = [
            "# Generated by mmlspark_tpu.codegen.generate_r_package — do not edit.",
            "",
        ]
        for info in sorted(stages, key=lambda s: s["name"]):
            fn = _r_name(info["name"])
            params = sorted(info["params"].items())
            sig = ", ".join(f"{n} = {_r_default(p)}" for n, p in params)
            doc1 = (info["doc"] or info["name"]).splitlines()[0].replace("'", "")
            lines += [
                f"#' {doc1}",
                f"#' ({info['kind']}: mmlspark_tpu.{info['module'].split('.', 1)[-1]}.{info['name']})",
                "#' @export",
                f"{fn} <- function({sig}) {{",
                "  # snapshot formals BEFORE any local assignment leaks in",
                "  args <- as.list(environment())",
                "  args <- args[!vapply(args, is.null, logical(1))]",
                f'  m <- reticulate::import("{info["module"]}")',
                f'  do.call(m${info["name"]}, args)',
                "}",
                "",
            ]
        path = os.path.join(out_dir, "R", f"{pkg}.R")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        written.append(path)
    return written
