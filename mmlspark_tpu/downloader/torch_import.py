"""Import torchvision-format ResNet checkpoints into the model zoo.

The reference's zoo ships real trained backbones and ImageFeaturizer loads
them by name (downloader/ModelDownloader.scala:210-276, Schema.scala:54-66,
ImageFeaturizer.scala:133-178). This egress-free environment cannot fetch
ImageNet weights, so instead the zoo accepts the de-facto standard
serialized format: a torchvision ResNet ``state_dict`` (torch ``.pth``).
Any externally trained ResNet-18/34/50/101 drops into the flax backbone:

    from mmlspark_tpu.downloader import install_torch_checkpoint
    schema = install_torch_checkpoint("resnet50-imagenet.pth", name="ResNet50")
    ImageFeaturizer(model_name="ResNet50", ...)   # real semantic features

The conversion is exact: convs transpose OIHW -> HWIO, batch norms map
(weight, bias, running_mean, running_var) -> (scale, bias, mean, var), the
classifier transposes, and the module is built with ``torch_padding=True``
so strided convs/pool pad symmetrically like torch (XLA's SAME padding is
asymmetric at stride 2 — without this every strided feature map shifts by
one pixel and features stop matching torchvision's).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import numpy as np

log = logging.getLogger("mmlspark_tpu.downloader")

# stage sizes per variant (must match models/resnet.py factories)
_STAGES = {
    "ResNet18": ([2, 2, 2, 2], "BasicBlock"),
    "ResNet34": ([3, 4, 6, 3], "BasicBlock"),
    "ResNet50": ([3, 4, 6, 3], "BottleneckBlock"),
    "ResNet101": ([3, 4, 23, 3], "BottleneckBlock"),
}


def _np(t: Any) -> np.ndarray:
    """torch tensor or array-like -> float32 numpy (host)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, np.float32)


def _take(sd: dict, key: str) -> Any:
    try:
        return sd.pop(key)
    except KeyError:
        raise ValueError(
            f"state_dict is missing {key!r} — architecture mismatch with "
            "the requested variant"
        ) from None


def _conv(sd: dict, key: str) -> np.ndarray:
    """torch conv weight (O, I, kh, kw) -> flax kernel (kh, kw, I, O)."""
    return _np(_take(sd, key)).transpose(2, 3, 1, 0)


def _bn(sd: dict, prefix: str) -> tuple:
    """-> (params {scale, bias}, stats {mean, var})."""
    sd.pop(f"{prefix}.num_batches_tracked", None)
    return (
        {
            "scale": _np(_take(sd, f"{prefix}.weight")),
            "bias": _np(_take(sd, f"{prefix}.bias")),
        },
        {
            "mean": _np(_take(sd, f"{prefix}.running_mean")),
            "var": _np(_take(sd, f"{prefix}.running_var")),
        },
    )


def import_torch_resnet(state_dict: dict, variant: str = "ResNet50") -> dict:
    """torchvision ResNet ``state_dict`` -> flax variables
    ``{"params": ..., "batch_stats": ...}`` for ``RESNETS[variant]`` built
    with ``torch_padding=True``. Strict: every weight must be consumed and
    every expected key present, so silent architecture drift is impossible.
    """
    if variant not in _STAGES:
        raise ValueError(f"unsupported variant {variant!r}; known: {list(_STAGES)}")
    stages, block_kind = _STAGES[variant]
    sd = dict(state_dict)
    params: dict = {}
    stats: dict = {}

    params["conv_init"] = {"kernel": _conv(sd, "conv1.weight")}
    params["bn_init"], stats["bn_init"] = _bn(sd, "bn1")

    flat = 0
    for li, blocks in enumerate(stages):
        for bj in range(blocks):
            t = f"layer{li + 1}.{bj}"
            name = f"{block_kind}_{flat}"
            flat += 1
            p: dict = {}
            s: dict = {}
            n_convs = 3 if block_kind == "BottleneckBlock" else 2
            for ci in range(n_convs):
                p[f"Conv_{ci}"] = {"kernel": _conv(sd, f"{t}.conv{ci + 1}.weight")}
                p[f"BatchNorm_{ci}"], s[f"BatchNorm_{ci}"] = _bn(
                    sd, f"{t}.bn{ci + 1}"
                )
            if f"{t}.downsample.0.weight" in sd:
                p["proj"] = {"kernel": _conv(sd, f"{t}.downsample.0.weight")}
                p["proj_bn"], s["proj_bn"] = _bn(sd, f"{t}.downsample.1")
            params[name] = p
            stats[name] = s

    if "fc.weight" in sd:
        params["head"] = {
            "kernel": _np(sd.pop("fc.weight")).T,
            "bias": _np(_take(sd, "fc.bias")),
        }
    else:
        raise ValueError(
            "state_dict has no fc.weight — import the full torchvision "
            "checkpoint (the featurizer cuts the head at runtime instead)"
        )
    leftovers = [k for k in sd if not k.endswith("num_batches_tracked")]
    if leftovers:
        raise ValueError(
            f"unconsumed keys in state_dict (architecture mismatch with "
            f"{variant}): {leftovers[:8]}{'...' if len(leftovers) > 8 else ''}"
        )
    return {"params": params, "batch_stats": stats}


def import_torch_vit(
    state_dict: dict, num_heads: Optional[int] = None,
    variant: str = "ViTB16",
) -> dict:
    """torchvision ViT ``state_dict`` (``vit_b_16`` layout) -> flax
    variables ``{"params": ...}`` for ``VITS[variant]``. Strict like the
    ResNet importer: every weight consumed, every expected key present,
    and the checkpoint's geometry (patch size, hidden dim, depth,
    mlp width) validated against the variant — a mismatched checkpoint
    fails HERE, not at serve time deep inside flax apply.

    Layout notes: torch packs q/k/v as ``in_proj_weight`` (3C, C) with
    heads contiguous inside each of q/k/v — exactly the (C, 3, H, D)
    DenseGeneral kernel after a transpose+reshape; linears transpose;
    LayerNorm weight/bias -> scale/bias.
    """
    from mmlspark_tpu.models.vit import VITS

    if variant not in VITS:
        raise ValueError(f"unsupported variant {variant!r}; known: {list(VITS)}")
    ref = VITS[variant]()
    if num_heads is None:
        num_heads = ref.num_heads
    sd = dict(state_dict)
    params: dict = {}

    params["conv_proj"] = {
        "kernel": _conv(sd, "conv_proj.weight"),
        "bias": _np(_take(sd, "conv_proj.bias")),
    }
    kh, kw_ = params["conv_proj"]["kernel"].shape[:2]
    if (kh, kw_) != (ref.patch_size, ref.patch_size):
        raise ValueError(
            f"checkpoint patch size {kh}x{kw_} != {variant}'s "
            f"{ref.patch_size}"
        )
    params["cls_token"] = _np(_take(sd, "class_token"))
    params["pos_embedding"] = _np(_take(sd, "encoder.pos_embedding"))
    c = params["pos_embedding"].shape[-1]
    if c != ref.hidden_dim:
        raise ValueError(
            f"checkpoint hidden dim {c} != {variant}'s {ref.hidden_dim}"
        )
    if c % num_heads:
        raise ValueError(f"hidden dim {c} not divisible by heads {num_heads}")
    d = c // num_heads

    def _ln(prefix: str) -> dict:
        return {
            "scale": _np(_take(sd, f"{prefix}.weight")),
            "bias": _np(_take(sd, f"{prefix}.bias")),
        }

    def _linear(prefix: str) -> dict:
        return {
            "kernel": _np(_take(sd, f"{prefix}.weight")).T,
            "bias": _np(_take(sd, f"{prefix}.bias")),
        }

    i = 0
    while f"encoder.layers.encoder_layer_{i}.ln_1.weight" in sd:
        t = f"encoder.layers.encoder_layer_{i}"
        w_in = _np(_take(sd, f"{t}.self_attention.in_proj_weight"))
        b_in = _np(_take(sd, f"{t}.self_attention.in_proj_bias"))
        params[f"block_{i}"] = {
            "ln_1": _ln(f"{t}.ln_1"),
            "qkv": {
                "kernel": w_in.T.reshape(c, 3, num_heads, d),
                "bias": b_in.reshape(3, num_heads, d),
            },
            "out": _linear(f"{t}.self_attention.out_proj"),
            "ln_2": _ln(f"{t}.ln_2"),
            "mlp_1": _linear(f"{t}.mlp.0"),
            "mlp_2": _linear(f"{t}.mlp.3"),
        }
        i += 1
    if i == 0:
        raise ValueError(
            "state_dict has no encoder.layers.encoder_layer_0 — not a "
            "torchvision ViT checkpoint"
        )
    if i != ref.depth:
        raise ValueError(
            f"checkpoint has {i} encoder layers != {variant}'s {ref.depth}"
        )
    if params["block_0"]["mlp_1"]["kernel"].shape[1] != ref.mlp_dim:
        raise ValueError(
            f"checkpoint mlp width "
            f"{params['block_0']['mlp_1']['kernel'].shape[1]} != "
            f"{variant}'s {ref.mlp_dim}"
        )
    params["ln"] = _ln("encoder.ln")
    params["head"] = _linear("heads.head")
    leftovers = list(sd)
    if leftovers:
        raise ValueError(
            f"unconsumed keys in state_dict (architecture mismatch with "
            f"{variant}): {leftovers[:8]}{'...' if len(leftovers) > 8 else ''}"
        )
    return {"params": params}


def install_torch_checkpoint(
    src: Any,
    name: str,
    variant: Optional[str] = None,
    num_classes: Optional[int] = None,
    image_size: int = 224,
    downloader: Any = None,
) -> Any:
    """Load a torch ``.pth``/state_dict and register it in the local zoo.

    ``src``: a path to a torch-serialized file or an in-memory state_dict.
    Returns the installed :class:`ModelSchema`; afterwards
    ``ImageFeaturizer(model_name=name)`` serves REAL features from it.
    """
    from mmlspark_tpu.downloader.zoo import ModelDownloader, ModelSchema

    if isinstance(src, (str, bytes, os.PathLike)):
        import torch

        state_dict = torch.load(src, map_location="cpu", weights_only=True)
        if hasattr(state_dict, "state_dict"):  # a full module was saved
            state_dict = state_dict.state_dict()
    else:
        state_dict = src
    variant = variant or name.split("_", 1)[0]
    is_vit = variant.startswith("ViT")
    if is_vit:
        from mmlspark_tpu.models.vit import VITS, ViT

        variables = import_torch_vit(state_dict, variant=variant)
        # pos-embedding length is input-size-dependent: serving at a
        # different size than the checkpoint was trained for would only
        # fail at transform time, so pin it here
        n_ck = variables["params"]["pos_embedding"].shape[1]
        ps = VITS[variant]().patch_size
        n_want = (image_size // ps) ** 2 + 1
        if n_ck != n_want:
            raise ValueError(
                f"checkpoint pos_embedding has {n_ck} tokens but "
                f"image_size={image_size} needs {n_want} — pass the "
                f"image_size the checkpoint was trained at"
            )
        layer_names = list(ViT.LAYER_NAMES)
    else:
        variables = import_torch_resnet(state_dict, variant=variant)
        layer_names = None  # schema default (ResNet stage names)
    if num_classes is None:
        num_classes = int(variables["params"]["head"]["bias"].shape[0])
    dl = downloader or ModelDownloader()
    extra = {} if layer_names is None else {"layer_names": layer_names}
    schema = ModelSchema(
        name=name,
        variant=variant,
        num_classes=num_classes,
        image_size=image_size,
        # ViT has no strided-conv SAME/symmetric divergence (patch conv is
        # VALID at stride = kernel); torch_padding only concerns ResNets
        torch_padding=not is_vit,
        **extra,
    )
    dl.register(schema, variables)
    log.info("installed torch checkpoint %r as zoo model %r", variant, name)
    return schema
