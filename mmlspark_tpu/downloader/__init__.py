from mmlspark_tpu.downloader.zoo import ModelDownloader, ModelSchema, RemoteRepository
from mmlspark_tpu.downloader.torch_import import (
    import_torch_resnet,
    install_torch_checkpoint,
)

__all__ = [
    "ModelDownloader",
    "ModelSchema",
    "RemoteRepository",
    "import_torch_resnet",
    "install_torch_checkpoint",
]
