from mmlspark_tpu.downloader.zoo import ModelDownloader, ModelSchema

__all__ = ["ModelDownloader", "ModelSchema"]
