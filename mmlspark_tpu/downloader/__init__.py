from mmlspark_tpu.downloader.zoo import ModelDownloader, ModelSchema, RemoteRepository

__all__ = ["ModelDownloader", "ModelSchema", "RemoteRepository"]
